"""Template rendering.

Reference: client/allocrunner/taskrunner/template/template.go (759 LoC,
consul-template). Without Consul/Vault in the tree, the supported
function set is the env-shaped subset real jobspecs rely on:

    {{ env "NOMAD_ALLOC_ID" }}
    {{ key "path" }}          -> empty string (no Consul KV)
    {{ meta "k" }}            -> NOMAD_META_k
    ${NOMAD_...}              -> plain interpolation

change_mode restart/signal/noop is honored by the task runner on
re-render; templates render once before task start (the reference's
initial render gate — prestart blocks until all templates render).
"""

from __future__ import annotations

import os
import re

from ..structs.structs import Template

_FUNC_RE = re.compile(
    r"\{\{\s*(env|key|meta|service|secret)\s+\"([^\"]+)\"\s*\}\}"
)


class TemplateError(Exception):
    pass


def compute_template(
    tmpl: Template, task_dir: str, env: dict[str, str], service_fn=None,
    secret_fn=None,
) -> tuple[str, str]:
    """Render without writing: (confined destination path, content)."""
    from .allocdir import EscapeError, alloc_sandbox, confine
    from .taskenv import interpolate

    sandbox = alloc_sandbox(task_dir)

    if tmpl.embedded_tmpl:
        src = tmpl.embedded_tmpl
    elif tmpl.source_path:
        path = interpolate(tmpl.source_path, env)
        if not os.path.isabs(path):
            path = os.path.join(task_dir, path)
        try:
            path = confine(sandbox, path)
        except EscapeError as e:
            raise TemplateError(str(e)) from e
        try:
            with open(path) as f:
                src = f.read()
        except OSError as e:
            raise TemplateError(f"template source: {e}") from e
    else:
        raise TemplateError("template has neither data nor source")

    def repl(m: re.Match) -> str:
        fn, arg = m.group(1), m.group(2)
        if fn == "env":
            return env.get(arg, "")
        if fn == "meta":
            return env.get(f"NOMAD_META_{arg}", env.get(f"meta.{arg}", ""))
        if fn == "service":
            # native service discovery: one "address:port" per line
            # (consul-template's {{ range service }} collapsed to the
            # address list jobs actually template in)
            if service_fn is None:
                return ""
            try:
                regs = service_fn(arg) or []
            except Exception:
                return ""
            return "\n".join(
                f"{r.address}:{r.port}" for r in regs
            )
        if fn == "secret":
            # {{ secret "path:key" }} reads the embedded secrets store
            # (the consul-template vault function collapsed to one
            # path:key lookup; values never transit the event stream)
            if secret_fn is None:
                return ""
            path, _, key = arg.partition(":")
            try:
                entry = secret_fn(path)
            except Exception as e:
                # transient lookup failure must FAIL the render, not
                # render an empty credential (prestart then retries; the
                # watcher skips the poll instead of flip-flopping)
                raise TemplateError(
                    f"secret lookup {path!r} failed: {e}"
                ) from e
            if entry is None:
                return ""
            if key:
                return entry.items.get(key, "")
            return "\n".join(
                f"{k}={v}" for k, v in sorted(entry.items.items())
            )
        return ""  # key: no Consul KV backend

    rendered = _FUNC_RE.sub(repl, src)
    rendered = interpolate(rendered, env)

    dest = interpolate(tmpl.dest_path, env)
    if not dest:
        raise TemplateError("template missing destination")
    if not os.path.isabs(dest):
        dest = os.path.join(task_dir, dest)
    try:
        dest = confine(sandbox, dest)
    except EscapeError as e:
        raise TemplateError(str(e)) from e
    return dest, rendered


def write_template(tmpl: Template, dest: str, content: str) -> None:
    os.makedirs(os.path.dirname(dest), exist_ok=True)
    with open(dest, "w") as f:
        f.write(content)
    try:
        os.chmod(dest, int(tmpl.perms or "0644", 8))
    except ValueError:
        pass


def render_template(
    tmpl: Template, task_dir: str, env: dict[str, str], service_fn=None,
    secret_fn=None,
) -> str:
    """Render to task_dir/<dest_path>; returns the destination path."""
    dest, content = compute_template(
        tmpl, task_dir, env, service_fn, secret_fn
    )
    write_template(tmpl, dest, content)
    return dest


class TemplateWatcher:
    """The re-render loop (reference template.go's runner): poll each
    template's inputs, and when the rendered content changes, rewrite the
    destination and fire change_mode — signal via the driver, restart via
    the task runner's template-restart hook (which does NOT consume the
    restart policy's budget, matching the reference's
    SetRestartTriggered).

    Dynamic inputs here are source files (artifacts refreshed on disk)
    and any env drift; without Consul/Vault in the tree there is no KV
    watch, so polling the rendered output is the honest equivalent.
    """

    def __init__(
        self,
        templates,
        task_dir: str,
        env: dict[str, str],
        signal_fn,  # (signal_name) -> None
        restart_fn,  # () -> None
        poll_interval_s: float = 2.0,
        service_fn=None,  # (name) -> [ServiceRegistration] (native SD)
        secret_fn=None,  # (path) -> SecretEntry | None
    ) -> None:
        import threading

        self.service_fn = service_fn
        self.secret_fn = secret_fn
        self.templates = list(templates)
        self.task_dir = task_dir
        self.env = env
        self.signal_fn = signal_fn
        self.restart_fn = restart_fn
        self.poll_interval_s = poll_interval_s
        self._last: dict[int, str] = {}
        self._stop = threading.Event()
        self._thread = None

    def prime(self) -> None:
        """Record current rendered contents as the baseline (call after
        the initial prestart render)."""
        for i, tmpl in enumerate(self.templates):
            try:
                _, content = compute_template(
                    tmpl, self.task_dir, self.env, self.service_fn,
                    self.secret_fn,
                )
                self._last[i] = content
            except TemplateError:
                pass

    def start(self) -> None:
        import threading

        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, args=(self._stop,), daemon=True,
            name="template-watcher",
        )
        self._thread.start()

    def stop(self) -> None:
        """Stop AND join: after return, no callback can fire — the task
        runner clears its restart event right after this, and a straggler
        set() would bounce the fresh task for no reason."""
        self._stop.set()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout=5)
        self._thread = None

    def _run(self, stop) -> None:
        while not stop.wait(self.poll_interval_s):
            restart = False
            signals: list[str] = []
            for i, tmpl in enumerate(self.templates):
                try:
                    dest, content = compute_template(
                        tmpl, self.task_dir, self.env, self.service_fn,
                        self.secret_fn,
                    )
                except TemplateError:
                    continue
                if content == self._last.get(i):
                    continue
                mode_ = tmpl.change_mode or "restart"
                if mode_ != "noop" and tmpl.splay_s > 0:
                    # randomized, NOT capped: splay exists to stagger a
                    # fleet's restarts when a shared input changes
                    import random

                    if stop.wait(random.uniform(0, tmpl.splay_s)):
                        return
                write_template(tmpl, dest, content)
                self._last[i] = content
                mode = tmpl.change_mode or "restart"
                if mode == "restart":
                    restart = True
                elif mode == "signal":
                    signals.append(tmpl.change_signal or "SIGHUP")
            if stop.is_set():
                return
            # coalesce: one restart beats any number of signals
            if restart:
                self.restart_fn()
            else:
                for sig in signals:
                    self.signal_fn(sig)
