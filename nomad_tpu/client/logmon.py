"""Log rotation.

Reference: client/logmon/ (~1,000 LoC) — an out-of-process plugin that
pumps task FIFOs into size-rotated files (logging/rotator.go). Our
drivers append directly to files, so rotation is copy-truncate (the
writer keeps its fd; we copy the full file to the next index and
truncate in place — the same trade logrotate's copytruncate makes: a
small window of loss between copy and truncate).

Files are named <task>.<stream>.<n> with n=0 the live file, matching the
reference's naming that the fs/logs API sorts on.
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Optional

logger = logging.getLogger("nomad_tpu.logmon")


class LogRotator:
    def __init__(
        self,
        live_path: str,  # e.g. .../logs/web.stdout.0
        max_files: int = 10,
        max_file_size_mb: int = 10,
        check_interval_s: float = 2.0,
    ) -> None:
        self.live_path = live_path
        self.max_files = max(1, max_files)
        self.max_bytes = max_file_size_mb * 1024 * 1024
        self.check_interval_s = check_interval_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="logmon"
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=2)

    def _loop(self) -> None:
        while not self._stop.wait(self.check_interval_s):
            try:
                self.rotate_if_needed()
            except OSError:
                logger.exception("log rotation failed for %s", self.live_path)

    def rotate_if_needed(self) -> bool:
        try:
            size = os.path.getsize(self.live_path)
        except OSError:
            return False
        if size < self.max_bytes:
            return False
        base = self.live_path[: -len(".0")]
        # shift .(n) -> .(n+1), dropping the oldest beyond max_files
        oldest = self.max_files - 1
        for n in range(oldest, 0, -1):
            src = f"{base}.{n}"
            if not os.path.exists(src):
                continue
            if n == oldest:
                os.unlink(src)
            else:
                os.replace(src, f"{base}.{n + 1}")
        # copy-truncate the live file into .1
        with open(self.live_path, "rb") as live, open(f"{base}.1", "wb") as out:
            while True:
                chunk = live.read(1 << 20)
                if not chunk:
                    break
                out.write(chunk)
        with open(self.live_path, "r+b") as live:
            live.truncate(0)
        return True
