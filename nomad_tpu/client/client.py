"""The node agent.

Reference: client/client.go (3,085 LoC) — NewClient :325, registration +
heartbeat :1554, watchAllocations :2003 (blocking query), runAllocs :2233
(diff desired vs running), batched status sync allocSync :1936.

The server connection is the `rpc` object — in-process round 1, the
msgpack-RPC fabric in Phase 2. The client only uses five verbs, mirroring
the reference's Node.* RPCs: register, heartbeat, get_client_allocs,
update_allocs, deregister.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Optional

from ..drivers import BUILTIN_DRIVERS, Driver
from ..drivers.base import HEALTH_STATE_HEALTHY, HEALTH_STATE_UNDETECTED
from ..structs import Allocation, Node
from ..structs.structs import ALLOC_DESIRED_STATUS_RUN, DriverInfo, now_ns
from .allocrunner import AllocRunner
from .fingerprint import dynamic_attributes, fingerprint_node

logger = logging.getLogger("nomad_tpu.client")

ALLOC_SYNC_INTERVAL_S = 0.2  # reference: allocSyncIntv 200ms


class ServerRPC:
    """In-process stand-in for the client<->server RPC fabric."""

    def __init__(self, server) -> None:
        self.server = server

    def register(self, node: Node) -> float:
        return self.server.node_register(node)

    def heartbeat(self, node_id: str) -> float:
        return self.server.node_heartbeat(node_id)

    def get_client_allocs(self, node_id: str, min_index: int, timeout_s: float):
        return self.server.get_client_allocs(node_id, min_index, timeout_s)

    def update_allocs(self, allocs: list[Allocation]) -> None:
        self.server.update_allocs_from_client(allocs)

    def volumes_for_alloc(self, alloc_id: str) -> list:
        return self.server.state.volumes_for_alloc(alloc_id)

    def services_register(self, regs: list) -> None:
        self.server.services_register(regs)

    def services_deregister_alloc(self, alloc_id: str) -> None:
        self.server.services_deregister_alloc(alloc_id)

    def service_lookup(self, namespace: str, name: str) -> list:
        return self.server.state.service_registrations(namespace, name)

    def secret_read(self, namespace: str, path: str, token: str = ""):
        # in-process dev shim: no ACL enforcement (the fabric endpoint
        # enforces read-secret when the cluster runs with ACLs on)
        return self.server.state.secret_by_path(namespace, path)

    def derive_token(self, alloc_id: str, task_name: str) -> dict:
        return self.server.derive_task_token(alloc_id, task_name)

    def renew_token(self, accessor_id: str) -> float:
        return self.server.renew_task_token(accessor_id)

    def revoke_token(self, accessor_id: str) -> None:
        self.server.acl_token_delete([accessor_id])

    def alloc_client_addr(self, alloc_id: str):
        """(alloc, 'host:port' of its node's client fabric) or (None, None)
        — the prev-alloc migrator's cross-node lookup."""
        alloc = self.server.state.alloc_by_id(alloc_id)
        if alloc is None:
            return None, None
        node = self.server.state.node_by_id(alloc.node_id)
        addr = node.attributes.get("unique.client.rpc") if node else None
        return alloc, addr


class Client:
    def __init__(
        self,
        rpc,
        data_dir: str = "/tmp/nomad_tpu",
        datacenter: str = "dc1",
        node_class: str = "",
        node: Optional[Node] = None,
        drivers: Optional[dict[str, Driver]] = None,
        rpc_secret="",  # str | rpc.keyring.Keyring (shared by the agent)
        advertise_host: str = "127.0.0.1",
        csi_plugins: Optional[dict] = None,
        driver_plugins: Optional[dict] = None,  # name -> "module:Class"
        device_plugins: Optional[dict] = None,  # name -> "module:Class"
        chroot_env: Optional[dict] = None,  # exec driver's chroot map
        host_volumes: Optional[dict] = None,  # name -> {path, read_only}
        node_meta: Optional[dict] = None,  # static node metadata
        reserved: Optional[dict] = None,  # {cpu, memory, disk} carve-out
        tls=None,  # (server_ctx, client_ctx) — fabric TLS, rpc/tls.py
    ) -> None:
        self.rpc = rpc
        self.tls = tls
        self.data_dir = data_dir
        os.makedirs(data_dir, exist_ok=True)
        # Fingerprint against the REAL data dir: the periodic loop
        # recomputes storage attributes from it, and a mismatched initial
        # value would force a spurious re-register on the first tick.
        self.node = node or fingerprint_node(
            datacenter=datacenter, node_class=node_class, data_dir=data_dir
        )
        # Streaming fs/logs/exec listener; its address is advertised as a
        # node attribute so servers can dial back (client/endpoints.py).
        # advertise_host must be reachable FROM the servers (the agent
        # passes its bind_addr; loopback only works single-host).
        from .endpoints import ClientEndpoints
        from ..rpc.keyring import ensure_keyring

        # One keyring for the streaming listener and every dialer this
        # client spawns (reverse-dial, prev-alloc migration): a live
        # rpc_secret rotation moves them all together (rpc/keyring.py).
        self.keyring = ensure_keyring(rpc_secret)
        self.endpoints = ClientEndpoints(
            self, host=advertise_host, secret=self.keyring,
            tls_context=tls[0] if tls else None,
        )
        host, port = self.endpoints.addr
        self.node.attributes["unique.client.rpc"] = f"{host}:{port}"
        if drivers is not None:
            self.drivers = dict(drivers)
        else:
            self.drivers = {
                name: cls() for name, cls in BUILTIN_DRIVERS.items()
            }
            if chroot_env:
                from ..drivers.exec import ExecDriver

                self.drivers["exec"] = ExecDriver(chroot_env=chroot_env)
        # external driver plugins overlay the builtins (reference:
        # go-plugin catalog); Client owns the merge so builtins are
        # instantiated in exactly one place
        if driver_plugins:
            from ..drivers.plugin import ExternalDriver

            for name, ref in driver_plugins.items():
                self.drivers[name] = ExternalDriver(name, ref)
        # Device plugins: accelerators fingerprint onto the node so the
        # scheduler's DeviceAllocator has real instances to assign.
        from .devicemanager import DeviceManager

        self.device_manager = DeviceManager(external=device_plugins)
        # Bridge networking state (lazy: nothing touches the host until
        # the first bridge-mode alloc lands)
        from .network import BridgeNetwork

        self.bridge_network = BridgeNetwork()
        # CSI plugins (reference: client/pluginmanager/csimanager) — config
        # maps plugin_id -> builtin catalog name | "module:Class" ref.
        from .csimanager import CSIManager

        # operator meta + reserved capacity land on the node BEFORE the
        # class hash (reference: client config meta/reserved stanzas)
        if node_meta:
            self.node.meta.update(
                {str(k): str(v) for k, v in node_meta.items()}
            )
        if reserved:
            self.node.reserved.cpu = int(reserved.get("cpu", 0))
            self.node.reserved.memory_mb = int(reserved.get("memory", 0))
            self.node.reserved.disk_mb = int(reserved.get("disk", 0))
        # operator host volumes land on the node BEFORE the class hash
        # (reference: client config host_volume → Node.HostVolumes)
        if host_volumes:
            from ..structs.structs import HostVolumeConfig

            for name, hv in host_volumes.items():
                self.node.host_volumes[name] = HostVolumeConfig(
                    name=name,
                    path=str(hv.get("path", "")),
                    read_only=bool(hv.get("read_only", False)),
                )
        self.csi_manager = CSIManager(data_dir, node_id=self.node.id)
        self.csi_manager.register_from_config(csi_plugins or {})
        # Task secrets-token derivation + renewal (reference
        # client/vaultclient; the server mints TTL'd cluster tokens).
        from .vaultclient import VaultClient

        self.vault_client = VaultClient(rpc)
        self._fingerprint_drivers()
        self._fingerprint_devices()
        self._fingerprint_csi()
        from ..structs.node_class import compute_node_class

        self.node.computed_class = compute_node_class(self.node)

        # Local persistence: allocs, task state, driver handles — so a
        # restarted agent reattaches to live tasks (reference
        # client/state/state_database.go; restore path client.go:325).
        from .state_db import StateDB

        self.state_db = StateDB(data_dir)
        prev_node_id = self.state_db.get_meta("node_id")
        if node is None and prev_node_id:
            # keep our identity across restarts (reference: node ID file)
            self.node.id = prev_node_id
        self.state_db.put_meta("node_id", self.node.id)

        self.alloc_runners: dict[str, AllocRunner] = {}
        self._pending_updates: dict[str, Allocation] = {}
        self._lock = threading.Lock()
        self._shutdown = threading.Event()
        self._registered = threading.Event()
        self._threads: list[threading.Thread] = []
        self.heartbeat_ttl = 10.0
        # Periodic re-fingerprint cadence (reference fingerprint.go:31
        # runs each fingerprinter on its own period; one loop suffices
        # here). Tests shrink it to exercise the update path.
        self.fingerprint_interval_s = 30.0

    # -- lifecycle -----------------------------------------------------

    def start(self) -> None:
        self.endpoints.start()
        self.vault_client.start()
        # Reverse-dial fallback (reference client_rpc.go): park sessions
        # on the servers so they can reach us even when forward-dial to
        # our advertised address fails (NAT/firewall). Enabled whenever
        # the rpc shim can name server fabric addresses.
        addrs_fn = getattr(self.rpc, "reverse_addrs", None)
        if addrs_fn is not None and addrs_fn():
            from .endpoints import ReverseDialer

            self._reverse = ReverseDialer(
                self, self.endpoints, addrs_fn,
                secret=self.keyring,
                tls_context=self.tls[1] if self.tls else None,
            )
            self._reverse.start()
        self._restore()
        # Registration happens ON the heartbeat thread with retries
        # (reference registerAndHeartbeat runs in a goroutine): agent boot
        # must not block on servers that are still electing a leader.
        for target, name in (
            (self._heartbeat_loop, "client-heartbeat"),
            (self._watch_allocs, "client-watch"),
            (self._alloc_sync, "client-allocsync"),
            (self._fingerprint_loop, "client-fingerprint"),
        ):
            t = threading.Thread(target=target, daemon=True, name=name)
            t.start()
            self._threads.append(t)

    def shutdown(self, kill_allocs: bool = True) -> None:
        """kill_allocs=False = agent restart semantics: leave tasks
        running under their executors and keep local state for the next
        incarnation's restore (the reference's default — tasks outlive
        the agent process)."""
        self._shutdown.set()
        if getattr(self, "_reverse", None) is not None:
            self._reverse.stop()
        self.endpoints.stop()
        if kill_allocs:
            runners = list(self.alloc_runners.values())
            for ar in runners:
                ar.destroy()
            # destroy() only SIGNALS the task threads; wait for the
            # kill→destroy path to actually run or the process exits
            # with supervisors still alive (daemonized executors would
            # linger forever after their tasks die). ONE shared deadline
            # — a per-runner bound would multiply by the task count.
            deadline = time.monotonic() + 10.0
            for ar in runners:
                ar.wait(timeout_s=max(0.0, deadline - time.monotonic()))
        self.vault_client.stop()
        self.csi_manager.shutdown()
        self.device_manager.shutdown()
        # kill_allocs=False leaves tasks running in their namespaces;
        # only the in-process port relays stop (the next incarnation
        # adopts the netns and restarts them)
        self.bridge_network.shutdown(keep_namespaces=not kill_allocs)
        # out-of-process driver plugins die with us, not as orphans
        for driver in self.drivers.values():
            stop = getattr(driver, "shutdown_plugin", None)
            if stop is not None:
                try:
                    stop()
                except Exception:
                    logger.exception("driver plugin shutdown failed")
        self.state_db.close()

    # -- loops ---------------------------------------------------------

    def wait_registered(self, timeout_s: float = 15.0) -> bool:
        return self._registered.wait(timeout_s)

    def update_node_meta(self, meta: dict) -> None:
        """Agent-reload path (reference client.Reload → UpdateConfig):
        replace the operator-set static metadata and push the node so
        schedulers see the new constraint/spread targets immediately."""
        from ..structs.node_class import compute_node_class

        self.node.meta = {str(k): str(v) for k, v in meta.items()}
        self.node.computed_class = compute_node_class(self.node)
        if self._registered.is_set():
            try:
                self.rpc.register(self.node)
            except Exception:
                logger.exception("node update after meta reload failed")

    def _fingerprint_drivers(self) -> bool:
        """Run every driver's fingerprint and fold the results into the
        node. Honors each driver's verdict — an undetected driver (e.g.
        docker with no daemon) must not advertise as schedulable or the
        feasibility mask places jobs this node cannot run. Returns True
        when anything observable changed."""
        changed = False
        for name, driver in self.drivers.items():
            try:
                fp = driver.fingerprint()
            except Exception:
                logger.exception("fingerprint of driver %s failed", name)
                continue
            info = DriverInfo(
                attributes=fp.attributes,
                detected=fp.health != HEALTH_STATE_UNDETECTED,
                healthy=fp.health == HEALTH_STATE_HEALTHY,
                health_description=fp.health_description,
                update_time_ns=now_ns(),
            )
            prev = self.node.drivers.get(name)
            if (
                prev is None
                or prev.detected != info.detected
                or prev.healthy != info.healthy
                or prev.attributes != info.attributes
            ):
                changed = True
                self.node.drivers[name] = info
                # drop attributes a now-undetected driver used to claim
                if prev is not None:
                    for k in prev.attributes:
                        if k not in fp.attributes:
                            self.node.attributes.pop(k, None)
            self.node.attributes.update(fp.attributes)
        return changed

    def _fingerprint_devices(self) -> bool:
        """Refresh node.resources.devices from the device plugins;
        True when the device set changed."""
        devices = self.device_manager.fingerprint()
        prev = {
            d.id_string(): [i.id for i in d.instances]
            for d in self.node.resources.devices
        }
        cur = {d.id_string(): [i.id for i in d.instances] for d in devices}
        if prev == cur:
            return False
        self.node.resources.devices = devices
        return True

    def _fingerprint_csi(self) -> bool:
        """Refresh node.csi_plugins from the CSI manager; True on change."""
        cur = self.csi_manager.fingerprint()
        if cur == self.node.csi_plugins:
            return False
        self.node.csi_plugins = cur
        return True

    def _fingerprint_loop(self) -> None:
        """Periodic re-fingerprint (reference fingerprint.go:31-48 —
        periodic fingerprinters push node updates): drivers can appear
        (dockerd started after the agent) or die; dynamic host attributes
        (free disk) drift. On change, re-register so the schedulers see
        the new truth."""
        while not self._shutdown.is_set():
            self._shutdown.wait(self.fingerprint_interval_s)
            if self._shutdown.is_set():
                return
            changed = self._fingerprint_drivers()
            changed = self._fingerprint_devices() or changed
            changed = self._fingerprint_csi() or changed
            dyn = dynamic_attributes(self.data_dir)
            for k, v in dyn.items():
                if self.node.attributes.get(k) != v:
                    self.node.attributes[k] = v
                    changed = True
            # a periodic attribute that STOPPED being reported must be
            # dropped (e.g. cgroups unmounted) — but only after TWO
            # consecutive misses, so a transient sample failure doesn't
            # strip attributes and churn re-registration cluster-wide
            misses = getattr(self, "_dyn_miss_counts", {})
            known = getattr(self, "_dyn_known_keys", set()) | set(dyn)
            for k in list(known):
                if k in dyn:
                    misses.pop(k, None)
                    continue
                misses[k] = misses.get(k, 0) + 1
                if misses[k] >= 2:
                    known.discard(k)
                    misses.pop(k, None)
                    if self.node.attributes.pop(k, None) is not None:
                        changed = True
            self._dyn_miss_counts = misses
            self._dyn_known_keys = known
            if not changed or not self._registered.is_set():
                continue
            from ..structs.node_class import compute_node_class

            self.node.computed_class = compute_node_class(self.node)
            try:
                self.rpc.register(self.node)
            except Exception:
                logger.exception("node update after re-fingerprint failed")

    def _heartbeat_loop(self) -> None:
        while not self._shutdown.is_set() and not self._registered.is_set():
            try:
                self.heartbeat_ttl = self.rpc.register(self.node)
                # Fingerprinting is already done, so promote to ready NOW
                # (reference: updateNodeStatus(ready) right after the
                # batched fingerprint completes) instead of letting the
                # node sit `initializing` until the first TTL/2 beat.
                self.heartbeat_ttl = self.rpc.heartbeat(self.node.id)
                self._registered.set()
            except Exception as e:
                # Honor the node door's Retry-After pacing (429-class,
                # server/cluster.py node_limiter): during a reconnect
                # storm the server admits at a fixed rate and each
                # rejected client backs off exactly as told — with
                # jitter, so a cohort throttled together doesn't return
                # together.
                import random

                from ..ratelimit import retry_after_from_text

                hint = retry_after_from_text(str(e))
                if hint:
                    delay = hint + random.uniform(0, hint / 2)
                    logger.debug(
                        "registration throttled; retrying in %.2fs", delay
                    )
                else:
                    delay = 0.2
                    logger.debug("registration failed; retrying")
                self._shutdown.wait(delay)
        while not self._shutdown.is_set():
            # heartbeat at half the granted TTL (reference client.go:1606)
            self._shutdown.wait(max(self.heartbeat_ttl / 2, 0.5))
            if self._shutdown.is_set():
                return
            try:
                self.heartbeat_ttl = self.rpc.heartbeat(self.node.id)
            except Exception:
                logger.exception("heartbeat failed")

    def _watch_allocs(self) -> None:
        """Blocking-query loop on our alloc set (reference :2003).

        The 10s hold matters at fleet scale: the server wakes this
        query through its per-node watch hub the moment OUR alloc set
        changes, so a long hold costs nothing in reaction latency and
        divides the idle re-poll RPC rate by ten versus the old 1s
        spin (10k clients at 1s = 10k RPCs/s of pure no-change churn)."""
        index = 0
        while not self._shutdown.is_set():
            try:
                allocs, index = self.rpc.get_client_allocs(
                    self.node.id, index + 1, timeout_s=10.0
                )
            except Exception:
                if self._shutdown.is_set():
                    return
                logger.exception("alloc watch failed")
                self._shutdown.wait(1)
                continue
            self._run_allocs(allocs)

    def _run_allocs(self, server_allocs: list[Allocation]) -> None:
        """Diff desired vs running (reference runAllocs :2233)."""
        desired = {a.id: a for a in server_allocs}
        with self._lock:
            existing = dict(self.alloc_runners)
        # removals (server GC'd the alloc entirely)
        for alloc_id, runner in existing.items():
            if alloc_id not in desired:
                runner.destroy()
                with self._lock:
                    self.alloc_runners.pop(alloc_id, None)
        for alloc_id, alloc in desired.items():
            runner = existing.get(alloc_id)
            if runner is None:
                if (
                    alloc.desired_status == ALLOC_DESIRED_STATUS_RUN
                    and not alloc.client_terminal_status()
                ):
                    self.state_db.put_alloc(alloc)
                    runner = AllocRunner(
                        alloc,
                        self.drivers,
                        self.data_dir,
                        self._alloc_updated,
                        node=self.node,
                        state_db=self.state_db,
                        client=self,
                    )
                    with self._lock:
                        self.alloc_runners[alloc_id] = runner
                    runner.run()
            else:
                if alloc.modify_index > runner.alloc.modify_index:
                    self.state_db.put_alloc(alloc)
                    runner.update(alloc)

    def _restore(self) -> None:
        """Recreate runners for persisted allocs, reattaching to live
        tasks (reference client.go restore → allocRunner.Restore)."""
        for alloc in self.state_db.get_allocs():
            if alloc.client_terminal_status():
                continue
            runner = AllocRunner(
                alloc,
                self.drivers,
                self.data_dir,
                self._alloc_updated,
                node=self.node,
                state_db=self.state_db,
                restore=True,
                client=self,
            )
            with self._lock:
                self.alloc_runners[alloc.id] = runner
            runner.run()
            logger.info("restored alloc %s", alloc.id[:8])

    def _alloc_updated(self, alloc: Allocation) -> None:
        """AllocRunner reported a state change; queue for batched sync."""
        with self._lock:
            stub = alloc.copy(keep_job=False)
            self._pending_updates[alloc.id] = stub

    def _alloc_sync(self) -> None:
        """Batched status push (reference allocSync :1936)."""
        while not self._shutdown.is_set():
            self._shutdown.wait(ALLOC_SYNC_INTERVAL_S)
            with self._lock:
                updates = list(self._pending_updates.values())
                self._pending_updates.clear()
            if not updates:
                continue
            try:
                self.rpc.update_allocs(updates)
            except Exception:
                logger.exception("alloc sync failed")
                with self._lock:
                    for u in updates:
                        self._pending_updates.setdefault(u.id, u)

    # -- introspection -------------------------------------------------

    def num_allocs(self) -> int:
        with self._lock:
            return len(self.alloc_runners)
