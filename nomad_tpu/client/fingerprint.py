"""Host fingerprinting: what does this machine offer?

Reference: client/fingerprint/fingerprint.go:31-48 — arch, cpu, memory,
storage, network, host, nomad-version fingerprinters, merged into the Node.
"""

from __future__ import annotations

import os
import platform
import shutil
import socket
import uuid

from ..structs import NetworkResource, Node, NodeResources
from ..structs.node_class import compute_node_class


def _total_memory_mb() -> int:
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemTotal:"):
                    return int(line.split()[1]) // 1024
    except OSError:
        pass
    return 1024


def _cpu_mhz_total() -> int:
    cores = os.cpu_count() or 1
    mhz = 2000.0
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.lower().startswith("cpu mhz"):
                    mhz = float(line.split(":")[1])
                    break
    except OSError:
        pass
    return int(cores * mhz)


def _cpu_model() -> str:
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.lower().startswith("model name"):
                    return line.split(":", 1)[1].strip()
    except OSError:
        pass
    return ""


def _default_ip() -> str:
    """The host's outbound IP (no packets are sent by a UDP connect)."""
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            s.connect(("8.8.8.8", 9))
            return s.getsockname()[0]
        finally:
            s.close()
    except OSError:
        return "127.0.0.1"


# Dynamic attributes drift at runtime; granularity keeps jitter (a few MB
# of disk churn) from re-registering the node every fingerprint period.
_STORAGE_GRANULARITY_MB = 1024


def dynamic_attributes(data_dir: str = "/tmp") -> dict[str, str]:
    """Attributes the periodic re-fingerprint refreshes (reference:
    client/fingerprint/storage.go is a periodic fingerprinter)."""
    try:
        disk = shutil.disk_usage(data_dir)
        free_mb = (disk.free // (1024 * 1024)) // _STORAGE_GRANULARITY_MB
        free_mb *= _STORAGE_GRANULARITY_MB
        total_mb = disk.total // (1024 * 1024)
    except OSError:
        return {}
    return {
        "unique.storage.bytesfree": str(free_mb * 1024 * 1024),
        "unique.storage.bytestotal": str(total_mb * 1024 * 1024),
    }


def fingerprint_node(
    node_id: str = "",
    datacenter: str = "dc1",
    node_class: str = "",
    data_dir: str = "/tmp",
) -> Node:
    cores = os.cpu_count() or 1
    disk = shutil.disk_usage(data_dir)
    node = Node(
        id=node_id or str(uuid.uuid4()),
        name=socket.gethostname(),
        datacenter=datacenter,
        node_class=node_class,
        attributes={
            "kernel.name": platform.system().lower(),
            "kernel.version": platform.release(),
            "arch": platform.machine(),
            "os.name": platform.system().lower(),
            "os.version": platform.version(),
            "cpu.numcores": str(cores),
            "cpu.totalcompute": str(_cpu_mhz_total()),
            "cpu.arch": platform.machine(),
            "cpu.modelname": _cpu_model(),
            "memory.totalbytes": str(_total_memory_mb() * 1024 * 1024),
            "unique.hostname": socket.gethostname(),
            "unique.storage.volume": data_dir,
            "unique.network.ip-address": _default_ip(),
            "nomad.version": "0.1.0",
            **dynamic_attributes(data_dir),
        },
        resources=NodeResources(
            cpu=_cpu_mhz_total(),
            memory_mb=_total_memory_mb(),
            disk_mb=disk.free // (1024 * 1024),
            networks=[
                NetworkResource(
                    device="lo", cidr="127.0.0.1/32", ip="127.0.0.1", mbits=1000
                )
            ],
        ),
    )
    node.computed_class = compute_node_class(node)
    return node
