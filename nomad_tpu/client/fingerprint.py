"""Host fingerprinting: what does this machine offer?

Reference: client/fingerprint/fingerprint.go:31-48 — arch, cpu, memory,
storage, network, host, nomad-version fingerprinters, merged into the Node.
"""

from __future__ import annotations

import os
import platform
import shutil
import socket
import uuid

from ..structs import NetworkResource, Node, NodeResources
from ..structs.node_class import compute_node_class


def _total_memory_mb() -> int:
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemTotal:"):
                    return int(line.split()[1]) // 1024
    except OSError:
        pass
    return 1024


def _cpu_mhz_total() -> int:
    cores = os.cpu_count() or 1
    mhz = 2000.0
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.lower().startswith("cpu mhz"):
                    mhz = float(line.split(":")[1])
                    break
    except OSError:
        pass
    return int(cores * mhz)


def fingerprint_node(
    node_id: str = "",
    datacenter: str = "dc1",
    node_class: str = "",
    data_dir: str = "/tmp",
) -> Node:
    cores = os.cpu_count() or 1
    disk = shutil.disk_usage(data_dir)
    node = Node(
        id=node_id or str(uuid.uuid4()),
        name=socket.gethostname(),
        datacenter=datacenter,
        node_class=node_class,
        attributes={
            "kernel.name": platform.system().lower(),
            "kernel.version": platform.release(),
            "arch": platform.machine(),
            "os.name": platform.system().lower(),
            "cpu.numcores": str(cores),
            "cpu.totalcompute": str(_cpu_mhz_total()),
            "memory.totalbytes": str(_total_memory_mb() * 1024 * 1024),
            "unique.hostname": socket.gethostname(),
            "unique.storage.volume": data_dir,
            "nomad.version": "0.1.0",
        },
        resources=NodeResources(
            cpu=_cpu_mhz_total(),
            memory_mb=_total_memory_mb(),
            disk_mb=disk.free // (1024 * 1024),
            networks=[
                NetworkResource(
                    device="lo", cidr="127.0.0.1/32", ip="127.0.0.1", mbits=1000
                )
            ],
        ),
    )
    node.computed_class = compute_node_class(node)
    return node
