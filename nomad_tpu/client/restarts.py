"""Restart tracker: local restart policy decisions.

Reference: client/allocrunner/taskrunner/restarts — given a task exit and
the group's RestartPolicy, decide restart (after delay), or fail the task.
"""

from __future__ import annotations

import time

from ..structs import RestartPolicy

DECISION_RESTART = "restart"
DECISION_FAIL = "fail"


class RestartTracker:
    def __init__(self, policy: RestartPolicy) -> None:
        self.policy = policy
        self.attempts: list[float] = []  # wall-clock restart times

    def next_restart(self, exit_success: bool, batch: bool) -> tuple[str, float]:
        """(decision, delay_s) for a task exit.

        Service tasks restart on any exit; batch tasks only restart failures
        (reference: restarts.go handleWaitResult).
        """
        if exit_success and batch:
            return DECISION_FAIL, 0.0  # batch success = done, no restart
        now = time.monotonic()
        window_start = now - self.policy.interval_s
        self.attempts = [t for t in self.attempts if t > window_start]
        if len(self.attempts) >= self.policy.attempts:
            if self.policy.mode == "delay":
                # wait out the window, then restart
                delay = self.attempts[0] + self.policy.interval_s - now
                return DECISION_RESTART, max(delay, self.policy.delay_s)
            return DECISION_FAIL, 0.0
        self.attempts.append(now)
        return DECISION_RESTART, self.policy.delay_s
