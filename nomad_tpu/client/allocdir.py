"""Per-allocation directory tree.

Reference: client/allocdir/ (~1,500 LoC) — the shared alloc dir
(SharedAllocDir: alloc/data, alloc/logs, alloc/tmp) plus per-task dirs
(TaskDir: local, secrets, tmp, private), and the chroot builder the
exec driver uses (fs_linux.go: the configured chroot_env map is
materialized into the task dir, which then becomes the task's root).
Hard links are used where the filesystem allows (free), falling back
to copies — same economics as the reference's link-or-copy walk.
"""

from __future__ import annotations

import os
import shutil
import stat


SHARED_ALLOC_NAME = "alloc"


class EscapeError(Exception):
    """A job-controlled path tried to escape its sandbox."""


def alloc_sandbox(task_dir: str) -> str:
    """The confinement root for a task's job-controlled paths: the alloc
    dir (its task dirs and the shared alloc/ dir all live under it)."""
    return os.path.dirname(os.path.realpath(task_dir))


def confine(base_dir: str, path: str) -> str:
    """Resolve `path` and require it to stay inside `base_dir`.

    Job-controlled paths (template dest/source, artifact dests) must not
    reach outside the alloc dir — the reference sandboxes the same way
    (go-getter dest + consul-template path escapes were upstream CVEs).
    Symlinks are resolved before the containment check.
    """
    base = os.path.realpath(base_dir)
    resolved = os.path.realpath(
        path if os.path.isabs(path) else os.path.join(base, path)
    )
    if resolved != base and not resolved.startswith(base + os.sep):
        raise EscapeError(f"path {path!r} escapes alloc dir {base_dir!r}")
    return resolved


def build_chroot(chroot_dir: str, chroot_env: dict[str, str]) -> None:
    """Materialize ``{host_src: dst_in_chroot}`` under chroot_dir
    (reference client/allocdir/fs_linux.go buildChroot). Missing
    sources are skipped like the reference (the default map names
    paths not every distro has)."""

    def place(src: str, dst: str) -> None:
        if os.path.islink(src):
            target = os.readlink(src)
            os.makedirs(os.path.dirname(dst), exist_ok=True)
            if not os.path.lexists(dst):
                os.symlink(target, dst)
            return
        if os.path.isdir(src):
            try:
                entries = os.listdir(src)
            except OSError:
                return
            os.makedirs(dst, exist_ok=True)
            for name in entries:
                place(os.path.join(src, name), os.path.join(dst, name))
            return
        os.makedirs(os.path.dirname(dst), exist_ok=True)
        if os.path.lexists(dst):
            return
        try:
            os.link(src, dst)  # free when same filesystem
        except OSError:
            try:
                shutil.copy2(src, dst)
            except OSError:
                pass  # unreadable/special file: skip, like the reference

    os.makedirs(chroot_dir, exist_ok=True)
    for src, dst in chroot_env.items():
        if not os.path.lexists(src):
            continue
        # dst is JOB-controlled: a traversal like ../../etc/x would make
        # this root-privileged walk write onto the host — confine it
        target = confine(chroot_dir, dst.lstrip("/"))
        place(src, target)


class AllocDir:
    def __init__(self, base_dir: str, alloc_id: str) -> None:
        self.alloc_dir = os.path.join(base_dir, "allocs", alloc_id)
        self.shared_dir = os.path.join(self.alloc_dir, SHARED_ALLOC_NAME)

    # shared paths
    @property
    def logs_dir(self) -> str:
        return os.path.join(self.shared_dir, "logs")

    @property
    def data_dir(self) -> str:
        return os.path.join(self.shared_dir, "data")

    @property
    def tmp_dir(self) -> str:
        return os.path.join(self.shared_dir, "tmp")

    def build(self) -> None:
        for d in (self.logs_dir, self.data_dir, self.tmp_dir):
            os.makedirs(d, exist_ok=True)

    def task_dir(self, task_name: str) -> "TaskDir":
        return TaskDir(self.alloc_dir, task_name)

    def build_task_dir(self, task_name: str) -> "TaskDir":
        td = self.task_dir(task_name)
        td.build()
        return td

    def destroy(self) -> None:
        shutil.rmtree(self.alloc_dir, ignore_errors=True)

    def stdout_path(self, task_name: str) -> str:
        return os.path.join(self.logs_dir, f"{task_name}.stdout.0")

    def stderr_path(self, task_name: str) -> str:
        return os.path.join(self.logs_dir, f"{task_name}.stderr.0")


class TaskDir:
    def __init__(self, alloc_dir: str, task_name: str) -> None:
        self.dir = os.path.join(alloc_dir, task_name)
        self.local_dir = os.path.join(self.dir, "local")
        self.secrets_dir = os.path.join(self.dir, "secrets")
        self.tmp_dir = os.path.join(self.dir, "tmp")

    def build(self) -> None:
        os.makedirs(self.local_dir, exist_ok=True)
        os.makedirs(self.tmp_dir, exist_ok=True)
        os.makedirs(self.secrets_dir, exist_ok=True)
        # secrets are owner-only (reference: tmpfs mount 0700 when root)
        os.chmod(self.secrets_dir, stat.S_IRWXU)
