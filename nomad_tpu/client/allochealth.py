"""Alloc deployment-health tracker.

Reference: client/allochealth/tracker.go — watches an alloc that belongs to
a deployment (or is being drain-migrated) and reports healthy once every
task has been running for min_healthy_time, or unhealthy on task failure /
healthy_deadline expiry. The alloc runner forwards the verdict to the
server through the normal alloc-sync path, where the deployment watcher
consumes it.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

from ..structs import Allocation
from ..structs.structs import AllocDeploymentStatus, now_ns


class HealthTracker:
    def __init__(
        self,
        alloc: Allocation,
        task_states_fn: Callable[[], dict],
        on_healthy: Callable[[bool], None],
        poll_interval_s: float = 0.05,
    ) -> None:
        self.alloc = alloc
        self.task_states_fn = task_states_fn
        self.on_healthy = on_healthy
        self.poll_interval_s = poll_interval_s
        self._stop = threading.Event()
        self._verdict_lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None

        tg = alloc.job.lookup_task_group(alloc.task_group) if alloc.job else None
        update = tg.update if tg else None
        self.min_healthy_s = update.min_healthy_time_s if update else 10.0
        self.deadline_s = update.healthy_deadline_s if update else 300.0

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._run, daemon=True, name=f"health-{self.alloc.id[:8]}"
        )
        self._thread.start()

    def stop(self) -> None:
        # Taking the verdict lock means stop() can't land between the
        # tracker's last poll and its callback: after stop returns, no
        # healthy-verdict for a being-killed alloc can be delivered.
        with self._verdict_lock:
            self._stop.set()

    def _deliver(self, healthy: bool) -> None:
        with self._verdict_lock:
            if self._stop.is_set():
                return
            self.on_healthy(healthy)

    def _run(self) -> None:
        deadline = time.monotonic() + self.deadline_s
        healthy_since: Optional[float] = None
        while not self._stop.wait(self.poll_interval_s):
            states = self.task_states_fn()
            if not states:
                continue
            if any(s.failed for s in states.values()):
                self._deliver(False)
                return
            now = time.monotonic()
            # batch-style tasks that ran to successful completion count as
            # healthy; otherwise every task must be running
            ok = all(
                s.state == "running" or s.successful() for s in states.values()
            )
            if ok:
                if healthy_since is None:
                    healthy_since = now
                if now - healthy_since >= self.min_healthy_s:
                    self._deliver(True)
                    return
            else:
                healthy_since = None
            if now > deadline:
                self._deliver(False)
                return


def new_deployment_status(healthy: bool) -> AllocDeploymentStatus:
    return AllocDeploymentStatus(healthy=healthy, timestamp_ns=now_ns())
