"""Client-agent RPC surface: streaming fs/logs/exec.

Reference: the client half of the 4-boundary streaming path (SURVEY
§3.5) — client/fs_endpoint.go (Logs/Stream/List/Stat), client
/alloc_endpoint.go (exec → driver ExecTaskStreaming). The reference
reverse-dials over pooled yamux sessions (nomad/client_rpc.go); here the
client agent runs a small listener on the shared fabric and advertises
its address as the node attribute `unique.client.rpc` — servers dial it
directly to splice streams through to API consumers.

Stream wire format (msgpack frames over a fabric StreamSession):
  {"data": bytes}            — payload chunk (fs/logs: file bytes;
                                exec: process output)
  {"stdin": bytes}           — exec input (consumer → client)
  {"eof": True}              — end of stream
  {"error": str}             — terminal failure
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Optional

from ..rpc.server import RPCServer, StreamSession

logger = logging.getLogger("nomad_tpu.client.endpoints")

CHUNK = 64 * 1024


class ClientEndpoints:
    """Owns the client agent's listener and its stream handlers."""

    def __init__(self, client, host: str = "127.0.0.1", secret="",
                 tls_context=None) -> None:
        self.client = client
        self.rpc = RPCServer(
            host=host, port=0, secret=secret, tls_context=tls_context
        )
        self.rpc.register_stream("FS.logs", self._fs_logs)
        self.rpc.register_stream("FS.ls", self._fs_ls)
        self.rpc.register_stream("FS.cat", self._fs_cat)
        self.rpc.register_stream("FS.stat", self._fs_stat)
        self.rpc.register_stream("Exec.exec", self._exec)
        self.rpc.register_stream("Alloc.restart", self._alloc_restart)
        self.rpc.register_stream("Alloc.signal", self._alloc_signal)
        self.rpc.register_stream("Alloc.stats", self._alloc_stats)
        self.rpc.register_stream("CSI.create", self._csi_create)
        self.rpc.register_stream("CSI.delete", self._csi_delete)
        self.rpc.register_stream(
            "CSI.create_snapshot", self._csi_create_snapshot
        )
        self.rpc.register_stream(
            "CSI.delete_snapshot", self._csi_delete_snapshot
        )
        self.rpc.register_stream(
            "CSI.list_snapshots", self._csi_list_snapshots
        )
        self.rpc.register_stream(
            "CSI.controller_unpublish", self._csi_controller_unpublish
        )

    @property
    def addr(self) -> tuple[str, int]:
        return self.rpc.addr

    def start(self) -> None:
        self.rpc.start()

    def stop(self) -> None:
        self.rpc.shutdown()

    # -- CSI controller relay (reference client/csi_endpoint.go: the
    # server routes controller RPCs to a node running the plugin) ------

    def _csi_plugin(self, session, header):
        plugin = self.client.csi_manager.plugins.get(
            header.get("plugin_id", "")
        )
        if plugin is None:
            session.send({
                "error": f"plugin {header.get('plugin_id')!r} not on "
                f"this client"
            })
            return None
        return plugin

    def _csi_create(self, session, header) -> None:
        plugin = self._csi_plugin(session, header)
        if plugin is None:
            return
        try:
            out = plugin.create_volume(
                header.get("name", ""), header.get("params") or {}
            )
            session.send({"ok": True, **out})
        except Exception as e:
            session.send({"error": f"{type(e).__name__}: {e}"})

    def _csi_delete(self, session, header) -> None:
        plugin = self._csi_plugin(session, header)
        if plugin is None:
            return
        try:
            plugin.delete_volume(header.get("external_id", ""))
            session.send({"ok": True})
        except Exception as e:
            session.send({"error": f"{type(e).__name__}: {e}"})

    def _csi_controller_unpublish(self, session, header) -> None:
        plugin = self._csi_plugin(session, header)
        if plugin is None:
            return
        try:
            plugin.controller_unpublish(
                header.get("volume_id", ""),
                header.get("external_id", ""),
                header.get("node_id", ""),
            )
            session.send({"ok": True})
        except Exception as e:
            session.send({"error": f"{type(e).__name__}: {e}"})

    def _csi_create_snapshot(self, session, header) -> None:
        plugin = self._csi_plugin(session, header)
        if plugin is None:
            return
        try:
            out = plugin.create_snapshot(
                header.get("external_id", ""),
                header.get("name", ""),
                header.get("params") or {},
            )
            session.send({"ok": True, **out})
        except Exception as e:
            session.send({"error": f"{type(e).__name__}: {e}"})

    def _csi_delete_snapshot(self, session, header) -> None:
        plugin = self._csi_plugin(session, header)
        if plugin is None:
            return
        try:
            plugin.delete_snapshot(header.get("snapshot_id", ""))
            session.send({"ok": True})
        except Exception as e:
            session.send({"error": f"{type(e).__name__}: {e}"})

    def _csi_list_snapshots(self, session, header) -> None:
        plugin = self._csi_plugin(session, header)
        if plugin is None:
            return
        try:
            session.send(
                {"ok": True, "snapshots": plugin.list_snapshots()}
            )
        except Exception as e:
            session.send({"error": f"{type(e).__name__}: {e}"})

    # -- alloc lifecycle (reference client/alloc_endpoint.go) -----------

    def _alloc_lifecycle(self, session, header, verb) -> None:
        runner = self.client.alloc_runners.get(header.get("alloc_id", ""))
        if runner is None:
            session.send({"error": "alloc not running on this client"})
            return
        try:
            verb(runner)
            session.send({"ok": True})
        except KeyError as e:
            session.send({"error": str(e)})
        except Exception as e:
            session.send({"error": f"{type(e).__name__}: {e}"})

    def _alloc_restart(self, session, header) -> None:
        self._alloc_lifecycle(
            session, header,
            lambda r: r.restart(header.get("task", "")),
        )

    def _alloc_signal(self, session, header) -> None:
        self._alloc_lifecycle(
            session, header,
            lambda r: r.signal(
                header.get("signal", "SIGTERM"), header.get("task", "")
            ),
        )

    def _alloc_stats(self, session, header) -> None:
        """Resource usage for one alloc: per-task driver stats plus the
        alloc's reserved device instances' stats (reference:
        GET /v1/client/allocation/:id/stats → AllocResourceUsage; the
        nvidia plugin's Stats stream feeds the DeviceStats section)."""
        runner = self.client.alloc_runners.get(header.get("alloc_id", ""))
        if runner is None:
            session.send({"error": "alloc not running on this client"})
            return
        tasks: dict = {}
        for name, tr in runner.task_runners.items():
            try:
                tasks[name] = tr.driver.task_stats(tr.task_id) or {}
            except Exception:
                tasks[name] = {}
        # device stats, filtered to the instances this alloc holds
        assigned: set[str] = set()
        res = runner.alloc.resources
        if res is not None:
            for tr_res in res.tasks.values():
                for dev in tr_res.devices or []:
                    assigned.update(dev.get("device_ids", []))
        devices: dict = {}
        if assigned:
            for plugin, insts in self.client.device_manager.stats().items():
                mine = {
                    iid: s for iid, s in insts.items() if iid in assigned
                }
                if mine:
                    devices[plugin] = mine
        session.send({"tasks": tasks, "devices": devices})

    # -- helpers --------------------------------------------------------

    def _alloc_dir(self, alloc_id: str):
        runner = self.client.alloc_runners.get(alloc_id)
        if runner is None:
            return None
        return runner.allocdir

    def _resolve(self, alloc_dir, rel_path: str) -> str:
        """Confine a user path to the alloc dir (same rule as templates)."""
        from .allocdir import confine

        return confine(alloc_dir.alloc_dir, rel_path or ".")

    # -- fs -------------------------------------------------------------

    def _fs_logs(self, session: StreamSession, header: dict) -> None:
        """Stream a task's stdout/stderr log, optionally following
        (reference client/fs_endpoint.go Logs)."""
        try:
            alloc_id = header.get("alloc_id", "")
            adir = self._alloc_dir(alloc_id)
            runner = self.client.alloc_runners.get(alloc_id)
            if adir is None or runner is None:
                session.send({"error": "unknown allocation"})
                return
            task = header.get("task", "")
            # The task name is caller-controlled: a path-shaped value
            # would escape the alloc dir through stdout_path's join.
            if task not in runner.task_runners:
                session.send({"error": f"unknown task {task!r}"})
                return
            log_type = header.get("type", "stdout")
            if log_type not in ("stdout", "stderr"):
                session.send({"error": f"bad log type {log_type!r}"})
                return
            path = (
                adir.stdout_path(task)
                if log_type == "stdout"
                else adir.stderr_path(task)
            )
            follow = bool(header.get("follow"))
            offset = int(header.get("offset", 0))
            origin = header.get("origin", "start")
            try:
                f = open(path, "rb")
            except OSError as e:
                session.send({"error": f"open log: {e}"})
                return
            with f:
                if origin == "end":
                    f.seek(0, os.SEEK_END)
                    size = f.tell()
                    f.seek(max(0, size - offset))
                elif offset:
                    f.seek(offset)
                idle = 0.0
                while True:
                    chunk = f.read(CHUNK)
                    if chunk:
                        idle = 0.0
                        session.send({"data": chunk})
                        continue
                    if not follow:
                        session.send({"eof": True})
                        return
                    # follow: wait for growth; detect copy-truncate
                    # rotation (logmon) by the file shrinking under us
                    time.sleep(0.2)
                    idle += 0.2
                    try:
                        size = os.path.getsize(path)
                    except OSError:
                        size = 0
                    if size < f.tell():
                        f.seek(0)
                    if idle > 5.0:
                        # heartbeat keeps half-open connections detected
                        session.send({"data": b""})
                        idle = 0.0
        except (ConnectionError, OSError):
            pass
        finally:
            session.close()

    def _fs_ls(self, session: StreamSession, header: dict) -> None:
        from .allocdir import EscapeError

        try:
            adir = self._alloc_dir(header.get("alloc_id", ""))
            if adir is None:
                session.send({"error": "unknown allocation"})
                return
            try:
                path = self._resolve(adir, header.get("path", ""))
            except EscapeError as e:
                session.send({"error": str(e)})
                return
            entries = []
            try:
                for name in sorted(os.listdir(path)):
                    full = os.path.join(path, name)
                    st = os.stat(full)
                    entries.append(
                        {
                            "name": name,
                            "is_dir": os.path.isdir(full),
                            "size": st.st_size,
                            "mtime_ns": st.st_mtime_ns,
                        }
                    )
            except OSError as e:
                session.send({"error": f"ls: {e}"})
                return
            session.send({"entries": entries, "eof": True})
        except (ConnectionError, OSError):
            pass
        finally:
            session.close()

    def _fs_stat(self, session: StreamSession, header: dict) -> None:
        from .allocdir import EscapeError

        try:
            adir = self._alloc_dir(header.get("alloc_id", ""))
            if adir is None:
                session.send({"error": "unknown allocation"})
                return
            try:
                path = self._resolve(adir, header.get("path", ""))
                st = os.stat(path)
            except (EscapeError, OSError) as e:
                session.send({"error": str(e)})
                return
            session.send(
                {
                    "stat": {
                        "name": os.path.basename(path),
                        "is_dir": os.path.isdir(path),
                        "size": st.st_size,
                        "mtime_ns": st.st_mtime_ns,
                    },
                    "eof": True,
                }
            )
        except (ConnectionError, OSError):
            pass
        finally:
            session.close()

    def _fs_cat(self, session: StreamSession, header: dict) -> None:
        from .allocdir import EscapeError

        try:
            adir = self._alloc_dir(header.get("alloc_id", ""))
            if adir is None:
                session.send({"error": "unknown allocation"})
                return
            try:
                path = self._resolve(adir, header.get("path", ""))
                f = open(path, "rb")
            except (EscapeError, OSError) as e:
                session.send({"error": str(e)})
                return
            with f:
                while True:
                    chunk = f.read(CHUNK)
                    if not chunk:
                        break
                    session.send({"data": chunk})
            session.send({"eof": True})
        except (ConnectionError, OSError):
            pass
        finally:
            session.close()

    # -- exec -----------------------------------------------------------

    def _exec(self, session: StreamSession, header: dict) -> None:
        """Interactive exec into a running task: splice the fabric
        session onto the driver's exec socket (reference
        client/alloc_endpoint.go exec → ExecTaskStreaming)."""
        sock = None
        try:
            alloc_id = header.get("alloc_id", "")
            task_name = header.get("task", "")
            cmd = list(header.get("cmd") or [])
            runner = self.client.alloc_runners.get(alloc_id)
            if runner is None:
                session.send({"error": "unknown allocation"})
                return
            tr = runner.task_runners.get(task_name)
            if tr is None:
                names = list(runner.task_runners)
                if len(names) == 1 and not task_name:
                    tr = runner.task_runners[names[0]]
                else:
                    session.send({"error": f"unknown task {task_name!r}"})
                    return
            if not cmd:
                session.send({"error": "exec needs a command"})
                return
            try:
                sock = tr.driver.exec_task_streaming(
                    tr.task_id, cmd, tty=bool(header.get("tty"))
                )
            except Exception as e:
                session.send({"error": f"exec: {e}"})
                return
            session.send({"ok": True})
            done = threading.Event()

            def pump_out() -> None:
                try:
                    while True:
                        data = sock.recv(CHUNK)
                        if not data:
                            break
                        session.send({"data": data})
                    session.send({"eof": True})
                except (ConnectionError, OSError):
                    pass
                finally:
                    done.set()

            t = threading.Thread(
                target=pump_out, name="alloc-exec-out", daemon=True
            )
            t.start()
            while not done.is_set():
                try:
                    msg = session.recv(timeout_s=0.5)
                except TimeoutError:
                    continue
                except (ConnectionError, OSError):
                    break
                if msg is None or msg.get("eof"):
                    try:
                        sock.shutdown(2)
                    except OSError:
                        pass
                    break
                stdin = msg.get("stdin")
                if stdin:
                    try:
                        sock.sendall(stdin)
                    except OSError:
                        break
            done.wait(timeout=5)
        except (ConnectionError, OSError):
            pass
        finally:
            if sock is not None:
                sock.close()
            session.close()


class ReverseDialer:
    """Reverse-dial fallback for NAT'd clients (reference
    nomad/client_rpc.go: servers open streams over yamux sessions the
    CLIENT established).

    Keeps `idle_target` connections parked on a server's fabric: each
    registers with our node id, then blocks waiting for the server to
    send a stream request header. On receipt the request is dispatched to
    the SAME handlers the forward-dial listener uses, then the connection
    is consumed and a fresh one parked in its place.
    """

    def __init__(
        self,
        client,
        endpoints: ClientEndpoints,
        addrs_fn,  # () -> list[(host, port)] of server fabric addrs
        idle_target: int = 2,
        secret="",  # str | rpc.keyring.Keyring
        retry_s: float = 2.0,
        tls_context=None,
    ) -> None:
        from ..rpc import ConnPool

        self.client = client
        self.endpoints = endpoints
        self.addrs_fn = addrs_fn
        self.idle_target = idle_target
        self.retry_s = retry_s
        self.pool = ConnPool(secret=secret, tls_context=tls_context)
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self._lock = threading.Lock()
        self._parked: list[StreamSession] = []

    def start(self) -> None:
        self._stop = threading.Event()
        # One parker per known server (at least idle_target threads):
        # the relay only finds sessions parked on the SERVER IT RUNS ON,
        # so every server needs coverage, not just addrs[0].
        n = max(self.idle_target, len(self.addrs_fn() or []))
        for i in range(n):
            t = threading.Thread(
                target=self._run, args=(self._stop, i), daemon=True,
                name=f"reverse-dial-{i}",
            )
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        self._stop.set()
        with self._lock:
            parked = list(self._parked)
            self._parked.clear()
        for s in parked:
            s.close()  # unblocks the recv below

    def _run(self, stop: threading.Event, base: int = 0) -> None:
        rotate = 0
        while not stop.is_set():
            addrs = list(self.addrs_fn() or [])
            if not addrs:
                stop.wait(self.retry_s)
                continue
            # thread i pins to server i (mod n); rotate only on failure
            addr = tuple(addrs[(base + rotate) % len(addrs)])
            try:
                session = self.pool.stream(
                    addr,
                    "ClientReverse.register",
                    {"node_id": self.client.node.id},
                )
            except Exception:
                rotate += 1
                stop.wait(self.retry_s)
                continue
            with self._lock:
                self._parked.append(session)
            try:
                req = session.recv(timeout_s=None)  # park until needed
            except Exception:
                with self._lock:
                    if session in self._parked:
                        self._parked.remove(session)
                session.close()
                stop.wait(self.retry_s if not stop.is_set() else 0)
                continue
            with self._lock:
                if session in self._parked:
                    self._parked.remove(session)
            if stop.is_set():
                session.close()
                return
            method = (req or {}).get("method", "")
            handler = self.endpoints.rpc._stream_handlers.get(method)
            if handler is None:
                try:
                    session.send({"error": f"unknown stream method {method!r}"})
                finally:
                    session.close()
                continue
            try:
                session.send({"ok": True})
                handler(session, req)
            except Exception:
                logger.exception("reverse stream %s failed", method)
            finally:
                session.close()
