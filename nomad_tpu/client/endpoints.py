"""Client-agent RPC surface: streaming fs/logs/exec.

Reference: the client half of the 4-boundary streaming path (SURVEY
§3.5) — client/fs_endpoint.go (Logs/Stream/List/Stat), client
/alloc_endpoint.go (exec → driver ExecTaskStreaming). The reference
reverse-dials over pooled yamux sessions (nomad/client_rpc.go); here the
client agent runs a small listener on the shared fabric and advertises
its address as the node attribute `unique.client.rpc` — servers dial it
directly to splice streams through to API consumers.

Stream wire format (msgpack frames over a fabric StreamSession):
  {"data": bytes}            — payload chunk (fs/logs: file bytes;
                                exec: process output)
  {"stdin": bytes}           — exec input (consumer → client)
  {"eof": True}              — end of stream
  {"error": str}             — terminal failure
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Optional

from ..rpc.server import RPCServer, StreamSession

logger = logging.getLogger("nomad_tpu.client.endpoints")

CHUNK = 64 * 1024


class ClientEndpoints:
    """Owns the client agent's listener and its stream handlers."""

    def __init__(self, client, host: str = "127.0.0.1", secret: str = "") -> None:
        self.client = client
        self.rpc = RPCServer(host=host, port=0, secret=secret)
        self.rpc.register_stream("FS.logs", self._fs_logs)
        self.rpc.register_stream("FS.ls", self._fs_ls)
        self.rpc.register_stream("FS.cat", self._fs_cat)
        self.rpc.register_stream("FS.stat", self._fs_stat)
        self.rpc.register_stream("Exec.exec", self._exec)

    @property
    def addr(self) -> tuple[str, int]:
        return self.rpc.addr

    def start(self) -> None:
        self.rpc.start()

    def stop(self) -> None:
        self.rpc.shutdown()

    # -- helpers --------------------------------------------------------

    def _alloc_dir(self, alloc_id: str):
        runner = self.client.alloc_runners.get(alloc_id)
        if runner is None:
            return None
        return runner.allocdir

    def _resolve(self, alloc_dir, rel_path: str) -> str:
        """Confine a user path to the alloc dir (same rule as templates)."""
        from .allocdir import confine

        return confine(alloc_dir.alloc_dir, rel_path or ".")

    # -- fs -------------------------------------------------------------

    def _fs_logs(self, session: StreamSession, header: dict) -> None:
        """Stream a task's stdout/stderr log, optionally following
        (reference client/fs_endpoint.go Logs)."""
        try:
            alloc_id = header.get("alloc_id", "")
            adir = self._alloc_dir(alloc_id)
            runner = self.client.alloc_runners.get(alloc_id)
            if adir is None or runner is None:
                session.send({"error": "unknown allocation"})
                return
            task = header.get("task", "")
            # The task name is caller-controlled: a path-shaped value
            # would escape the alloc dir through stdout_path's join.
            if task not in runner.task_runners:
                session.send({"error": f"unknown task {task!r}"})
                return
            log_type = header.get("type", "stdout")
            if log_type not in ("stdout", "stderr"):
                session.send({"error": f"bad log type {log_type!r}"})
                return
            path = (
                adir.stdout_path(task)
                if log_type == "stdout"
                else adir.stderr_path(task)
            )
            follow = bool(header.get("follow"))
            offset = int(header.get("offset", 0))
            origin = header.get("origin", "start")
            try:
                f = open(path, "rb")
            except OSError as e:
                session.send({"error": f"open log: {e}"})
                return
            with f:
                if origin == "end":
                    f.seek(0, os.SEEK_END)
                    size = f.tell()
                    f.seek(max(0, size - offset))
                elif offset:
                    f.seek(offset)
                idle = 0.0
                while True:
                    chunk = f.read(CHUNK)
                    if chunk:
                        idle = 0.0
                        session.send({"data": chunk})
                        continue
                    if not follow:
                        session.send({"eof": True})
                        return
                    # follow: wait for growth; detect copy-truncate
                    # rotation (logmon) by the file shrinking under us
                    time.sleep(0.2)
                    idle += 0.2
                    try:
                        size = os.path.getsize(path)
                    except OSError:
                        size = 0
                    if size < f.tell():
                        f.seek(0)
                    if idle > 5.0:
                        # heartbeat keeps half-open connections detected
                        session.send({"data": b""})
                        idle = 0.0
        except (ConnectionError, OSError):
            pass
        finally:
            session.close()

    def _fs_ls(self, session: StreamSession, header: dict) -> None:
        from .allocdir import EscapeError

        try:
            adir = self._alloc_dir(header.get("alloc_id", ""))
            if adir is None:
                session.send({"error": "unknown allocation"})
                return
            try:
                path = self._resolve(adir, header.get("path", ""))
            except EscapeError as e:
                session.send({"error": str(e)})
                return
            entries = []
            try:
                for name in sorted(os.listdir(path)):
                    full = os.path.join(path, name)
                    st = os.stat(full)
                    entries.append(
                        {
                            "name": name,
                            "is_dir": os.path.isdir(full),
                            "size": st.st_size,
                            "mtime_ns": st.st_mtime_ns,
                        }
                    )
            except OSError as e:
                session.send({"error": f"ls: {e}"})
                return
            session.send({"entries": entries, "eof": True})
        except (ConnectionError, OSError):
            pass
        finally:
            session.close()

    def _fs_stat(self, session: StreamSession, header: dict) -> None:
        from .allocdir import EscapeError

        try:
            adir = self._alloc_dir(header.get("alloc_id", ""))
            if adir is None:
                session.send({"error": "unknown allocation"})
                return
            try:
                path = self._resolve(adir, header.get("path", ""))
                st = os.stat(path)
            except (EscapeError, OSError) as e:
                session.send({"error": str(e)})
                return
            session.send(
                {
                    "stat": {
                        "name": os.path.basename(path),
                        "is_dir": os.path.isdir(path),
                        "size": st.st_size,
                        "mtime_ns": st.st_mtime_ns,
                    },
                    "eof": True,
                }
            )
        except (ConnectionError, OSError):
            pass
        finally:
            session.close()

    def _fs_cat(self, session: StreamSession, header: dict) -> None:
        from .allocdir import EscapeError

        try:
            adir = self._alloc_dir(header.get("alloc_id", ""))
            if adir is None:
                session.send({"error": "unknown allocation"})
                return
            try:
                path = self._resolve(adir, header.get("path", ""))
                f = open(path, "rb")
            except (EscapeError, OSError) as e:
                session.send({"error": str(e)})
                return
            with f:
                while True:
                    chunk = f.read(CHUNK)
                    if not chunk:
                        break
                    session.send({"data": chunk})
            session.send({"eof": True})
        except (ConnectionError, OSError):
            pass
        finally:
            session.close()

    # -- exec -----------------------------------------------------------

    def _exec(self, session: StreamSession, header: dict) -> None:
        """Interactive exec into a running task: splice the fabric
        session onto the driver's exec socket (reference
        client/alloc_endpoint.go exec → ExecTaskStreaming)."""
        sock = None
        try:
            alloc_id = header.get("alloc_id", "")
            task_name = header.get("task", "")
            cmd = list(header.get("cmd") or [])
            runner = self.client.alloc_runners.get(alloc_id)
            if runner is None:
                session.send({"error": "unknown allocation"})
                return
            tr = runner.task_runners.get(task_name)
            if tr is None:
                names = list(runner.task_runners)
                if len(names) == 1 and not task_name:
                    tr = runner.task_runners[names[0]]
                else:
                    session.send({"error": f"unknown task {task_name!r}"})
                    return
            if not cmd:
                session.send({"error": "exec needs a command"})
                return
            try:
                sock = tr.driver.exec_task_streaming(
                    tr.task_id, cmd, tty=bool(header.get("tty"))
                )
            except Exception as e:
                session.send({"error": f"exec: {e}"})
                return
            session.send({"ok": True})
            done = threading.Event()

            def pump_out() -> None:
                try:
                    while True:
                        data = sock.recv(CHUNK)
                        if not data:
                            break
                        session.send({"data": data})
                    session.send({"eof": True})
                except (ConnectionError, OSError):
                    pass
                finally:
                    done.set()

            t = threading.Thread(target=pump_out, daemon=True)
            t.start()
            while not done.is_set():
                try:
                    msg = session.recv(timeout_s=0.5)
                except TimeoutError:
                    continue
                except (ConnectionError, OSError):
                    break
                if msg is None or msg.get("eof"):
                    try:
                        sock.shutdown(2)
                    except OSError:
                        pass
                    break
                stdin = msg.get("stdin")
                if stdin:
                    try:
                        sock.sendall(stdin)
                    except OSError:
                        break
            done.wait(timeout=5)
        except (ConnectionError, OSError):
            pass
        finally:
            if sock is not None:
                sock.close()
            session.close()
