"""Artifact fetcher.

Reference: client/allocrunner/taskrunner/getter/ (go-getter): downloads
artifacts into the task dir before start, supporting archives and
checksums. Sources here: local paths / file:// always; http(s):// via
urllib (no sandboxing proxy — the reference shells out to go-getter
which this build deliberately avoids). Checksum option:
`checksum = "sha256:<hex>"` like go-getter's ?checksum.
"""

from __future__ import annotations

import hashlib
import os
import shutil
import urllib.parse
import urllib.request

from ..structs.structs import TaskArtifact

ARCHIVE_EXTS = (".tar.gz", ".tgz", ".tar.bz2", ".tar.xz", ".tar", ".zip")


class ArtifactError(Exception):
    pass


def _file_artifacts_allowed() -> bool:
    """file:// and bare-path artifact sources read host files as the
    agent user; operators can disable them (the reference gates
    filesystem isolation per-agent the same way)."""
    return os.environ.get("NOMAD_TPU_ARTIFACT_ALLOW_FILE", "1") != "0"


def fetch_artifact(
    artifact: TaskArtifact,
    task_dir: str,
    env: dict[str, str] | None = None,
    allow_file: bool | None = None,
) -> str:
    """Fetch into task_dir/<relative_dest>; returns the destination."""
    from .allocdir import EscapeError, alloc_sandbox, confine
    from .taskenv import interpolate

    env = env or {}
    source = interpolate(artifact.getter_source, env)
    dest_rel = interpolate(artifact.relative_dest or "local/", env)
    # Job-controlled dest must stay inside the alloc dir.
    sandbox = alloc_sandbox(task_dir)
    try:
        dest = confine(sandbox, os.path.join(task_dir, dest_rel))
    except EscapeError as e:
        raise ArtifactError(str(e)) from e
    os.makedirs(dest, exist_ok=True)

    parsed = urllib.parse.urlparse(source)
    if parsed.scheme in ("", "file"):
        if not (_file_artifacts_allowed() if allow_file is None else allow_file):
            raise ArtifactError(
                "file artifacts disabled (NOMAD_TPU_ARTIFACT_ALLOW_FILE=0)"
            )
        local = parsed.path if parsed.scheme == "file" else source
        if not os.path.exists(local):
            raise ArtifactError(f"artifact not found: {local}")
        fetched = local
        copied = os.path.join(dest, os.path.basename(local))
        if os.path.isdir(local):
            shutil.copytree(local, copied, dirs_exist_ok=True)
            return dest
        shutil.copy2(local, copied)
        fetched = copied
    elif parsed.scheme in ("http", "https"):
        name = os.path.basename(parsed.path) or "artifact"
        fetched = os.path.join(dest, name)
        try:
            with urllib.request.urlopen(source, timeout=30) as resp, open(
                fetched, "wb"
            ) as out:
                shutil.copyfileobj(resp, out)
        except Exception as e:
            raise ArtifactError(f"fetch {source}: {e}") from e
    else:
        raise ArtifactError(f"unsupported artifact scheme {parsed.scheme!r}")

    _verify_checksum(fetched, artifact.getter_options.get("checksum", ""))

    mode = artifact.getter_mode or "any"
    if mode in ("any", "dir") and fetched.endswith(ARCHIVE_EXTS):
        import tarfile

        try:
            if fetched.endswith(".zip"):
                # zipfile sanitizes member paths itself; tar needs the
                # 'data' filter to block ../-traversal and device nodes.
                shutil.unpack_archive(fetched, dest)
            else:
                shutil.unpack_archive(fetched, dest, filter="data")
            os.unlink(fetched)
        except tarfile.FilterError as e:
            # A traversal attempt is an error in EVERY mode, never a
            # silently-ignored "not an archive".
            raise ArtifactError(f"unsafe archive {fetched}: {e}") from e
        except (shutil.ReadError, ValueError) as e:
            if mode == "dir":
                raise ArtifactError(f"unpack {fetched}: {e}") from e
    return dest


def _verify_checksum(path: str, spec: str) -> None:
    if not spec:
        return
    algo, _, want = spec.partition(":")
    h = hashlib.new(algo)
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    if h.hexdigest() != want.lower():
        raise ArtifactError(
            f"checksum mismatch for {os.path.basename(path)}: "
            f"got {h.hexdigest()}, want {want}"
        )
