"""Artifact fetcher.

Reference: client/allocrunner/taskrunner/getter/getter.go:22 (go-getter):
downloads artifacts into the task dir before start. Parity here:

  * sources: local paths / file://, http(s)://, git (forced `git::` or a
    `.git` suffix, with `ref=` for branches/tags/SHAs), and s3://
    (translated to the bucket's public HTTPS endpoint — no SDK).
  * options, via getter_options OR go-getter-style URL query params:
    - checksum = "[algo:]hex"  (md5/sha1/sha256/sha512; bare hex infers
      the algorithm from its length, as go-getter does)
    - archive  = "false" to disable auto-unpack, or an explicit format
      ("zip", "tar.gz", ...) to force unpacking extension-less files
    - ref      = git branch / tag / commit SHA
"""

from __future__ import annotations

import hashlib
import os
import re
import shutil
import subprocess
import urllib.parse
import urllib.request

from ..structs.structs import TaskArtifact

ARCHIVE_EXTS = (".tar.gz", ".tgz", ".tar.bz2", ".tar.xz", ".tar", ".zip")

#: go-getter query params that are options, not part of the source URL
_OPTION_PARAMS = ("checksum", "archive", "ref", "depth")

#: bare-hex checksum length -> algorithm (go-getter checksum.go)
_HEX_ALGOS = {32: "md5", 40: "sha1", 64: "sha256", 128: "sha512"}


class ArtifactError(Exception):
    pass


def _file_artifacts_allowed() -> bool:
    """file:// and bare-path artifact sources read host files as the
    agent user; operators can disable them (the reference gates
    filesystem isolation per-agent the same way)."""
    return os.environ.get("NOMAD_TPU_ARTIFACT_ALLOW_FILE", "1") != "0"


def fetch_artifact(
    artifact: TaskArtifact,
    task_dir: str,
    env: dict[str, str] | None = None,
    allow_file: bool | None = None,
) -> str:
    """Fetch into task_dir/<relative_dest>; returns the destination."""
    from .allocdir import EscapeError, alloc_sandbox, confine
    from .taskenv import interpolate

    env = env or {}
    source = interpolate(artifact.getter_source, env)
    dest_rel = interpolate(artifact.relative_dest or "local/", env)
    # Job-controlled dest must stay inside the alloc dir.
    sandbox = alloc_sandbox(task_dir)
    try:
        dest = confine(sandbox, os.path.join(task_dir, dest_rel))
    except EscapeError as e:
        raise ArtifactError(str(e)) from e
    os.makedirs(dest, exist_ok=True)

    options = dict(artifact.getter_options or {})
    # go-getter forced scheme: "git::<real url>"
    forced = ""
    m = re.match(r"^([a-z0-9]+)::(.+)$", source)
    if m:
        forced, source = m.group(1), m.group(2)
    # go-getter option query params ride the source URL
    source, url_opts = _split_option_params(source)
    for k, v in url_opts.items():
        options.setdefault(k, v)

    parsed = urllib.parse.urlparse(source)
    if forced == "git" or parsed.path.endswith(".git"):
        if parsed.scheme in ("", "file") and not (
            _file_artifacts_allowed() if allow_file is None else allow_file
        ):
            # local-path git sources read host files like file:// does
            raise ArtifactError(
                "file artifacts disabled (NOMAD_TPU_ARTIFACT_ALLOW_FILE=0)"
            )
        if options.get("checksum"):
            # go-getter rejects checksums on directory sources; silently
            # dropping an integrity option would be worse
            raise ArtifactError("checksum is not supported for git sources")
        _fetch_git(source, options.get("ref", ""), dest)
        return dest
    if parsed.scheme == "s3":
        # public-bucket parity without an SDK: s3://bucket/key ->
        # https://bucket.s3.amazonaws.com/key (go-getter's s3 getter
        # additionally signs with credentials; out of scope here)
        source = f"https://{parsed.netloc}.s3.amazonaws.com{parsed.path}"
        parsed = urllib.parse.urlparse(source)
    if forced and forced not in ("git", "file", "http", "https"):
        raise ArtifactError(f"unsupported forced getter {forced!r}")
    if parsed.scheme in ("", "file"):
        if not (_file_artifacts_allowed() if allow_file is None else allow_file):
            raise ArtifactError(
                "file artifacts disabled (NOMAD_TPU_ARTIFACT_ALLOW_FILE=0)"
            )
        local = parsed.path if parsed.scheme == "file" else source
        if not os.path.exists(local):
            raise ArtifactError(f"artifact not found: {local}")
        fetched = local
        copied = os.path.join(dest, os.path.basename(local))
        if os.path.isdir(local):
            shutil.copytree(local, copied, dirs_exist_ok=True)
            return dest
        shutil.copy2(local, copied)
        fetched = copied
    elif parsed.scheme in ("http", "https"):
        name = os.path.basename(parsed.path) or "artifact"
        fetched = os.path.join(dest, name)
        try:
            with urllib.request.urlopen(source, timeout=30) as resp, open(
                fetched, "wb"
            ) as out:
                shutil.copyfileobj(resp, out)
        except Exception as e:
            raise ArtifactError(f"fetch {source}: {e}") from e
    else:
        raise ArtifactError(f"unsupported artifact scheme {parsed.scheme!r}")

    _verify_checksum(fetched, options.get("checksum", ""))

    archive_opt = str(options.get("archive", "")).lower()
    mode = artifact.getter_mode or "any"
    unpack_as = ""
    if archive_opt in ("false", "0", "no"):
        pass  # go-getter archive=false: never unpack
    elif archive_opt and archive_opt not in ("true", "1"):
        unpack_as = archive_opt  # forced format for extension-less files
    elif mode in ("any", "dir") and fetched.endswith(ARCHIVE_EXTS):
        unpack_as = "auto"
    if unpack_as:
        import tarfile

        try:
            if unpack_as == "auto":
                if fetched.endswith(".zip"):
                    # zipfile sanitizes member paths itself; tar needs
                    # the 'data' filter to block ../-traversal.
                    shutil.unpack_archive(fetched, dest)
                else:
                    shutil.unpack_archive(fetched, dest, filter="data")
            else:
                fmt = _SHUTIL_FORMATS.get(unpack_as)
                if fmt is None:
                    raise ArtifactError(
                        f"unknown archive format {unpack_as!r}"
                    )
                if fmt == "zip":
                    shutil.unpack_archive(fetched, dest, format=fmt)
                else:
                    shutil.unpack_archive(
                        fetched, dest, format=fmt, filter="data"
                    )
            os.unlink(fetched)
        except tarfile.FilterError as e:
            # A traversal attempt is an error in EVERY mode, never a
            # silently-ignored "not an archive".
            raise ArtifactError(f"unsafe archive {fetched}: {e}") from e
        except (shutil.ReadError, ValueError) as e:
            if mode == "dir" or unpack_as != "auto":
                raise ArtifactError(f"unpack {fetched}: {e}") from e
    return dest


_SHUTIL_FORMATS = {
    "zip": "zip",
    "tar": "tar",
    "tar.gz": "gztar",
    "tgz": "gztar",
    "tar.bz2": "bztar",
    "tar.xz": "xztar",
}


def _split_option_params(source: str) -> tuple[str, dict[str, str]]:
    """Pull go-getter option params (?checksum=&archive=&ref=) off the
    source URL; everything else stays for the server."""
    parsed = urllib.parse.urlparse(source)
    if not parsed.query:
        return source, {}
    opts: dict[str, str] = {}
    keep = []
    for k, v in urllib.parse.parse_qsl(parsed.query, keep_blank_values=True):
        if k in _OPTION_PARAMS:
            opts[k] = v
        else:
            keep.append((k, v))
    if not opts:
        # untouched: re-encoding would corrupt signature-sensitive
        # queries (presigned URLs encode spaces as %20, urlencode as +)
        return source, {}
    rebuilt = parsed._replace(query=urllib.parse.urlencode(keep))
    return urllib.parse.urlunparse(rebuilt), opts


def _fetch_git(source: str, ref: str, dest: str) -> None:
    """Clone a git source at ref into dest (reference: go-getter's git
    getter — clone, then checkout the requested ref; SHAs need the full
    history, branches/tags clone shallow)."""
    target = dest if not os.listdir(dest) else os.path.join(
        dest, os.path.basename(source.rstrip("/")).removesuffix(".git") or "repo"
    )
    is_sha = bool(re.fullmatch(r"[0-9a-f]{7,40}", ref))
    cmd = ["git", "clone", "--quiet"]
    if ref and not is_sha:
        cmd += ["--depth", "1", "--branch", ref]
    elif not ref:
        cmd += ["--depth", "1"]
    cmd += [source, target]
    env = dict(os.environ)
    env["GIT_TERMINAL_PROMPT"] = "0"  # never hang on credentials
    try:
        proc = subprocess.run(
            cmd, capture_output=True, text=True, timeout=120, env=env
        )
    except subprocess.TimeoutExpired as e:
        raise ArtifactError(f"git clone {source}: timed out") from e
    except FileNotFoundError as e:
        raise ArtifactError("git is not installed on this node") from e
    if proc.returncode != 0:
        raise ArtifactError(
            f"git clone {source}: {proc.stderr.strip() or proc.returncode}"
        )
    if is_sha:
        proc = subprocess.run(
            ["git", "-C", target, "checkout", "--quiet", ref],
            capture_output=True, text=True, timeout=60, env=env,
        )
        if proc.returncode != 0:
            raise ArtifactError(
                f"git checkout {ref}: {proc.stderr.strip() or proc.returncode}"
            )


def _verify_checksum(path: str, spec: str) -> None:
    if not spec:
        return
    algo, _, want = spec.partition(":")
    if not want:
        # bare hex: infer the algorithm from its length (go-getter)
        want = algo
        algo = _HEX_ALGOS.get(len(want), "")
        if not algo:
            raise ArtifactError(
                f"cannot infer checksum algorithm from {len(want)}-char hex"
            )
    try:
        h = hashlib.new(algo)
    except ValueError as e:
        raise ArtifactError(f"unknown checksum algorithm {algo!r}") from e
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    if h.hexdigest() != want.lower():
        raise ArtifactError(
            f"checksum mismatch for {os.path.basename(path)}: "
            f"got {h.hexdigest()}, want {want}"
        )
