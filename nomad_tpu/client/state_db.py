"""Client-local persistent state.

Reference: client/state/state_database.go — BoltDB buckets (:61-94) for
allocations, task runner state, driver task handles, and dyn plugin
state, so a restarted agent restores its allocs and REATTACHES to live
tasks instead of killing them. sqlite3 (stdlib) stands in for BoltDB;
blobs are codec-packed structs. The `schema_version` row is the upgrade
hook (reference client/state/upgrade.go).
"""

from __future__ import annotations

import os
import sqlite3
import threading
from typing import Optional

from .. import codec
from ..structs import Allocation, TaskState

SCHEMA_VERSION = 1


class StateDB:
    def __init__(self, data_dir: str) -> None:
        os.makedirs(data_dir, exist_ok=True)
        self.path = os.path.join(data_dir, "client_state.db")
        self._lock = threading.Lock()
        self._closed = False
        self._db = sqlite3.connect(self.path, check_same_thread=False)
        self._db.execute("PRAGMA journal_mode=WAL")
        self._migrate()

    def _migrate(self) -> None:
        with self._lock, self._db:
            self._db.execute(
                "CREATE TABLE IF NOT EXISTS meta (key TEXT PRIMARY KEY, value TEXT)"
            )
            self._db.execute(
                "CREATE TABLE IF NOT EXISTS allocs (id TEXT PRIMARY KEY, blob BLOB)"
            )
            self._db.execute(
                "CREATE TABLE IF NOT EXISTS task_state ("
                "alloc_id TEXT, task TEXT, blob BLOB,"
                "PRIMARY KEY (alloc_id, task))"
            )
            self._db.execute(
                "CREATE TABLE IF NOT EXISTS task_handles ("
                "alloc_id TEXT, task TEXT, blob BLOB,"
                "PRIMARY KEY (alloc_id, task))"
            )
            row = self._db.execute(
                "SELECT value FROM meta WHERE key='schema_version'"
            ).fetchone()
            if row is None:
                self._db.execute(
                    "INSERT INTO meta VALUES ('schema_version', ?)",
                    (str(SCHEMA_VERSION),),
                )
            # future: elif int(row[0]) < SCHEMA_VERSION: upgrade path

    # -- meta ----------------------------------------------------------

    def get_meta(self, key: str) -> Optional[str]:
        with self._lock:
            row = self._db.execute(
                "SELECT value FROM meta WHERE key=?", (key,)
            ).fetchone()
        return row[0] if row else None

    def put_meta(self, key: str, value: str) -> None:
        with self._lock, self._db:
            self._db.execute(
                "INSERT OR REPLACE INTO meta VALUES (?, ?)", (key, value)
            )

    # -- allocs --------------------------------------------------------

    def put_alloc(self, alloc: Allocation) -> None:
        with self._lock:
            if self._closed:
                return
            with self._db:
                self._db.execute(
                    "INSERT OR REPLACE INTO allocs VALUES (?, ?)",
                    (alloc.id, codec.pack(alloc)),
                )

    def get_allocs(self) -> list[Allocation]:
        with self._lock:
            rows = self._db.execute("SELECT blob FROM allocs").fetchall()
        return [codec.unpack(r[0]) for r in rows]

    def delete_alloc(self, alloc_id: str) -> None:
        with self._lock:
            if self._closed:
                return
            self._delete_alloc_locked(alloc_id)

    def _delete_alloc_locked(self, alloc_id: str) -> None:
        with self._db:
            self._db.execute("DELETE FROM allocs WHERE id=?", (alloc_id,))
            self._db.execute(
                "DELETE FROM task_state WHERE alloc_id=?", (alloc_id,)
            )
            self._db.execute(
                "DELETE FROM task_handles WHERE alloc_id=?", (alloc_id,)
            )

    # -- task state / handles ------------------------------------------

    def put_task_state(self, alloc_id: str, task: str, state: TaskState) -> None:
        with self._lock:
            if self._closed:
                # late writes from still-draining runner threads after an
                # agent shutdown are expected; drop them
                return
            with self._db:
                self._db.execute(
                    "INSERT OR REPLACE INTO task_state VALUES (?, ?, ?)",
                    (alloc_id, task, codec.pack(state)),
                )

    def get_task_states(self, alloc_id: str) -> dict[str, TaskState]:
        with self._lock:
            rows = self._db.execute(
                "SELECT task, blob FROM task_state WHERE alloc_id=?",
                (alloc_id,),
            ).fetchall()
        return {task: codec.unpack(blob) for task, blob in rows}

    def put_task_handle(self, alloc_id: str, task: str, handle: dict) -> None:
        with self._lock:
            if self._closed:
                return
            self._put_task_handle_locked(alloc_id, task, handle)

    def _put_task_handle_locked(self, alloc_id: str, task: str, handle: dict) -> None:
        with self._db:
            self._db.execute(
                "INSERT OR REPLACE INTO task_handles VALUES (?, ?, ?)",
                (alloc_id, task, codec.pack(handle)),
            )

    def get_task_handle(self, alloc_id: str, task: str) -> Optional[dict]:
        with self._lock:
            row = self._db.execute(
                "SELECT blob FROM task_handles WHERE alloc_id=? AND task=?",
                (alloc_id, task),
            ).fetchone()
        return codec.unpack(row[0]) if row else None

    def close(self) -> None:
        with self._lock:
            self._closed = True
            self._db.close()
