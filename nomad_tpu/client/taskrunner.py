"""Task runner: one task's lifecycle state machine.

Reference: client/allocrunner/taskrunner/task_runner.go — the MAIN loop
:516 (hooks → dispatch driver → wait → restart tracker → repeat), task
event recording, kill handling. Round-1 hooks: task directory + env
construction inline; artifact/template/logmon land with their subsystems.
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Optional

from ..drivers import Driver, DriverError, TaskConfig
from ..structs import Allocation, Task, TaskState, now_ns
from .restarts import DECISION_RESTART, RestartTracker

logger = logging.getLogger("nomad_tpu.taskrunner")

EVENT_RECEIVED = "Received"
EVENT_TASK_SETUP = "Task Setup"
EVENT_STARTED = "Started"
EVENT_TERMINATED = "Terminated"
EVENT_RESTARTING = "Restarting"
EVENT_NOT_RESTARTING = "Not Restarting"
EVENT_KILLING = "Killing"
EVENT_KILLED = "Killed"
EVENT_DRIVER_FAILURE = "Driver Failure"


class TaskRunner:
    def __init__(
        self,
        alloc: Allocation,
        task: Task,
        driver: Driver,
        alloc_dir: str,
        on_state_change,
        batch: bool = False,
    ) -> None:
        self.alloc = alloc
        self.task = task
        self.driver = driver
        self.alloc_dir = alloc_dir
        self.on_state_change = on_state_change
        self.batch = batch
        self.task_id = f"{alloc.id[:8]}/{task.name}"
        self.state = TaskState(state="pending")
        self.restart_tracker = RestartTracker(self._restart_policy())
        self._kill = threading.Event()
        self._done = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _restart_policy(self):
        from ..structs import RestartPolicy

        tg = self.alloc.job.lookup_task_group(self.alloc.task_group) if self.alloc.job else None
        return tg.restart_policy if tg is not None else RestartPolicy()

    # -- lifecycle -----------------------------------------------------

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self.run, daemon=True, name=f"task-{self.task_id}"
        )
        self._thread.start()

    def run(self) -> None:
        """The MAIN loop (reference task_runner.go:516)."""
        self._event(EVENT_RECEIVED)
        task_dir = os.path.join(self.alloc_dir, self.task.name)
        os.makedirs(os.path.join(task_dir, "local"), exist_ok=True)
        os.makedirs(os.path.join(task_dir, "secrets"), exist_ok=True)
        self._event(EVENT_TASK_SETUP)

        while not self._kill.is_set():
            try:
                handle = self.driver.start_task(self._task_config(task_dir))
            except DriverError as e:
                self._event(EVENT_DRIVER_FAILURE, str(e))
                decision, delay = self.restart_tracker.next_restart(
                    exit_success=False, batch=self.batch
                )
                if decision == DECISION_RESTART:
                    self._kill.wait(delay)
                    if not self._kill.is_set():
                        self._event(EVENT_RESTARTING)
                        continue
                    break  # killed during backoff: killed, not failed
                self._fail(f"driver failure: {e}")
                return

            self.state.state = "running"
            self.state.started_at_ns = now_ns()
            self._event(EVENT_STARTED)
            self.on_state_change()

            # wait for exit OR kill
            result = None
            while result is None and not self._kill.is_set():
                result = self.driver.wait_task(self.task_id, timeout_s=0.2)
            if self._kill.is_set():
                self._event(EVENT_KILLING)
                try:
                    self.driver.stop_task(self.task_id, self.task.kill_timeout_s)
                    self.driver.destroy_task(self.task_id, force=True)
                except DriverError:
                    pass
                self.state.state = "dead"
                self.state.finished_at_ns = now_ns()
                self._event(EVENT_KILLED)
                self.on_state_change()
                self._done.set()
                return

            success = result.successful()
            self._event(
                EVENT_TERMINATED,
                f"exit_code={result.exit_code} signal={result.signal}",
            )
            try:
                self.driver.destroy_task(self.task_id, force=True)
            except DriverError:
                pass

            if success and self.batch:
                self.state.state = "dead"
                self.state.failed = False
                self.state.finished_at_ns = now_ns()
                self.on_state_change()
                self._done.set()
                return

            decision, delay = self.restart_tracker.next_restart(
                exit_success=success, batch=self.batch
            )
            if decision == DECISION_RESTART:
                self.state.restarts += 1
                self.state.last_restart_ns = now_ns()
                self._event(EVENT_RESTARTING, f"in {delay:.1f}s")
                self.on_state_change()
                self._kill.wait(delay)
                continue  # outer loop re-checks the kill flag
            # no more restarts
            if success:
                self.state.state = "dead"
                self.state.failed = False
            else:
                self._event(EVENT_NOT_RESTARTING)
                self.state.failed = True
                self.state.state = "dead"
            self.state.finished_at_ns = now_ns()
            self.on_state_change()
            self._done.set()
            return
        # Killed while between runs (e.g. during a restart delay).
        if self.state.state != "dead":
            self.state.state = "dead"
            self.state.finished_at_ns = now_ns()
            self._event(EVENT_KILLED)
            self.on_state_change()
        self._done.set()

    def kill(self) -> None:
        self._kill.set()

    def wait(self, timeout_s: Optional[float] = None) -> bool:
        return self._done.wait(timeout_s)

    def _fail(self, reason: str) -> None:
        self.state.state = "dead"
        self.state.failed = True
        self.state.finished_at_ns = now_ns()
        self.on_state_change()
        self._done.set()

    def _task_config(self, task_dir: str) -> TaskConfig:
        env = dict(self.task.env)
        env.update(self._nomad_env())
        return TaskConfig(
            id=self.task_id,
            name=self.task.name,
            alloc_id=self.alloc.id,
            env=env,
            config=dict(self.task.config),
            resources_cpu=self.task.resources.cpu,
            resources_memory_mb=self.task.resources.memory_mb,
            task_dir=task_dir,
            stdout_path=os.path.join(task_dir, f"{self.task.name}.stdout.log"),
            stderr_path=os.path.join(task_dir, f"{self.task.name}.stderr.log"),
            user=self.task.user,
        )

    def _nomad_env(self) -> dict[str, str]:
        """NOMAD_* task environment (reference client/taskenv)."""
        alloc = self.alloc
        env = {
            "NOMAD_ALLOC_ID": alloc.id,
            "NOMAD_ALLOC_NAME": alloc.name,
            "NOMAD_ALLOC_INDEX": str(alloc.index()),
            "NOMAD_TASK_NAME": self.task.name,
            "NOMAD_GROUP_NAME": alloc.task_group,
            "NOMAD_JOB_ID": alloc.job_id,
            "NOMAD_JOB_NAME": alloc.job.name if alloc.job else "",
            "NOMAD_NAMESPACE": alloc.namespace,
            "NOMAD_DC": "",
            "NOMAD_CPU_LIMIT": str(self.task.resources.cpu),
            "NOMAD_MEMORY_LIMIT": str(self.task.resources.memory_mb),
        }
        if alloc.resources is not None:
            tr = alloc.resources.tasks.get(self.task.name)
            if tr is not None:
                for net in tr.networks:
                    for p in list(net.reserved_ports) + list(net.dynamic_ports):
                        env[f"NOMAD_PORT_{p.label}"] = str(p.value)
                        env[f"NOMAD_IP_{p.label}"] = net.ip
        for k, v in self.task.meta.items():
            env[f"NOMAD_META_{k.upper()}"] = v
        return env

    def _event(self, etype: str, details: str = "") -> None:
        self.state.events.append(
            {"type": etype, "time": now_ns(), "details": details}
        )
