"""Task runner: one task's lifecycle state machine.

Reference: client/allocrunner/taskrunner/task_runner.go — the MAIN loop
:516 (restore → hooks → dispatch driver → wait → restart tracker →
repeat), task event recording, kill handling. Hook pipeline
(task_runner_hooks.go:63-159 subset): task dir → env build → artifacts →
templates → logmon → driver dispatch, with the driver handle persisted
for reattach (Restore :1065).
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Callable, Optional

from ..drivers import Driver, DriverError, TaskConfig
from ..drivers.base import TaskHandle
from ..structs import Allocation, Node, Task, TaskState, now_ns
from .allocdir import AllocDir
from .getter import ArtifactError, fetch_artifact
from .logmon import LogRotator
from .restarts import DECISION_RESTART, RestartTracker
from .taskenv import build_env, interpolate
from .template import TemplateError, render_template
from .vaultclient import VaultClientError

logger = logging.getLogger("nomad_tpu.taskrunner")

EVENT_RECEIVED = "Received"
EVENT_TASK_SETUP = "Task Setup"
EVENT_ARTIFACTS = "Downloading Artifacts"
EVENT_TEMPLATES = "Rendering Templates"
EVENT_STARTED = "Started"
EVENT_TERMINATED = "Terminated"
EVENT_RESTARTING = "Restarting"
EVENT_NOT_RESTARTING = "Not Restarting"
EVENT_KILLING = "Killing"
EVENT_KILLED = "Killed"
EVENT_DRIVER_FAILURE = "Driver Failure"
EVENT_SETUP_FAILURE = "Setup Failure"
EVENT_RESTORED = "Restored"
EVENT_SIGNALING = "Signaling"


class TaskRunner:
    def __init__(
        self,
        alloc: Allocation,
        task: Task,
        driver: Driver,
        alloc_dir: AllocDir,
        on_state_change,
        batch: bool = False,
        node: Optional[Node] = None,
        on_handle: Optional[Callable[[str, dict], None]] = None,
        restore_handle: Optional[dict] = None,
        restore_state: Optional[TaskState] = None,
        device_manager=None,  # the client's configured DeviceManager
        volume_paths: Optional[dict] = None,  # volume name -> (path, ro)
        service_fn=None,  # (name) -> [ServiceRegistration] (native SD)
        secret_fn=None,  # (path) -> SecretEntry | None (embedded Vault)
        vault_client=None,  # the client's VaultClient (token lifecycle)
        network_ns: str = "",  # bridge mode: the alloc's netns path
    ) -> None:
        self.network_ns = network_ns
        self.device_manager = device_manager
        self.volume_paths = volume_paths or {}
        self.service_fn = service_fn
        self.secret_fn = secret_fn
        self.vault_client = vault_client
        self._vault_accessor: Optional[str] = None
        self._vault_secret: str = ""
        self.alloc = alloc
        self.task = task
        self.driver = driver
        self.alloc_dir = alloc_dir
        self.on_state_change = on_state_change
        self.batch = batch
        self.node = node
        self.on_handle = on_handle  # persist driver handles (state db)
        self.restore_handle = restore_handle
        self.task_id = f"{alloc.id[:8]}/{task.name}"
        self.state = restore_state or TaskState(state="pending")
        self.restart_tracker = RestartTracker(self._restart_policy())
        self._kill = threading.Event()
        self._done = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._rotators: list[LogRotator] = []
        self._template_restart = threading.Event()
        # check_restart trips: like a template restart but CONSUMES the
        # restart-policy budget (reference check_watcher → restartTracker
        # SetRestartTriggered(failure=true)) so flapping converges to
        # failed instead of bouncing forever
        self._failure_restart = threading.Event()
        self._failure_restart_reason = ""
        # instance token: the started_at_ns the trip was aimed at — a
        # trip raised against a PREVIOUS instance (set during the
        # stop/backoff window while state still reads "running") must
        # not kill the fresh one
        self._failure_restart_token = 0
        self._tmpl_watcher = None
        # template re-render poll cadence (env knob so tests can shrink it
        # through the full client stack)
        import os as _os

        self.template_poll_interval_s = float(
            _os.environ.get("NOMAD_TEMPLATE_POLL_INTERVAL", "2.0")
        )

    def trigger_restart(self) -> None:
        """Operator-initiated restart (reference alloc restart): bounces
        the task WITHOUT consuming the restart policy budget — same path
        a template change_mode=restart rides. A dead/backoff task has no
        process to bounce (the reference returns "Task not running")."""
        if self.state.state != "running":
            raise RuntimeError(
                f"task {self.task.name!r} is not running "
                f"({self.state.state})"
            )
        self._template_restart.set()

    def trigger_failure_restart(self, reason: str) -> None:
        """Health-check-initiated restart (reference check_watcher.go):
        counts against the restart policy. No-op unless running."""
        if self.state.state != "running":
            return
        self._failure_restart_token = self.state.started_at_ns
        self._failure_restart_reason = reason
        self._failure_restart.set()

    def signal(self, sig: str) -> None:
        """Operator-initiated signal (reference alloc signal)."""
        self.driver.signal_task(self.task_id, sig)

    def _restart_policy(self):
        from ..structs import RestartPolicy

        tg = self.alloc.job.lookup_task_group(self.alloc.task_group) if self.alloc.job else None
        return tg.restart_policy if tg is not None else RestartPolicy()

    # -- lifecycle -----------------------------------------------------

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self.run, daemon=True, name=f"task-{self.task_id}"
        )
        self._thread.start()

    def run(self) -> None:
        """The MAIN loop (reference task_runner.go:516)."""
        try:
            self._run()
        finally:
            for r in self._rotators:
                r.stop()
            self._stop_template_watcher()
            if self._vault_accessor and self.vault_client is not None:
                # task is done for good: stop renewing + revoke the
                # derived token (reference task_runner vault_hook)
                self.vault_client.stop_renew(self._vault_accessor)
                self._vault_accessor = None

    def _run(self) -> None:
        self._event(EVENT_RECEIVED)
        task_dir = self.alloc_dir.build_task_dir(self.task.name)
        env = build_env(
            self.alloc,
            self.task,
            node=self.node,
            alloc_dir=self.alloc_dir.shared_dir,
            task_dir=task_dir.local_dir,
            secrets_dir=task_dir.secrets_dir,
        )
        # Assigned device instances → visibility env vars (the scheduler
        # picked the ids; reference: device plugin Reserve response).
        if self.alloc.resources is not None:
            tr_res = self.alloc.resources.tasks.get(self.task.name)
            if tr_res is not None and getattr(tr_res, "devices", None):
                dm = self.device_manager
                if dm is None:
                    from .devicemanager import DeviceManager

                    dm = DeviceManager()
                env.update(dm.task_env(tr_res))
        self._event(EVENT_TASK_SETUP)

        # Restore path: reattach to a live task instead of starting a new
        # one (reference Restore :1065 → driver RecoverTask).
        restored = False
        if self.restore_handle is not None:
            try:
                self.driver.recover_task(TaskHandle.from_dict(self.restore_handle))
                restored = True
                self._resume_vault_token(task_dir, env)
                self._event(EVENT_RESTORED)
                self.state.state = "running"
                self.on_state_change()
            except DriverError as e:
                logger.info(
                    "task %s: reattach failed (%s); restarting", self.task_id, e
                )

        while not self._kill.is_set():
            if not restored:
                # prestart hooks: artifacts then templates
                try:
                    self._prestart(task_dir, env)
                except (ArtifactError, TemplateError, VaultClientError) as e:
                    self._event(EVENT_SETUP_FAILURE, str(e))
                    if not self._maybe_restart(success=False):
                        return
                    continue
                try:
                    handle = self.driver.start_task(
                        self._task_config(task_dir, env)
                    )
                    if self.on_handle is not None:
                        self.on_handle(self.task.name, handle.to_dict())
                except DriverError as e:
                    self._event(EVENT_DRIVER_FAILURE, str(e))
                    if not self._maybe_restart(success=False):
                        return
                    continue
                self.state.state = "running"
                self.state.started_at_ns = now_ns()
                self._event(EVENT_STARTED)
                self.on_state_change()
                self._start_logmon()
                self._start_template_watcher(task_dir, env)
            restored = False

            # wait for exit OR kill OR a template-triggered restart
            result = None
            while result is None and not self._kill.is_set():
                if self._template_restart.is_set():
                    break
                if self._failure_restart.is_set():
                    if (
                        self._failure_restart_token
                        == self.state.started_at_ns
                    ):
                        break
                    # stale: aimed at a previous instance
                    self._failure_restart.clear()
                try:
                    result = self.driver.wait_task(self.task_id, timeout_s=0.2)
                except DriverError:
                    break
            # a trip aimed at a PREVIOUS instance is stale however the
            # wait loop exited (it may have broken on template/kill
            # before the in-loop staleness check ran)
            if (
                self._failure_restart.is_set()
                and self._failure_restart_token
                != self.state.started_at_ns
            ):
                self._failure_restart.clear()
            # a kill always wins over pending restarts: acting on a
            # restart first would spawn a throwaway instance
            if (
                self._failure_restart.is_set()
                and result is None
                and not self._kill.is_set()
            ):
                self._failure_restart.clear()
                # a concurrently pending template restart is satisfied
                # by this bounce too — the new instance starts from the
                # latest rendered templates
                self._template_restart.clear()
                self._event(
                    EVENT_RESTARTING,
                    self._failure_restart_reason or "unhealthy check",
                )
                try:
                    self.driver.stop_task(self.task_id, self.task.kill_timeout_s)
                    self.driver.destroy_task(self.task_id, force=True)
                except DriverError:
                    pass
                if not self._maybe_restart(success=False):
                    return
                continue
            if (
                self._template_restart.is_set()
                and result is None
                and not self._kill.is_set()
            ):
                # change_mode=restart fired: bounce the task WITHOUT
                # consuming the restart policy's budget (reference
                # restarts.go SetRestartTriggered).
                self._template_restart.clear()
                self._event(EVENT_RESTARTING, "template re-rendered")
                try:
                    self.driver.stop_task(self.task_id, self.task.kill_timeout_s)
                    self.driver.destroy_task(self.task_id, force=True)
                except DriverError:
                    pass
                self.state.restarts += 1
                self.state.last_restart_ns = now_ns()
                self.on_state_change()
                continue
            if self._kill.is_set():
                self._event(EVENT_KILLING)
                try:
                    self.driver.stop_task(self.task_id, self.task.kill_timeout_s)
                    self.driver.destroy_task(self.task_id, force=True)
                except DriverError:
                    pass
                self.state.state = "dead"
                self.state.finished_at_ns = now_ns()
                self._event(EVENT_KILLED)
                self.on_state_change()
                self._done.set()
                return
            if result is None:
                # driver lost track of the task (e.g. reattach went stale)
                self._event(EVENT_DRIVER_FAILURE, "task lost")
                if not self._maybe_restart(success=False):
                    return
                continue

            # the task exited on its own: a restart request that raced
            # the exit is stale — acting on it would kill the NEXT
            # instance within a beat (and charge the budget)
            self._failure_restart.clear()
            self._template_restart.clear()
            success = result.successful()
            self._event(
                EVENT_TERMINATED,
                f"exit_code={result.exit_code} signal={result.signal}",
            )
            try:
                self.driver.destroy_task(self.task_id, force=True)
            except DriverError:
                pass

            if success and self.batch:
                self.state.state = "dead"
                self.state.failed = False
                self.state.finished_at_ns = now_ns()
                self.on_state_change()
                self._done.set()
                return

            if not self._maybe_restart(success=success):
                return
        # Killed while between runs (e.g. during a restart delay).
        if self.state.state != "dead":
            self.state.state = "dead"
            self.state.finished_at_ns = now_ns()
            self._event(EVENT_KILLED)
            self.on_state_change()
        self._done.set()

    # -- hooks ---------------------------------------------------------

    def _secret_lookup(self, path: str):
        """Template {{ secret }} reads authenticate with the TASK'S
        derived token — a task without a vault stanza has no token and
        (under ACL enforcement) reads nothing."""
        if self.secret_fn is None:
            return None
        return self.secret_fn(path, self._vault_secret)

    def _resume_vault_token(self, task_dir, env: dict[str, str]) -> None:
        """Client-restart restore: re-enroll the persisted token for
        renewal so it doesn't silently expire mid-run (reference: vault
        tokens ride the client state db and resume renewal on restore).
        env gets VAULT_TOKEN back too, so a later restart of the restored
        task starts its fresh process with the token."""
        if not self.task.vault or self.vault_client is None:
            return
        try:
            with open(
                os.path.join(task_dir.secrets_dir, ".vault_accessor")
            ) as f:
                accessor = f.read().strip()
            with open(
                os.path.join(task_dir.secrets_dir, "vault_token")
            ) as f:
                self._vault_secret = f.read().strip()
        except OSError:
            return
        if accessor:
            self._vault_accessor = accessor
            self.vault_client.track(accessor)
            if self.task.vault.get("env", True) and self._vault_secret:
                env["VAULT_TOKEN"] = self._vault_secret

    def _prestart(self, task_dir, env: dict[str, str]) -> None:
        if self.task.vault and self.vault_client is not None \
                and self._vault_accessor is None:
            # derive the task's secrets token (reference vault_hook
            # Prestart: block task start until the token exists)
            try:
                tok = self.vault_client.derive_token(
                    self.alloc.id, self.task.name
                )
            except Exception as e:
                raise VaultClientError(f"deriving task token: {e}") from e
            self._vault_accessor = tok["accessor_id"]
            self._vault_secret = tok["secret_id"]
            token_path = os.path.join(task_dir.secrets_dir, "vault_token")
            with open(token_path, "w") as f:
                f.write(tok["secret_id"])
            os.chmod(token_path, 0o600)
            # accessor persisted beside the token: a restarted client
            # resumes renewal instead of letting the token expire
            acc_path = os.path.join(task_dir.secrets_dir, ".vault_accessor")
            with open(acc_path, "w") as f:
                f.write(tok["accessor_id"])
            os.chmod(acc_path, 0o600)
            if self.task.vault.get("env", True):
                env["VAULT_TOKEN"] = tok["secret_id"]
        if self.task.artifacts:
            self._event(EVENT_ARTIFACTS)
            for artifact in self.task.artifacts:
                fetch_artifact(artifact, task_dir.dir, env)
        if self.task.templates:
            self._event(EVENT_TEMPLATES)
            for tmpl in self.task.templates:
                render_template(
                    tmpl, task_dir.dir, env, self.service_fn,
                    self._secret_lookup,
                )

    def _start_template_watcher(self, task_dir, env: dict[str, str]) -> None:
        """change_mode lives here: the watcher re-renders and fires
        signal/restart (reference template.go runner + task runner's
        template hook)."""
        from .template import TemplateWatcher

        self._stop_template_watcher()  # joins: no straggler set() after
        self._template_restart.clear()
        if not self.task.templates:
            return
        # noop templates are WATCHED too (consul-template semantics:
        # re-render on change, take no action) — the connect sidecar's
        # upstream address files depend on exactly that.
        dynamic = list(self.task.templates)

        def signal_fn(sig: str) -> None:
            try:
                self.driver.signal_task(self.task_id, sig)
                self._event(EVENT_SIGNALING, f"template re-rendered: {sig}")
            except DriverError as e:
                logger.warning("template signal failed: %s", e)

        watcher = TemplateWatcher(
            dynamic,
            task_dir.dir,
            env,
            signal_fn=signal_fn,
            restart_fn=self._template_restart.set,
            poll_interval_s=self.template_poll_interval_s,
            service_fn=self.service_fn,
            secret_fn=self._secret_lookup,
        )
        watcher.prime()
        watcher.start()
        self._tmpl_watcher = watcher

    def _stop_template_watcher(self) -> None:
        if self._tmpl_watcher is not None:
            self._tmpl_watcher.stop()
            self._tmpl_watcher = None

    def _start_logmon(self) -> None:
        for r in self._rotators:
            r.stop()
        self._rotators = []
        lc = self.task.log_config
        for path in (
            self.alloc_dir.stdout_path(self.task.name),
            self.alloc_dir.stderr_path(self.task.name),
        ):
            rot = LogRotator(
                path,
                max_files=lc.max_files,
                max_file_size_mb=lc.max_file_size_mb,
            )
            rot.start()
            self._rotators.append(rot)

    def _maybe_restart(self, success: bool) -> bool:
        """Consult the restart tracker. False ⇒ terminal (caller returns)."""
        decision, delay = self.restart_tracker.next_restart(
            exit_success=success, batch=self.batch
        )
        if decision == DECISION_RESTART:
            self.state.restarts += 1
            self.state.last_restart_ns = now_ns()
            self._event(EVENT_RESTARTING, f"in {delay:.1f}s")
            self.on_state_change()
            self._kill.wait(delay)
            return True
        if success:
            self.state.state = "dead"
            self.state.failed = False
        else:
            self._event(EVENT_NOT_RESTARTING)
            self.state.failed = True
            self.state.state = "dead"
        self.state.finished_at_ns = now_ns()
        self.on_state_change()
        self._done.set()
        return False

    def kill(self) -> None:
        self._kill.set()

    def wait(self, timeout_s: Optional[float] = None) -> bool:
        return self._done.wait(timeout_s)

    def _setup_volume_mounts(self, task_dir) -> list[dict]:
        """Materialize task.volume_mounts (reference: volume_hook.go).

        Each mount's destination gets a symlink inside the task dir so
        filesystem drivers (exec/rawexec/java) see the volume; the mount
        list also rides TaskConfig.mounts for drivers that bind-mount
        (docker). Destinations are confined to the task dir."""
        from .allocdir import EscapeError, confine

        mounts: list[dict] = []
        for vm in self.task.volume_mounts:
            vp = self.volume_paths.get(vm.volume)
            if vp is None:
                raise DriverError(
                    f"volume_mount {vm.volume!r}: no such group volume "
                    f"resolved on this node"
                )
            host_path, vol_ro = vp
            dest = vm.destination or vm.volume
            try:
                link = confine(task_dir.dir, dest.lstrip("/"))
            except EscapeError as e:
                raise DriverError(str(e)) from None
            os.makedirs(os.path.dirname(link), exist_ok=True)
            if not os.path.lexists(link):
                os.symlink(host_path, link)
            if (vm.read_only or vol_ro) and not getattr(
                self.driver, "bind_mounts", False
            ):
                # Filesystem drivers see the volume through a symlink,
                # which cannot enforce read-only (the reference's exec
                # driver uses real ro bind mounts via libcontainer;
                # raw_exec doesn't support volume_mounts at all). Surface
                # the advisory gap instead of silently dropping it.
                logger.warning(
                    "task %s: read_only mount %r is advisory under driver "
                    "%s (no bind-mount isolation)",
                    self.task_id, vm.volume, self.task.driver,
                )
            mounts.append({
                "host_path": host_path,
                "task_path": dest,
                "read_only": vm.read_only or vol_ro,
            })
        return mounts

    def _task_config(self, task_dir, env: dict[str, str]) -> TaskConfig:
        granted_res = (
            self.alloc.resources.tasks.get(self.task.name)
            if self.alloc.resources is not None
            else None
        )
        return TaskConfig(
            id=self.task_id,
            name=self.task.name,
            alloc_id=self.alloc.id,
            env=env,
            config=interpolate(dict(self.task.config), env),
            # the GRANT, not the ask: a cores task's cpu share is
            # derived (cores x MHz/core) and drives cgroup weight
            resources_cpu=(
                granted_res.cpu
                if granted_res is not None and granted_res.cpu
                else self.task.resources.cpu
            ),
            resources_memory_mb=self.task.resources.memory_mb,
            resources_memory_max_mb=self.task.resources.memory_max_mb,
            reserved_cores=(
                list(granted_res.reserved_cores)
                if granted_res is not None
                else []
            ),
            task_dir=task_dir.dir,
            stdout_path=self.alloc_dir.stdout_path(self.task.name),
            stderr_path=self.alloc_dir.stderr_path(self.task.name),
            user=self.task.user,
            mounts=self._setup_volume_mounts(task_dir),
            network_ns=self.network_ns,
        )

    def _event(self, etype: str, details: str = "") -> None:
        self.state.events.append(
            {"type": etype, "time": now_ns(), "details": details}
        )
