"""The fault plane: deterministic, seedable fault injection (core).

Production-side leaf module (stdlib-only, like metrics/trace) holding
the :class:`FaultPlane` rule engine and the process-global ``plane``
slot that four production hook sites read:

  - ``rpc/client.py`` ConnPool.call       -> :meth:`FaultPlane.on_rpc_call`
  - ``rpc/server.py`` RPCServer._dispatch -> :meth:`FaultPlane.on_rpc_serve`
  - ``server/raft_store.py`` append/set_state/store_snapshot
                                          -> :meth:`FaultPlane.on_disk`
  - the TPU worker's device stage         -> :meth:`FaultPlane.on_device`

Rules inject per-connection drops/delays, symmetric partitions, fsync
failures and slow disk on the raft log, and device-stage exceptions —
each optionally probabilistic (one seeded RNG consulted under one lock,
so a seed fixes the whole fault schedule) and/or bounded by a count.
Every hook is a single module-attribute check when no plane is
installed; nothing here touches production behavior until
``install(FaultPlane(seed=...))``.

``bench.py`` refuses to gate while :func:`env_knobs_active` is
non-empty, so injected faults can never pollute a BENCH capture.

The scenario harness (ChaosCluster: scripted kill/partition/heal with
the no-acked-write-lost / no-duplicate-alloc / convergence invariants)
lives in ``nomad_tpu/testing/chaos.py``, which re-exports this module —
tests and docs use the ``testing.chaos`` surface; production code
imports only this leaf. See docs/fault-injection.md.
"""


from __future__ import annotations

import os
import random
import threading
import time
from typing import Callable, Iterable, Optional

# The installed plane. Hook sites read this module attribute directly
# (`if chaos.plane is not None: ...`) so the disabled cost is one
# attribute load per hook — no function call, no lock.
plane: Optional["FaultPlane"] = None


def install(p: "FaultPlane") -> "FaultPlane":
    """Install the fault plane process-wide. Returns it for chaining."""
    global plane
    plane = p
    return p


def uninstall() -> None:
    global plane
    plane = None


def active() -> bool:
    """Is any fault injection live (installed plane with rules)?"""
    return plane is not None and plane.has_rules()


def env_knobs_active() -> list[str]:
    """Names of NOMAD_TPU_INJECT_* env knobs currently set non-zero,
    plus a sentinel for an installed fault plane — the bench gate
    refuses to certify a capture while any of these are live."""
    out = [
        k
        for k, v in os.environ.items()
        if k.startswith("NOMAD_TPU_INJECT_") and v.strip() not in ("", "0")
    ]
    if active():
        out.append("<fault-plane-installed>")
    return out


class InjectedRPCError(ConnectionError):
    """An injected connection-level drop; subclasses ConnectionError so
    the production rundown/redial paths treat it as a real network
    failure."""


class DropResponse(Exception):
    """Server-side injection: swallow the request, send no response
    (the caller sees a timeout, as with a partition after delivery)."""


class InjectedDiskError(OSError):
    """An injected fsync/write failure on the raft log store."""


class DeviceFault(Exception):
    """An injected device-stage failure. ``retriable`` mirrors the real
    classification the worker applies to XLA errors: retriable faults
    fall back to the host solve path; terminal ones nack the batch."""

    def __init__(self, msg: str = "injected device fault", retriable: bool = True):
        super().__init__(msg)
        self.retriable = retriable


class _Rule:
    """One fault rule. `times=None` means unlimited; `prob` draws from
    the plane's seeded RNG (under its lock — one global draw order, so
    a seed fixes the whole schedule)."""

    __slots__ = ("kind", "match", "action", "prob", "times")

    def __init__(self, kind: str, match: Callable, action, prob: float,
                 times: Optional[int]) -> None:
        self.kind = kind
        self.match = match
        self.action = action
        self.prob = prob
        self.times = times


class FaultPlane:
    def __init__(self, seed: int = 0) -> None:
        self.rng = random.Random(seed)
        self._lock = threading.Lock()
        self._rules: list[_Rule] = []
        # node label <-> advertised fabric addr, so partition rules
        # written in terms of node ids can match a ConnPool's dial
        # target (registered by ChaosCluster / tests).
        self._addr_label: dict[tuple[str, int], str] = {}
        # observability for assertions: kind -> injections fired
        self.fired: dict[str, int] = {}

    # -- wiring --------------------------------------------------------

    def register_addr(self, label: str, addr: tuple[str, int]) -> None:
        with self._lock:
            self._addr_label[(addr[0], addr[1])] = label

    def label_of(self, addr) -> str:
        try:
            return self._addr_label.get((addr[0], addr[1]), "")
        except (TypeError, IndexError):
            return ""

    def has_rules(self) -> bool:
        with self._lock:
            return bool(self._rules)

    def heal(self, kind: Optional[str] = None) -> None:
        """Drop all rules (or all rules of one kind)."""
        with self._lock:
            if kind is None:
                self._rules.clear()
            else:
                self._rules = [r for r in self._rules if r.kind != kind]

    # -- rule builders -------------------------------------------------

    def _add(self, rule: _Rule) -> "FaultPlane":
        with self._lock:
            self._rules.append(rule)
        return self

    def drop_rpc(self, src: Optional[str] = None, dst: Optional[str] = None,
                 method: Optional[str] = None, prob: float = 1.0,
                 times: Optional[int] = None) -> "FaultPlane":
        """Fail matching client-side calls with InjectedRPCError before
        the frame is written (the request is never delivered)."""

        def match(s, d, m):
            return (
                (src is None or s == src)
                and (dst is None or d == dst)
                and (method is None or m == method or m.startswith(method))
            )

        return self._add(_Rule("rpc.drop", match, None, prob, times))

    def delay_rpc(self, delay_s: float, src: Optional[str] = None,
                  dst: Optional[str] = None, method: Optional[str] = None,
                  prob: float = 1.0, times: Optional[int] = None) -> "FaultPlane":
        def match(s, d, m):
            return (
                (src is None or s == src)
                and (dst is None or d == dst)
                and (method is None or m == method or m.startswith(method))
            )

        return self._add(_Rule("rpc.delay", match, delay_s, prob, times))

    def partition(self, group_a: Iterable[str], group_b: Iterable[str]) -> "FaultPlane":
        """Symmetric partition between two node-label groups: every call
        whose (src, dst) crosses the cut is dropped, both directions —
        raft, forwards, everything riding the fabric."""
        a, b = frozenset(group_a), frozenset(group_b)

        def match(s, d, m):
            return (s in a and d in b) or (s in b and d in a)

        return self._add(_Rule("rpc.drop", match, None, 1.0, None))

    def isolate(self, label: str, others: Iterable[str]) -> "FaultPlane":
        return self.partition([label], others)

    def drop_response(self, label: Optional[str] = None,
                      method: Optional[str] = None, prob: float = 1.0,
                      times: Optional[int] = None) -> "FaultPlane":
        """Server-side: the handler never runs and no response is sent —
        the request was DELIVERED but the answer is lost (the nastier
        half of a partition; the caller can't tell it from a drop)."""

        def match(lbl, m):
            return (label is None or lbl == label) and (
                method is None or m == method or m.startswith(method)
            )

        return self._add(_Rule("serve.drop", match, None, prob, times))

    def fail_disk(self, label: Optional[str] = None, op: Optional[str] = None,
                  prob: float = 1.0, times: Optional[int] = None) -> "FaultPlane":
        """Inject InjectedDiskError from the raft store's write ops
        (op in {append, state, snapshot}; None = all)."""

        def match(lbl, o):
            return (label is None or lbl == label) and (op is None or o == op)

        return self._add(_Rule("disk.fail", match, None, prob, times))

    def slow_disk(self, delay_s: float, label: Optional[str] = None,
                  op: Optional[str] = None, prob: float = 1.0,
                  times: Optional[int] = None) -> "FaultPlane":
        def match(lbl, o):
            return (label is None or lbl == label) and (op is None or o == op)

        return self._add(_Rule("disk.slow", match, delay_s, prob, times))

    def fail_device(self, phase: Optional[str] = None, retriable: bool = True,
                    prob: float = 1.0, times: Optional[int] = None) -> "FaultPlane":
        """Raise DeviceFault from the worker's device stage (phase in
        {dispatch, finish}; None = both)."""

        def match(p):
            return phase is None or p == phase

        return self._add(_Rule("device.fail", match, retriable, prob, times))

    # -- hook entry points (called from production code) ---------------

    def _fire(self, kinds: tuple[str, ...], *args):
        """Match rules of the given kinds against args; return the first
        firing rule (consuming its count / probability draw) or None.
        One lock + one RNG draw order = deterministic under a seed."""
        with self._lock:
            for rule in self._rules:
                if rule.kind not in kinds:
                    continue
                if rule.times is not None and rule.times <= 0:
                    continue
                if not rule.match(*args):
                    continue
                if rule.prob < 1.0 and self.rng.random() >= rule.prob:
                    continue
                if rule.times is not None:
                    rule.times -= 1
                self.fired[rule.kind] = self.fired.get(rule.kind, 0) + 1
                return rule
        return None

    def on_rpc_call(self, src_label: str, addr, method: str) -> None:
        dst = self.label_of(addr)
        rule = self._fire(("rpc.delay",), src_label, dst, method)
        if rule is not None:
            time.sleep(rule.action)
        rule = self._fire(("rpc.drop",), src_label, dst, method)
        if rule is not None:
            raise InjectedRPCError(
                f"injected rpc drop {src_label or '?'} -> {dst or addr} {method}"
            )

    def on_rpc_serve(self, label: str, method: str) -> None:
        rule = self._fire(("serve.drop",), label, method)
        if rule is not None:
            raise DropResponse(f"injected response drop at {label} {method}")

    def on_disk(self, label: str, op: str) -> None:
        rule = self._fire(("disk.slow",), label, op)
        if rule is not None:
            time.sleep(rule.action)
        rule = self._fire(("disk.fail",), label, op)
        if rule is not None:
            raise InjectedDiskError(f"injected {op} failure at {label}")

    def on_device(self, phase: str) -> None:
        rule = self._fire(("device.fail",), phase)
        if rule is not None:
            raise DeviceFault(
                f"injected device fault in {phase}", retriable=rule.action
            )


