"""RPC fabric (reference: nomad/rpc.go, helper/pool/)."""

from .client import AuthFailedError, ConnPool, RPCError
from .keyring import Keyring
from .server import RPCServer, StreamSession

__all__ = [
    "AuthFailedError",
    "ConnPool",
    "Keyring",
    "RPCError",
    "RPCServer",
    "StreamSession",
]
