"""RPC fabric (reference: nomad/rpc.go, helper/pool/)."""

from .client import ConnPool, RPCError
from .server import RPCServer, StreamSession

__all__ = ["ConnPool", "RPCError", "RPCServer", "StreamSession"]
