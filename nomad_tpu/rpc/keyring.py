"""Dual-accept keyring for the RPC fabric's shared secret.

Reference: command/agent/keyring.go — the agent's gossip keyring
installs a new key alongside the old one, uses it for new traffic, and
removes the old key once every member has rotated. This fabric
authenticates peers with a single shared ``rpc_secret`` (rpc/server.py
trust-boundary note), so the analog is a TWO-slot keyring: the
``current`` secret every new dial presents, plus the ``previous``
secret accepted for a bounded window after a rotation.

The window is what makes *live* rotation safe: operators push the new
secret agent-by-agent (config edit + SIGHUP → ``Agent.reload``), so for
a while the cluster is mixed. During the window

- an already-rotated server accepts dials from not-yet-rotated peers
  (they present the previous secret), and
- an already-rotated dialer whose new secret a not-yet-rotated server
  rejects falls back to presenting the previous secret on redial
  (rpc/client.py ConnPool auth-failure path),

so either rotation order drains cleanly with zero dropped RPCs.
Established connections are never touched — authentication happens once
per connection at dial time, exactly like the reference's TLS posture.
After the window closes the previous secret is rejected everywhere.

One Keyring instance is shared by every socket owner in a process
(the agent wires its single keyring into the server's RPCServer +
ConnPool and the client's listener/dialers), so one ``rotate()`` call
moves the whole agent atomically.
"""

from __future__ import annotations

import hashlib
import hmac
import threading
import time

from .. import metrics

DEFAULT_WINDOW_S = 60.0


def key_fingerprint(secret: str) -> str:
    """A short non-reversible identifier for a secret, for status
    surfaces and operator logs (never the secret itself)."""
    if not secret:
        return ""
    return hashlib.sha256(secret.encode()).hexdigest()[:12]


class Keyring:
    """Two-slot secret holder with a bounded dual-accept window.

    Thread-safety: every method takes the internal lock; nothing
    blocking ever runs under it (nomad-vet NV-lock-blocking).
    """

    def __init__(self, secret: str = "", window_s: float = DEFAULT_WINDOW_S):
        self._lock = threading.Lock()
        self._current = secret or ""
        self._previous = ""
        self._previous_expires = 0.0  # monotonic deadline
        self._installed_at = time.monotonic()
        self._rotated_at: float = 0.0  # 0 = never rotated
        self.window_s = float(window_s)
        # the window actually APPLIED to the open previous slot (a
        # rotate() may override the default; status must report the
        # real deadline operators pace the rollout against)
        self._applied_window_s = float(window_s)
        self.generation = 0  # bumps on every effective rotation

    # -- dial/accept ---------------------------------------------------

    @property
    def current(self) -> str:
        """The secret dialers present on new connections."""
        with self._lock:
            return self._current

    @property
    def enabled(self) -> bool:
        """Whether the fabric requires authentication at all."""
        with self._lock:
            return bool(self._current)

    def previous_active(self) -> str:
        """The previous secret while its window is open, else ''."""
        with self._lock:
            return self._previous_locked()

    def _previous_locked(self) -> str:
        if self._previous and time.monotonic() < self._previous_expires:
            return self._previous
        return ""

    def accepts(self, presented: bytes) -> bool:
        """Acceptor-side check: the current secret always passes; the
        previous secret passes only while the dual-accept window is
        open. Constant-time compares."""
        with self._lock:
            current = self._current.encode()
            previous = self._previous_locked().encode()
        if current and hmac.compare_digest(presented, current):
            return True
        if previous and hmac.compare_digest(presented, previous):
            # dual-accept hit: a not-yet-rotated peer is still dialing
            # with the old secret — expected during the window, and a
            # climbing counter near its end says the rollout stalled
            metrics.incr("nomad.keyring.accept_previous")
            return True
        metrics.incr("nomad.keyring.auth_fail")
        return False

    # -- rotation ------------------------------------------------------

    def rotate(self, new_secret: str, window_s: float | None = None) -> bool:
        """Install ``new_secret`` as current and open the dual-accept
        window for the old one. Returns False (no-op) when the secret is
        unchanged — an idempotent re-SIGHUP must not restart the window
        or demote a live secret. Rotating BACK to the previous secret
        within its window swaps the slots (the old secret becomes
        current again, the aborted one drains out through the window).

        Rotating to the empty string is refused: disabling fabric auth
        is a restart-worthy topology change, not a rotation (a window
        cannot represent "accept unauthenticated dials")."""
        if not new_secret:
            raise ValueError("cannot rotate the rpc secret to empty")
        with self._lock:
            if new_secret == self._current:
                return False
            window = self.window_s if window_s is None else float(window_s)
            old = self._current
            self._current = new_secret
            # old == "" (enabling auth on a previously open fabric) has
            # no previous to accept; the window only applies to a real
            # old secret
            self._previous = old
            self._previous_expires = (
                time.monotonic() + window if old else 0.0
            )
            self._applied_window_s = window
            self._rotated_at = time.monotonic()
            self.generation += 1
        metrics.incr("nomad.keyring.rotations")
        return True

    # -- observation ---------------------------------------------------

    def status(self) -> dict:
        """Operator-facing state for /v1/agent/self and `operator
        keyring status` — fingerprints and ages only, never secrets."""
        now = time.monotonic()
        with self._lock:
            prev = self._previous_locked()
            window_remaining = (
                max(0.0, self._previous_expires - now) if prev else 0.0
            )
            return {
                "enabled": bool(self._current),
                "generation": self.generation,
                "current_fingerprint": key_fingerprint(self._current),
                "age_s": round(
                    now - (self._rotated_at or self._installed_at), 3
                ),
                "dual_accept": bool(prev),
                "previous_fingerprint": key_fingerprint(prev),
                "window_s": (
                    self._applied_window_s if prev else self.window_s
                ),
                "window_remaining_s": round(window_remaining, 3),
            }


def ensure_keyring(secret) -> Keyring:
    """Normalize a constructor argument: callers pass either a plain
    secret string (standalone pools/servers get a private keyring) or a
    shared Keyring instance (the agent path — one rotation moves every
    socket owner)."""
    if isinstance(secret, Keyring):
        return secret
    return Keyring(secret or "")
