"""Framing + protocol bytes shared by RPC client and server.

Reference: nomad/rpc.go:229-316 — a raw TCP connection's first byte selects
the protocol (RpcNomad/RpcRaft/RpcMultiplex/RpcTLS/RpcStreaming). The
TPU-native fabric keeps the same first-byte switch with length-prefixed
msgpack frames instead of net/rpc + yamux: one logical request/response (or
stream chunk) per frame, with interleaving by sequence number replacing
stream multiplexing — simpler, and equally pipelined.
"""

from __future__ import annotations

import socket
import struct

# First-byte protocol identifiers (reference nomad/rpc.go RpcNomad=0x01...)
BYTE_RPC = 0x01
BYTE_RAFT = 0x02
BYTE_STREAMING = 0x03

# Trace-context propagation fields in the RPC envelope (trace.py): a
# request may carry TRACE_KEY = {"id": trace_id, "parent": span_id}; the
# handler side opens a remote segment of that trace and sends its spans
# back under TRACE_SPANS_KEY in the response, so a trace stitches a
# client submit on a follower to the raft apply on the leader. Absent
# fields cost nothing — the envelope stays byte-identical when tracing
# is off.
TRACE_KEY = "trace"
TRACE_SPANS_KEY = "trace_spans"

# Source-identity propagation field (clusterobs.py): a dialing pool
# whose owner has a node label stamps SRC_KEY on every request so the
# handler side can attribute served seconds to the PEER (server-to-
# server forwards, raft, serf). Requests about a node (heartbeats)
# attribute to that node from the args instead — see
# clusterobs.source_of. Absent costs nothing, like TRACE_KEY.
SRC_KEY = "src"

MAX_FRAME = 256 * 1024 * 1024

_LEN = struct.Struct("!I")


def send_frame(sock: socket.socket, payload: bytes) -> None:
    sock.sendall(_LEN.pack(len(payload)) + payload)


def recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("connection closed")
        buf.extend(chunk)
    return bytes(buf)


def recv_frame(sock: socket.socket) -> bytes:
    (length,) = _LEN.unpack(recv_exact(sock, _LEN.size))
    if length > MAX_FRAME:
        raise ConnectionError(f"frame too large: {length}")
    return recv_exact(sock, length)
