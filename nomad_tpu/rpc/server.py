"""RPC server: TCP listener with first-byte protocol switch.

Reference: nomad/rpc.go — listen loop (:178 listen), handleConn (:229,
first-byte switch), handleNomadConn request loop (:352), endpoint structs
registered on a net/rpc server (nomad/server.go:1137-1184), streaming
handlers (:299 RpcStreaming), and the dedicated Raft stream layer
(nomad/raft_rpc.go).

Design: each accepted connection gets a reader thread. RPC requests are
dispatched to a small worker pool so one slow handler doesn't stall the
connection (net/rpc semantics — responses may arrive out of order, matched
by seq). Streaming connections hand the raw socket to the registered
stream handler. Raft connections are dispatched to the raft transport
handler installed by the replication layer.

Trust boundary: the fabric authenticates PEERS, not requests — when a
cluster `secret` is configured every connection (RPC, streaming, raft)
must present it in a preamble frame right after the protocol byte, or
it is dropped. This is the reference's mTLS-on-the-fabric posture in
shared-secret form: any authenticated peer (server or client agent) may
invoke any endpoint; per-request ACL capability checks happen at the
HTTP layer. Without a secret the fabric trusts the network (dev mode).
"""

from __future__ import annotations

import logging
import socket
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Optional

from .. import clusterobs, codec, metrics, trace
from .. import faultplane
from .keyring import ensure_keyring
from .wire import (
    BYTE_RAFT,
    BYTE_RPC,
    BYTE_STREAMING,
    SRC_KEY,
    TRACE_KEY,
    TRACE_SPANS_KEY,
    recv_frame,
    send_frame,
)

logger = logging.getLogger("nomad_tpu.rpc")


class StreamSession:
    """A byte-frame session handed to streaming handlers (reference:
    nomad/structs/streaming_rpc.go)."""

    def __init__(self, sock: socket.socket) -> None:
        self._sock = sock
        self._wlock = threading.Lock()

    def send(self, obj) -> None:
        with self._wlock:
            send_frame(self._sock, codec.pack(obj))

    def recv(self, timeout_s: Optional[float] = None):
        self._sock.settimeout(timeout_s)
        return codec.unpack(recv_frame(self._sock))

    def close(self) -> None:
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self._sock.close()


class RPCServer:
    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        num_workers: int = 8,
        secret="",  # str | Keyring — the agent shares ONE Keyring
        tls_context=None,  # ssl.SSLContext (server side) — fabric TLS
    ) -> None:
        # Dual-accept keyring (rpc/keyring.py): a plain string gets a
        # private keyring; the agent passes its shared instance so a
        # live rotation moves listener + dialers together.
        self.keyring = ensure_keyring(secret)
        self.tls_context = tls_context
        self._endpoints: dict[str, object] = {}
        self._stream_handlers: dict[str, Callable[[StreamSession, dict], None]] = {}
        self.raft_handler: Optional[Callable[[StreamSession], None]] = None
        # Fixed-port binds retry briefly: an in-process restart races the
        # previous incarnation's sockets draining out of FIN_WAIT.
        deadline = time.monotonic() + (5.0 if port else 0.0)
        while True:
            try:
                self._listener = socket.create_server((host, port))
                break
            except OSError:
                if time.monotonic() >= deadline:
                    raise
                time.sleep(0.05)
        self.addr = self._listener.getsockname()  # (host, port)
        self._pool = ThreadPoolExecutor(
            max_workers=num_workers, thread_name_prefix="rpc"
        )
        # Raft traffic gets its own lane: blocking queries and slow
        # forwards on the shared pool must never delay heartbeats or
        # elections destabilize (the reference runs raft on a dedicated
        # stream layer, nomad/raft_rpc.go, for the same reason).
        self._priority_pool = ThreadPoolExecutor(
            max_workers=4, thread_name_prefix="rpc-raft"
        )
        # Serf shares the lane: a starved probe ack looks like a dead
        # member and gets a live raft peer removed.
        self._priority_prefixes = ("Raft.", "Serf.")
        self._shutdown = threading.Event()
        self._accept_thread: Optional[threading.Thread] = None
        self._conns: set[socket.socket] = set()
        self._conns_lock = threading.Lock()
        # Fault-plane identity (faultplane.py): the owning node's
        # label, so injected response drops can target this server.
        self.chaos_label = ""
        # Per-source cost ledger (clusterobs.py): every dispatched
        # request's handler seconds are attributed to its source node /
        # peer / namespace. ClusterServer installs its own instance so
        # in-process test clusters attribute per member; a bare
        # RPCServer shares the process-global default.
        self.source_ledger = clusterobs.ledger()

    @property
    def secret(self) -> str:
        """The current cluster secret (legacy accessor — prefer passing
        the keyring itself so rotation propagates)."""
        return self.keyring.current

    # -- registration --------------------------------------------------

    def register(self, name: str, endpoint: object) -> None:
        """Register an endpoint struct; its public methods become
        `Name.method` RPCs (reference nomad/server.go setupRpcServer)."""
        self._endpoints[name] = endpoint

    def register_stream(
        self, method: str, handler: Callable[[StreamSession, dict], None]
    ) -> None:
        self._stream_handlers[method] = handler

    # -- lifecycle -----------------------------------------------------

    def start(self) -> None:
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="rpc-accept", daemon=True
        )
        self._accept_thread.start()

    def shutdown(self) -> None:
        self._shutdown.set()
        # shutdown() interrupts the thread blocked in accept(); a bare
        # close() would leave the fd (and the LISTEN port) held until the
        # accept call returned.
        try:
            self._listener.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._listener.close()
        except OSError:
            pass
        with self._conns_lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            c.close()
        self._pool.shutdown(wait=False)
        self._priority_pool.shutdown(wait=False)
        if self._accept_thread:
            self._accept_thread.join(timeout=5)

    # -- connection handling -------------------------------------------

    def _accept_loop(self) -> None:
        while not self._shutdown.is_set():
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._conns_lock:
                self._conns.add(conn)
            threading.Thread(
                target=self._handle_conn, args=(conn,),
                name="rpc-conn", daemon=True,
            ).start()

    def _drop_conn(self, conn: socket.socket) -> None:
        with self._conns_lock:
            self._conns.discard(conn)
        try:
            conn.close()
        except OSError:
            pass

    def _authenticate(self, conn: socket.socket) -> bool:
        """When a cluster secret is configured, require the auth
        preamble frame before serving any protocol. The keyring accepts
        the current secret always and the previous one during the
        dual-accept window (live rotation, rpc/keyring.py)."""
        if not self.keyring.enabled:
            return True
        conn.settimeout(10.0)
        try:
            presented = recv_frame(conn)
        except (ConnectionError, OSError):
            return False
        finally:
            conn.settimeout(None)
        if not self.keyring.accepts(presented):
            logger.warning("rpc connection rejected: bad cluster secret")
            # Tell the dialer WHY before closing: a silent close is
            # indistinguishable from a crash, but an auth reject means
            # "nothing you pipelined was dispatched — redial with a
            # fresh secret" (ConnPool re-reads its keyring and falls
            # back to the previous secret within the window).
            try:
                send_frame(
                    conn,
                    codec.pack(
                        {"auth_error": "permission denied: bad rpc secret"}
                    ),
                )
                # The frame must SURVIVE the close: the dialer pipelines
                # request frames right behind the preamble, and closing
                # with them unread emits an RST that discards our reject
                # on the peer (it would see a bare ECONNRESET and skip
                # the previous-secret fallback). Half-close so FIN
                # follows the frame, then drain the pipelined bytes
                # until the client sees the reject and hangs up.
                conn.settimeout(1.0)
                conn.shutdown(socket.SHUT_WR)
                # bounded BOTH ways: 1s idle gap per recv, 5s overall —
                # a peer that keeps streaming must not pin this thread
                deadline = time.monotonic() + 5.0
                while time.monotonic() < deadline and conn.recv(4096):
                    pass
            except (ConnectionError, OSError):
                pass
            return False
        return True

    def _handle_conn(self, conn: socket.socket) -> None:
        try:
            if self.tls_context is not None:
                # per-connection handshake in THIS worker thread — the
                # accept loop must never block on a silent client
                conn.settimeout(30.0)
                plain = conn
                try:
                    conn = self.tls_context.wrap_socket(
                        conn, server_side=True
                    )
                except (OSError, ValueError) as e:
                    logger.debug("fabric TLS handshake failed: %s", e)
                    return
                # wrap_socket DETACHES the plain socket: re-track the
                # SSLSocket or shutdown() force-closes a dead husk while
                # the live connection's reader blocks forever
                with self._conns_lock:
                    self._conns.discard(plain)
                    if self._shutdown.is_set():
                        conn.close()
                        return
                    self._conns.add(conn)
                conn.settimeout(None)
            first = conn.recv(1)
            if not first:
                return
            proto = first[0]
            if not self._authenticate(conn):
                return
            if proto == BYTE_RPC:
                self._handle_rpc_conn(conn)
            elif proto == BYTE_STREAMING:
                self._handle_stream_conn(conn)
            elif proto == BYTE_RAFT:
                if self.raft_handler is not None:
                    self.raft_handler(StreamSession(conn))
                else:
                    logger.warning("raft connection but no raft handler")
            else:
                logger.warning("unrecognized rpc protocol byte %#x", proto)
        except (ConnectionError, OSError):
            pass
        except Exception:
            logger.exception("rpc connection handler failed")
        finally:
            self._drop_conn(conn)

    def _handle_rpc_conn(self, conn: socket.socket) -> None:
        wlock = threading.Lock()
        while not self._shutdown.is_set():
            req = codec.unpack(recv_frame(conn))
            method = req.get("method", "")
            pool = (
                self._priority_pool
                if method.startswith(self._priority_prefixes)
                else self._pool
            )
            pool.submit(self._dispatch, conn, wlock, req)

    def _dispatch(self, conn: socket.socket, wlock: threading.Lock, req) -> None:
        seq = req.get("seq")
        method = req.get("method", "")
        if faultplane.plane is not None:
            # Injected response drop: the request was DELIVERED but the
            # answer is lost — the caller sees a timeout, the nastier
            # half of a partition (retries must tolerate a possibly
            # already-applied write).
            try:
                faultplane.plane.on_rpc_serve(self.chaos_label, method)
            except faultplane.DropResponse:
                return
        # Remote trace segment (wire.py TRACE_KEY): the handler runs with
        # the caller's trace installed as this thread's current context,
        # so every span recorded below (raft applies included) stitches
        # into the originator's trace; the spans ride back in the
        # response rather than landing in this server's ring.
        segment = None
        ref = req.get(TRACE_KEY)
        if isinstance(ref, dict) and ref.get("id"):
            segment = trace.open_segment(f"rpc.{method}", ref)
        # Source attribution (clusterobs.py): derive who this request is
        # FOR, publish it on the thread->source registry so the hostobs
        # sampler can attribute handler CPU to the source, and record
        # the handler seconds in the bounded per-source ledger.
        args = req.get("args")
        source = clusterobs.source_of(req.get(SRC_KEY) or "", args)
        clusterobs.set_thread_source(source)
        t0 = time.perf_counter()
        try:
            with trace.use(segment):
                result = self.dispatch_local(method, args)
            resp = {"seq": seq, "result": result}
        except Exception as e:  # handler errors travel as strings
            logger.debug("rpc %s failed: %s", method, e)
            resp = {"seq": seq, "error": f"{type(e).__name__}: {e}"}
        finally:
            clusterobs.clear_thread_source()
        dt = time.perf_counter() - t0
        self.source_ledger.record(source, method, dt)
        # handler-side latency (the client-side nomad.rpc.call_seconds
        # minus this is wire + queueing time)
        metrics.observe(f"nomad.rpc.served_seconds.{method}", dt)
        if segment is not None:
            segment.finish(record=False)
            resp[TRACE_SPANS_KEY] = [s.to_wire() for s in segment.spans]
        try:
            with wlock:
                send_frame(conn, codec.pack(resp))
        except (ConnectionError, OSError):
            pass

    # Optional pre-dispatch hook: (method, args) -> None, raising to
    # reject. The cluster layer uses it to re-authorize cross-region
    # requests regardless of whether they arrive in-process or over the
    # fabric socket.
    precheck = None

    def dispatch_local(self, method: str, args):
        """Resolve `Endpoint.method` and invoke it (also used in-process to
        skip the socket for self-calls, like the reference's
        server.RPC fast path)."""
        if self.precheck is not None:
            self.precheck(method, args)
        try:
            name, meth = method.split(".", 1)
        except ValueError:
            raise ValueError(f"malformed rpc method {method!r}")
        endpoint = self._endpoints.get(name)
        if endpoint is None:
            raise ValueError(f"unknown rpc endpoint {name!r}")
        if meth.startswith("_"):
            raise ValueError(f"invalid rpc method {method!r}")
        fn = getattr(endpoint, meth, None)
        if fn is None or not callable(fn):
            raise ValueError(f"unknown rpc method {method!r}")
        return fn(args)

    def _handle_stream_conn(self, conn: socket.socket) -> None:
        session = StreamSession(conn)
        header = session.recv(timeout_s=30)
        method = header.get("method", "")
        handler = self._stream_handlers.get(method)
        if handler is None:
            session.send({"error": f"unknown stream method {method!r}"})
            session.close()
            return
        session.send({"ok": True})
        handler(session, header)
