"""RPC client: pooled, pipelined connections.

Reference: helper/pool/pool.go — one pooled session per remote address,
many in-flight requests multiplexed over it (the reference uses yamux
streams; here, pipelined frames matched by sequence number), with
connection rundown on error and a streaming-connection escape hatch.
"""

from __future__ import annotations

import itertools
import logging
import socket
import threading
import time
from typing import Optional

from .. import codec, metrics, trace
from .. import faultplane
from .keyring import ensure_keyring
from .server import StreamSession
from .wire import (
    BYTE_RPC,
    BYTE_STREAMING,
    SRC_KEY,
    TRACE_KEY,
    TRACE_SPANS_KEY,
    recv_frame,
    send_frame,
)

logger = logging.getLogger("nomad_tpu.rpc")


class RPCError(Exception):
    """A handler-side error string carried back over the wire."""


class AuthFailedError(ConnectionError):
    """The peer rejected our secret at the connection preamble. Nothing
    pipelined behind the preamble was ever dispatched (the server
    authenticates BEFORE its request loop), so `request_sent` is False:
    callers may redial and re-send blindly — the pool does, re-reading
    its keyring so a rotated secret takes effect without a restart."""

    def __init__(self, msg: str = "permission denied: bad rpc secret"):
        super().__init__(msg)
        self.request_sent = False


class _Conn:
    """One pipelined connection: writer = any caller thread (locked),
    reader = dedicated thread demuxing responses by seq."""

    def __init__(
        self, addr: tuple[str, int], connect_timeout_s: float,
        secret: str = "", tls_context=None, src: str = "",
    ) -> None:
        # source-identity stamp for every request on this connection
        # (wire.SRC_KEY): the dialing pool's owner label, so the peer
        # can attribute served seconds to us (clusterobs.py)
        self._src = src
        self.sock = socket.create_connection(addr, timeout=connect_timeout_s)
        if tls_context is not None:
            self.sock = tls_context.wrap_socket(
                self.sock, server_hostname=addr[0]
            )
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self.sock.settimeout(None)
        self.sock.sendall(bytes([BYTE_RPC]))
        if secret:
            send_frame(self.sock, secret.encode())
        self._wlock = threading.Lock()
        self._seq = itertools.count()
        self._pending: dict[int, dict] = {}
        self._pending_lock = threading.Lock()
        self.dead = False
        # set by the reader when the peer answers the preamble with an
        # auth reject (rotated secret): pending + future calls fail
        # with AuthFailedError instead of a generic dead-conn error
        self.auth_failed = False
        self._reader = threading.Thread(
            target=self._read_loop, name="rpc-conn-reader", daemon=True
        )
        self._reader.start()

    def _read_loop(self) -> None:
        try:
            while True:
                resp = codec.unpack(recv_frame(self.sock))
                if isinstance(resp, dict) and "auth_error" in resp:
                    # preamble reject (rpc/server.py _authenticate):
                    # the server dispatched nothing on this connection
                    self.auth_failed = True
                    return
                with self._pending_lock:
                    waiter = self._pending.pop(resp.get("seq"), None)
                if waiter is not None:
                    waiter["resp"] = resp
                    waiter["event"].set()
        except (ConnectionError, OSError):
            pass
        except Exception:
            logger.exception("rpc reader failed")
        finally:
            self.dead = True
            # Close our half immediately so the peer's port can leave
            # FIN_WAIT and be rebound (matters for fast server restarts).
            try:
                self.sock.close()
            except OSError:
                pass
            with self._pending_lock:
                pending, self._pending = self._pending, {}
            for waiter in pending.values():
                waiter["resp"] = (
                    {"error": "auth failed", "auth_error": True}
                    if self.auth_failed
                    else {"error": "connection closed"}
                )
                waiter["event"].set()

    def call(self, method: str, args, timeout_s: float):
        """Errors carry `request_sent`: False means the request never
        reached the peer (dead conn found up front, send failed — a
        partial frame is never dispatched), so a caller may re-send
        blindly; True means it WAS delivered and only the response is
        unaccounted for (timeout, conn died while waiting) — re-sending
        could double-apply a non-idempotent write."""
        seq = next(self._seq)
        waiter = {"event": threading.Event(), "resp": None}
        with self._pending_lock:
            if self.dead:
                if self.auth_failed:
                    raise AuthFailedError()
                err = ConnectionError("connection closed")
                err.request_sent = False
                raise err
            self._pending[seq] = waiter
        # Trace propagation (wire.py TRACE_KEY): when the calling thread
        # carries a trace, the request envelope forwards its context and
        # the response brings the remote segment's spans home.
        tctx = trace.current()
        rpc_span = None
        if tctx is not None:
            rpc_span = tctx.start_span("rpc.call", method=method)
        # the span must end on EVERY exit (a codec TypeError included) or
        # it stays open on this thread's active-span stack and mis-parents
        # everything the thread records afterwards
        try:
            try:
                req = {"seq": seq, "method": method, "args": args}
                if self._src:
                    req[SRC_KEY] = self._src
                if tctx is not None:
                    req[TRACE_KEY] = trace.wire_ref(tctx, rpc_span)
                payload = codec.pack(req)
                with self._wlock:
                    send_frame(self.sock, payload)
            except (ConnectionError, OSError) as e:
                with self._pending_lock:
                    self._pending.pop(seq, None)
                self.dead = True
                e.request_sent = False
                raise
            ok = waiter["event"].wait(timeout_s)
        finally:
            if rpc_span is not None:
                tctx.end_span(rpc_span)
        if not ok:
            with self._pending_lock:
                self._pending.pop(seq, None)
            err = TimeoutError(f"rpc {method} timed out after {timeout_s}s")
            err.request_sent = True
            raise err
        resp = waiter["resp"]
        if tctx is not None and resp.get(TRACE_SPANS_KEY):
            tctx.merge_remote(resp[TRACE_SPANS_KEY], rpc_span)
        if "error" in resp:
            if resp.get("auth_error"):
                raise AuthFailedError()
            if resp["error"] == "connection closed":
                err = ConnectionError("connection closed")
                err.request_sent = True  # delivered; the reply was lost
                raise err
            raise RPCError(resp["error"])
        return resp.get("result")

    def close(self) -> None:
        self.dead = True
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self.sock.close()


class ConnPool:
    """Pooled RPC connections keyed by address (reference helper/pool)."""

    def __init__(self, connect_timeout_s: float = 5.0, secret="",
                 tls_context=None) -> None:
        self._conns: dict[tuple[str, int], _Conn] = {}
        self._lock = threading.Lock()
        self._connect_timeout_s = connect_timeout_s
        # Single-flight dial tracking: addr -> Event set when the
        # in-flight dial to that peer resolves. Callers that find a
        # flight in progress queue behind it instead of stacking their
        # own TCP/TLS handshakes against a peer that is likely down.
        self._dials: dict[tuple[str, int], threading.Event] = {}
        self._dial_waiters = 0
        # Dual-accept keyring (rpc/keyring.py): the CURRENT secret is
        # read at every dial, never cached per-connection state — a
        # rotation pushed via SIGHUP takes effect on the next redial
        # without restarting the process. A plain string gets a private
        # keyring; the agent passes its shared instance.
        self.keyring = ensure_keyring(secret)
        self.tls_context = tls_context  # ssl client ctx — fabric TLS
        # Fault-plane identity: the owning node's label (ClusterServer
        # sets its node_id) so injected partitions can match this pool's
        # outbound calls. Empty = an unlabeled client pool.
        self.owner = ""

    @property
    def secret(self) -> str:
        """The current dial secret (legacy accessor — prefer sharing
        the keyring itself so rotation propagates)."""
        return self.keyring.current

    def call(
        self,
        addr: tuple[str, int],
        method: str,
        args=None,
        timeout_s: float = 30.0,
        retries: int = 1,
    ):
        """Invoke `Endpoint.method` at addr. One automatic retry on a dead
        pooled connection (the reference's pool does the same rundown +
        redial) — but ONLY when the request provably never reached the
        peer (`request_sent` False): re-sending a delivered request
        whose response was lost could double-apply a non-idempotent
        write (at-most-once at this layer; idempotent or
        leaderless-classified retries happen above, retry.py)."""
        addr = (addr[0], addr[1])
        last_err: Optional[Exception] = None
        # per-method latency as the CALLER saw it — redial retries
        # included (that stall is real caller-visible latency). Method
        # names are the closed Endpoint.method set, so cardinality is
        # bounded.
        t0 = time.perf_counter()
        try:
            attempts = retries + 1
            use_previous = False
            while attempts > 0:
                attempts -= 1
                conn = self._get(addr, use_previous=use_previous)
                try:
                    # Fault plane (faultplane.py): injected drops/
                    # delays/partitions act here, inside the attempt, so
                    # they ride the SAME rundown + redial path a real
                    # network failure does — a times=1 drop is absorbed
                    # by the pool's retry exactly like a transient blip,
                    # while a persistent partition fails every attempt.
                    # No-op unless a plane is installed.
                    if faultplane.plane is not None:
                        faultplane.plane.on_rpc_call(self.owner, addr, method)
                    return conn.call(method, args, timeout_s)
                except AuthFailedError as e:
                    last_err = e
                    self._drop(addr, conn)
                    # The peer rejected the secret this dial presented
                    # (nothing was dispatched — safe to re-send). One
                    # extra attempt presents the PREVIOUS secret: during
                    # a staggered rotation a not-yet-rotated server
                    # still speaks the old one (dual-accept's mirror
                    # image, rpc/keyring.py module docs).
                    if not use_previous and self.keyring.previous_active():
                        use_previous = True
                        attempts += 1
                        metrics.incr("nomad.keyring.dial_fallback")
                        continue
                    raise
                except (ConnectionError, OSError) as e:
                    last_err = e
                    self._drop(addr, conn)
                    if getattr(e, "request_sent", False):
                        raise
            raise last_err  # type: ignore[misc]
        finally:
            metrics.observe(
                f"nomad.rpc.call_seconds.{method}",
                time.perf_counter() - t0,
            )

    def stream(
        self, addr: tuple[str, int], method: str, header: Optional[dict] = None
    ) -> StreamSession:
        """Open a dedicated streaming session (reference RpcStreaming).
        Same keyring discipline as call(): present the current secret,
        fall back to the previous one within the rotation window."""
        try:
            return self._stream_dial(addr, method, header,
                                     self.keyring.current)
        except AuthFailedError:
            prev = self.keyring.previous_active()
            if not prev:
                raise
            metrics.incr("nomad.keyring.dial_fallback")
            return self._stream_dial(addr, method, header, prev)

    def _stream_dial(
        self, addr: tuple[str, int], method: str,
        header: Optional[dict], secret: str,
    ) -> StreamSession:
        sock = socket.create_connection(addr, timeout=self._connect_timeout_s)
        if self.tls_context is not None:
            sock = self.tls_context.wrap_socket(
                sock, server_hostname=addr[0]
            )
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        sock.settimeout(None)
        sock.sendall(bytes([BYTE_STREAMING]))
        if secret:
            send_frame(sock, secret.encode())
        session = StreamSession(sock)
        hdr = dict(header or {})
        hdr["method"] = method
        session.send(hdr)
        ack = session.recv(timeout_s=30)
        if isinstance(ack, dict) and "auth_error" in ack:
            session.close()
            raise AuthFailedError()
        if "error" in ack:
            session.close()
            raise RPCError(ack["error"])
        return session

    def _get(self, addr: tuple[str, int], use_previous: bool = False) -> _Conn:
        """Pooled conn for addr, dialing at most ONCE per peer at a time.

        The seed dialed inside the pool-wide lock: during a reconnect
        storm every RPC thread whose pooled conn died lined up on the
        lock while ONE of them sat in a 5s connect timeout — to ANY
        peer. Dials now run outside the lock (other peers' traffic is
        unaffected) and are single-flight per addr: concurrent callers
        queue behind the in-flight dial (``nomad.rpc.dial_queue_depth``)
        and adopt its result instead of stacking handshakes against a
        peer that is likely down.
        """
        while True:
            dial_flight: Optional[threading.Event] = None
            waiting = False
            with self._lock:
                conn = self._conns.get(addr)
                if conn is not None and not conn.dead:
                    return conn
                # rotation-window fallback dials present the PREVIOUS
                # secret — never share a flight keyed to the current one
                if not use_previous:
                    flight = self._dials.get(addr)
                    if flight is not None:
                        waiting = True
                        self._dial_waiters += 1
                        depth = self._dial_waiters
                    else:
                        dial_flight = threading.Event()
                        self._dials[addr] = dial_flight
                # dial-time secret read: rotation propagates to every
                # redial without pool (or process) restarts
                secret = (
                    self.keyring.previous_active()
                    if use_previous
                    else self.keyring.current
                )
            if waiting:
                metrics.set_gauge("nomad.rpc.dial_queue_depth", depth)
                flight.wait(self._connect_timeout_s + 1.0)
                with self._lock:
                    self._dial_waiters -= 1
                    depth = self._dial_waiters
                metrics.set_gauge("nomad.rpc.dial_queue_depth", depth)
                continue  # adopt the dialed conn, or take over the flight
            try:
                conn = _Conn(addr, self._connect_timeout_s, secret,
                             tls_context=self.tls_context, src=self.owner)
            except BaseException:
                if dial_flight is not None:
                    with self._lock:
                        if self._dials.get(addr) is dial_flight:
                            del self._dials[addr]
                    dial_flight.set()  # waiters retry (and fail) promptly
                raise
            with self._lock:
                stale = self._conns.get(addr)
                self._conns[addr] = conn
                if dial_flight is not None and self._dials.get(addr) is dial_flight:
                    del self._dials[addr]
            if dial_flight is not None:
                dial_flight.set()
            if stale is not None and stale is not conn:
                stale.close()
            return conn

    def _drop(self, addr: tuple[str, int], conn: _Conn) -> None:
        with self._lock:
            if self._conns.get(addr) is conn:
                del self._conns[addr]
        conn.close()

    def shutdown(self) -> None:
        with self._lock:
            conns = list(self._conns.values())
            self._conns.clear()
        for c in conns:
            c.close()
