"""Fabric TLS: encrypt server<->server and server<->client RPC.

Reference: nomad/rpc.go (rpcTLS / tlsutil.Config) — the fabric listener
multiplexes a TLS-wrapped byte stream when `tls { rpc = true }`; with a
ca_file both directions verify peer certificates (the reference's
verify_incoming/verify_outgoing mTLS posture). Certificates are
IP/host-agnostic here (check_hostname off) because fabric peers are
addressed by gossip-advertised IPs, matching the reference's
verify_server_hostname=false default.
"""

from __future__ import annotations

import ssl


def fabric_contexts(
    cert_file: str, key_file: str, ca_file: str = ""
) -> tuple[ssl.SSLContext, ssl.SSLContext]:
    """Build the (server_side, client_side) contexts every fabric socket
    shares. With ca_file: full mTLS — servers require client certs and
    dialers verify the presented chain. Without: encryption only
    (dev-mode, analogous to verify_incoming/outgoing = false)."""
    server = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    server.load_cert_chain(cert_file, key_file)
    client = client_context(ca_file, cert_file, key_file)
    if ca_file:
        server.load_verify_locations(ca_file)
        server.verify_mode = ssl.CERT_REQUIRED
    return server, client


def client_context(
    ca_file: str = "", cert_file: str = "", key_file: str = ""
) -> ssl.SSLContext:
    """Dialer-side context alone — for tools (alloc exec) that talk TO
    a TLS fabric without being fabric members. Cert/key optional: an
    encryption-only fabric (no ca_file server-side) accepts cert-less
    dialers; an mTLS fabric requires them."""
    client = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
    client.check_hostname = False
    if cert_file:
        # present identity when we have one: mTLS servers demand it,
        # harmless otherwise
        client.load_cert_chain(cert_file, key_file)
    if ca_file:
        client.load_verify_locations(ca_file)
        client.verify_mode = ssl.CERT_REQUIRED
    else:
        client.verify_mode = ssl.CERT_NONE
    return client
