"""Jobspec DSL (reference: jobspec/ + jobspec2/)."""

from .hcl import Body, HCLParseError, parse, parse_duration
from .parse import JobspecError, parse_job

__all__ = [
    "Body",
    "HCLParseError",
    "JobspecError",
    "parse",
    "parse_duration",
    "parse_job",
]
