"""HCL2-subset parser (no third-party deps).

Reference: jobspec2/ parses the job DSL with hashicorp/hcl/v2; this is a
from-scratch subset covering what jobspecs actually use:

  * attributes `key = expr` and blocks `type "label" ... { body }`
  * strings with escapes and `${var.name}` interpolation, heredocs
  * numbers, bools, null, lists, objects
  * line (`#`, `//`) and block (`/* */`) comments
  * `variable "name" { default = ... }` declarations with caller
    overrides (the jobspec2 variables feature)

Expressions are data-only: a `${...}` may reference `var.<name>` or
`meta.<name>`-style dotted names resolved from the caller-supplied
variable map. Function calls/conditionals are out of scope (jobspec2
supports them; almost no real jobspec uses them).
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Any, Optional


class HCLParseError(Exception):
    def __init__(self, msg: str, line: int) -> None:
        super().__init__(f"line {line}: {msg}")
        self.line = line


@dataclass
class Attr:
    key: str
    value: Any
    line: int


@dataclass
class Block:
    type: str
    labels: list[str]
    body: "Body"
    line: int


@dataclass
class Body:
    items: list = field(default_factory=list)

    def attrs(self) -> dict[str, Any]:
        return {i.key: i.value for i in self.items if isinstance(i, Attr)}

    def blocks(self, btype: Optional[str] = None) -> list[Block]:
        out = [i for i in self.items if isinstance(i, Block)]
        if btype is not None:
            out = [b for b in out if b.type == btype]
        return out

    def block(self, btype: str) -> Optional[Block]:
        bs = self.blocks(btype)
        return bs[0] if bs else None


# Sentinel for `${...}` references resolved at evaluation time.
@dataclass
class Ref:
    path: str  # e.g. "var.region"
    line: int


@dataclass
class Template:
    """A string with interpolation parts: list of str | Ref."""

    parts: list


_TOKEN_RE = re.compile(
    r"""
    (?P<ws>[ \t\r]+)
  | (?P<comment>\#[^\n]*|//[^\n]*|/\*.*?\*/)
  | (?P<nl>\n)
  | (?P<heredoc><<-?(?P<htag>[A-Za-z_][A-Za-z0-9_]*)\n)
  | (?P<num>-?\d+(\.\d+)?(?![A-Za-z_]))
  | (?P<ident>[A-Za-z_][A-Za-z0-9_.-]*)
  | (?P<string>")
  | (?P<punct>[{}\[\]=,:()])
    """,
    re.VERBOSE | re.DOTALL,
)


class _Lexer:
    def __init__(self, src: str) -> None:
        self.src = src
        self.pos = 0
        self.line = 1
        self.tokens: list[tuple[str, Any, int]] = []
        self._lex()
        self.i = 0

    def _lex(self) -> None:
        src = self.src
        while self.pos < len(src):
            m = _TOKEN_RE.match(src, self.pos)
            if m is None:
                raise HCLParseError(
                    f"unexpected character {src[self.pos]!r}", self.line
                )
            kind = m.lastgroup
            if kind == "htag":
                kind = "heredoc"
            text = m.group(0)
            if kind == "ws":
                pass
            elif kind == "comment":
                self.line += text.count("\n")
            elif kind == "nl":
                self.tokens.append(("nl", None, self.line))
                self.line += 1
            elif kind == "heredoc":
                self.pos = m.end()
                self._lex_heredoc(m.group("htag"), text.startswith("<<-"))
                continue
            elif kind == "num":
                n = float(text) if "." in text else int(text)
                self.tokens.append(("num", n, self.line))
            elif kind == "ident":
                self.tokens.append(("ident", text, self.line))
            elif kind == "string":
                self.pos = m.end()
                self._lex_string()
                continue
            else:
                self.tokens.append(("punct", text, self.line))
            self.pos = m.end()
        self.tokens.append(("eof", None, self.line))

    def _lex_heredoc(self, tag: str, indent: bool) -> None:
        self.line += 1
        lines = []
        while True:
            end = self.src.find("\n", self.pos)
            if end == -1:
                raise HCLParseError(f"unterminated heredoc {tag}", self.line)
            ln = self.src[self.pos : end]
            self.pos = end + 1
            self.line += 1
            if ln.strip() == tag:
                break
            lines.append(ln)
        if indent and lines:
            pad = min(
                (len(l) - len(l.lstrip()) for l in lines if l.strip()),
                default=0,
            )
            lines = [l[pad:] for l in lines]
        self.tokens.append(("str", "\n".join(lines) + "\n", self.line))

    def _lex_string(self) -> None:
        """From after the opening quote: handle escapes + ${...}."""
        parts: list = []
        buf: list[str] = []
        src = self.src
        while True:
            if self.pos >= len(src):
                raise HCLParseError("unterminated string", self.line)
            ch = src[self.pos]
            if ch == '"':
                self.pos += 1
                break
            if ch == "\\":
                esc = src[self.pos + 1 : self.pos + 2]
                buf.append(
                    {"n": "\n", "t": "\t", '"': '"', "\\": "\\"}.get(esc, esc)
                )
                self.pos += 2
                continue
            if ch == "$" and src[self.pos + 1 : self.pos + 2] == "{":
                end = src.find("}", self.pos)
                if end == -1:
                    raise HCLParseError("unterminated interpolation", self.line)
                expr = src[self.pos + 2 : end].strip()
                if buf:
                    parts.append("".join(buf))
                    buf = []
                parts.append(Ref(expr, self.line))
                self.pos = end + 1
                continue
            if ch == "\n":
                self.line += 1
            buf.append(ch)
            self.pos += 1
        if buf or not parts:
            parts.append("".join(buf))
        if len(parts) == 1 and isinstance(parts[0], str):
            self.tokens.append(("str", parts[0], self.line))
        else:
            self.tokens.append(("str", Template(parts), self.line))

    # -- token stream --------------------------------------------------

    def peek(self, skip_nl: bool = True):
        i = self.i
        while skip_nl and self.tokens[i][0] == "nl":
            i += 1
        return self.tokens[i]

    def next(self, skip_nl: bool = True):
        while skip_nl and self.tokens[self.i][0] == "nl":
            self.i += 1
        tok = self.tokens[self.i]
        self.i += 1
        return tok

    def expect_punct(self, p: str):
        kind, val, line = self.next()
        if kind != "punct" or val != p:
            raise HCLParseError(f"expected {p!r}, got {val!r}", line)


def _parse_body(lx: _Lexer, outermost: bool = False) -> Body:
    body = Body()
    while True:
        kind, val, line = lx.peek()
        if kind == "eof":
            if not outermost:
                raise HCLParseError("unexpected EOF in block", line)
            return body
        if kind == "punct" and val == "}":
            lx.next()
            return body
        if kind != "ident" and kind != "str":
            raise HCLParseError(f"expected identifier, got {val!r}", line)
        name = lx.next()[1]
        # attribute or block?
        kind2, val2, line2 = lx.peek()
        if kind2 == "punct" and val2 == "=":
            lx.next()
            body.items.append(Attr(name, _parse_expr(lx), line))
            continue
        labels: list[str] = []
        while True:
            kind2, val2, line2 = lx.peek()
            if kind2 in ("str", "ident"):
                labels.append(lx.next()[1])
                continue
            break
        lx.expect_punct("{")
        body.items.append(Block(name, labels, _parse_body(lx), line))


def _parse_expr(lx: _Lexer):
    kind, val, line = lx.next()
    if kind in ("num", "str"):
        return val
    if kind == "ident":
        if val == "true":
            return True
        if val == "false":
            return False
        if val == "null":
            return None
        return Ref(val, line)  # bare reference, e.g. var.count
    if kind == "punct" and val == "[":
        items = []
        while True:
            k, v, l = lx.peek()
            if k == "punct" and v == "]":
                lx.next()
                return items
            items.append(_parse_expr(lx))
            k, v, l = lx.peek()
            if k == "punct" and v == ",":
                lx.next()
    if kind == "punct" and val == "{":
        obj = {}
        while True:
            k, v, l = lx.peek()
            if k == "punct" and v == "}":
                lx.next()
                return obj
            key = lx.next()
            if key[0] not in ("ident", "str"):
                raise HCLParseError(f"bad object key {key[1]!r}", l)
            sep = lx.next()
            if sep[0] != "punct" or sep[1] not in ("=", ":"):
                raise HCLParseError("expected = or : in object", l)
            obj[key[1]] = _parse_expr(lx)
            k, v, l = lx.peek()
            if k == "punct" and v == ",":
                lx.next()
    raise HCLParseError(f"unexpected token {val!r}", line)


def _resolve(value, variables: dict):
    """Evaluate Refs/Templates against the variable map. Non-`var.`
    references (`${attr.kernel.name}`, `${node.datacenter}`, `${meta.x}`,
    `${env "X"}`-style) are RUNTIME interpolations — the scheduler and
    taskenv resolve them later — so they pass through as literal
    `${...}` text, exactly like the reference jobspec."""
    if isinstance(value, Ref):
        return _lookup(value.path, variables, value.line)
    if isinstance(value, Template):
        out = []
        for p in value.parts:
            if isinstance(p, Ref):
                v = _lookup(p.path, variables, p.line)
                out.append(v if isinstance(v, str) else str(v))
            else:
                out.append(p)
        return "".join(out)
    if isinstance(value, list):
        return [_resolve(v, variables) for v in value]
    if isinstance(value, dict):
        return {k: _resolve(v, variables) for k, v in value.items()}
    return value


def _lookup(path: str, variables: dict, line: int):
    parts = path.split(".")
    if parts[0] != "var":
        return "${" + path + "}"  # runtime interpolation: pass through
    parts = parts[1:]
    cur: Any = variables
    for p in parts:
        if isinstance(cur, dict) and p in cur:
            cur = cur[p]
        else:
            raise HCLParseError(f"unknown variable {path!r}", line)
    return cur


def parse(src: str, variables: Optional[dict] = None) -> Body:
    """Parse HCL source; resolve `variable` blocks + interpolation."""
    lx = _Lexer(src)
    body = _parse_body(lx, outermost=True)
    # collect variable defaults (jobspec2 Variables)
    var_map: dict[str, Any] = {}
    rest = Body()
    for item in body.items:
        if isinstance(item, Block) and item.type == "variable":
            name = item.labels[0] if item.labels else ""
            var_map[name] = _resolve(item.body.attrs().get("default"), {})
        else:
            rest.items.append(item)
    var_map.update(variables or {})
    return _resolve_body(rest, var_map)


def _resolve_body(body: Body, variables: dict) -> Body:
    out = Body()
    for item in body.items:
        if isinstance(item, Attr):
            out.items.append(
                Attr(item.key, _resolve(item.value, variables), item.line)
            )
        else:
            out.items.append(
                Block(
                    item.type,
                    [
                        _resolve(l, variables) if not isinstance(l, str) else l
                        for l in item.labels
                    ],
                    _resolve_body(item.body, variables),
                    item.line,
                )
            )
    return out


def parse_duration(v) -> float:
    """'30s' / '5m' / '1h' / '250ms' / bare number → seconds."""
    if isinstance(v, (int, float)):
        return float(v)
    s = str(v).strip()
    m = re.fullmatch(r"(\d+(?:\.\d+)?)(ms|s|m|h|d)?", s)
    if m is None:
        raise ValueError(f"bad duration {v!r}")
    n = float(m.group(1))
    unit = m.group(2) or "s"
    return n * {"ms": 0.001, "s": 1.0, "m": 60.0, "h": 3600.0, "d": 86400.0}[unit]
