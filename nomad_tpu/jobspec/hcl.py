"""HCL2-subset parser (no third-party deps).

Reference: jobspec2/ parses the job DSL with hashicorp/hcl/v2; this is a
from-scratch subset covering what jobspecs actually use:

  * attributes `key = expr` and blocks `type "label" ... { body }`
  * strings with escapes and `${var.name}` interpolation, heredocs
  * numbers, bools, null, lists, objects
  * line (`#`, `//`) and block (`/* */`) comments
  * `variable "name" { default = ... }` declarations with caller
    overrides (the jobspec2 variables feature; NOMAD_VAR_* env between
    defaults and explicit -var, with type conversion to the default)
  * `locals { ... }` evaluated in declaration order against vars
  * the HCL2 expression layer: function calls (~30 stdlib functions),
    arithmetic/comparison/logic operators, `cond ? a : b`, indexing
  * `dynamic "type" { for_each / iterator / labels / content }` blocks

Runtime references (`${attr.x}`, `${meta.x}`, `${node.x}`) pass through
as literal text only when BARE — using one inside an expression is an
error, since it resolves at placement/task time, after evaluation.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Any, Optional


class HCLParseError(Exception):
    def __init__(self, msg: str, line: int) -> None:
        super().__init__(f"line {line}: {msg}")
        self.line = line


@dataclass
class Attr:
    key: str
    value: Any
    line: int


@dataclass
class Block:
    type: str
    labels: list[str]
    body: "Body"
    line: int


@dataclass
class Body:
    items: list = field(default_factory=list)

    def attrs(self) -> dict[str, Any]:
        return {i.key: i.value for i in self.items if isinstance(i, Attr)}

    def blocks(self, btype: Optional[str] = None) -> list[Block]:
        out = [i for i in self.items if isinstance(i, Block)]
        if btype is not None:
            out = [b for b in out if b.type == btype]
        return out

    def block(self, btype: str) -> Optional[Block]:
        bs = self.blocks(btype)
        return bs[0] if bs else None


class RuntimePassthrough(str):
    """A `${...}` reference deferred to runtime (scheduler/taskenv).
    Legal as a whole attr value or template part; ILLEGAL inside an
    expression, where it would silently compute on the literal text."""


# Sentinel for `${...}` references resolved at evaluation time.
@dataclass
class Ref:
    path: str  # e.g. "var.region"
    line: int


@dataclass
class Template:
    """A string with interpolation parts: list of str | Ref | expr."""

    parts: list


# Expression AST (the jobspec2/HCL2 expression subset: functions,
# operators, conditionals — reference jobspec2/parse.go + hcl/v2).
@dataclass
class Call:
    fn: str
    args: list
    line: int


@dataclass
class BinOp:
    op: str
    left: Any
    right: Any
    line: int


@dataclass
class Unary:
    op: str  # "-" | "!"
    operand: Any
    line: int


@dataclass
class Cond:
    cond: Any
    then: Any
    other: Any
    line: int


@dataclass
class Index:
    obj: Any
    key: Any
    line: int


_SIMPLE_REF_RE = re.compile(r"[A-Za-z_][A-Za-z0-9_.-]*")


def _match_brace(src: str, open_pos: int, line: int) -> int:
    """Index of the '}' matching src[open_pos]=='{', honoring nested
    braces and string literals."""
    depth = 0
    i = open_pos
    in_str = False
    while i < len(src):
        ch = src[i]
        # Inner strings appear either bare (`"a"`, HCL2 template style)
        # or outer-escaped (`\"a\"`); both toggle string state.
        if ch == "\\" and src[i + 1 : i + 2] == '"':
            in_str = not in_str
            i += 2
            continue
        if in_str:
            if ch == "\\":
                i += 2
                continue
            if ch == '"':
                in_str = False
        elif ch == '"':
            in_str = True
        elif ch == "{":
            depth += 1
        elif ch == "}":
            depth -= 1
            if depth == 0:
                return i
        i += 1
    raise HCLParseError("unterminated interpolation", line)


_TOKEN_RE = re.compile(
    r"""
    (?P<ws>[ \t\r]+)
  | (?P<comment>\#[^\n]*|//[^\n]*|/\*.*?\*/)
  | (?P<nl>\n)
  | (?P<heredoc><<-?(?P<htag>[A-Za-z_][A-Za-z0-9_]*)\n)
  | (?P<num>\d+(\.\d+)?(?![A-Za-z_]))
  | (?P<ident>[A-Za-z_][A-Za-z0-9_.-]*)
  | (?P<string>")
  | (?P<punct>==|!=|<=|>=|&&|\|\||[{}\[\]=,:()?<>!+\-*/%])
    """,
    re.VERBOSE | re.DOTALL,
)


class _Lexer:
    def __init__(self, src: str) -> None:
        self.src = src
        self.pos = 0
        self.line = 1
        self.tokens: list[tuple[str, Any, int]] = []
        self._lex()
        self.i = 0

    def _lex(self) -> None:
        src = self.src
        while self.pos < len(src):
            m = _TOKEN_RE.match(src, self.pos)
            if m is None:
                raise HCLParseError(
                    f"unexpected character {src[self.pos]!r}", self.line
                )
            kind = m.lastgroup
            if kind == "htag":
                kind = "heredoc"
            text = m.group(0)
            if kind == "ws":
                pass
            elif kind == "comment":
                self.line += text.count("\n")
            elif kind == "nl":
                self.tokens.append(("nl", None, self.line))
                self.line += 1
            elif kind == "heredoc":
                self.pos = m.end()
                self._lex_heredoc(m.group("htag"), text.startswith("<<-"))
                continue
            elif kind == "num":
                n = float(text) if "." in text else int(text)
                self.tokens.append(("num", n, self.line))
            elif kind == "ident":
                self.tokens.append(("ident", text, self.line))
            elif kind == "string":
                self.pos = m.end()
                self._lex_string()
                continue
            else:
                self.tokens.append(("punct", text, self.line))
            self.pos = m.end()
        self.tokens.append(("eof", None, self.line))

    def _lex_heredoc(self, tag: str, indent: bool) -> None:
        self.line += 1
        lines = []
        while True:
            end = self.src.find("\n", self.pos)
            if end == -1:
                raise HCLParseError(f"unterminated heredoc {tag}", self.line)
            ln = self.src[self.pos : end]
            self.pos = end + 1
            self.line += 1
            if ln.strip() == tag:
                break
            lines.append(ln)
        if indent and lines:
            pad = min(
                (len(l) - len(l.lstrip()) for l in lines if l.strip()),
                default=0,
            )
            lines = [l[pad:] for l in lines]
        self.tokens.append(("str", "\n".join(lines) + "\n", self.line))

    def _lex_string(self) -> None:
        """From after the opening quote: handle escapes + ${...}."""
        parts: list = []
        buf: list[str] = []
        src = self.src
        while True:
            if self.pos >= len(src):
                raise HCLParseError("unterminated string", self.line)
            ch = src[self.pos]
            if ch == '"':
                self.pos += 1
                break
            if ch == "\\":
                esc = src[self.pos + 1 : self.pos + 2]
                buf.append(
                    {"n": "\n", "t": "\t", '"': '"', "\\": "\\"}.get(esc, esc)
                )
                self.pos += 2
                continue
            if ch == "$" and src[self.pos + 1 : self.pos + 2] == "{":
                end = _match_brace(src, self.pos + 1, self.line)
                expr = src[self.pos + 2 : end].strip()
                if buf:
                    parts.append("".join(buf))
                    buf = []
                # simple dotted path stays a Ref (runtime refs like
                # ${attr.cpu} pass through); anything else is a full
                # expression parsed by the sub-lexer
                if _SIMPLE_REF_RE.fullmatch(expr):
                    parts.append(Ref(expr, self.line))
                else:
                    # outer-escaped inner quotes normalize to bare for
                    # the sub-parse
                    sub = _Lexer(expr.replace('\\"', '"'))
                    node = _parse_expr(sub)
                    k, v, l = sub.peek()
                    if k != "eof":
                        raise HCLParseError(
                            f"trailing {v!r} in interpolation", self.line
                        )
                    parts.append(node)
                self.pos = end + 1
                continue
            if ch == "\n":
                self.line += 1
            buf.append(ch)
            self.pos += 1
        if buf or not parts:
            parts.append("".join(buf))
        if len(parts) == 1 and isinstance(parts[0], str):
            self.tokens.append(("str", parts[0], self.line))
        else:
            self.tokens.append(("str", Template(parts), self.line))

    # -- token stream --------------------------------------------------

    def peek(self, skip_nl: bool = True):
        i = self.i
        while skip_nl and self.tokens[i][0] == "nl":
            i += 1
        return self.tokens[i]

    def next(self, skip_nl: bool = True):
        while skip_nl and self.tokens[self.i][0] == "nl":
            self.i += 1
        tok = self.tokens[self.i]
        self.i += 1
        return tok

    def expect_punct(self, p: str):
        kind, val, line = self.next()
        if kind != "punct" or val != p:
            raise HCLParseError(f"expected {p!r}, got {val!r}", line)


def _parse_body(lx: _Lexer, outermost: bool = False) -> Body:
    body = Body()
    while True:
        kind, val, line = lx.peek()
        if kind == "eof":
            if not outermost:
                raise HCLParseError("unexpected EOF in block", line)
            return body
        if kind == "punct" and val == "}":
            lx.next()
            return body
        if kind != "ident" and kind != "str":
            raise HCLParseError(f"expected identifier, got {val!r}", line)
        name = lx.next()[1]
        # attribute or block?
        kind2, val2, line2 = lx.peek()
        if kind2 == "punct" and val2 == "=":
            lx.next()
            body.items.append(Attr(name, _parse_expr(lx), line))
            continue
        labels: list[str] = []
        while True:
            kind2, val2, line2 = lx.peek()
            if kind2 in ("str", "ident"):
                labels.append(lx.next()[1])
                continue
            break
        lx.expect_punct("{")
        body.items.append(Block(name, labels, _parse_body(lx), line))


def _parse_expr(lx: _Lexer):
    """Full expression: ternary over binary operators over primaries
    (the HCL2 expression subset jobspec2 exposes)."""
    return _parse_ternary(lx)


def _parse_ternary(lx: _Lexer):
    cond = _parse_or(lx)
    k, v, line = lx.peek()
    if k == "punct" and v == "?":
        lx.next()
        then = _parse_ternary(lx)
        kk, vv, ll = lx.next()
        if kk != "punct" or vv != ":":
            raise HCLParseError(f"expected ':' in conditional, got {vv!r}", ll)
        other = _parse_ternary(lx)
        return Cond(cond, then, other, line)
    return cond


def _parse_binop(lx, ops, next_level):
    left = next_level(lx)
    while True:
        k, v, line = lx.peek()
        if k == "punct" and v in ops:
            lx.next()
            left = BinOp(v, left, next_level(lx), line)
        else:
            return left


def _parse_or(lx):
    return _parse_binop(lx, ("||",), _parse_and)


def _parse_and(lx):
    return _parse_binop(lx, ("&&",), _parse_cmp)


def _parse_cmp(lx):
    return _parse_binop(
        lx, ("==", "!=", "<", "<=", ">", ">="), _parse_add
    )


def _parse_add(lx):
    return _parse_binop(lx, ("+", "-"), _parse_mul)


def _parse_mul(lx):
    return _parse_binop(lx, ("*", "/", "%"), _parse_unary)


def _parse_unary(lx):
    k, v, line = lx.peek()
    if k == "punct" and v in ("-", "!"):
        lx.next()
        return Unary(v, _parse_unary(lx), line)
    return _parse_postfix(lx)


def _parse_postfix(lx):
    node = _parse_primary(lx)
    while True:
        k, v, line = lx.peek(skip_nl=False)
        if k == "punct" and v == "[":
            lx.next()
            key = _parse_expr(lx)
            kk, vv, ll = lx.next()
            if kk != "punct" or vv != "]":
                raise HCLParseError(f"expected ']', got {vv!r}", ll)
            node = Index(node, key, line)
        else:
            return node


def _parse_primary(lx):
    kind, val, line = lx.next()
    if kind in ("num", "str"):
        return val
    if kind == "ident":
        if val == "true":
            return True
        if val == "false":
            return False
        if val == "null":
            return None
        # function call?
        k2, v2, l2 = lx.peek(skip_nl=False)
        if k2 == "punct" and v2 == "(":
            lx.next()
            args = []
            while True:
                k3, v3, l3 = lx.peek()
                if k3 == "punct" and v3 == ")":
                    lx.next()
                    break
                args.append(_parse_expr(lx))
                k3, v3, l3 = lx.peek()
                if k3 == "punct" and v3 == ",":
                    lx.next()
            return Call(val, args, line)
        return Ref(val, line)  # bare reference, e.g. var.count
    if kind == "punct" and val == "(":
        node = _parse_expr(lx)
        k2, v2, l2 = lx.next()
        if k2 != "punct" or v2 != ")":
            raise HCLParseError(f"expected ')', got {v2!r}", l2)
        return node
    if kind == "punct" and val == "[":
        items = []
        while True:
            k, v, l = lx.peek()
            if k == "punct" and v == "]":
                lx.next()
                return items
            items.append(_parse_expr(lx))
            k, v, l = lx.peek()
            if k == "punct" and v == ",":
                lx.next()
    if kind == "punct" and val == "{":
        obj = {}
        while True:
            k, v, l = lx.peek()
            if k == "punct" and v == "}":
                lx.next()
                return obj
            key = lx.next()
            if key[0] not in ("ident", "str"):
                raise HCLParseError(f"bad object key {key[1]!r}", l)
            sep = lx.next()
            if sep[0] != "punct" or sep[1] not in ("=", ":"):
                raise HCLParseError("expected = or : in object", l)
            obj[key[1]] = _parse_expr(lx)
            k, v, l = lx.peek()
            if k == "punct" and v == ",":
                lx.next()
    raise HCLParseError(f"unexpected token {val!r}", line)


def _resolve(value, variables: dict):
    """Evaluate expression nodes against the variable map. Non-`var.`/
    `local.` references (`${attr.kernel.name}`, `${node.datacenter}`,
    `${meta.x}`) are RUNTIME interpolations — the scheduler and taskenv
    resolve them later — so a bare Ref to one passes through as literal
    `${...}` text, exactly like the reference jobspec."""
    if isinstance(value, Ref):
        return _lookup(value.path, variables, value.line)
    if isinstance(value, Template):
        out = []
        for p in value.parts:
            if isinstance(p, str):
                out.append(p)
            else:
                v = _resolve(p, variables)
                if isinstance(v, bool):
                    v = "true" if v else "false"
                out.append(v if isinstance(v, str) else str(v))
        return "".join(out)
    if isinstance(value, Call):
        fn = _FUNCTIONS.get(value.fn)
        if fn is None:
            raise HCLParseError(f"unknown function {value.fn!r}", value.line)
        args = [_resolve(a, variables) for a in value.args]
        _no_runtime(args, value.line)
        try:
            return fn(*args)
        except HCLParseError:
            raise
        except Exception as e:
            raise HCLParseError(
                f"{value.fn}(...): {e}", value.line
            ) from e
    if isinstance(value, BinOp):
        left = _resolve(value.left, variables)
        _no_runtime([left], value.line)
        if value.op == "&&":
            if not left:
                return False
            right = _resolve(value.right, variables)
            _no_runtime([right], value.line)
            return bool(right)
        if value.op == "||":
            if left:
                return True
            right = _resolve(value.right, variables)
            _no_runtime([right], value.line)
            return bool(right)
        right = _resolve(value.right, variables)
        _no_runtime([right], value.line)
        try:
            if value.op == "==":
                return left == right
            if value.op == "!=":
                return left != right
            if value.op == "<":
                return left < right
            if value.op == "<=":
                return left <= right
            if value.op == ">":
                return left > right
            if value.op == ">=":
                return left >= right
            if value.op == "+":
                return left + right
            if value.op == "-":
                return left - right
            if value.op == "*":
                return left * right
            if value.op == "/":
                return left / right
            if value.op == "%":
                return left % right
        except TypeError as e:
            raise HCLParseError(
                f"operator {value.op!r}: {e}", value.line
            ) from e
        raise HCLParseError(f"unknown operator {value.op!r}", value.line)
    if isinstance(value, Unary):
        v = _resolve(value.operand, variables)
        _no_runtime([v], value.line)
        if value.op == "-":
            return -v
        return not v
    if isinstance(value, Cond):
        cond = _resolve(value.cond, variables)
        _no_runtime([cond], value.line)
        return (
            _resolve(value.then, variables)
            if cond
            else _resolve(value.other, variables)
        )
    if isinstance(value, Index):
        obj = _resolve(value.obj, variables)
        key = _resolve(value.key, variables)
        _no_runtime([obj, key], value.line)
        try:
            if isinstance(obj, list):
                return obj[int(key)]
            return obj[key]
        except (KeyError, IndexError, TypeError, ValueError) as e:
            raise HCLParseError(f"index {key!r}: {e}", value.line) from e
    if isinstance(value, list):
        return [_resolve(v, variables) for v in value]
    if isinstance(value, dict):
        return {k: _resolve(v, variables) for k, v in value.items()}
    return value


def _no_runtime(values, line: int) -> None:
    """Deep-scan for runtime passthroughs (containers included: a list
    element feeding join() is just as wrong as a direct operand)."""
    for v in values:
        if isinstance(v, list):
            _no_runtime(v, line)
            continue
        if isinstance(v, dict):
            _no_runtime(list(v.values()), line)
            continue
        if isinstance(v, RuntimePassthrough):
            raise HCLParseError(
                f"runtime reference {v} cannot be used inside an "
                f"expression — it resolves at placement/task time, after "
                f"the jobspec is evaluated; only a bare ${{...}} "
                f"interpolation may defer", line,
            )


def _lookup(path: str, variables: dict, line: int):
    parts = path.split(".")
    if parts[0] not in ("var", "local") and parts[0] not in variables.get(
        "__iterators__", ()
    ):
        # runtime interpolation: pass through as literal text
        return RuntimePassthrough("${" + path + "}")
    if parts[0] == "var":
        cur: Any = variables
        parts = parts[1:]
    elif parts[0] == "local":
        cur = variables.get("__locals__", {})
        parts = parts[1:]
    else:
        cur = variables["__iterators__"]
    for p in parts:
        if isinstance(cur, dict) and p in cur:
            cur = cur[p]
        else:
            raise HCLParseError(f"unknown variable {path!r}", line)
    return cur


# -- function table (reference: jobspec2/functions.go / go-cty stdlib) --

def _format(fmt, *args):
    # Go-style verbs → Python: %s %d %f %q cover real jobspecs
    out = []
    i = 0
    ai = 0
    while i < len(fmt):
        ch = fmt[i]
        if ch == "%" and i + 1 < len(fmt):
            verb = fmt[i + 1]
            if verb == "%":
                out.append("%")
            elif verb in "sdfvq":
                a = args[ai]
                ai += 1
                if verb == "d":
                    out.append(str(int(a)))
                elif verb == "f":
                    out.append(str(float(a)))
                elif verb == "q":
                    out.append('"%s"' % a)
                else:
                    out.append(
                        ("true" if a else "false")
                        if isinstance(a, bool)
                        else str(a)
                    )
            else:
                raise ValueError(f"unsupported format verb %{verb}")
            i += 2
            continue
        out.append(ch)
        i += 1
    return "".join(out)


_FUNCTIONS = {
    "upper": lambda s: str(s).upper(),
    "lower": lambda s: str(s).lower(),
    "title": lambda s: str(s).title(),
    "trimspace": lambda s: str(s).strip(),
    "format": _format,
    "replace": lambda s, a, b: str(s).replace(str(a), str(b)),
    "split": lambda sep, s: str(s).split(str(sep)),
    "join": lambda sep, xs: str(sep).join(str(x) for x in xs),
    "length": lambda x: len(x),
    "concat": lambda *ls: [x for l in ls for x in l],
    "contains": lambda xs, v: v in xs,
    "distinct": lambda xs: list(dict.fromkeys(xs)),
    "flatten": lambda xs: [
        y for x in xs for y in (x if isinstance(x, list) else [x])
    ],
    "compact": lambda xs: [x for x in xs if x not in ("", None)],
    "reverse": lambda xs: list(reversed(xs)),
    "sort": lambda xs: sorted(xs),
    "merge": lambda *ds: {k: v for d in ds for k, v in d.items()},
    "keys": lambda d: sorted(d.keys()),
    "values": lambda d: [d[k] for k in sorted(d.keys())],
    "lookup": lambda d, k, *default: d.get(k, default[0] if default else None),
    "min": lambda *xs: min(xs[0] if len(xs) == 1 else xs),
    "max": lambda *xs: max(xs[0] if len(xs) == 1 else xs),
    "abs": lambda x: abs(x),
    "floor": lambda x: int(__import__("math").floor(x)),
    "ceil": lambda x: int(__import__("math").ceil(x)),
    "range": lambda *a: list(range(*[int(x) for x in a])),
    "coalesce": lambda *xs: next(
        (x for x in xs if x not in (None, "")), None
    ),
    "tonumber": lambda x: float(x) if "." in str(x) else int(x),
    "tostring": lambda x: (
        ("true" if x else "false") if isinstance(x, bool) else str(x)
    ),
    "substr": lambda s, off, ln: str(s)[off : off + ln if ln >= 0 else None],
    "base64encode": lambda s: __import__("base64").b64encode(
        str(s).encode()
    ).decode(),
    "base64decode": lambda s: __import__("base64").b64decode(
        str(s)
    ).decode(),
    "regex_replace": lambda s, pat, rep: __import__("re").sub(
        pat, rep, str(s)
    ),
    "trimprefix": lambda s, p: (
        str(s)[len(p):] if str(s).startswith(p) else str(s)
    ),
    "trimsuffix": lambda s, p: (
        str(s)[: -len(p)] if p and str(s).endswith(p) else str(s)
    ),
}


def parse(src: str, variables: Optional[dict] = None) -> Body:
    """Parse HCL source; resolve `variable`/`locals` blocks, functions,
    conditionals, and dynamic blocks (the jobspec2 feature set).

    Variable precedence (reference jobspec2): defaults < NOMAD_VAR_*
    env < explicit `variables` (CLI -var)."""
    import os as _os

    lx = _Lexer(src)
    body = _parse_body(lx, outermost=True)
    # collect variable defaults (jobspec2 Variables)
    var_map: dict[str, Any] = {}
    locals_blocks: list[Body] = []
    rest = Body()
    for item in body.items:
        if isinstance(item, Block) and item.type == "variable":
            name = item.labels[0] if item.labels else ""
            var_map[name] = _resolve(item.body.attrs().get("default"), {})
        elif isinstance(item, Block) and item.type == "locals":
            locals_blocks.append(item.body)
        else:
            rest.items.append(item)
    defaults = dict(var_map)
    for name in list(var_map):
        env_val = _os.environ.get(f"NOMAD_VAR_{name}")
        if env_val is not None:
            var_map[name] = env_val
    var_map.update(variables or {})
    # CLI -var / env overrides arrive as strings: convert to the
    # default's type (the jobspec2 variable-type conversion)
    for name, val in list(var_map.items()):
        default = defaults.get(name)
        if not isinstance(val, str) or isinstance(default, str):
            continue
        try:
            if isinstance(default, bool):
                var_map[name] = val.lower() in ("1", "true", "yes")
            elif isinstance(default, int):
                var_map[name] = int(val)
            elif isinstance(default, float):
                var_map[name] = float(val)
        except ValueError:
            raise HCLParseError(
                f"variable {name!r}: cannot convert {val!r} to "
                f"{type(default).__name__}", 0,
            ) from None
    # locals may reference vars and earlier locals (reference: HCL2
    # evaluates locals in dependency order; declaration order suffices
    # for the jobspec2 subset)
    locals_map: dict[str, Any] = {}
    scope = dict(var_map)
    scope["__locals__"] = locals_map
    for lb in locals_blocks:
        for a in (i for i in lb.items if isinstance(i, Attr)):
            locals_map[a.key] = _resolve(a.value, scope)
    return _resolve_body(rest, scope)


def _expand_dynamic(block: Block, variables: dict) -> list[Block]:
    """dynamic "target" { for_each = ...; iterator = name;
    labels = [...]; content { ... } } → N target blocks (reference
    jobspec2 dynamic blocks / hcl2 dynblock)."""
    target = block.labels[0] if block.labels else ""
    attrs = block.body.attrs()
    if "for_each" not in attrs:
        raise HCLParseError(
            f'dynamic "{target}": missing for_each', block.line
        )
    for_each = _resolve(attrs["for_each"], variables)
    iterator = attrs.get("iterator") or target
    if isinstance(iterator, Ref):
        # `iterator = v` names the loop variable, it doesn't reference one
        iterator = iterator.path
    elif isinstance(iterator, Template):
        iterator = _resolve(iterator, variables)
    content = block.body.block("content")
    if content is None:
        raise HCLParseError(
            f'dynamic "{target}": missing content block', block.line
        )
    if isinstance(for_each, dict):
        pairs = list(for_each.items())
    elif isinstance(for_each, list):
        pairs = list(enumerate(for_each))
    else:
        raise HCLParseError(
            f'dynamic "{target}": for_each must be a list or map',
            block.line,
        )
    out: list[Block] = []
    for key, val in pairs:
        scope = dict(variables)
        iters = dict(scope.get("__iterators__", {}))
        iters[iterator] = {"key": key, "value": val}
        scope["__iterators__"] = iters
        labels = attrs.get("labels", [])
        labels = [
            x if isinstance(x, str) else str(x)
            for x in (_resolve(labels, scope) or [])
        ]
        out.append(
            Block(target, labels, _resolve_body(content.body, scope),
                  block.line)
        )
    return out


def _resolve_body(body: Body, variables: dict) -> Body:
    out = Body()
    for item in body.items:
        if isinstance(item, Attr):
            out.items.append(
                Attr(item.key, _resolve(item.value, variables), item.line)
            )
        elif item.type == "dynamic":
            out.items.extend(_expand_dynamic(item, variables))
        else:
            out.items.append(
                Block(
                    item.type,
                    [
                        _resolve(l, variables) if not isinstance(l, str) else l
                        for l in item.labels
                    ],
                    _resolve_body(item.body, variables),
                    item.line,
                )
            )
    return out


def parse_duration(v) -> float:
    """'30s' / '5m' / '1h' / '250ms' / bare number → seconds."""
    if isinstance(v, (int, float)):
        return float(v)
    s = str(v).strip()
    m = re.fullmatch(r"(\d+(?:\.\d+)?)(ms|s|m|h|d)?", s)
    if m is None:
        raise ValueError(f"bad duration {v!r}")
    n = float(m.group(1))
    unit = m.group(2) or "s"
    return n * {"ms": 0.001, "s": 1.0, "m": 60.0, "h": 3600.0, "d": 86400.0}[unit]
