"""Jobspec stanza mapping: HCL body → Job struct.

Reference: jobspec/parse.go + parse_job.go / parse_group.go /
parse_task.go (5,330 LoC of hand-rolled mapstructure); same stanza
vocabulary here, mapped onto the TPU-native structs.
"""

from __future__ import annotations

from typing import Optional

from ..structs.structs import (
    Affinity,
    Constraint,
    EphemeralDisk,
    Job,
    LogConfig,
    MigrateStrategy,
    NetworkResource,
    ParameterizedJobConfig,
    PeriodicConfig,
    Port,
    ReschedulePolicy,
    Resources,
    RestartPolicy,
    ScalingPolicy,
    Service,
    Spread,
    SpreadTarget,
    Task,
    TaskArtifact,
    TaskGroup,
    TaskLifecycleConfig,
    Template,
    UpdateStrategy,
    VolumeMount,
    VolumeRequest,
    RequestedDevice,
)
from .hcl import Block, Body, HCLParseError, parse, parse_duration


class JobspecError(Exception):
    pass


def parse_job(src: str, variables: Optional[dict] = None) -> Job:
    """Parse an HCL jobspec into a Job (reference jobspec2.Parse)."""
    body = parse(src, variables)
    jb = body.block("job")
    if jb is None:
        raise JobspecError("no job block found")
    return _job(jb)


def _job(b: Block) -> Job:
    a = b.body.attrs()
    job = Job(
        id=b.labels[0] if b.labels else a.get("id", ""),
        name=a.get("name", b.labels[0] if b.labels else ""),
        namespace=a.get("namespace", "default"),
        region=a.get("region", "global"),
        type=a.get("type", "service"),
        priority=int(a.get("priority", 50)),
        all_at_once=bool(a.get("all_at_once", False)),
        datacenters=list(a.get("datacenters", ["dc1"])),
        meta={k: str(v) for k, v in a.get("meta", {}).items()},
    )
    mb = b.body.block("meta")
    if mb is not None:
        job.meta.update({k: str(v) for k, v in mb.body.attrs().items()})
    job.constraints = [_constraint(c) for c in b.body.blocks("constraint")]
    job.affinities = [_affinity(c) for c in b.body.blocks("affinity")]
    job.spreads = [_spread(c) for c in b.body.blocks("spread")]
    ub = b.body.block("update")
    if ub is not None:
        job.update = _update(ub)
    pb = b.body.block("periodic")
    if pb is not None:
        job.periodic = _periodic(pb)
    qb = b.body.block("parameterized")
    if qb is not None:
        job.parameterized = _parameterized(qb)
    groups = b.body.blocks("group")
    if groups:
        job.task_groups = [_group(g, job) for g in groups]
    else:
        # task directly under job: implicit group of the same name
        # (reference jobspec behavior)
        tasks = b.body.blocks("task")
        if tasks:
            tg = TaskGroup(name=job.id, count=1, tasks=[_task(t) for t in tasks])
            job.task_groups = [tg]
    if not job.task_groups:
        raise JobspecError(f"job {job.id!r} has no groups or tasks")
    return job


def _group(b: Block, job: Job) -> TaskGroup:
    a = b.body.attrs()
    tg = TaskGroup(
        name=b.labels[0] if b.labels else "",
        count=int(a.get("count", 1)),
        meta={k: str(v) for k, v in a.get("meta", {}).items()},
    )
    mb = b.body.block("meta")
    if mb is not None:
        tg.meta.update({k: str(v) for k, v in mb.body.attrs().items()})
    tg.constraints = [_constraint(c) for c in b.body.blocks("constraint")]
    tg.affinities = [_affinity(c) for c in b.body.blocks("affinity")]
    tg.spreads = [_spread(c) for c in b.body.blocks("spread")]
    rb = b.body.block("restart")
    if rb is not None:
        tg.restart_policy = _restart(rb)
    sb = b.body.block("reschedule")
    if sb is not None:
        tg.reschedule_policy = _reschedule(sb)
    ub = b.body.block("update")
    if ub is not None:
        tg.update = _update(ub)
    mb2 = b.body.block("migrate")
    if mb2 is not None:
        tg.migrate = _migrate(mb2)
    eb = b.body.block("ephemeral_disk")
    if eb is not None:
        ea = eb.body.attrs()
        tg.ephemeral_disk = EphemeralDisk(
            sticky=bool(ea.get("sticky", False)),
            size_mb=int(ea.get("size", 300)),
            migrate=bool(ea.get("migrate", False)),
        )
    nb = b.body.block("network")
    if nb is not None:
        tg.networks = [_network(nb)]
    for vb in b.body.blocks("volume"):
        va = vb.body.attrs()
        tg.volumes[vb.labels[0] if vb.labels else ""] = VolumeRequest(
            name=vb.labels[0] if vb.labels else "",
            type=va.get("type", "host"),
            source=va.get("source", ""),
            read_only=bool(va.get("read_only", False)),
            access_mode=va.get("access_mode", ""),
            attachment_mode=va.get("attachment_mode", ""),
            per_alloc=bool(va.get("per_alloc", False)),
        )
    scb = b.body.block("scaling")
    if scb is not None:
        sca = scb.body.attrs()
        pol = {}
        pb2 = scb.body.block("policy")
        if pb2 is not None:
            # the policy is OPAQUE autoscaler config: round-trip nested
            # blocks (check/strategy stanzas) as nested dicts, not just
            # top-level attrs
            pol = _config_dict(pb2.body)
        tg.scaling = ScalingPolicy(
            type=sca.get("type", "horizontal"),
            min=int(sca.get("min", 0)),
            max=int(sca.get("max", 0)),
            enabled=bool(sca.get("enabled", True)),
            policy=pol,
        )
    for sb2 in b.body.blocks("service"):
        tg.services.append(_service(sb2))
    tg.tasks = [_task(t) for t in b.body.blocks("task")]
    sd = a.get("shutdown_delay")
    if sd is not None:
        tg.shutdown_delay_s = parse_duration(sd)
    return tg


def _task(b: Block) -> Task:
    a = b.body.attrs()
    task = Task(
        name=b.labels[0] if b.labels else "",
        driver=a.get("driver", "mock"),
        user=a.get("user", ""),
        leader=bool(a.get("leader", False)),
        kill_signal=a.get("kill_signal", ""),
        meta={k: str(v) for k, v in a.get("meta", {}).items()},
    )
    cb = b.body.block("config")
    if cb is not None:
        task.config = _config_dict(cb.body)
    eb = b.body.block("env")
    if eb is not None:
        task.env = {k: str(v) for k, v in eb.body.attrs().items()}
    mb = b.body.block("meta")
    if mb is not None:
        task.meta.update({k: str(v) for k, v in mb.body.attrs().items()})
    rb = b.body.block("resources")
    if rb is not None:
        task.resources = _resources(rb)
    task.constraints = [_constraint(c) for c in b.body.blocks("constraint")]
    task.affinities = [_affinity(c) for c in b.body.blocks("affinity")]
    vb = b.body.block("vault")
    if vb is not None:
        va = vb.body.attrs()
        task.vault = {
            "policies": [str(x) for x in va.get("policies", [])],
            "env": bool(va.get("env", True)),
        }
    for vm in b.body.blocks("volume_mount"):
        vma = vm.body.attrs()
        task.volume_mounts.append(
            VolumeMount(
                volume=vma.get("volume", ""),
                destination=vma.get("destination", ""),
                read_only=bool(vma.get("read_only", False)),
                propagation_mode=vma.get("propagation_mode", "private"),
            )
        )
    for ab in b.body.blocks("artifact"):
        aa = ab.body.attrs()
        opts = {}
        ob = ab.body.block("options")
        if ob is not None:
            opts = {k: str(v) for k, v in ob.body.attrs().items()}
        task.artifacts.append(
            TaskArtifact(
                getter_source=aa.get("source", ""),
                getter_options=opts,
                getter_mode=aa.get("mode", "any"),
                relative_dest=aa.get("destination", "local/"),
            )
        )
    for tb in b.body.blocks("template"):
        ta = tb.body.attrs()
        task.templates.append(
            Template(
                source_path=ta.get("source", ""),
                dest_path=ta.get("destination", ""),
                embedded_tmpl=ta.get("data", ""),
                change_mode=ta.get("change_mode", "restart"),
                change_signal=ta.get("change_signal", ""),
                splay_s=parse_duration(ta.get("splay", "5s")),
                perms=str(ta.get("perms", "0644")),
            )
        )
    lb = b.body.block("logs")
    if lb is not None:
        la = lb.body.attrs()
        task.log_config = LogConfig(
            max_files=int(la.get("max_files", 10)),
            max_file_size_mb=int(la.get("max_file_size", 10)),
        )
    lcb = b.body.block("lifecycle")
    if lcb is not None:
        la = lcb.body.attrs()
        task.lifecycle = TaskLifecycleConfig(
            hook=la.get("hook", ""), sidecar=bool(la.get("sidecar", False))
        )
    for sb in b.body.blocks("service"):
        task.services.append(_service(sb))
    kt = a.get("kill_timeout")
    if kt is not None:
        task.kill_timeout_s = parse_duration(kt)
    sdd = a.get("shutdown_delay")
    if sdd is not None:
        task.shutdown_delay_s = parse_duration(sdd)
    return task


def _config_dict(body: Body) -> dict:
    out = dict(body.attrs())
    for blk in body.blocks():
        out.setdefault(blk.type, []).append(_config_dict(blk.body))
    return out


def _resources(b: Block) -> Resources:
    a = b.body.attrs()
    res = Resources(
        cpu=int(a.get("cpu", 100)),
        memory_mb=int(a.get("memory", 300)),
        memory_max_mb=int(a.get("memory_max", 0)),
        disk_mb=int(a.get("disk", 0)),
        cores=int(a.get("cores", 0)),
    )
    nb = b.body.block("network")
    if nb is not None:
        res.networks = [_network(nb)]
    for db in b.body.blocks("device"):
        da = db.body.attrs()
        res.devices.append(
            RequestedDevice(
                name=db.labels[0] if db.labels else "",
                count=int(da.get("count", 1)),
                constraints=[
                    _constraint(c) for c in db.body.blocks("constraint")
                ],
                affinities=[_affinity(c) for c in db.body.blocks("affinity")],
            )
        )
    return res


def _network(b: Block) -> NetworkResource:
    a = b.body.attrs()
    net = NetworkResource(
        mode=a.get("mode", "host"), mbits=int(a.get("mbits", 0))
    )
    for pb in b.body.blocks("port"):
        pa = pb.body.attrs()
        label = pb.labels[0] if pb.labels else ""
        port = Port(
            label=label,
            value=int(pa.get("static", 0)),
            to=int(pa.get("to", 0)),
            host_network=pa.get("host_network", "default"),
        )
        if port.value:
            net.reserved_ports.append(port)
        else:
            net.dynamic_ports.append(port)
    return net


def _service(b: Block) -> Service:
    a = b.body.attrs()
    svc = Service(
        name=a.get("name", b.labels[0] if b.labels else ""),
        port_label=str(a.get("port", "")),
        tags=[str(t) for t in a.get("tags", [])],
        provider=a.get("provider", "builtin"),
    )
    conn = b.body.block("connect")
    if conn is not None:
        from ..structs.structs import Connect, ConnectUpstream, SidecarService

        c = Connect(native=bool(conn.body.attrs().get("native", False)))
        sb = conn.body.block("sidecar_service")
        if sb is not None:
            sc = SidecarService(port=str(sb.body.attrs().get("port", "")))
            pb = sb.body.block("proxy")
            if pb is not None:
                for ub in pb.body.blocks("upstreams"):
                    ua = ub.body.attrs()
                    sc.upstreams.append(
                        ConnectUpstream(
                            destination_name=str(
                                ua.get("destination_name", "")
                            ),
                            local_bind_port=int(
                                ua.get("local_bind_port", 0)
                            ),
                        )
                    )
            c.sidecar_service = sc
        svc.connect = c
    for cb in b.body.blocks("check"):
        ca = cb.body.attrs()
        check = {
            "name": ca.get("name", ""),
            "type": ca.get("type", "tcp"),
            "path": ca.get("path", ""),
            "interval_s": parse_duration(ca.get("interval", "10s")),
            "timeout_s": parse_duration(ca.get("timeout", "2s")),
        }
        if ca.get("task"):
            # group-service checks name the task that hosts script
            # execs / owns the restart (reference ServiceCheck.TaskName)
            check["task"] = str(ca["task"])
        if ca.get("command"):
            # script checks exec inside the task (reference
            # structs.go ServiceCheck Command/Args)
            check["command"] = str(ca["command"])
            check["args"] = [str(x) for x in ca.get("args", [])]
        for rb in cb.body.blocks("check_restart"):
            ra = rb.body.attrs()
            check["check_restart"] = {
                "limit": int(ra.get("limit", 0)),
                "grace_s": parse_duration(ra.get("grace", "1s")),
            }
        svc.checks.append(check)
    return svc


def _constraint(b: Block) -> Constraint:
    a = b.body.attrs()
    operand = a.get("operator", "=")
    # sugar: `distinct_hosts = true` / `distinct_property = "x"`
    if "distinct_hosts" in a:
        return Constraint(operand="distinct_hosts")
    if "distinct_property" in a:
        return Constraint(
            ltarget=str(a["distinct_property"]),
            rtarget=str(a.get("value", "")),
            operand="distinct_property",
        )
    return Constraint(
        ltarget=str(a.get("attribute", "")),
        rtarget=str(a.get("value", "")),
        operand=operand,
    )


def _affinity(b: Block) -> Affinity:
    a = b.body.attrs()
    return Affinity(
        ltarget=str(a.get("attribute", "")),
        rtarget=str(a.get("value", "")),
        operand=a.get("operator", "="),
        weight=int(a.get("weight", 50)),
    )


def _spread(b: Block) -> Spread:
    a = b.body.attrs()
    sp = Spread(
        attribute=str(a.get("attribute", "")), weight=int(a.get("weight", 50))
    )
    for tb in b.body.blocks("target"):
        ta = tb.body.attrs()
        sp.targets.append(
            SpreadTarget(
                value=tb.labels[0] if tb.labels else str(ta.get("value", "")),
                percent=int(ta.get("percent", 0)),
            )
        )
    return sp


def _update(b: Block) -> UpdateStrategy:
    a = b.body.attrs()
    u = UpdateStrategy(
        max_parallel=int(a.get("max_parallel", 1)),
        health_check=a.get("health_check", "checks"),
        auto_revert=bool(a.get("auto_revert", False)),
        auto_promote=bool(a.get("auto_promote", False)),
        canary=int(a.get("canary", 0)),
    )
    if "stagger" in a:
        u.stagger_s = parse_duration(a["stagger"])
    if "min_healthy_time" in a:
        u.min_healthy_time_s = parse_duration(a["min_healthy_time"])
    if "healthy_deadline" in a:
        u.healthy_deadline_s = parse_duration(a["healthy_deadline"])
    if "progress_deadline" in a:
        u.progress_deadline_s = parse_duration(a["progress_deadline"])
    return u


def _migrate(b: Block) -> MigrateStrategy:
    a = b.body.attrs()
    m = MigrateStrategy(
        max_parallel=int(a.get("max_parallel", 1)),
        health_check=a.get("health_check", "checks"),
    )
    if "min_healthy_time" in a:
        m.min_healthy_time_s = parse_duration(a["min_healthy_time"])
    if "healthy_deadline" in a:
        m.healthy_deadline_s = parse_duration(a["healthy_deadline"])
    return m


def _restart(b: Block) -> RestartPolicy:
    a = b.body.attrs()
    r = RestartPolicy(
        attempts=int(a.get("attempts", 2)),
        mode=a.get("mode", "fail"),
    )
    if "interval" in a:
        r.interval_s = parse_duration(a["interval"])
    if "delay" in a:
        r.delay_s = parse_duration(a["delay"])
    return r


def _reschedule(b: Block) -> ReschedulePolicy:
    a = b.body.attrs()
    r = ReschedulePolicy(
        attempts=int(a.get("attempts", 0)),
        delay_function=a.get("delay_function", "exponential"),
        unlimited=bool(a.get("unlimited", True)),
    )
    if "interval" in a:
        r.interval_s = parse_duration(a["interval"])
    if "delay" in a:
        r.delay_s = parse_duration(a["delay"])
    if "max_delay" in a:
        r.max_delay_s = parse_duration(a["max_delay"])
    return r


def _periodic(b: Block) -> PeriodicConfig:
    a = b.body.attrs()
    return PeriodicConfig(
        enabled=bool(a.get("enabled", True)),
        spec=a.get("cron", a.get("crons", "")),
        prohibit_overlap=bool(a.get("prohibit_overlap", False)),
        timezone=a.get("time_zone", "UTC"),
    )


def _parameterized(b: Block) -> ParameterizedJobConfig:
    a = b.body.attrs()
    return ParameterizedJobConfig(
        payload=a.get("payload", "optional"),
        meta_required=[str(m) for m in a.get("meta_required", [])],
        meta_optional=[str(m) for m in a.get("meta_optional", [])],
    )
