"""CPython GC tuning for the scheduling hot paths.

CPython's generational collector triggers every ~700 container
allocations and each young-gen pass walks survivors while the whole
multi-hundred-thousand-object cluster state (nodes, allocs, jobs) sits
in the older generations. Measured on the c2m benchmark shape, that is
~5us of pure GC overhead per minted Allocation — ~70% of the object's
construction cost — and it applies equally to the store's insert loop
and the reconciler's request minting.

The batch scheduler and plan applier therefore pause the collector for
the duration of one batch (a bounded, non-reentrant critical section)
and re-enable it on exit; servers additionally `freeze()` their
post-bootstrap heap so the long-lived cluster state is never rescanned.
This mirrors what the reference gets for free from Go's concurrent
collector (no stop-the-world young-gen scans proportional to live set)
and the gc.freeze() pattern CPython grew for exactly this shape of
workload (long-lived heap + high allocation rate).

The pause is reentrancy-safe: nested sections (solve inside plan apply
inside an agent request) keep the collector off until the outermost
exit, and a section never re-enables a collector the process had
disabled globally.
"""

from __future__ import annotations

import gc
import threading
import time
from contextlib import contextmanager

_lock = threading.Lock()
_depth = 0
_was_enabled = False
_section_t0 = 0

# Host-observability hook (nomad_tpu/hostobs.py sets this to its
# paused-section recorder when the profiler starts): called with the
# OUTERMOST section's duration in ns on exit. One attribute test when
# unset — the hot paths pay nothing until a profiler is attached. A
# long paused section is itself a signal: the re-enable pays one
# young-gen scan proportional to everything allocated inside it.
on_section_end = None


@contextmanager
def paused_gc(freeze_on_exit: bool = False):
    """Pause the cyclic collector for a bounded batch of allocations.

    The depth counter is process-wide (the collector is), so sections
    entered concurrently from scheduler workers and the plan applier
    coordinate under a lock: the collector comes back when the LAST
    section exits, and never if the process had it disabled globally.

    freeze_on_exit: when this section is the LAST one out (the flag is
    honored only at the outermost exit; a concurrent section still open
    elsewhere wins and the freeze is skipped), gc.freeze() right before
    re-enabling. A paused section only DEFERS the young-gen scan — the
    first collection after re-enable still walks everything the section
    allocated (a c2m cluster build is ~10^6 objects, and every
    registered gc callback — jax's included — runs against it).
    Freezing instead moves the section's survivors straight to the
    permanent generation: no scan ever happens, which is exactly right
    when the survivors ARE resident state (a built cluster, committed
    store rows). Dead temporaries still free by refcount, but CYCLES
    allocated inside the section are frozen forever — so this is for
    bounded-lifetime resident-heap bursts (the bench process), never
    for arbitrary scratch work in a long-lived server (production
    agents use freeze_resident_heap at warmup instead).
    """
    global _depth, _was_enabled, _section_t0
    with _lock:
        if _depth == 0:
            _was_enabled = gc.isenabled()
            gc.disable()
            _section_t0 = time.monotonic_ns()
        _depth += 1
    try:
        yield
    finally:
        with _lock:
            _depth -= 1
            last_out = _depth == 0
            if last_out:
                if freeze_on_exit:
                    gc.freeze()
                if _was_enabled:
                    gc.enable()
            dur_ns = (
                time.monotonic_ns() - _section_t0 if last_out else 0
            )
        if last_out and on_section_end is not None:
            on_section_end(dur_ns)


def freeze_startup_heap() -> None:
    """Move everything currently alive out of the collector's sight.

    Called by the agent after bootstrap (modules, config, stores built):
    the long-lived heap no longer participates in any generational scan,
    so steady-state collections only walk genuinely young objects.
    """
    gc.collect()
    gc.freeze()


def release_frozen_garbage() -> int:
    """Unfreeze, collect, re-freeze: reclaim CYCLES stranded in the
    permanent generation.

    Loops that rebuild a frozen resident heap (the bench's
    fresh-cluster passes: build, freeze, measure, drop, repeat) leak
    each dropped heap's cyclic residue — refcounting frees the acyclic
    bulk, but cycles sit frozen where no collection ever looks
    (measured: ~64MB/pass at c2m scale, unbounded). One
    unfreeze + full collect walks everything ONCE and re-freezes the
    true survivors; call it in the untimed gap between passes, never
    inside a measured section (the walk is proportional to the whole
    live heap). Returns the collected-object count."""
    gc.unfreeze()
    n = gc.collect()
    gc.freeze()
    return n


def freeze_resident_heap() -> int:
    """Re-freeze the CURRENT live heap (post-warmup form of
    freeze_startup_heap): after a server replays its log or a bench
    config builds its cluster, the resident store/log heap is orders of
    magnitude bigger than at bootstrap, and every collection that walks
    it also runs every registered gc callback — jax's _xla_gc_callback
    measured 16.5-17% of c2m wall before this. One collect + freeze
    moves the whole resident set into the permanent generation; later
    collections see only genuinely young objects. Safe to call
    repeatedly (freeze is additive); frozen objects still free by
    refcount — only CYCLES frozen here would outlive their heap, so
    callers freeze long-lived resident state, not per-batch scratch.
    Returns the frozen-object count for telemetry.
    """
    gc.collect()
    gc.freeze()
    return gc.get_freeze_count()
