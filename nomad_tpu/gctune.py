"""CPython GC tuning for the scheduling hot paths.

CPython's generational collector triggers every ~700 container
allocations and each young-gen pass walks survivors while the whole
multi-hundred-thousand-object cluster state (nodes, allocs, jobs) sits
in the older generations. Measured on the c2m benchmark shape, that is
~5us of pure GC overhead per minted Allocation — ~70% of the object's
construction cost — and it applies equally to the store's insert loop
and the reconciler's request minting.

The batch scheduler and plan applier therefore pause the collector for
the duration of one batch (a bounded, non-reentrant critical section)
and re-enable it on exit; servers additionally `freeze()` their
post-bootstrap heap so the long-lived cluster state is never rescanned.
This mirrors what the reference gets for free from Go's concurrent
collector (no stop-the-world young-gen scans proportional to live set)
and the gc.freeze() pattern CPython grew for exactly this shape of
workload (long-lived heap + high allocation rate).

The pause is reentrancy-safe: nested sections (solve inside plan apply
inside an agent request) keep the collector off until the outermost
exit, and a section never re-enables a collector the process had
disabled globally.
"""

from __future__ import annotations

import gc
import threading
import time
from contextlib import contextmanager

_lock = threading.Lock()
_depth = 0
_was_enabled = False
_section_t0 = 0

# Host-observability hook (nomad_tpu/hostobs.py sets this to its
# paused-section recorder when the profiler starts): called with the
# OUTERMOST section's duration in ns on exit. One attribute test when
# unset — the hot paths pay nothing until a profiler is attached. A
# long paused section is itself a signal: the re-enable pays one
# young-gen scan proportional to everything allocated inside it.
on_section_end = None


@contextmanager
def paused_gc():
    """Pause the cyclic collector for a bounded batch of allocations.

    The depth counter is process-wide (the collector is), so sections
    entered concurrently from scheduler workers and the plan applier
    coordinate under a lock: the collector comes back when the LAST
    section exits, and never if the process had it disabled globally.
    """
    global _depth, _was_enabled, _section_t0
    with _lock:
        if _depth == 0:
            _was_enabled = gc.isenabled()
            gc.disable()
            _section_t0 = time.monotonic_ns()
        _depth += 1
    try:
        yield
    finally:
        with _lock:
            _depth -= 1
            last_out = _depth == 0
            if last_out and _was_enabled:
                gc.enable()
            dur_ns = (
                time.monotonic_ns() - _section_t0 if last_out else 0
            )
        if last_out and on_section_end is not None:
            on_section_end(dur_ns)


def freeze_startup_heap() -> None:
    """Move everything currently alive out of the collector's sight.

    Called by the agent after bootstrap (modules, config, stores built):
    the long-lived heap no longer participates in any generational scan,
    so steady-state collections only walk genuinely young objects.
    """
    gc.collect()
    gc.freeze()
