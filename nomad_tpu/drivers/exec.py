"""Exec driver: isolated execution via the native C++ executor.

Reference: drivers/exec (852 LoC) — fork/exec under the shared executor
with cgroup isolation (libcontainer there; cgroup v2 best-effort here —
full namespace isolation needs root and is gated the same way the
reference gates on Linux capabilities). The executor daemonizes, so
tasks survive client-agent restarts and `recover_task` reconnects to the
executor's unix socket (reference RecoverTask → ReattachConfig).

Config keys: command (required), args, cgroup_v2 (bool, default auto).
"""

from __future__ import annotations

import os
import signal as _signal
import threading
from pathlib import Path
from typing import Any, Optional

from ..structs import now_ns
from .base import (
    TASK_STATE_EXITED,
    TASK_STATE_RUNNING,
    Driver,
    DriverError,
    ExitResult,
    Fingerprint,
    TaskConfig,
    TaskHandle,
    TaskStatus,
)
from .executor import ExecutorError, ExecutorHandle, executor_binary, launch_executor

CGROUP_ROOT = "/sys/fs/cgroup"


def _cgroup_available() -> bool:
    path = Path(CGROUP_ROOT)
    return (path / "cgroup.controllers").exists() and os.access(
        CGROUP_ROOT, os.W_OK
    )


class _ExecTask:
    def __init__(self, cfg: TaskConfig, handle: ExecutorHandle):
        self.cfg = cfg
        self.handle = handle


class ExecDriver(Driver):
    name = "exec"

    def __init__(self, chroot_env=None) -> None:
        # operator-configured {host_src: dst} chroot map (agent config)
        self.chroot_env = dict(chroot_env or {})
        self.tasks: dict[str, _ExecTask] = {}
        self._lock = threading.Lock()

    def fingerprint(self) -> Fingerprint:
        try:
            executor_binary()
        except ExecutorError as e:
            return Fingerprint(
                attributes={},
                health="unhealthy",
                health_description=str(e),
            )
        return Fingerprint(
            attributes={
                "driver.exec": "1",
                "driver.exec.cgroups": "1" if _cgroup_available() else "0",
            }
        )

    def start_task(self, cfg: TaskConfig) -> TaskHandle:
        from .configspec import EXEC_SPEC

        conf = EXEC_SPEC.validate(cfg.config, "exec")
        chroot = ""
        if self.chroot_env:
            # chroot sources are OPERATOR config (constructor), never
            # jobspec config — a job-chosen source map would let any
            # submitter hard-link arbitrary host files (/etc/shadow)
            # into a root-owned chroot. Reference: chroot_env is client
            # agent config for exactly this reason.
            if os.geteuid() != 0:
                raise DriverError(
                    "exec: chroot_env is configured but the agent is "
                    "not root — refusing to run without the requested "
                    "isolation"
                )
            from ..client.allocdir import build_chroot

            build_chroot(cfg.task_dir, self.chroot_env)
            chroot = cfg.task_dir
        command = conf.get("command")
        if not command:
            raise DriverError("exec: missing 'command' in task config")
        args = [str(a) for a in conf.get("args", [])]
        cgroup = ""
        if conf.get("cgroup_v2", True) and _cgroup_available():
            cgroup = f"{CGROUP_ROOT}/nomad-tpu-{cfg.id.replace('/', '-')}"
        try:
            handle = launch_executor(
                task_dir=cfg.task_dir or "/tmp",
                command=command,
                args=args,
                env=cfg.env,
                stdout_path=cfg.stdout_path,
                stderr_path=cfg.stderr_path,
                cwd="/" if chroot else cfg.task_dir,
                chroot=chroot,
                user=cfg.user,
                cgroup=cgroup,
                memory_max_bytes=(
                    cfg.resources_memory_max_mb or cfg.resources_memory_mb
                ) * 1024 * 1024,
                # cgroup v2 cpu.weight range 1..10000; map MHz shares
                cpu_weight=min(10000, max(1, cfg.resources_cpu // 10)) if cfg.resources_cpu else 0,
                cores=cfg.reserved_cores,
                # the executor enters the netns before chroot/privdrop
                netns=cfg.network_ns,
            )
        except ExecutorError as e:
            raise DriverError(f"exec: {e}") from e
        with self._lock:
            self.tasks[cfg.id] = _ExecTask(cfg, handle)
        return TaskHandle(
            cfg.id,
            self.name,
            {
                "socket_path": handle.socket_path,
                "daemon_pid": handle.daemon_pid,
                "task_name": cfg.name,
            },
        )

    def wait_task(
        self, task_id: str, timeout_s: Optional[float] = None
    ) -> Optional[ExitResult]:
        task = self._get(task_id)
        res = task.handle.wait(timeout_s=timeout_s)
        if res is None:
            return None
        return ExitResult(
            exit_code=res.get("exit_code", 0), signal=res.get("signal", 0)
        )

    def stop_task(self, task_id: str, timeout_s: float, signal: str = "") -> None:
        task = self._get(task_id)
        signo = (
            int(getattr(_signal, signal))
            if signal and hasattr(_signal, signal)
            else _signal.SIGTERM
        )
        try:
            task.handle.stop(grace_s=timeout_s, signo=int(signo))
            task.handle.wait(timeout_s=timeout_s + 5)
        except (ExecutorError, OSError):
            pass

    def destroy_task(self, task_id: str, force: bool = False) -> None:
        with self._lock:
            task = self.tasks.get(task_id)
        if task is None:
            return
        try:
            st = task.handle.status()
            if st.get("state") == "running":
                if not force:
                    raise DriverError("task still running")
                self.stop_task(task_id, timeout_s=2)
        except (ExecutorError, OSError):
            pass
        # ALWAYS attempt the supervisor shutdown — a failed status probe
        # must not leave the daemonized supervisor listening forever
        try:
            task.handle.shutdown()
        except (ExecutorError, OSError):
            pass
        with self._lock:
            self.tasks.pop(task_id, None)

    def inspect_task(self, task_id: str) -> TaskStatus:
        task = self._get(task_id)
        try:
            st = task.handle.status()
        except (ExecutorError, OSError):
            return TaskStatus(id=task_id, state="unknown")
        running = st.get("state") == "running"
        return TaskStatus(
            id=task_id,
            name=task.cfg.name,
            state=TASK_STATE_RUNNING if running else TASK_STATE_EXITED,
            started_at_ns=st.get("start_ns", 0),
            completed_at_ns=st.get("end_ns", 0),
            exit_result=None
            if running
            else ExitResult(
                exit_code=st.get("exit_code", 0), signal=st.get("signal", 0)
            ),
        )

    def task_stats(self, task_id: str) -> dict[str, Any]:
        task = self._get(task_id)
        try:
            s = task.handle.stats()
        except (ExecutorError, OSError):
            return {}
        hz = s.get("hz", 100) or 100
        return {
            "cpu_user_s": s.get("utime_ticks", 0) / hz,
            "cpu_system_s": s.get("stime_ticks", 0) / hz,
            "memory_rss_bytes": s.get("rss_bytes", 0),
            "memory_cgroup_bytes": s.get("cgroup_mem_bytes", -1),
        }

    def signal_task(self, task_id: str, signal: str) -> None:
        task = self._get(task_id)
        sig = getattr(_signal, signal, None)
        if sig is None:
            raise DriverError(f"unknown signal {signal}")
        task.handle.signal(int(sig))

    def exec_task_streaming(self, task_id: str, cmd: list[str], tty: bool = False):
        task = self._get(task_id)
        try:
            return task.handle.exec_stream(cmd, tty=tty)
        except (ExecutorError, OSError) as e:
            raise DriverError(f"exec: {e}") from e

    def exec_task(
        self, task_id: str, cmd: list[str], timeout_s: float = 30.0
    ) -> tuple[bytes, int]:
        """One-shot exec: run, collect output until EOF.

        The raw bridge carries no exit-status trailer, so the command is
        wrapped to append one (stripped before returning)."""
        import re as _re
        import shlex as _shlex
        import time as _time

        wrapped = [
            "/bin/sh",
            "-c",
            _shlex.join(cmd) + '; printf "\\n__NOMAD_EXIT:%d\\n" $?',
        ]
        sock = self.exec_task_streaming(task_id, wrapped, tty=False)
        out = b""
        sock.settimeout(timeout_s)
        deadline = _time.monotonic() + timeout_s
        timed_out = True
        try:
            while _time.monotonic() < deadline:
                try:
                    chunk = sock.recv(65536)
                except TimeoutError:
                    # socket.timeout: the deadline elapsed mid-recv. Must
                    # stay timed_out=True — it is an OSError subclass, and
                    # catching it below misreported timeouts as exit -1.
                    break
                except OSError:
                    timed_out = False
                    break
                if not chunk:
                    timed_out = False
                    break
                out += chunk
        finally:
            sock.close()
        m = _re.search(rb"\n__NOMAD_EXIT:(\d+)\n", out)
        if m:
            return out[: m.start()], int(m.group(1))
        return out, 124 if timed_out else -1

    def recover_task(self, handle: TaskHandle) -> None:
        """Reconnect to the surviving executor daemon."""
        sock = handle.state.get("socket_path")
        if not sock:
            raise DriverError("no socket_path in handle")
        eh = ExecutorHandle(sock, handle.state.get("daemon_pid", 0))
        if not eh.alive():
            raise DriverError("executor is gone")
        cfg = TaskConfig(id=handle.task_id, name=handle.state.get("task_name", ""))
        with self._lock:
            self.tasks[handle.task_id] = _ExecTask(cfg, eh)

    def _get(self, task_id: str) -> _ExecTask:
        with self._lock:
            task = self.tasks.get(task_id)
        if task is None:
            raise DriverError(f"unknown task {task_id}")
        return task
