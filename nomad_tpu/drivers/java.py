"""Java task driver.

Reference: drivers/java/driver.go — fingerprints the JVM and launches
`java [jvm_options] -jar <jar> [args]` (or a main class) through the
shared executor machinery. Here it layers on RawExecDriver exactly the
way the reference layers on the shared executor: the only java-specific
parts are the fingerprint and the command-line translation.
"""

from __future__ import annotations

import re
import shutil
import subprocess

from .base import (
    DriverError,
    Fingerprint,
    HEALTH_STATE_HEALTHY,
    HEALTH_STATE_UNDETECTED,
    TaskConfig,
    TaskHandle,
)
from .rawexec import RawExecDriver

_VERSION_RE = re.compile(r'version "([^"]+)"')


class JavaDriver(RawExecDriver):
    name = "java"

    def fingerprint(self) -> Fingerprint:
        java = shutil.which("java")
        if java is None:
            return Fingerprint(
                attributes={},
                health=HEALTH_STATE_UNDETECTED,
                health_description="java binary not found",
            )
        version = "unknown"
        try:
            out = subprocess.run(
                [java, "-version"], capture_output=True, timeout=10
            )
            m = _VERSION_RE.search(out.stderr.decode(errors="replace"))
            if m:
                version = m.group(1)
        except (OSError, subprocess.TimeoutExpired):
            pass
        return Fingerprint(
            attributes={
                "driver.java": "1",
                "driver.java.version": version,
            },
            health=HEALTH_STATE_HEALTHY,
        )

    def start_task(self, cfg: TaskConfig) -> TaskHandle:
        from .configspec import JAVA_SPEC

        conf = JAVA_SPEC.validate(cfg.config, "java")
        jar = conf.get("jar_path")
        main_class = conf.get("class")
        if not jar and not main_class:
            raise DriverError("java config requires 'jar_path' or 'class'")
        argv = ["java"]
        argv.extend(str(o) for o in conf.get("jvm_options") or [])
        if jar:
            argv.extend(["-jar", str(jar)])
        else:
            if conf.get("class_path"):
                argv.extend(["-cp", str(conf["class_path"])])
            argv.append(str(main_class))
        argv.extend(str(a) for a in conf.get("args") or [])
        translated = TaskConfig(
            id=cfg.id,
            name=cfg.name,
            alloc_id=cfg.alloc_id,
            env=cfg.env,
            config={"command": argv[0], "args": argv[1:]},
            resources_cpu=cfg.resources_cpu,
            resources_memory_mb=cfg.resources_memory_mb,
            resources_memory_max_mb=cfg.resources_memory_max_mb,
            task_dir=cfg.task_dir,
            stdout_path=cfg.stdout_path,
            stderr_path=cfg.stderr_path,
            user=cfg.user,
        )
        handle = super().start_task(translated)
        handle.driver = self.name
        return handle
