"""Mock driver: configurable fake for tests and fault injection.

Reference: drivers/mock (918 LoC) — start errors, run_for durations, exit
codes, signal errors, kill-after. Config keys (per task config dict):
  run_for          seconds the task "runs" ("0s"/float/str; default forever)
  exit_code        exit code when run_for elapses
  start_error      error string raised on start
  start_block_for  seconds start_task blocks before returning
  kill_after       seconds after which the task kills itself with exit 9
"""

from __future__ import annotations

import threading
import time
from typing import Any, Optional

from ..structs import now_ns
from .base import (
    Driver,
    DriverError,
    ExitResult,
    Fingerprint,
    TaskConfig,
    TaskHandle,
    TaskStatus,
    TASK_STATE_EXITED,
    TASK_STATE_RUNNING,
)


def _parse_duration(v) -> Optional[float]:
    if v is None:
        return None
    if isinstance(v, (int, float)):
        return float(v)
    s = str(v).strip()
    if s.endswith("ms"):
        return float(s[:-2]) / 1000.0
    if s.endswith("s"):
        return float(s[:-1])
    if s.endswith("m"):
        return float(s[:-1]) * 60
    if s.endswith("h"):
        return float(s[:-1]) * 3600
    return float(s)


class _MockTask:
    def __init__(self, cfg: TaskConfig):
        self.cfg = cfg
        self.started_at = now_ns()
        self.completed_at = 0
        self.exit_result: Optional[ExitResult] = None
        self.done = threading.Event()
        self.timer: Optional[threading.Timer] = None

    def finish(self, result: ExitResult) -> None:
        if self.done.is_set():
            return
        self.exit_result = result
        self.completed_at = now_ns()
        self.done.set()


class MockDriver(Driver):
    name = "mock"

    def __init__(self) -> None:
        self.tasks: dict[str, _MockTask] = {}
        self._lock = threading.Lock()

    def fingerprint(self) -> Fingerprint:
        return Fingerprint(attributes={"driver.mock": "1"})

    def start_task(self, cfg: TaskConfig) -> TaskHandle:
        conf = cfg.config
        if conf.get("start_error"):
            raise DriverError(str(conf["start_error"]))
        block = _parse_duration(conf.get("start_block_for"))
        if block:
            time.sleep(block)
        task = _MockTask(cfg)
        with self._lock:
            if cfg.id in self.tasks and not self.tasks[cfg.id].done.is_set():
                raise DriverError(f"task {cfg.id} already running")
            self.tasks[cfg.id] = task

        run_for = _parse_duration(conf.get("run_for"))
        kill_after = _parse_duration(conf.get("kill_after"))
        if run_for is not None:
            exit_code = int(conf.get("exit_code", 0))
            t = threading.Timer(
                run_for, task.finish, args=(ExitResult(exit_code=exit_code),)
            )
            t.daemon = True
            task.timer = t
            t.start()
        if kill_after is not None:
            t = threading.Timer(
                kill_after, task.finish, args=(ExitResult(exit_code=9, signal=9),)
            )
            t.daemon = True
            t.start()
        return TaskHandle(cfg.id, self.name, {"started_at": task.started_at})

    def wait_task(self, task_id: str, timeout_s: Optional[float] = None) -> Optional[ExitResult]:
        task = self._get(task_id)
        if not task.done.wait(timeout_s):
            return None
        return task.exit_result

    def stop_task(self, task_id: str, timeout_s: float, signal: str = "") -> None:
        task = self._get(task_id)
        task.finish(ExitResult(exit_code=0, signal=15))

    def destroy_task(self, task_id: str, force: bool = False) -> None:
        with self._lock:
            task = self.tasks.get(task_id)
            if task is None:
                return
            if not task.done.is_set():
                if not force:
                    raise DriverError("task still running")
                task.finish(ExitResult(exit_code=9, signal=9))
            del self.tasks[task_id]

    def inspect_task(self, task_id: str) -> TaskStatus:
        task = self._get(task_id)
        return TaskStatus(
            id=task_id,
            name=task.cfg.name,
            state=TASK_STATE_EXITED if task.done.is_set() else TASK_STATE_RUNNING,
            started_at_ns=task.started_at,
            completed_at_ns=task.completed_at,
            exit_result=task.exit_result,
        )

    def signal_task(self, task_id: str, signal: str) -> None:
        task = self._get(task_id)
        if task.cfg.config.get("signal_error"):
            raise DriverError(str(task.cfg.config["signal_error"]))

    def exec_task(self, task_id: str, cmd: list[str], timeout_s: float = 30.0) -> tuple[bytes, int]:
        self._get(task_id)
        return (" ".join(cmd)).encode() + b"\n", 0

    def exec_task_streaming(self, task_id: str, cmd: list[str], tty: bool = False):
        """Echo server standing in for a real exec session (tests)."""
        import socket as _socket

        self._get(task_id)
        parent, inner = _socket.socketpair()

        def _echo():
            try:
                inner.sendall((" ".join(cmd)).encode() + b"\n")
                while True:
                    data = inner.recv(4096)
                    if not data:
                        break
                    inner.sendall(data)
            except OSError:
                pass
            finally:
                inner.close()

        threading.Thread(
            target=_echo, name="mock-exec-echo", daemon=True
        ).start()
        return parent

    def recover_task(self, handle: TaskHandle) -> None:
        with self._lock:
            if handle.task_id in self.tasks:
                return
        raise DriverError("mock task lost on restart")

    def _get(self, task_id: str) -> _MockTask:
        with self._lock:
            task = self.tasks.get(task_id)
        if task is None:
            raise DriverError(f"unknown task {task_id}")
        return task
