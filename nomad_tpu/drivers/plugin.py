"""Out-of-process driver plugins.

Reference: plugins/ — hashicorp/go-plugin launches the plugin binary,
reads a handshake line on stdout, then talks gRPC
(plugins/drivers/proto/driver.proto; client/server wrappers in
plugins/drivers/{client,server}.go). TPU-native equivalent: the plugin
process hosts its driver on the framed-msgpack RPC fabric and prints

    NOMAD_TPU_PLUGIN|1|127.0.0.1:<port>

The parent connects via ConnPool and forwards the Driver verbs. The
plugin exits when its stdin closes (parent-death detection, exactly
go-plugin's behavior), so orphaned plugins never outlive the agent.

Run a plugin process with:
    python -m nomad_tpu.drivers.plugin my_module:MyDriverClass
"""

from __future__ import annotations

import sys
from typing import Any, Optional

from ..rpc import RPCServer
from .base import (
    Driver,
    DriverError,
    ExitResult,
    Fingerprint,
    TaskConfig,
    TaskHandle,
    TaskStatus,
)

HANDSHAKE_PREFIX = "NOMAD_TPU_PLUGIN|1|"


class DriverEndpoint:
    """RPC surface wrapping a concrete Driver (plugin side)."""

    def __init__(self, driver: Driver) -> None:
        self.driver = driver

    def fingerprint(self, args):
        return self.driver.fingerprint()

    def start_task(self, args):
        handle = self.driver.start_task(args["cfg"])
        return handle.to_dict()

    def wait_task(self, args):
        return self.driver.wait_task(args["task_id"], args.get("timeout_s"))

    def stop_task(self, args):
        self.driver.stop_task(
            args["task_id"], args["timeout_s"], args.get("signal", "")
        )

    def destroy_task(self, args):
        self.driver.destroy_task(args["task_id"], args.get("force", False))

    def inspect_task(self, args):
        return self.driver.inspect_task(args["task_id"])

    def task_stats(self, args):
        return self.driver.task_stats(args["task_id"])

    def signal_task(self, args):
        self.driver.signal_task(args["task_id"], args["signal"])

    def exec_task(self, args):
        out, code = self.driver.exec_task(
            args["task_id"], args["cmd"], args.get("timeout_s", 30.0)
        )
        return {"output": out, "code": code}

    def recover_task(self, args):
        self.driver.recover_task(TaskHandle.from_dict(args["handle"]))


def serve_plugin(driver: Driver) -> None:
    """Plugin-process main: host the driver, handshake, die with parent."""
    server = RPCServer(host="127.0.0.1", port=0)
    server.register("Driver", DriverEndpoint(driver))
    server.start()
    host, port = server.addr
    sys.stdout.write(f"{HANDSHAKE_PREFIX}{host}:{port}\n")
    sys.stdout.flush()
    # Block until the parent goes away (stdin EOF), then exit.
    try:
        while sys.stdin.readline():
            pass
    except (KeyboardInterrupt, OSError):
        pass
    server.shutdown()


class ExternalDriver(Driver):
    """Parent-side proxy speaking to a plugin process.

    `factory_ref` is "module.path:ClassName" resolved in the plugin
    process (reference: the plugin catalog's launcher config).
    """

    def __init__(self, name: str, factory_ref: str) -> None:
        from ..plugins.launcher import PluginProcess

        self.name = name
        self.factory_ref = factory_ref
        self._proc = PluginProcess(
            [sys.executable, "-m", "nomad_tpu.drivers.plugin", factory_ref],
            HANDSHAKE_PREFIX,
            DriverError,
        )

    # -- process lifecycle ---------------------------------------------

    def shutdown_plugin(self) -> None:
        self._proc.shutdown()

    def _call(self, method: str, args=None, timeout_s: float = 30.0):
        return self._proc.call(method, args, timeout_s=timeout_s)

    # -- Driver verbs --------------------------------------------------

    def fingerprint(self) -> Fingerprint:
        return self._call("Driver.fingerprint")

    def start_task(self, cfg: TaskConfig) -> TaskHandle:
        return TaskHandle.from_dict(self._call("Driver.start_task", {"cfg": cfg}))

    def wait_task(
        self, task_id: str, timeout_s: Optional[float] = None
    ) -> Optional[ExitResult]:
        rpc_timeout = (timeout_s + 10.0) if timeout_s is not None else 3600.0
        return self._call(
            "Driver.wait_task",
            {"task_id": task_id, "timeout_s": timeout_s},
            timeout_s=rpc_timeout,
        )

    def stop_task(self, task_id: str, timeout_s: float, signal: str = "") -> None:
        self._call(
            "Driver.stop_task",
            {"task_id": task_id, "timeout_s": timeout_s, "signal": signal},
            timeout_s=timeout_s + 15.0,
        )

    def destroy_task(self, task_id: str, force: bool = False) -> None:
        self._call("Driver.destroy_task", {"task_id": task_id, "force": force})

    def inspect_task(self, task_id: str) -> TaskStatus:
        return self._call("Driver.inspect_task", {"task_id": task_id})

    def task_stats(self, task_id: str) -> dict[str, Any]:
        return self._call("Driver.task_stats", {"task_id": task_id})

    def signal_task(self, task_id: str, signal: str) -> None:
        self._call("Driver.signal_task", {"task_id": task_id, "signal": signal})

    def exec_task(
        self, task_id: str, cmd: list[str], timeout_s: float = 30.0
    ) -> tuple[bytes, int]:
        out = self._call(
            "Driver.exec_task",
            {"task_id": task_id, "cmd": cmd, "timeout_s": timeout_s},
            timeout_s=timeout_s + 10.0,
        )
        return out["output"], out["code"]

    def recover_task(self, handle: TaskHandle) -> None:
        self._call("Driver.recover_task", {"handle": handle.to_dict()})


def _main() -> None:
    import importlib

    if len(sys.argv) != 2 or ":" not in sys.argv[1]:
        sys.stderr.write("usage: python -m nomad_tpu.drivers.plugin module:Class\n")
        sys.exit(2)
    mod_name, _, cls_name = sys.argv[1].partition(":")
    mod = importlib.import_module(mod_name)
    driver_cls = getattr(mod, cls_name)
    serve_plugin(driver_cls())


if __name__ == "__main__":
    _main()
