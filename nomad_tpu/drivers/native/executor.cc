// nomad-tpu native executor: out-of-process task supervisor.
//
// Reference: drivers/shared/executor (executor_linux.go — the
// libcontainer-backed process supervisor that outlives the client agent
// so tasks survive agent restarts, plus the gRPC control surface in
// proto/executor.proto). This is the TPU-native equivalent in C++:
//
//   * reads a tab-separated spec file (see Spec below);
//   * daemonizes (the task must NOT die with the client agent);
//   * forks the task into its own session/process-group, with optional
//     cgroup v2 placement (memory.max / cpu.weight, best-effort) and
//     optional setuid/setgid;
//   * serves a line protocol on a unix socket: status / wait / signal /
//     stop <grace_ms> / stats / shutdown — the Python driver reconnects
//     to the same socket after a client restart (RecoverTask).
//
// Protocol responses are single lines: "ok k=v k=v ..." or "err <msg>".
// Single-threaded poll(2) loop; "wait" parks the connection until the
// task exits (deferred response), so no threads are needed.

#include <algorithm>
#include <cerrno>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <ctime>
#include <fcntl.h>
#include <grp.h>
#include <poll.h>
#include <pwd.h>
#include <signal.h>
#include <string>
#include <sys/resource.h>
#include <sys/socket.h>
#include <sys/stat.h>
#include <sys/types.h>
#include <sys/un.h>
#include <sys/wait.h>
#include <unistd.h>
#include <vector>

struct Spec {
  std::string command;
  std::vector<std::string> args;
  std::vector<std::string> env;   // KEY=VAL
  std::string cwd;
  std::string stdout_path;
  std::string stderr_path;
  std::string socket_path;
  std::string pidfile;
  std::string cgroup;             // cgroup v2 dir to create/join
  long long memory_max = 0;       // bytes, 0 = unset
  int cpu_weight = 0;             // cgroup v2 cpu.weight, 0 = unset
  std::string user;
};

// Values are backslash-escaped by the launcher (\\ \n \r \t) so that
// job-controlled strings (env, args) can never inject spec directives.
static std::string unescape(const std::string &in) {
  std::string out;
  out.reserve(in.size());
  for (size_t i = 0; i < in.size(); i++) {
    if (in[i] != '\\' || i + 1 >= in.size()) {
      out.push_back(in[i]);
      continue;
    }
    char c = in[++i];
    switch (c) {
      case 'n': out.push_back('\n'); break;
      case 'r': out.push_back('\r'); break;
      case 't': out.push_back('\t'); break;
      case '\\': out.push_back('\\'); break;
      default: out.push_back(c); break;
    }
  }
  return out;
}

static bool read_spec(const char *path, Spec &s) {
  FILE *f = fopen(path, "r");
  if (!f) return false;
  char *line = nullptr;
  size_t cap = 0;
  ssize_t n;
  while ((n = getline(&line, &cap, f)) > 0) {
    if (line[n - 1] == '\n') line[n - 1] = '\0';
    char *tab = strchr(line, '\t');
    if (!tab) continue;
    *tab = '\0';
    std::string key = line, val = unescape(tab + 1);
    if (key == "command") s.command = val;
    else if (key == "arg") s.args.push_back(val);
    else if (key == "env") s.env.push_back(val);
    else if (key == "cwd") s.cwd = val;
    else if (key == "stdout") s.stdout_path = val;
    else if (key == "stderr") s.stderr_path = val;
    else if (key == "socket") s.socket_path = val;
    else if (key == "pidfile") s.pidfile = val;
    else if (key == "cgroup") s.cgroup = val;
    else if (key == "memory_max") s.memory_max = atoll(val.c_str());
    else if (key == "cpu_weight") s.cpu_weight = atoi(val.c_str());
    else if (key == "user") s.user = val;
  }
  free(line);
  fclose(f);
  return !s.command.empty() && !s.socket_path.empty();
}

static void write_file(const std::string &path, const std::string &val) {
  int fd = open(path.c_str(), O_WRONLY | O_CREAT | O_TRUNC, 0644);
  if (fd >= 0) {
    ssize_t r = write(fd, val.c_str(), val.size());
    (void)r;
    close(fd);
  }
}

// Best-effort cgroup v2 setup. Returns true if the task pid should be
// written into cgroup.procs (dir exists/writable).
static bool setup_cgroup(const Spec &s) {
  if (s.cgroup.empty()) return false;
  if (mkdir(s.cgroup.c_str(), 0755) != 0 && errno != EEXIST) return false;
  if (s.memory_max > 0)
    write_file(s.cgroup + "/memory.max", std::to_string(s.memory_max));
  if (s.cpu_weight > 0)
    write_file(s.cgroup + "/cpu.weight", std::to_string(s.cpu_weight));
  return true;
}

struct TaskState {
  pid_t pid = -1;
  bool exited = false;
  int exit_code = 0;
  int term_signal = 0;
  long long start_ns = 0;
  long long end_ns = 0;
};

static long long now_ns() {
  struct timespec ts;
  clock_gettime(CLOCK_REALTIME, &ts);
  return (long long)ts.tv_sec * 1000000000LL + ts.tv_nsec;
}

static pid_t spawn_task(const Spec &s, bool join_cgroup) {
  pid_t pid = fork();
  if (pid != 0) return pid;
  // task child
  setsid();
  if (join_cgroup) {
    // v2: write 0 (self) into cgroup.procs before exec
    std::string procs = s.cgroup + "/cgroup.procs";
    int fd = open(procs.c_str(), O_WRONLY);
    if (fd >= 0) {
      ssize_t r = write(fd, "0", 1);
      (void)r;
      close(fd);
    }
  }
  if (!s.cwd.empty() && chdir(s.cwd.c_str()) != 0) _exit(126);
  if (!s.user.empty() && getuid() == 0) {
    struct passwd *pw = getpwnam(s.user.c_str());
    if (pw) {
      if (initgroups(pw->pw_name, pw->pw_gid) != 0 ||
          setgid(pw->pw_gid) != 0 || setuid(pw->pw_uid) != 0)
        _exit(126);
    }
  }
  // Open log sinks only AFTER the privilege drop: a hostile stdout path
  // must never be opened with root credentials (the launcher pre-creates
  // and chowns the real log files so the task user can append).
  if (!s.stdout_path.empty()) {
    int fd = open(s.stdout_path.c_str(),
                  O_WRONLY | O_CREAT | O_APPEND | O_NOFOLLOW, 0644);
    if (fd >= 0) { dup2(fd, 1); close(fd); }
  }
  if (!s.stderr_path.empty()) {
    int fd = open(s.stderr_path.c_str(),
                  O_WRONLY | O_CREAT | O_APPEND | O_NOFOLLOW, 0644);
    if (fd >= 0) { dup2(fd, 2); close(fd); }
  }
  std::vector<char *> argv;
  argv.push_back(const_cast<char *>(s.command.c_str()));
  for (auto &a : s.args) argv.push_back(const_cast<char *>(a.c_str()));
  argv.push_back(nullptr);
  std::vector<char *> envp;
  for (auto &e : s.env) envp.push_back(const_cast<char *>(e.c_str()));
  envp.push_back(nullptr);
  execvpe(s.command.c_str(), argv.data(), envp.data());
  _exit(127);
}

// /proc/<pid>/stat fields 14/15 (utime/stime, ticks) and 24 (rss pages).
static bool read_proc_stats(pid_t pid, long long &utime, long long &stime,
                            long long &rss_bytes) {
  char path[64];
  snprintf(path, sizeof path, "/proc/%d/stat", pid);
  FILE *f = fopen(path, "r");
  if (!f) return false;
  char buf[4096];
  size_t n = fread(buf, 1, sizeof buf - 1, f);
  fclose(f);
  buf[n] = '\0';
  // skip past comm field "(...)" which may contain spaces
  char *p = strrchr(buf, ')');
  if (!p) return false;
  p += 2;
  long long vals[22] = {0};
  int i = 0;
  char *tok = strtok(p, " ");
  while (tok && i < 22) { vals[i++] = atoll(tok); tok = strtok(nullptr, " "); }
  if (i < 22) return false;
  utime = vals[11];  // field 14 overall
  stime = vals[12];
  rss_bytes = vals[21] * sysconf(_SC_PAGESIZE);
  return true;
}

struct Waiter { int fd; };
struct PendingKill { bool armed = false; long long deadline_ns = 0; };

static void reply(int fd, const std::string &line) {
  std::string out = line + "\n";
  ssize_t r = write(fd, out.c_str(), out.size());
  (void)r;
}

static std::string status_line(const TaskState &t) {
  char buf[256];
  snprintf(buf, sizeof buf,
           "ok state=%s pid=%d exit_code=%d signal=%d start_ns=%lld end_ns=%lld",
           t.exited ? "exited" : "running", t.pid, t.exit_code, t.term_signal,
           t.start_ns, t.end_ns);
  return buf;
}

int main(int argc, char **argv) {
  if (argc < 2) {
    fprintf(stderr, "usage: nomad-executor <specfile>\n");
    return 2;
  }
  Spec spec;
  if (!read_spec(argv[1], spec)) {
    fprintf(stderr, "bad spec %s\n", argv[1]);
    return 2;
  }

  // Bind the control socket BEFORE daemonizing so the launcher can
  // connect as soon as we print READY.
  unlink(spec.socket_path.c_str());
  int lfd = socket(AF_UNIX, SOCK_STREAM, 0);
  struct sockaddr_un addr;
  memset(&addr, 0, sizeof addr);
  addr.sun_family = AF_UNIX;
  strncpy(addr.sun_path, spec.socket_path.c_str(), sizeof addr.sun_path - 1);
  if (bind(lfd, (struct sockaddr *)&addr, sizeof addr) != 0 ||
      listen(lfd, 8) != 0) {
    fprintf(stderr, "bind %s: %s\n", spec.socket_path.c_str(), strerror(errno));
    return 2;
  }

  // Daemonize: the supervisor must survive the launching client agent.
  pid_t child = fork();
  if (child < 0) return 2;
  if (child > 0) {
    printf("READY %d\n", child);
    fflush(stdout);
    return 0;
  }
  setsid();
  signal(SIGPIPE, SIG_IGN);
  // Detach stdio: the launcher's pipe must reach EOF once the parent
  // prints READY, or its subprocess.run would hang on the inherited fd.
  int devnull = open("/dev/null", O_RDWR);
  if (devnull >= 0) {
    dup2(devnull, 0);
    dup2(devnull, 1);
    dup2(devnull, 2);
    if (devnull > 2) close(devnull);
  }

  bool join_cg = setup_cgroup(spec);
  TaskState task;
  task.start_ns = now_ns();
  task.pid = spawn_task(spec, join_cg);
  if (!spec.pidfile.empty())
    write_file(spec.pidfile, std::to_string(getpid()));

  std::vector<struct pollfd> fds;
  std::vector<Waiter> waiters;
  std::vector<int> clients;
  PendingKill pending;
  bool shutdown_req = false;

  while (true) {
    // reap
    if (!task.exited) {
      int st;
      pid_t r = waitpid(task.pid, &st, WNOHANG);
      if (r == task.pid) {
        task.exited = true;
        task.end_ns = now_ns();
        if (WIFEXITED(st)) task.exit_code = WEXITSTATUS(st);
        else if (WIFSIGNALED(st)) {
          task.term_signal = WTERMSIG(st);
          task.exit_code = 128 + task.term_signal;
        }
        for (auto &w : waiters) { reply(w.fd, status_line(task)); }
        waiters.clear();
      }
    }
    if (pending.armed && !task.exited && now_ns() >= pending.deadline_ns) {
      kill(-task.pid, SIGKILL);
      pending.armed = false;
    }
    if (shutdown_req && task.exited && waiters.empty()) break;

    fds.clear();
    fds.push_back({lfd, POLLIN, 0});
    for (int cfd : clients) fds.push_back({cfd, POLLIN, 0});
    int rc = poll(fds.data(), fds.size(), 200);
    if (rc < 0 && errno != EINTR) break;
    if (rc <= 0) continue;
    if (fds[0].revents & POLLIN) {
      int cfd = accept(lfd, nullptr, nullptr);
      if (cfd >= 0) clients.push_back(cfd);
    }
    for (size_t i = 1; i < fds.size(); i++) {
      if (!(fds[i].revents & (POLLIN | POLLHUP))) continue;
      int cfd = fds[i].fd;
      char buf[512];
      ssize_t n = read(cfd, buf, sizeof buf - 1);
      if (n <= 0) {
        close(cfd);
        clients.erase(std::remove(clients.begin(), clients.end(), cfd),
                      clients.end());
        // drop any waiter on this fd
        for (size_t w = 0; w < waiters.size();) {
          if (waiters[w].fd == cfd) waiters.erase(waiters.begin() + w);
          else w++;
        }
        continue;
      }
      buf[n] = '\0';
      char *nl = strchr(buf, '\n');
      if (nl) *nl = '\0';
      std::string cmd(buf);
      if (cmd == "status") {
        reply(cfd, status_line(task));
      } else if (cmd.rfind("wait", 0) == 0) {
        if (task.exited) reply(cfd, status_line(task));
        else waiters.push_back({cfd});
      } else if (cmd.rfind("signal ", 0) == 0) {
        int sig = atoi(cmd.c_str() + 7);
        if (task.exited) reply(cfd, "err task exited");
        else if (kill(-task.pid, sig) == 0) reply(cfd, "ok");
        else reply(cfd, std::string("err ") + strerror(errno));
      } else if (cmd.rfind("stop", 0) == 0) {
        long grace_ms = 5000;
        int sig = SIGTERM;
        sscanf(cmd.c_str(), "stop %ld %d", &grace_ms, &sig);
        if (!task.exited) {
          kill(-task.pid, sig);
          pending.armed = true;
          pending.deadline_ns = now_ns() + grace_ms * 1000000LL;
        }
        reply(cfd, "ok");
      } else if (cmd == "stats") {
        long long ut = 0, st = 0, rss = 0;
        if (!task.exited) read_proc_stats(task.pid, ut, st, rss);
        long long cg_mem = -1;
        if (!spec.cgroup.empty()) {
          FILE *f = fopen((spec.cgroup + "/memory.current").c_str(), "r");
          if (f) {
            if (fscanf(f, "%lld", &cg_mem) != 1) cg_mem = -1;
            fclose(f);
          }
        }
        char out[256];
        snprintf(out, sizeof out,
                 "ok utime_ticks=%lld stime_ticks=%lld rss_bytes=%lld "
                 "cgroup_mem_bytes=%lld hz=%ld",
                 ut, st, rss, cg_mem, sysconf(_SC_CLK_TCK));
        reply(cfd, out);
      } else if (cmd == "shutdown") {
        reply(cfd, "ok");
        shutdown_req = true;
      } else {
        reply(cfd, "err unknown command");
      }
    }
  }
  unlink(spec.socket_path.c_str());
  if (!spec.pidfile.empty()) unlink(spec.pidfile.c_str());
  if (!spec.cgroup.empty()) rmdir(spec.cgroup.c_str());
  return 0;
}
