// nomad-tpu native executor: out-of-process task supervisor.
//
// Reference: drivers/shared/executor (executor_linux.go — the
// libcontainer-backed process supervisor that outlives the client agent
// so tasks survive agent restarts, plus the gRPC control surface in
// proto/executor.proto). This is the TPU-native equivalent in C++:
//
//   * reads a tab-separated spec file (see Spec below);
//   * daemonizes (the task must NOT die with the client agent);
//   * forks the task into its own session/process-group, with optional
//     cgroup v2 placement (memory.max / cpu.weight, best-effort) and
//     optional setuid/setgid;
//   * serves a line protocol on a unix socket: status / wait / signal /
//     stop <grace_ms> / stats / shutdown — the Python driver reconnects
//     to the same socket after a client restart (RecoverTask);
//   * "exec <tty> <arg>..." (args backslash-escaped like the spec)
//     spawns a NEW process in the task's cgroup/credentials with a pty
//     (tty=1) or socketpair (tty=0) and switches that connection into a
//     raw byte bridge — the native half of the reference's
//     ExecTaskStreaming (plugins/drivers/execstreaming.go).
//
// Protocol responses are single lines: "ok k=v k=v ..." or "err <msg>".
// Single-threaded poll(2) loop; "wait" parks the connection until the
// task exits (deferred response) and exec bridges join the same loop,
// so no threads are needed.

#include <algorithm>
#include <cerrno>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <ctime>
#include <fcntl.h>
#include <grp.h>
#include <poll.h>
#include <pty.h>
#include <pwd.h>
#include <sched.h>
#include <signal.h>
#include <string>
#include <sys/resource.h>
#include <sys/socket.h>
#include <sys/stat.h>
#include <sys/types.h>
#include <sys/un.h>
#include <sys/wait.h>
#include <unistd.h>
#include <vector>

struct Spec {
  std::string command;
  std::vector<std::string> args;
  std::vector<std::string> env;   // KEY=VAL
  std::string cwd;
  std::string chroot_dir;        // chroot before exec (task filesystem
                                 // isolation; reference libcontainer)
  std::string stdout_path;
  std::string stderr_path;
  std::string socket_path;
  std::string pidfile;
  std::string cgroup;             // cgroup v2 dir to create/join
  long long memory_max = 0;       // bytes, 0 = unset
  int cpu_weight = 0;             // cgroup v2 cpu.weight, 0 = unset
  std::vector<int> cores;         // dedicated core ids: pin via affinity
                                  // (reference LinuxResources.CpusetCpus)
  std::string user;
  std::string netns;              // network namespace path (bridge mode)
};

// Values are backslash-escaped by the launcher (\\ \n \r \t) so that
// job-controlled strings (env, args) can never inject spec directives.
static std::string unescape(const std::string &in) {
  std::string out;
  out.reserve(in.size());
  for (size_t i = 0; i < in.size(); i++) {
    if (in[i] != '\\' || i + 1 >= in.size()) {
      out.push_back(in[i]);
      continue;
    }
    char c = in[++i];
    switch (c) {
      case 'n': out.push_back('\n'); break;
      case 'r': out.push_back('\r'); break;
      case 't': out.push_back('\t'); break;
      case '\\': out.push_back('\\'); break;
      default: out.push_back(c); break;
    }
  }
  return out;
}

static bool read_spec(const char *path, Spec &s) {
  FILE *f = fopen(path, "r");
  if (!f) return false;
  char *line = nullptr;
  size_t cap = 0;
  ssize_t n;
  while ((n = getline(&line, &cap, f)) > 0) {
    if (line[n - 1] == '\n') line[n - 1] = '\0';
    char *tab = strchr(line, '\t');
    if (!tab) continue;
    *tab = '\0';
    std::string key = line, val = unescape(tab + 1);
    if (key == "command") s.command = val;
    else if (key == "arg") s.args.push_back(val);
    else if (key == "env") s.env.push_back(val);
    else if (key == "cwd") s.cwd = val;
    else if (key == "chroot") s.chroot_dir = val;
    else if (key == "stdout") s.stdout_path = val;
    else if (key == "stderr") s.stderr_path = val;
    else if (key == "socket") s.socket_path = val;
    else if (key == "pidfile") s.pidfile = val;
    else if (key == "cgroup") s.cgroup = val;
    else if (key == "memory_max") s.memory_max = atoll(val.c_str());
    else if (key == "cpu_weight") s.cpu_weight = atoi(val.c_str());
    else if (key == "core") s.cores.push_back(atoi(val.c_str()));
    else if (key == "user") s.user = val;
    else if (key == "netns") s.netns = val;
  }
  free(line);
  fclose(f);
  return !s.command.empty() && !s.socket_path.empty();
}

static void write_file(const std::string &path, const std::string &val) {
  int fd = open(path.c_str(), O_WRONLY | O_CREAT | O_TRUNC, 0644);
  if (fd >= 0) {
    ssize_t r = write(fd, val.c_str(), val.size());
    (void)r;
    close(fd);
  }
}

// Best-effort cgroup v2 setup. Returns true if the task pid should be
// written into cgroup.procs (dir exists/writable).
static bool setup_cgroup(const Spec &s) {
  if (s.cgroup.empty()) return false;
  if (mkdir(s.cgroup.c_str(), 0755) != 0 && errno != EEXIST) return false;
  if (s.memory_max > 0)
    write_file(s.cgroup + "/memory.max", std::to_string(s.memory_max));
  if (s.cpu_weight > 0)
    write_file(s.cgroup + "/cpu.weight", std::to_string(s.cpu_weight));
  return true;
}

struct TaskState {
  pid_t pid = -1;
  bool exited = false;
  int exit_code = 0;
  int term_signal = 0;
  long long start_ns = 0;
  long long end_ns = 0;
};

static long long now_ns() {
  struct timespec ts;
  clock_gettime(CLOCK_REALTIME, &ts);
  return (long long)ts.tv_sec * 1000000000LL + ts.tv_nsec;
}

static pid_t spawn_task(const Spec &s, bool join_cgroup) {
  pid_t pid = fork();
  if (pid != 0) return pid;
  // task child
  setsid();
  if (!s.cores.empty()) {
    // pin to the scheduler-granted dedicated cores; best-effort (an
    // offline core must not fail the start — the grant is advisory
    // on hosts that shrank since fingerprinting)
    cpu_set_t set;
    CPU_ZERO(&set);
    for (int c : s.cores)
      if (c >= 0 && c < CPU_SETSIZE) CPU_SET(c, &set);
    sched_setaffinity(0, sizeof(set), &set);
  }
  if (join_cgroup) {
    // v2: write 0 (self) into cgroup.procs before exec
    std::string procs = s.cgroup + "/cgroup.procs";
    int fd = open(procs.c_str(), O_WRONLY);
    if (fd >= 0) {
      ssize_t r = write(fd, "0", 1);
      (void)r;
      close(fd);
    }
  }
  // Resolve the target user from the HOST passwd database before any
  // pivot: after chroot() getpwnam would consult the (job-controlled)
  // chroot's /etc/passwd — a miss silently kept root, and a planted
  // passwd could map any name to uid 0. A named user that does not
  // resolve is fatal.
  uid_t run_uid = 0;
  gid_t run_gid = 0;
  bool drop_user = false;
  if (!s.user.empty() && getuid() == 0) {
    struct passwd *pw = getpwnam(s.user.c_str());
    if (!pw) _exit(126);
    run_uid = pw->pw_uid;
    run_gid = pw->pw_gid;
    if (initgroups(pw->pw_name, pw->pw_gid) != 0) _exit(126);
    drop_user = true;
  }
  // Enter the alloc's network namespace BEFORE the chroot (the nsfs
  // path lives on the host filesystem) and before the privilege drop
  // (setns(CLONE_NEWNET) needs CAP_SYS_ADMIN). Bridge-mode isolation
  // must never silently degrade to the host network: failure is fatal.
  if (!s.netns.empty()) {
    int nsfd = open(s.netns.c_str(), O_RDONLY | O_CLOEXEC);
    if (nsfd < 0 || setns(nsfd, CLONE_NEWNET) != 0) _exit(126);
    close(nsfd);
  }
  bool logs_opened = false;
  if (!s.chroot_dir.empty()) {
    // Log sinks must be opened BEFORE the pivot: the alloc log dir
    // lives outside the new root. Paths here are launcher-controlled
    // (the alloc dir), not job-controlled, so the root-open note below
    // does not apply to this branch.
    if (!s.stdout_path.empty()) {
      int fd = open(s.stdout_path.c_str(),
                    O_WRONLY | O_CREAT | O_APPEND | O_NOFOLLOW, 0644);
      if (fd >= 0) { dup2(fd, 1); close(fd); }
    }
    if (!s.stderr_path.empty()) {
      int fd = open(s.stderr_path.c_str(),
                    O_WRONLY | O_CREAT | O_APPEND | O_NOFOLLOW, 0644);
      if (fd >= 0) { dup2(fd, 2); close(fd); }
    }
    logs_opened = true;
    if (chroot(s.chroot_dir.c_str()) != 0 || chdir("/") != 0) _exit(126);
  }
  if (!s.cwd.empty() && chdir(s.cwd.c_str()) != 0) _exit(126);
  if (drop_user) {
    if (setgid(run_gid) != 0 || setuid(run_uid) != 0) _exit(126);
  }
  // Open log sinks only AFTER the privilege drop: a hostile stdout path
  // must never be opened with root credentials (the launcher pre-creates
  // and chowns the real log files so the task user can append).
  if (!logs_opened && !s.stdout_path.empty()) {
    int fd = open(s.stdout_path.c_str(),
                  O_WRONLY | O_CREAT | O_APPEND | O_NOFOLLOW, 0644);
    if (fd >= 0) { dup2(fd, 1); close(fd); }
  }
  if (!logs_opened && !s.stderr_path.empty()) {
    int fd = open(s.stderr_path.c_str(),
                  O_WRONLY | O_CREAT | O_APPEND | O_NOFOLLOW, 0644);
    if (fd >= 0) { dup2(fd, 2); close(fd); }
  }
  std::vector<char *> argv;
  argv.push_back(const_cast<char *>(s.command.c_str()));
  for (auto &a : s.args) argv.push_back(const_cast<char *>(a.c_str()));
  argv.push_back(nullptr);
  std::vector<char *> envp;
  for (auto &e : s.env) envp.push_back(const_cast<char *>(e.c_str()));
  envp.push_back(nullptr);
  execvpe(s.command.c_str(), argv.data(), envp.data());
  _exit(127);
}

// /proc/<pid>/stat fields 14/15 (utime/stime, ticks) and 24 (rss pages).
static bool read_proc_stats(pid_t pid, long long &utime, long long &stime,
                            long long &rss_bytes) {
  char path[64];
  snprintf(path, sizeof path, "/proc/%d/stat", pid);
  FILE *f = fopen(path, "r");
  if (!f) return false;
  char buf[4096];
  size_t n = fread(buf, 1, sizeof buf - 1, f);
  fclose(f);
  buf[n] = '\0';
  // skip past comm field "(...)" which may contain spaces
  char *p = strrchr(buf, ')');
  if (!p) return false;
  p += 2;
  long long vals[22] = {0};
  int i = 0;
  char *tok = strtok(p, " ");
  while (tok && i < 22) { vals[i++] = atoll(tok); tok = strtok(nullptr, " "); }
  if (i < 22) return false;
  utime = vals[11];  // field 14 overall
  stime = vals[12];
  rss_bytes = vals[21] * sysconf(_SC_PAGESIZE);
  return true;
}

struct Waiter { int fd; };
struct PendingKill { bool armed = false; long long deadline_ns = 0; };

// One interactive exec session: the control connection becomes a raw
// bridge between the peer and the exec'd child's pty/socketpair.
// Both fds are NONBLOCKING with bounded in-flight buffers: a stalled
// consumer must never block the single poll loop (which also reaps the
// task and enforces stop-grace kills).
struct ExecSession {
  int conn = -1;   // unix-socket connection (raw bytes after "ok")
  int io = -1;     // pty master or socketpair end
  pid_t pid = -1;
  bool child_exited = false;
  bool io_eof = false;        // child side closed; flush to_conn then end
  std::string to_conn;        // child output awaiting the peer
  std::string to_io;          // peer input awaiting the child
};

static const size_t EXEC_BUF_CAP = 1 << 20;

static void set_nonblock(int fd) {
  int fl = fcntl(fd, F_GETFL, 0);
  if (fl >= 0) fcntl(fd, F_SETFL, fl | O_NONBLOCK);
}

// Write as much of buf as the fd accepts; false on hard error.
static bool drain_into(int fd, std::string &buf) {
  while (!buf.empty()) {
    ssize_t w = write(fd, buf.data(), buf.size());
    if (w > 0) {
      buf.erase(0, (size_t)w);
      continue;
    }
    if (w < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) return true;
    return false;
  }
  return true;
}

// Split an exec command line into backslash-unescaped fields.
static std::vector<std::string> split_fields(const std::string &line) {
  std::vector<std::string> out;
  std::string cur;
  for (size_t i = 0; i < line.size(); i++) {
    if (line[i] == '\t') {
      out.push_back(unescape(cur));
      cur.clear();
    } else {
      cur.push_back(line[i]);
    }
  }
  out.push_back(unescape(cur));
  return out;
}

// Spawn an exec child sharing the task's cgroup + credentials.
// Returns pid, with *io set to the parent's end (pty master or
// socketpair); -1 on failure.
static pid_t spawn_exec(const Spec &s, const std::vector<std::string> &argv_s,
                        bool tty, int *io) {
  int master = -1, sv[2] = {-1, -1};
  pid_t pid;
  if (tty) {
    pid = forkpty(&master, nullptr, nullptr, nullptr);
  } else {
    if (socketpair(AF_UNIX, SOCK_STREAM, 0, sv) != 0) return -1;
    pid = fork();
  }
  if (pid < 0) return -1;
  if (pid == 0) {
    // exec child: same containment as the task (cgroup, cwd, user)
    if (!tty) {
      setsid();
      dup2(sv[1], 0);
      dup2(sv[1], 1);
      dup2(sv[1], 2);
      close(sv[0]);
      close(sv[1]);
    }
    if (!s.cgroup.empty()) {
      std::string procs = s.cgroup + "/cgroup.procs";
      int fd = open(procs.c_str(), O_WRONLY);
      if (fd >= 0) {
        ssize_t r = write(fd, "0", 1);
        (void)r;
        close(fd);
      }
    }
    if (!s.cwd.empty() && chdir(s.cwd.c_str()) != 0) _exit(126);
    if (!s.user.empty() && getuid() == 0) {
      struct passwd *pw = getpwnam(s.user.c_str());
      if (pw) {
        if (initgroups(pw->pw_name, pw->pw_gid) != 0 ||
            setgid(pw->pw_gid) != 0 || setuid(pw->pw_uid) != 0)
          _exit(126);
      }
    }
    std::vector<char *> argv;
    for (auto &a : argv_s) argv.push_back(const_cast<char *>(a.c_str()));
    argv.push_back(nullptr);
    std::vector<char *> envp;
    for (auto &e : s.env) envp.push_back(const_cast<char *>(e.c_str()));
    envp.push_back(nullptr);
    execvpe(argv_s[0].c_str(), argv.data(), envp.data());
    _exit(127);
  }
  if (tty) {
    *io = master;
  } else {
    close(sv[1]);
    *io = sv[0];
  }
  return pid;
}

static void reply(int fd, const std::string &line) {
  std::string out = line + "\n";
  ssize_t r = write(fd, out.c_str(), out.size());
  (void)r;
}

static std::string status_line(const TaskState &t) {
  char buf[256];
  snprintf(buf, sizeof buf,
           "ok state=%s pid=%d exit_code=%d signal=%d start_ns=%lld end_ns=%lld",
           t.exited ? "exited" : "running", t.pid, t.exit_code, t.term_signal,
           t.start_ns, t.end_ns);
  return buf;
}

int main(int argc, char **argv) {
  if (argc < 2) {
    fprintf(stderr, "usage: nomad-executor <specfile>\n");
    return 2;
  }
  Spec spec;
  if (!read_spec(argv[1], spec)) {
    fprintf(stderr, "bad spec %s\n", argv[1]);
    return 2;
  }

  // Bind the control socket BEFORE daemonizing so the launcher can
  // connect as soon as we print READY.
  unlink(spec.socket_path.c_str());
  int lfd = socket(AF_UNIX, SOCK_STREAM, 0);
  struct sockaddr_un addr;
  memset(&addr, 0, sizeof addr);
  addr.sun_family = AF_UNIX;
  strncpy(addr.sun_path, spec.socket_path.c_str(), sizeof addr.sun_path - 1);
  if (bind(lfd, (struct sockaddr *)&addr, sizeof addr) != 0 ||
      listen(lfd, 8) != 0) {
    fprintf(stderr, "bind %s: %s\n", spec.socket_path.c_str(), strerror(errno));
    return 2;
  }

  // Daemonize: the supervisor must survive the launching client agent.
  pid_t child = fork();
  if (child < 0) return 2;
  if (child > 0) {
    printf("READY %d\n", child);
    fflush(stdout);
    return 0;
  }
  setsid();
  signal(SIGPIPE, SIG_IGN);
  // Detach stdio: the launcher's pipe must reach EOF once the parent
  // prints READY, or its subprocess.run would hang on the inherited fd.
  int devnull = open("/dev/null", O_RDWR);
  if (devnull >= 0) {
    dup2(devnull, 0);
    dup2(devnull, 1);
    dup2(devnull, 2);
    if (devnull > 2) close(devnull);
  }

  bool join_cg = setup_cgroup(spec);
  TaskState task;
  task.start_ns = now_ns();
  task.pid = spawn_task(spec, join_cg);
  if (!spec.pidfile.empty())
    write_file(spec.pidfile, std::to_string(getpid()));

  std::vector<struct pollfd> fds;
  std::vector<Waiter> waiters;
  std::vector<int> clients;
  std::vector<ExecSession> execs;
  PendingKill pending;
  bool shutdown_req = false;

  auto close_exec = [&](ExecSession &es) {
    if (es.io >= 0) close(es.io);
    if (es.conn >= 0) close(es.conn);
    if (es.pid > 0 && !es.child_exited) kill(es.pid, SIGKILL);
    es.io = es.conn = -1;
  };

  while (true) {
    // reap the task and any exec children
    int st;
    pid_t r;
    while ((r = waitpid(-1, &st, WNOHANG)) > 0) {
      if (r == task.pid && !task.exited) {
        task.exited = true;
        task.end_ns = now_ns();
        if (WIFEXITED(st)) task.exit_code = WEXITSTATUS(st);
        else if (WIFSIGNALED(st)) {
          task.term_signal = WTERMSIG(st);
          task.exit_code = 128 + task.term_signal;
        }
        for (auto &w : waiters) { reply(w.fd, status_line(task)); }
        waiters.clear();
      } else {
        for (auto &es : execs) {
          if (es.pid == r) es.child_exited = true;
        }
      }
    }
    if (pending.armed && !task.exited && now_ns() >= pending.deadline_ns) {
      kill(-task.pid, SIGKILL);
      pending.armed = false;
    }
    if (shutdown_req && task.exited && waiters.empty()) break;

    // drop finished exec sessions
    execs.erase(
        std::remove_if(execs.begin(), execs.end(),
                       [](const ExecSession &e) { return e.conn < 0; }),
        execs.end());

    fds.clear();
    fds.push_back({lfd, POLLIN, 0});
    for (int cfd : clients) fds.push_back({cfd, POLLIN, 0});
    size_t exec_base = fds.size();
    for (auto &es : execs) {
      short conn_ev = 0, io_ev = 0;
      if (es.to_io.size() < EXEC_BUF_CAP) conn_ev |= POLLIN;
      if (!es.to_conn.empty()) conn_ev |= POLLOUT;
      if (!es.io_eof && es.to_conn.size() < EXEC_BUF_CAP) io_ev |= POLLIN;
      if (!es.to_io.empty()) io_ev |= POLLOUT;
      fds.push_back({es.conn, conn_ev, 0});
      fds.push_back({es.io, io_ev, 0});
    }
    int rc = poll(fds.data(), fds.size(), 200);
    if (rc < 0 && errno != EINTR) break;
    if (rc <= 0) continue;
    if (fds[0].revents & POLLIN) {
      int cfd = accept(lfd, nullptr, nullptr);
      if (cfd >= 0) clients.push_back(cfd);
    }
    // exec bridges: peer <-> child via bounded nonblocking buffers
    for (size_t e = 0; e < execs.size(); e++) {
      ExecSession &es = execs[e];
      struct pollfd &pc = fds[exec_base + 2 * e];
      struct pollfd &pio = fds[exec_base + 2 * e + 1];
      char bb[4096];
      bool dead = false;
      if (pc.revents & POLLIN) {
        ssize_t n = read(es.conn, bb, sizeof bb);
        if (n > 0) es.to_io.append(bb, (size_t)n);
        else if (n == 0 || (n < 0 && errno != EAGAIN && errno != EWOULDBLOCK))
          dead = true;  // peer hung up
      } else if (pc.revents & (POLLHUP | POLLERR)) {
        dead = true;
      }
      if (!dead && !es.io_eof && (pio.revents & (POLLIN | POLLHUP | POLLERR))) {
        ssize_t n = read(es.io, bb, sizeof bb);
        if (n > 0) es.to_conn.append(bb, (size_t)n);
        else if (n == 0 || (n < 0 && errno != EAGAIN && errno != EWOULDBLOCK))
          es.io_eof = true;  // child closed (pty: EIO after exit)
      }
      if (!dead && !es.to_io.empty() && !es.io_eof)
        if (!drain_into(es.io, es.to_io)) es.io_eof = true;
      if (!dead && !es.to_conn.empty())
        if (!drain_into(es.conn, es.to_conn)) dead = true;
      if (dead || (es.io_eof && es.to_conn.empty())) close_exec(es);
    }
    for (size_t i = 1; i < exec_base; i++) {
      if (!(fds[i].revents & (POLLIN | POLLHUP))) continue;
      int cfd = fds[i].fd;
      char buf[4096];
      ssize_t n = read(cfd, buf, sizeof buf - 1);
      if (n <= 0) {
        close(cfd);
        clients.erase(std::remove(clients.begin(), clients.end(), cfd),
                      clients.end());
        // drop any waiter on this fd
        for (size_t w = 0; w < waiters.size();) {
          if (waiters[w].fd == cfd) waiters.erase(waiters.begin() + w);
          else w++;
        }
        continue;
      }
      buf[n] = '\0';
      char *nl = strchr(buf, '\n');
      if (nl) *nl = '\0';
      std::string cmd(buf);
      if (cmd == "status") {
        reply(cfd, status_line(task));
      } else if (cmd.rfind("wait", 0) == 0) {
        if (task.exited) reply(cfd, status_line(task));
        else waiters.push_back({cfd});
      } else if (cmd.rfind("signal ", 0) == 0) {
        int sig = atoi(cmd.c_str() + 7);
        if (task.exited) reply(cfd, "err task exited");
        else if (kill(-task.pid, sig) == 0) reply(cfd, "ok");
        else reply(cfd, std::string("err ") + strerror(errno));
      } else if (cmd.rfind("stop", 0) == 0) {
        long grace_ms = 5000;
        int sig = SIGTERM;
        sscanf(cmd.c_str(), "stop %ld %d", &grace_ms, &sig);
        if (!task.exited) {
          kill(-task.pid, sig);
          pending.armed = true;
          pending.deadline_ns = now_ns() + grace_ms * 1000000LL;
        }
        reply(cfd, "ok");
      } else if (cmd == "stats") {
        long long ut = 0, st = 0, rss = 0;
        if (!task.exited) read_proc_stats(task.pid, ut, st, rss);
        long long cg_mem = -1;
        if (!spec.cgroup.empty()) {
          FILE *f = fopen((spec.cgroup + "/memory.current").c_str(), "r");
          if (f) {
            if (fscanf(f, "%lld", &cg_mem) != 1) cg_mem = -1;
            fclose(f);
          }
        }
        char out[256];
        snprintf(out, sizeof out,
                 "ok utime_ticks=%lld stime_ticks=%lld rss_bytes=%lld "
                 "cgroup_mem_bytes=%lld hz=%ld",
                 ut, st, rss, cg_mem, sysconf(_SC_CLK_TCK));
        reply(cfd, out);
      } else if (cmd == "shutdown") {
        reply(cfd, "ok");
        shutdown_req = true;
      } else if (cmd.rfind("exec\t", 0) == 0) {
        std::vector<std::string> fields = split_fields(cmd.substr(5));
        if (fields.size() < 2) {
          reply(cfd, "err exec needs argv");
        } else {
          bool tty = fields[0] == "1";
          std::vector<std::string> argvs(fields.begin() + 1, fields.end());
          int io = -1;
          pid_t pid = spawn_exec(spec, argvs, tty, &io);
          if (pid < 0) {
            reply(cfd, "err exec spawn failed");
          } else {
            char ok[64];
            snprintf(ok, sizeof ok, "ok pid=%d", pid);
            reply(cfd, ok);
            ExecSession es;
            es.conn = cfd;
            es.io = io;
            es.pid = pid;
            set_nonblock(es.conn);
            set_nonblock(es.io);
            execs.push_back(es);
            // the connection is a raw bridge now, not a command client
            clients.erase(std::remove(clients.begin(), clients.end(), cfd),
                          clients.end());
          }
        }
      } else {
        reply(cfd, "err unknown command");
      }
    }
  }
  for (auto &es : execs) close_exec(es);
  unlink(spec.socket_path.c_str());
  if (!spec.pidfile.empty()) unlink(spec.pidfile.c_str());
  if (!spec.cgroup.empty()) rmdir(spec.cgroup.c_str());
  return 0;
}
