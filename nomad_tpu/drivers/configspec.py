"""Driver/plugin config schemas.

Reference: plugins/shared/hclspec/ — drivers publish an hclspec the
agent uses to decode + validate their task config stanza (each driver's
``taskConfigSpec``; e.g. drivers/qemu/driver.go:100-118). The tpu-native
equivalent is a declarative attr spec validated at start_task time:
unknown keys, wrong types, and missing required attrs are rejected with
the driver's name in the error, and defaults are applied — so a typo'd
stanza fails loudly at dispatch instead of silently misconfiguring the
task.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional

from .base import DriverError

_TYPES = {
    "string": str,
    "int": int,
    "float": (int, float),
    "bool": bool,
    "list": list,
    "map": dict,
    "any": object,
}


@dataclass
class Attr:
    """One config attribute (reference hclspec.NewAttr)."""

    name: str
    type: str = "string"
    required: bool = False
    default: Any = None


@dataclass
class Spec:
    """A driver's task-config schema (reference hclspec.NewObject)."""

    attrs: list[Attr] = field(default_factory=list)
    # drivers with passthrough stanzas (mock) can accept unknown keys
    allow_unknown: bool = False

    def validate(self, config: Optional[dict], who: str = "driver") -> dict:
        """Returns the config with defaults applied; raises DriverError
        on unknown keys / wrong types / missing required attrs."""
        config = dict(config or {})
        by_name = {a.name: a for a in self.attrs}
        if not self.allow_unknown:
            unknown = sorted(set(config) - set(by_name))
            if unknown:
                raise DriverError(
                    f"{who}: unknown config keys {unknown}; valid keys: "
                    f"{sorted(by_name)}"
                )
        for attr in self.attrs:
            if attr.name not in config:
                if attr.required:
                    raise DriverError(
                        f"{who}: missing required config key "
                        f"{attr.name!r}"
                    )
            elif attr.required and config[attr.name] in ("", None):
                # an interpolation that resolved to empty must fail at
                # dispatch, not as an opaque runtime error downstream
                raise DriverError(
                    f"{who}: required config key {attr.name!r} is empty"
                )
            if attr.name not in config:
                if attr.default is not None:
                    config[attr.name] = (
                        list(attr.default)
                        if isinstance(attr.default, list)
                        else dict(attr.default)
                        if isinstance(attr.default, dict)
                        else attr.default
                    )
                continue
            if attr.required and config[attr.name] in ("", None):
                # an interpolation that resolved to empty must fail at
                # dispatch, not as an opaque runtime error downstream
                raise DriverError(
                    f"{who}: required config key {attr.name!r} is empty"
                )
            want = _TYPES[attr.type]
            val = config[attr.name]
            if attr.type == "any":
                continue
            # bool is an int subclass: screen it from int attrs
            if attr.type == "int" and isinstance(val, bool):
                raise DriverError(
                    f"{who}: config key {attr.name!r} must be int, "
                    f"got bool"
                )
            if not isinstance(val, want):
                raise DriverError(
                    f"{who}: config key {attr.name!r} must be "
                    f"{attr.type}, got {type(val).__name__}"
                )
        return config


# -- builtin driver specs (reference: each driver's taskConfigSpec) ----

RAWEXEC_SPEC = Spec([
    Attr("command", "string", required=True),
    Attr("args", "list", default=[]),
    Attr("cgroup_v2", "bool", default=True),
])

EXEC_SPEC = Spec([
    Attr("command", "string", required=True),
    Attr("args", "list", default=[]),
    Attr("cgroup_v2", "bool", default=True),
])

JAVA_SPEC = Spec([
    Attr("jar_path", "string"),
    Attr("class", "string"),
    Attr("class_path", "string"),
    Attr("args", "list", default=[]),
    Attr("jvm_options", "list", default=[]),
    Attr("java_bin", "string"),
])

QEMU_SPEC = Spec([
    Attr("image_path", "string", required=True),
    Attr("accelerator", "string", default="tcg"),
    Attr("graceful_shutdown", "bool", default=False),
    Attr("args", "list", default=[]),
    Attr("port_map", "map", default={}),
])

DOCKER_SPEC = Spec([
    Attr("image", "string", required=True),
    Attr("command", "string"),
    Attr("args", "list", default=[]),
    Attr("entrypoint", "list"),
    Attr("volumes", "list", default=[]),
    Attr("ports", "list", default=[]),
    Attr("network_mode", "string"),
    Attr("labels", "map", default={}),
    Attr("force_pull", "bool", default=False),
    Attr("auth", "map"),
    Attr("work_dir", "string"),
])
