"""QEMU virtual-machine driver.

Reference: drivers/qemu/driver.go (875 LoC) — StartTask :341 builds the
qemu-system command line (machine/accel, -m, -drive, -nographic, user
netdev hostfwd port maps, passthrough args), graceful shutdown sends
``system_powerdown`` over a unix monitor socket (:42 monitor name, :69
the 108-byte socket-path truncation guard), fingerprint shells out for
the qemu version (:226), RecoverTask reattaches by pid (:261).

Config keys (same vocabulary):
  image_path         VM image (required; must live under the task's
                     alloc dir or an operator-allowed path)
  accelerator        "tcg" (default) | "kvm"
  graceful_shutdown  bool — use the monitor socket for powerdown
  args               passthrough qemu arguments
  port_map           {label: guest_port} → hostfwd via user netdev

The qemu binary itself is operator config (constructor), never jobspec
config — a job-settable binary would be arbitrary host execution.
"""

from __future__ import annotations

import os
import shutil
import signal as _signal
import socket
import subprocess
import threading
from typing import Any, Optional

from ..structs import now_ns
from .base import (
    Driver,
    DriverError,
    ExitResult,
    Fingerprint,
    TaskConfig,
    TaskHandle,
    TaskStatus,
    TASK_STATE_EXITED,
    TASK_STATE_RUNNING,
    HEALTH_STATE_HEALTHY,
    HEALTH_STATE_UNDETECTED,
)

QEMU_BINARY = "qemu-system-x86_64"
MONITOR_SOCKET_NAME = "qemu-monitor.sock"
# unix socket paths truncate at 108 bytes (reference :69)
MAX_SOCKET_PATH = 108


class _QemuTask:
    def __init__(self, cfg: TaskConfig, proc: subprocess.Popen,
                 monitor_path: str = "") -> None:
        self.cfg = cfg
        self.proc = proc
        self.monitor_path = monitor_path
        self.started_at = now_ns()
        self.completed_at = 0
        self.exit_result: Optional[ExitResult] = None
        self.done = threading.Event()
        self._waiter = threading.Thread(
            target=self._wait, name="qemu-waiter", daemon=True
        )
        self._waiter.start()

    def _wait(self) -> None:
        code = self.proc.wait()
        self.completed_at = now_ns()
        if code < 0:
            self.exit_result = ExitResult(exit_code=128 - code, signal=-code)
        else:
            self.exit_result = ExitResult(exit_code=code)
        self.done.set()


class QemuDriver(Driver):
    name = "qemu"

    def __init__(self, image_paths: Optional[list[str]] = None,
                 qemu_binary: Optional[str] = None) -> None:
        # operator-allowed image dirs beyond the alloc dir (reference
        # config image_paths) + optional binary override (tests stub it)
        self.image_paths = image_paths or []
        self.qemu_binary = qemu_binary
        self.tasks: dict[str, _QemuTask] = {}
        self._lock = threading.Lock()

    # -- fingerprint ---------------------------------------------------

    def fingerprint(self) -> Fingerprint:
        path = shutil.which(QEMU_BINARY)
        if path is None:
            return Fingerprint(
                attributes={},
                health=HEALTH_STATE_UNDETECTED,
                health_description="qemu-system binary not found",
            )
        try:
            out = subprocess.run(
                [path, "--version"], capture_output=True, text=True,
                timeout=10,
            ).stdout
            # "QEMU emulator version 8.2.0 ..." (reference :226)
            version = ""
            for tok in out.split():
                if tok and tok[0].isdigit():
                    version = tok
                    break
        except (OSError, subprocess.TimeoutExpired) as e:
            return Fingerprint(
                attributes={},
                health=HEALTH_STATE_UNDETECTED,
                health_description=f"qemu version probe failed: {e}",
            )
        return Fingerprint(
            attributes={
                "driver.qemu": "1",
                "driver.qemu.version": version,
            },
            health=HEALTH_STATE_HEALTHY,
        )

    # -- lifecycle ------------------------------------------------------

    def _allowed_image(self, task_dir: str, image: str) -> bool:
        """image must live under the alloc dir or an allowed path
        (reference isAllowedImagePath)."""
        image = os.path.realpath(image)
        alloc_dir = os.path.dirname(os.path.realpath(task_dir)) if task_dir else ""
        roots = [r for r in ([alloc_dir] + self.image_paths) if r]
        return any(
            image == r or image.startswith(os.path.realpath(r) + os.sep)
            for r in roots
        )

    def _monitor_path(self, task_dir: str) -> str:
        path = os.path.join(task_dir, MONITOR_SOCKET_NAME)
        if len(path.encode()) > MAX_SOCKET_PATH:
            raise DriverError(
                f"monitor socket path exceeds {MAX_SOCKET_PATH} bytes: "
                f"{path}"
            )
        return path

    def start_task(self, cfg: TaskConfig) -> TaskHandle:
        from .configspec import QEMU_SPEC

        conf = QEMU_SPEC.validate(cfg.config, "qemu")
        image = conf["image_path"]
        if not os.path.isabs(image):
            image = os.path.join(cfg.task_dir, image)
        if not self._allowed_image(cfg.task_dir, image):
            raise DriverError("qemu: image_path is not in the allowed paths")
        # binary override is OPERATOR config (constructor), never
        # jobspec config — a job-settable binary would be arbitrary
        # host execution, defeating the image allowlist
        binary = self.qemu_binary or shutil.which(QEMU_BINARY)
        if not binary:
            raise DriverError(f"qemu: {QEMU_BINARY} not found")
        accelerator = conf.get("accelerator", "tcg")
        mem_mb = int(cfg.resources_memory_mb or 0)
        if mem_mb < 128 or mem_mb > 4_000_000:
            raise DriverError("qemu: memory assignment out of bounds")
        vm_id = os.path.basename(image)
        args = [
            binary,
            "-machine", f"type=pc,accel={accelerator}",
            "-name", vm_id,
            "-m", f"{mem_mb}M",
            "-drive", f"file={image}",
            "-nographic",
        ]
        monitor_path = ""
        if conf.get("graceful_shutdown"):
            monitor_path = self._monitor_path(cfg.task_dir)
            args += ["-monitor", f"unix:{monitor_path},server,nowait"]
        args += [str(a) for a in conf.get("args", [])]
        # port_map {label: guest} → user-mode netdev hostfwd rules
        # (reference :441-466); host ports come from NOMAD_HOST_PORT_*
        port_map = conf.get("port_map") or {}
        fwd = []
        for label, guest in port_map.items():
            host = cfg.env.get(f"NOMAD_HOST_PORT_{label}") or cfg.env.get(
                f"NOMAD_PORT_{label}"
            )
            if not host:
                raise DriverError(f"qemu: unknown port label {label!r}")
            try:
                guest_port = int(guest)
            except (TypeError, ValueError):
                raise DriverError(
                    f"qemu: port_map[{label!r}] must be an integer guest "
                    f"port, got {guest!r}"
                ) from None
            for proto in ("udp", "tcp"):
                fwd.append(f"hostfwd={proto}::{host}-:{guest_port}")
        if fwd:
            args += [
                "-netdev", "user,id=user.0," + ",".join(fwd),
                "-device", "virtio-net,netdev=user.0",
            ]
        if accelerator == "kvm":
            args += ["-enable-kvm", "-cpu", "host"]

        stdout = (
            open(cfg.stdout_path, "ab")
            if cfg.stdout_path
            else subprocess.DEVNULL
        )
        stderr = (
            open(cfg.stderr_path, "ab")
            if cfg.stderr_path
            else subprocess.DEVNULL
        )
        try:
            proc = subprocess.Popen(
                args,
                stdout=stdout,
                stderr=stderr,
                cwd=cfg.task_dir or None,
                env={**os.environ, **cfg.env},
                start_new_session=True,
            )
        except OSError as e:
            raise DriverError(f"qemu: failed to start: {e}") from e
        finally:
            for f in (stdout, stderr):
                if hasattr(f, "close"):
                    f.close()
        task = _QemuTask(cfg, proc, monitor_path)
        with self._lock:
            self.tasks[cfg.id] = task
        return TaskHandle(
            cfg.id, self.name,
            {"pid": proc.pid, "monitor_path": monitor_path},
        )

    # -- graceful shutdown ---------------------------------------------

    def _send_powerdown(self, task: _QemuTask) -> bool:
        """system_powerdown over the monitor socket (reference
        sendQemuShutdown)."""
        if not task.monitor_path:
            return False
        try:
            with socket.socket(socket.AF_UNIX, socket.SOCK_STREAM) as s:
                s.settimeout(2.0)
                s.connect(task.monitor_path)
                s.sendall(b"system_powerdown\n")
            return True
        except OSError:
            return False

    def stop_task(self, task_id: str, timeout_s: float, signal: str = "") -> None:
        task = self._get(task_id)
        if task.done.is_set():
            return
        if self._send_powerdown(task):
            if task.done.wait(timeout_s):
                return
        else:
            sig = (
                getattr(_signal, signal, _signal.SIGTERM)
                if signal
                else _signal.SIGTERM
            )
            try:
                os.killpg(os.getpgid(task.proc.pid), sig)
            except ProcessLookupError:
                return
            if task.done.wait(timeout_s):
                return
        try:
            os.killpg(os.getpgid(task.proc.pid), _signal.SIGKILL)
        except ProcessLookupError:
            pass
        task.done.wait(5)

    # -- the rest of the Driver contract -------------------------------

    def wait_task(
        self, task_id: str, timeout_s: Optional[float] = None
    ) -> Optional[ExitResult]:
        task = self._get(task_id)
        if not task.done.wait(timeout_s):
            return None
        return task.exit_result

    def destroy_task(self, task_id: str, force: bool = False) -> None:
        with self._lock:
            task = self.tasks.get(task_id)
        if task is None:
            return
        if not task.done.is_set():
            if not force:
                raise DriverError("qemu task still running")
            self.stop_task(task_id, timeout_s=2)
        with self._lock:
            self.tasks.pop(task_id, None)

    def inspect_task(self, task_id: str) -> TaskStatus:
        task = self._get(task_id)
        return TaskStatus(
            id=task_id,
            name=task.cfg.name,
            state=TASK_STATE_EXITED if task.done.is_set() else TASK_STATE_RUNNING,
            started_at_ns=task.started_at,
            completed_at_ns=task.completed_at,
            exit_result=task.exit_result,
        )

    def task_stats(self, task_id: str) -> dict[str, Any]:
        task = self._get(task_id)
        try:
            with open(f"/proc/{task.proc.pid}/statm") as f:
                pages = int(f.read().split()[1])
            rss = pages * os.sysconf("SC_PAGE_SIZE")
        except (OSError, ValueError, IndexError):
            rss = 0
        return {"memory_rss_bytes": rss, "pid": task.proc.pid}

    def signal_task(self, task_id: str, signal: str) -> None:
        task = self._get(task_id)
        sig = getattr(_signal, signal, None)
        if sig is None:
            raise DriverError(f"unknown signal {signal!r}")
        try:
            os.kill(task.proc.pid, sig)
        except ProcessLookupError:
            raise DriverError("process gone") from None

    def exec_task(
        self, task_id: str, cmd: list[str], timeout_s: float = 30.0
    ) -> tuple[bytes, int]:
        raise DriverError("qemu driver does not support exec")

    def recover_task(self, handle: TaskHandle) -> None:
        """Reattach to a live VM by pid (reference RecoverTask :261)."""
        if handle.task_id in self.tasks:
            return
        pid = handle.state.get("pid")
        if not pid:
            raise DriverError("no pid in qemu handle")
        try:
            os.kill(pid, 0)
        except (ProcessLookupError, PermissionError):
            raise DriverError(f"qemu pid {pid} is gone") from None
        from .rawexec import _AdoptedProcess

        proc = _AdoptedProcess(pid)
        task = _QemuTask(
            TaskConfig(id=handle.task_id),
            proc,  # type: ignore[arg-type]
            handle.state.get("monitor_path", ""),
        )
        with self._lock:
            self.tasks[handle.task_id] = task

    def _get(self, task_id: str) -> _QemuTask:
        with self._lock:
            task = self.tasks.get(task_id)
        if task is None:
            raise DriverError(f"unknown qemu task {task_id}")
        return task
