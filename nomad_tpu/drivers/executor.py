"""Python side of the native executor.

Reference: drivers/shared/executor — the Go client half that talks gRPC
to the out-of-process supervisor (z_executor_cmd.go re-attach). Here:
compile `native/executor.cc` once per machine (g++, cached by source
hash), launch it detached, and speak its line protocol over the unix
socket. Re-attach after a client restart = reconnect to the socket.
"""

from __future__ import annotations

import hashlib
import os
import shutil
import socket
import subprocess
import threading
import time
from pathlib import Path
from typing import Optional

_SRC = Path(__file__).parent / "native" / "executor.cc"
_BUILD_LOCK = threading.Lock()


class ExecutorError(Exception):
    pass


def executor_binary(cache_dir: Optional[str] = None) -> str:
    """Compile (once) and return the executor binary path."""
    cache = Path(
        cache_dir
        or os.environ.get("NOMAD_TPU_BIN_DIR")
        or Path.home() / ".cache" / "nomad_tpu" / "bin"
    )
    src = _SRC.read_bytes()
    tag = hashlib.sha256(src).hexdigest()[:16]
    out = cache / f"nomad-executor-{tag}"
    if out.exists():
        return str(out)
    with _BUILD_LOCK:
        if out.exists():
            return str(out)
        cache.mkdir(parents=True, exist_ok=True)
        gxx = shutil.which("g++") or shutil.which("c++")
        if gxx is None:
            raise ExecutorError("no C++ compiler available")
        tmp = str(out) + ".tmp"
        proc = subprocess.run(
            [gxx, "-O2", "-std=c++17", "-o", tmp, str(_SRC)],
            capture_output=True,
            text=True,
        )
        if proc.returncode != 0:
            # pre-2.34 glibc keeps forkpty in libutil
            proc = subprocess.run(
                [gxx, "-O2", "-std=c++17", "-o", tmp, str(_SRC), "-lutil"],
                capture_output=True,
                text=True,
            )
        if proc.returncode != 0:
            raise ExecutorError(f"executor build failed:\n{proc.stderr}")
        os.replace(tmp, out)
    return str(out)


class ExecutorHandle:
    """Control connection to one running executor."""

    def __init__(self, socket_path: str, daemon_pid: int = 0) -> None:
        self.socket_path = socket_path
        self.daemon_pid = daemon_pid

    # -- protocol ------------------------------------------------------

    def _cmd(self, line: str, timeout_s: Optional[float] = 10.0) -> dict:
        conn = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        conn.settimeout(5.0)
        try:
            conn.connect(self.socket_path)
            conn.settimeout(timeout_s)
            conn.sendall(line.encode() + b"\n")
            buf = b""
            while not buf.endswith(b"\n"):
                chunk = conn.recv(4096)
                if not chunk:
                    raise ExecutorError("executor connection closed")
                buf += chunk
        finally:
            conn.close()
        text = buf.decode().strip()
        if text.startswith("err"):
            raise ExecutorError(text[4:] or "executor error")
        out: dict = {}
        for part in text.split()[1:]:
            if "=" in part:
                k, v = part.split("=", 1)
                try:
                    out[k] = int(v)
                except ValueError:
                    out[k] = v
        return out

    def status(self) -> dict:
        return self._cmd("status")

    def wait(self, timeout_s: Optional[float] = None) -> Optional[dict]:
        """Block until the task exits; None on timeout."""
        try:
            return self._cmd("wait", timeout_s=timeout_s)
        except socket.timeout:
            return None

    def signal(self, signo: int) -> None:
        self._cmd(f"signal {signo}")

    def stop(self, grace_s: float = 5.0, signo: int = 15) -> None:
        self._cmd(f"stop {int(grace_s * 1000)} {signo}")

    def stats(self) -> dict:
        return self._cmd("stats")

    def exec_stream(self, args: list[str], tty: bool = False) -> socket.socket:
        """Spawn a process inside the task's containment and return the
        raw bridge socket (pty master or socketpair on the other side).
        Caller owns the socket; closing it kills the exec'd process."""
        if not args:
            raise ExecutorError("exec needs argv")
        conn = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        conn.settimeout(5.0)
        try:
            conn.connect(self.socket_path)
            fields = ["exec", "1" if tty else "0"] + list(args)
            line = "\t".join(_esc(f) for f in fields)
            conn.sendall(line.encode() + b"\n")
            # Consume EXACTLY the first line: raw bridge bytes follow the
            # "ok" handshake immediately, and a fast-exiting child's output
            # (and exit trailer) can share the wire with it — recv'ing in
            # chunks "until the buffer ends with newline" swallowed them.
            buf = b""
            while not buf.endswith(b"\n"):
                chunk = conn.recv(1)
                if not chunk:
                    raise ExecutorError("executor connection closed")
                buf += chunk
        except Exception:
            conn.close()
            raise
        text = buf.decode().strip()
        if text.startswith("err"):
            conn.close()
            raise ExecutorError(text[4:] or "exec failed")
        conn.settimeout(None)
        return conn

    def shutdown(self) -> None:
        try:
            self._cmd("shutdown")
        except (ExecutorError, OSError):
            pass

    def alive(self) -> bool:
        try:
            self.status()
            return True
        except (OSError, ExecutorError):
            return False


# AF_UNIX sun_path is 108 bytes on Linux; leave headroom.
_SUN_PATH_MAX = 100


def _socket_path(task_dir: str) -> str:
    """Short, stable control-socket path for a task.

    The socket can NOT live under the task dir: pytest tmp_paths (and
    real data_dirs) routinely push the alloc-dir path past the 108-byte
    sun_path limit and bind() fails.  Key a short /tmp path by task-dir
    hash instead — deterministic, so a restarted agent recomputes the
    same path even if its state record predates this scheme.
    """
    run_root = os.environ.get("NOMAD_TPU_RUN_DIR")
    if not run_root:
        run_root = f"/tmp/nomadx-{os.getuid()}"
    os.makedirs(run_root, mode=0o700, exist_ok=True)
    # /tmp is a shared namespace: refuse a squatted dir (pre-created by
    # another user, or loosened perms) the same way sshd treats its run
    # dir — otherwise a local user could hijack root's control sockets.
    st = os.stat(run_root)
    if st.st_uid != os.getuid() or (st.st_mode & 0o077):
        raise ExecutorError(
            f"run dir {run_root} has unsafe owner/mode "
            f"(uid={st.st_uid}, mode={oct(st.st_mode & 0o777)})"
        )
    tag = hashlib.sha256(os.path.abspath(task_dir).encode()).hexdigest()[:16]
    sock = os.path.join(run_root, f"{tag}.sock")
    if len(sock) > _SUN_PATH_MAX:
        raise ExecutorError(
            f"socket path too long ({len(sock)} > {_SUN_PATH_MAX}): {sock}"
        )
    return sock


def _esc(val: str) -> str:
    """Escape a spec value for the executor's line/tab-framed format.

    Spec values are job-controlled (env vars, args); a raw newline or
    tab would inject spec directives into the C++ parser (user, stdout,
    ... — privilege escalation when the agent runs as root).  The
    executor unescapes symmetrically.
    """
    return (
        val.replace("\\", "\\\\")
        .replace("\n", "\\n")
        .replace("\r", "\\r")
        .replace("\t", "\\t")
    )


def launch_executor(
    task_dir: str,
    command: str,
    args: list[str],
    env: dict[str, str],
    stdout_path: str = "",
    stderr_path: str = "",
    cwd: str = "",
    chroot: str = "",
    user: str = "",
    cgroup: str = "",
    netns: str = "",
    memory_max_bytes: int = 0,
    cpu_weight: int = 0,
    cores: Optional[list] = None,
    cache_dir: Optional[str] = None,
) -> ExecutorHandle:
    """Write the spec, launch the daemonized supervisor, return a handle."""
    binary = executor_binary(cache_dir)
    ctl_dir = Path(task_dir)
    ctl_dir.mkdir(parents=True, exist_ok=True)
    sock = _socket_path(task_dir)
    spec_path = str(ctl_dir / "executor.spec")
    for k in env:
        if "=" in k:
            raise ExecutorError(f"invalid env key {k!r}")
    lines = [f"command\t{_esc(command)}"]
    lines += [f"arg\t{_esc(a)}" for a in args]
    lines += [f"core\t{int(c)}" for c in (cores or [])]
    lines += [f"env\t{_esc(f'{k}={v}')}" for k, v in env.items()]
    if cwd:
        lines.append(f"cwd\t{_esc(cwd)}")
    if netns:
        lines.append(f"netns\t{_esc(netns)}")
    if chroot:
        lines.append(f"chroot\t{_esc(chroot)}")
    if stdout_path:
        lines.append(f"stdout\t{_esc(stdout_path)}")
    if stderr_path:
        lines.append(f"stderr\t{_esc(stderr_path)}")
    lines.append(f"socket\t{_esc(sock)}")
    lines.append(f"pidfile\t{_esc(str(ctl_dir / 'executor.pid'))}")
    if user:
        lines.append(f"user\t{_esc(user)}")
    if cgroup:
        lines.append(f"cgroup\t{_esc(cgroup)}")
        if memory_max_bytes:
            lines.append(f"memory_max\t{memory_max_bytes}")
        if cpu_weight:
            lines.append(f"cpu_weight\t{cpu_weight}")
    Path(spec_path).write_text("\n".join(lines) + "\n")

    # Stdout/stderr are opened AFTER the setuid drop in the executor
    # (so an injected path could never be opened as root); pre-create
    # and chown them here so an unprivileged task user can still append.
    if user and os.geteuid() == 0:
        import pwd

        try:
            pw = pwd.getpwnam(user)
        except KeyError:
            pw = None
        if pw is not None:
            for p in (stdout_path, stderr_path):
                if not p:
                    continue
                Path(p).touch(exist_ok=True)
                os.chown(p, pw.pw_uid, pw.pw_gid)

    proc = subprocess.run(
        [binary, spec_path], capture_output=True, text=True, timeout=30
    )
    if proc.returncode != 0 or not proc.stdout.startswith("READY"):
        raise ExecutorError(
            f"executor launch failed: {proc.stdout} {proc.stderr}"
        )
    daemon_pid = int(proc.stdout.split()[1])
    handle = ExecutorHandle(sock, daemon_pid)
    # The daemon binds before the parent prints READY; still, guard the
    # first connect with a short retry for slow filesystems.
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        if handle.alive():
            return handle
        time.sleep(0.02)
    raise ExecutorError("executor socket never came up")
