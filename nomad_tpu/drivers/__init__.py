from .base import (
    Driver,
    DriverError,
    ExitResult,
    Fingerprint,
    TaskConfig,
    TaskHandle,
    TaskStatus,
)
from .docker import DockerDriver
from .exec import ExecDriver
from .java import JavaDriver
from .mock import MockDriver
from .qemu import QemuDriver
from .rawexec import RawExecDriver

BUILTIN_DRIVERS = {
    "mock": MockDriver,
    "rawexec": RawExecDriver,
    "exec": ExecDriver,
    "docker": DockerDriver,
    "java": JavaDriver,
    "qemu": QemuDriver,
}


def new_driver(name: str) -> Driver:
    factory = BUILTIN_DRIVERS.get(name)
    if factory is None:
        raise ValueError(f"unknown driver '{name}'")
    return factory()
