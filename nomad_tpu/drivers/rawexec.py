"""Raw fork/exec driver: real processes, no isolation.

Reference: drivers/rawexec (703 LoC). Config keys:
  command   executable path (required)
  args      list of arguments
The process group is killed on stop so children don't leak. Reattach after
a client restart works via the pid recorded in the handle (reference:
rawexec recoverTask using the executor reattach config).
"""

from __future__ import annotations

import os
import signal as _signal
import subprocess
import threading
import time
from typing import Any, Optional

from ..structs import now_ns
from .base import (
    Driver,
    DriverError,
    ExitResult,
    Fingerprint,
    TaskConfig,
    TaskHandle,
    TaskStatus,
    TASK_STATE_EXITED,
    TASK_STATE_RUNNING,
)


def _pin_cores(pid: int, cores: list) -> None:
    """Parent-side affinity pin for scheduler-granted dedicated cores
    (reference: cpuset cgroup via LinuxResources.CpusetCpus). Runs
    immediately after spawn — NOT via preexec_fn, which executes Python
    between fork and exec in this heavily threaded process (documented
    deadlock hazard). The window before the pin is microseconds; tasks
    needing fork-safe pinning from the first instruction use the exec
    driver, whose C++ supervisor pins in the child natively.
    Best-effort: an out-of-range id (host shrank) must not fail the
    start."""
    try:
        os.sched_setaffinity(pid, {int(c) for c in cores})
    except (OSError, AttributeError, ValueError):
        import logging

        logging.getLogger("nomad_tpu.drivers").warning(
            "could not pin pid %d to cores %s", pid, cores
        )



class _RawTask:
    def __init__(self, cfg: TaskConfig, proc: subprocess.Popen):
        self.cfg = cfg
        self.proc = proc
        self.started_at = now_ns()
        self.completed_at = 0
        self.exit_result: Optional[ExitResult] = None
        self.done = threading.Event()
        self._waiter = threading.Thread(
            target=self._wait, name="rawexec-waiter", daemon=True
        )
        self._waiter.start()

    def _wait(self) -> None:
        code = self.proc.wait()
        self.completed_at = now_ns()
        if code < 0:
            self.exit_result = ExitResult(exit_code=128 - code, signal=-code)
        else:
            self.exit_result = ExitResult(exit_code=code)
        self.done.set()


def _spawn_streaming(cmd: list[str], tty: bool):
    """Un-contained streaming exec: subprocess over a socketpair (or a
    pty when tty=True); returns the caller's socket end."""
    import socket as _socket

    if tty:
        import pty as _pty

        pid, master = _pty.fork()
        if pid == 0:
            try:
                os.execvp(cmd[0], cmd)
            finally:
                os._exit(127)
        # a pty master is not a socket: bridge it onto a socketpair
        parent, inner = _socket.socketpair()

        def _pump_out():
            try:
                while True:
                    data = os.read(master, 4096)
                    if not data:
                        break
                    inner.sendall(data)
            except OSError:
                pass
            finally:
                try:
                    inner.shutdown(_socket.SHUT_WR)
                except OSError:
                    pass
                try:
                    os.waitpid(pid, 0)  # reap: no zombie per exec
                except OSError:
                    pass

        def _pump_in():
            try:
                while True:
                    data = inner.recv(4096)
                    if not data:
                        break
                    os.write(master, data)
            except OSError:
                pass
            finally:
                try:
                    os.close(master)
                except OSError:
                    pass

        threading.Thread(
            target=_pump_out, name="exec-pty-out", daemon=True
        ).start()
        threading.Thread(
            target=_pump_in, name="exec-pty-in", daemon=True
        ).start()
        return parent
    parent, child = _socket.socketpair()
    try:
        proc = subprocess.Popen(
            cmd,
            stdin=child,
            stdout=child,
            stderr=child,
            start_new_session=True,
        )
    except OSError as e:
        parent.close()
        raise DriverError(f"exec spawn: {e}") from e
    finally:
        child.close()
    # reap in the background so exec children never pile up as zombies
    threading.Thread(
        target=proc.wait, name="exec-reaper", daemon=True
    ).start()
    return parent


class RawExecDriver(Driver):
    name = "rawexec"

    def __init__(self) -> None:
        self.tasks: dict[str, _RawTask] = {}
        self._lock = threading.Lock()

    def fingerprint(self) -> Fingerprint:
        return Fingerprint(attributes={"driver.rawexec": "1"})

    def start_task(self, cfg: TaskConfig) -> TaskHandle:
        from .configspec import RAWEXEC_SPEC

        conf = RAWEXEC_SPEC.validate(cfg.config, "rawexec")
        command = conf.get("command")
        if not command:
            raise DriverError("rawexec: missing 'command' in task config")
        args = [str(a) for a in conf.get("args", [])]
        stdout = open(cfg.stdout_path, "ab") if cfg.stdout_path else subprocess.DEVNULL
        stderr = open(cfg.stderr_path, "ab") if cfg.stderr_path else subprocess.DEVNULL
        env = dict(os.environ)
        env.update(cfg.env)
        argv = [command] + args
        if cfg.network_ns:
            # bridge mode: run inside the alloc's network namespace
            argv = ["nsenter", f"--net={cfg.network_ns}", "--"] + argv
        try:
            proc = subprocess.Popen(
                argv,
                stdout=stdout,
                stderr=stderr,
                env=env,
                cwd=cfg.task_dir or None,
                start_new_session=True,  # own process group for clean kill
            )
        except OSError as e:
            raise DriverError(f"rawexec: failed to start: {e}") from e
        finally:
            for f in (stdout, stderr):
                if hasattr(f, "close"):
                    f.close()
        if cfg.reserved_cores:
            _pin_cores(proc.pid, cfg.reserved_cores)
        task = _RawTask(cfg, proc)
        with self._lock:
            self.tasks[cfg.id] = task
        return TaskHandle(cfg.id, self.name, {"pid": proc.pid})

    def wait_task(self, task_id: str, timeout_s: Optional[float] = None) -> Optional[ExitResult]:
        task = self._get(task_id)
        if not task.done.wait(timeout_s):
            return None
        return task.exit_result

    def stop_task(self, task_id: str, timeout_s: float, signal: str = "") -> None:
        task = self._get(task_id)
        if task.done.is_set():
            return
        sig = getattr(_signal, signal, _signal.SIGTERM) if signal else _signal.SIGTERM
        try:
            os.killpg(os.getpgid(task.proc.pid), sig)
        except ProcessLookupError:
            return
        if not task.done.wait(timeout_s):
            try:
                os.killpg(os.getpgid(task.proc.pid), _signal.SIGKILL)
            except ProcessLookupError:
                pass
            task.done.wait(5)

    def destroy_task(self, task_id: str, force: bool = False) -> None:
        with self._lock:
            task = self.tasks.get(task_id)
        if task is None:
            return
        if not task.done.is_set():
            if not force:
                raise DriverError("task still running")
            self.stop_task(task_id, timeout_s=2)
        with self._lock:
            self.tasks.pop(task_id, None)

    def inspect_task(self, task_id: str) -> TaskStatus:
        task = self._get(task_id)
        return TaskStatus(
            id=task_id,
            name=task.cfg.name,
            state=TASK_STATE_EXITED if task.done.is_set() else TASK_STATE_RUNNING,
            started_at_ns=task.started_at,
            completed_at_ns=task.completed_at,
            exit_result=task.exit_result,
        )

    def signal_task(self, task_id: str, signal: str) -> None:
        task = self._get(task_id)
        sig = getattr(_signal, signal, None)
        if sig is None:
            raise DriverError(f"unknown signal {signal}")
        os.kill(task.proc.pid, sig)

    def exec_task(self, task_id: str, cmd: list[str], timeout_s: float = 30.0) -> tuple[bytes, int]:
        # rawexec has no container: exec runs in the same namespace
        out = subprocess.run(
            cmd, capture_output=True, timeout=timeout_s
        )
        return out.stdout + out.stderr, out.returncode

    def exec_task_streaming(self, task_id: str, cmd: list[str], tty: bool = False):
        self._get(task_id)  # validate the task exists
        return _spawn_streaming(cmd, tty)

    def recover_task(self, handle: TaskHandle) -> None:
        pid = handle.state.get("pid")
        if pid is None:
            raise DriverError("no pid in handle")
        with self._lock:
            if handle.task_id in self.tasks:
                return
        try:
            os.kill(pid, 0)  # liveness probe
        except ProcessLookupError:
            raise DriverError(f"pid {pid} is gone") from None
        # Re-adopt: poll the pid (we are not its parent after restart).
        cfg = TaskConfig(id=handle.task_id)
        task = _RawTask.__new__(_RawTask)
        task.cfg = cfg
        task.proc = _AdoptedProcess(pid)
        task.started_at = now_ns()
        task.completed_at = 0
        task.exit_result = None
        task.done = threading.Event()
        task._waiter = threading.Thread(
            target=task._wait, name="rawexec-waiter", daemon=True
        )
        task._waiter.start()
        with self._lock:
            self.tasks[handle.task_id] = task

    def _get(self, task_id: str) -> _RawTask:
        with self._lock:
            task = self.tasks.get(task_id)
        if task is None:
            raise DriverError(f"unknown task {task_id}")
        return task


class _AdoptedProcess:
    """Popen-alike for a re-attached pid we didn't spawn."""

    def __init__(self, pid: int):
        self.pid = pid

    def wait(self) -> int:
        while True:
            try:
                os.kill(self.pid, 0)
            except ProcessLookupError:
                return 0  # exit status unknowable once reparented
            time.sleep(0.2)
