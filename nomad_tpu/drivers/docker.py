"""Docker task driver.

Reference: drivers/docker/driver.go (container lifecycle, stats, exec,
docklog) and drivers/docker/coordinator.go (deduped concurrent image
pulls). The reference links the Docker SDK; here the Engine REST API is
spoken directly over the unix socket with stdlib http.client — no
dependency, and the tests can stand up a fake daemon on a temp socket
(real dockerd e2e runs when /var/run/docker.sock exists).

Layering:
  DockerAPI        — minimal Engine client (images, containers, exec)
  PullCoordinator  — one in-flight pull per image ref, others wait
  DockerDriver     — the Driver interface: start/wait/stop/destroy/
                     stats/signal/exec/recover; container logs are pumped
                     into the task's stdout/stderr files (the docklog
                     analog, feeding the existing logmon rotation).
"""

from __future__ import annotations

import http.client
import json
import logging
import os
import re
import socket
import struct
import threading
import time
from typing import Any, Optional

from .base import (
    Driver,
    DriverError,
    ExitResult,
    Fingerprint,
    HEALTH_STATE_HEALTHY,
    HEALTH_STATE_UNDETECTED,
    TASK_STATE_EXITED,
    TASK_STATE_RUNNING,
    TASK_STATE_UNKNOWN,
    TaskConfig,
    TaskHandle,
    TaskStatus,
)

logger = logging.getLogger("nomad_tpu.drivers.docker")

DEFAULT_SOCKET = "/var/run/docker.sock"
API_VERSION = "v1.40"


class _UnixHTTPConnection(http.client.HTTPConnection):
    def __init__(self, path: str, timeout: Optional[float] = None) -> None:
        super().__init__("localhost", timeout=timeout)
        self._unix_path = path

    def connect(self) -> None:
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        if self.timeout is not None:
            sock.settimeout(self.timeout)
        sock.connect(self._unix_path)
        self.sock = sock


class DockerAPIError(DriverError):
    def __init__(self, status: int, message: str) -> None:
        self.status = status
        super().__init__(f"docker api {status}: {message}")


class DockerAPI:
    """Minimal Docker Engine REST client over a unix socket."""

    def __init__(self, socket_path: str = DEFAULT_SOCKET,
                 timeout_s: float = 60.0) -> None:
        self.socket_path = socket_path
        self.timeout_s = timeout_s

    def _conn(self, timeout_s: Optional[float] = None) -> _UnixHTTPConnection:
        return _UnixHTTPConnection(
            self.socket_path, timeout=timeout_s or self.timeout_s
        )

    def _request(
        self,
        method: str,
        path: str,
        body: Optional[dict] = None,
        timeout_s: Optional[float] = None,
        stream: bool = False,
    ):
        """Returns parsed JSON (or b'' for 204). stream=True returns the
        live (conn, response) pair — caller owns closing the conn."""
        conn = self._conn(timeout_s)
        data = json.dumps(body).encode() if body is not None else None
        headers = {"Content-Type": "application/json"} if data else {}
        try:
            conn.request(method, f"/{API_VERSION}{path}", body=data,
                         headers=headers)
            resp = conn.getresponse()
        except (OSError, http.client.HTTPException) as e:
            conn.close()
            raise DriverError(f"docker daemon unreachable: {e}") from e
        if resp.status >= 400:
            try:
                msg = json.loads(resp.read() or b"{}").get("message", "")
            except Exception:
                msg = ""
            conn.close()
            raise DockerAPIError(resp.status, msg or resp.reason)
        if stream:
            return conn, resp
        try:
            raw = resp.read()
        finally:
            conn.close()
        if not raw:
            return None
        ctype = resp.headers.get("Content-Type", "")
        if "json" in ctype:
            # progress endpoints emit newline-delimited JSON objects
            lines = [ln for ln in raw.split(b"\n") if ln.strip()]
            if len(lines) > 1:
                return [json.loads(ln) for ln in lines]
            return json.loads(lines[0]) if lines else None
        return raw

    # -- daemon ---------------------------------------------------------

    def ping(self) -> bool:
        try:
            conn = self._conn(2.0)
            conn.request("GET", "/_ping")
            ok = conn.getresponse().status == 200
            conn.close()
            return ok
        except OSError:
            return False

    def version(self) -> dict:
        return self._request("GET", "/version") or {}

    # -- images ---------------------------------------------------------

    def image_inspect(self, ref: str) -> Optional[dict]:
        try:
            return self._request("GET", f"/images/{ref}/json")
        except DockerAPIError as e:
            if e.status == 404:
                return None
            raise

    def image_pull(self, ref: str, timeout_s: float = 300.0) -> None:
        """POST /images/create; consumes the progress stream to completion
        and surfaces daemon-reported errors."""
        if "@" in ref:
            # digest-pinned (image@sha256:...): the digest IS the
            # reference; a tag split would cut inside the digest
            query = f"fromImage={ref}"
        elif ":" in ref.rsplit("/", 1)[-1]:
            image, tag = ref.rsplit(":", 1)
            query = f"fromImage={image}&tag={tag}"
        else:
            query = f"fromImage={ref}&tag=latest"
        conn, resp = self._request(
            "POST",
            f"/images/create?{query}",
            timeout_s=timeout_s,
            stream=True,
        )
        try:
            buf = b""
            while True:
                chunk = resp.read(8192)
                if not chunk:
                    break
                buf += chunk
            for ln in buf.split(b"\n"):
                if not ln.strip():
                    continue
                try:
                    msg = json.loads(ln)
                except ValueError:
                    continue
                if msg.get("error"):
                    raise DriverError(f"pull {ref}: {msg['error']}")
        finally:
            conn.close()

    # -- containers -------------------------------------------------------

    def container_create(self, name: str, config: dict) -> str:
        out = self._request("POST", f"/containers/create?name={name}", config)
        return out["Id"]

    def container_start(self, cid: str) -> None:
        self._request("POST", f"/containers/{cid}/start")

    def container_stop(self, cid: str, timeout_s: int) -> None:
        self._request(
            "POST",
            f"/containers/{cid}/stop?t={int(timeout_s)}",
            timeout_s=timeout_s + 15,
        )

    def container_kill(self, cid: str, signal: str = "SIGKILL") -> None:
        self._request("POST", f"/containers/{cid}/kill?signal={signal}")

    def container_remove(self, cid: str, force: bool = False) -> None:
        f = "true" if force else "false"
        self._request("DELETE", f"/containers/{cid}?force={f}&v=true")

    def container_inspect(self, cid: str) -> dict:
        return self._request("GET", f"/containers/{cid}/json")

    def container_wait(self, cid: str, timeout_s: Optional[float] = None) -> int:
        out = self._request(
            "POST", f"/containers/{cid}/wait", timeout_s=timeout_s or 10**8
        )
        return int(out.get("StatusCode", -1))

    def container_stats(self, cid: str) -> dict:
        return self._request("GET", f"/containers/{cid}/stats?stream=false")

    def container_logs_stream(self, cid: str, since: int = 0):
        """(conn, resp) for the multiplexed follow stream."""
        return self._request(
            "GET",
            f"/containers/{cid}/logs?follow=true&stdout=true&stderr=true"
            f"&since={since}",
            timeout_s=10**8,
            stream=True,
        )

    # -- exec -------------------------------------------------------------

    def exec_create(self, cid: str, cmd: list[str], tty: bool) -> str:
        out = self._request(
            "POST",
            f"/containers/{cid}/exec",
            {
                "AttachStdin": True,
                "AttachStdout": True,
                "AttachStderr": True,
                "Tty": tty,
                "Cmd": cmd,
            },
        )
        return out["Id"]

    def exec_start_socket(self, exec_id: str, tty: bool) -> socket.socket:
        """Start the exec and hijack the connection into a raw socket.

        Hand-rolled handshake: http.client buffers past the headers, which
        would swallow the first stream bytes — instead the response head is
        read byte-wise up to the blank line and the socket handed over
        clean."""
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        sock.settimeout(self.timeout_s)
        try:
            sock.connect(self.socket_path)
            body = json.dumps({"Detach": False, "Tty": tty}).encode()
            req = (
                f"POST /{API_VERSION}/exec/{exec_id}/start HTTP/1.1\r\n"
                f"Host: localhost\r\nContent-Type: application/json\r\n"
                f"Content-Length: {len(body)}\r\n"
                f"Connection: Upgrade\r\nUpgrade: tcp\r\n\r\n"
            ).encode() + body
            sock.sendall(req)
            head = b""
            while b"\r\n\r\n" not in head:
                b = sock.recv(1)
                if not b:
                    raise DriverError("exec start: connection closed")
                head += b
            status_line = head.split(b"\r\n", 1)[0].decode(errors="replace")
            parts = status_line.split()
            status = int(parts[1]) if len(parts) > 1 else 500
            if status >= 400:
                raise DockerAPIError(status, status_line)
            sock.settimeout(None)
            return sock
        except DriverError:
            sock.close()
            raise
        except (OSError, ValueError) as e:
            sock.close()
            raise DriverError(f"exec start failed: {e}") from e

    def exec_inspect(self, exec_id: str) -> dict:
        return self._request("GET", f"/exec/{exec_id}/json")


def demux_stream(read_fn, on_stdout, on_stderr) -> None:
    """Decode Docker's 8-byte-header multiplexed stream until EOF
    (reference: stdcopy). read_fn(n) -> bytes ('' on EOF)."""
    buf = b""
    while True:
        while len(buf) < 8:
            chunk = read_fn(8 - len(buf))
            if not chunk:
                return
            buf += chunk
        kind, length = buf[0], struct.unpack(">I", buf[4:8])[0]
        buf = buf[8:]
        while len(buf) < length:
            chunk = read_fn(length - len(buf))
            if not chunk:
                return
            buf += chunk
        payload, buf = buf[:length], buf[length:]
        (on_stderr if kind == 2 else on_stdout)(payload)


class PullCoordinator:
    """One in-flight pull per image ref; concurrent requesters wait for
    the winner's outcome (reference drivers/docker/coordinator.go)."""

    def __init__(self, api: DockerAPI) -> None:
        self.api = api
        self._lock = threading.Lock()
        self._inflight: dict[str, threading.Event] = {}
        self._results: dict[str, Optional[Exception]] = {}

    def pull(self, ref: str, timeout_s: float = 300.0) -> None:
        with self._lock:
            ev = self._inflight.get(ref)
            if ev is None:
                ev = self._inflight[ref] = threading.Event()
                owner = True
            else:
                owner = False
        if not owner:
            if not ev.wait(timeout_s):
                raise DriverError(f"pull {ref}: timed out waiting on peer")
            err = self._results.get(ref)
            if err is not None:
                raise DriverError(f"pull {ref} failed: {err}")
            return
        err: Optional[Exception] = None
        try:
            self.api.image_pull(ref, timeout_s)
        except Exception as e:
            err = e
        finally:
            with self._lock:
                self._results[ref] = err
                self._inflight.pop(ref, None)
            ev.set()
        if err is not None:
            raise DriverError(f"pull {ref} failed: {err}")


class _DockerTask:
    def __init__(self, cfg: TaskConfig, cid: str) -> None:
        self.cfg = cfg
        self.cid = cid
        self.exit: Optional[ExitResult] = None
        self.done = threading.Event()
        self.started_ns = time.time_ns()
        self.completed_ns = 0
        self._log_conn = None


_NAME_RE = re.compile(r"[^a-zA-Z0-9_.-]")


class DockerDriver(Driver):
    """Reference parity: drivers/docker/driver.go StartTask :370,
    pull dedup via coordinator.go, docklog via the logs follow stream."""

    # volume_mounts become real (ro-capable) binds, not symlinks
    bind_mounts = True

    name = "docker"

    def __init__(self, socket_path: Optional[str] = None) -> None:
        # NOMAD_DOCKER_SOCKET mirrors the reference's docker.endpoint
        # plugin config knob (tests point it at a fake daemon).
        if socket_path is None:
            socket_path = os.environ.get("NOMAD_DOCKER_SOCKET", DEFAULT_SOCKET)
        self.api = DockerAPI(socket_path)
        self.coordinator = PullCoordinator(self.api)
        self.tasks: dict[str, _DockerTask] = {}
        self._lock = threading.Lock()

    # -- fingerprint ----------------------------------------------------

    def fingerprint(self) -> Fingerprint:
        if not os.path.exists(self.api.socket_path) or not self.api.ping():
            return Fingerprint(
                attributes={},
                health=HEALTH_STATE_UNDETECTED,
                health_description="docker daemon not reachable",
            )
        try:
            v = self.api.version()
        except DriverError:
            v = {}
        return Fingerprint(
            attributes={
                "driver.docker": "1",
                "driver.docker.version": str(v.get("Version", "unknown")),
            },
            health=HEALTH_STATE_HEALTHY,
            health_description="",
        )

    # -- lifecycle ------------------------------------------------------

    def start_task(self, cfg: TaskConfig) -> TaskHandle:
        from .configspec import DOCKER_SPEC

        conf = DOCKER_SPEC.validate(cfg.config, "docker")
        image = conf["image"]
        if conf.get("force_pull") or self.api.image_inspect(image) is None:
            self.coordinator.pull(image)

        env = [f"{k}={v}" for k, v in (cfg.env or {}).items()]
        binds = list(conf.get("volumes") or [])
        if cfg.task_dir:
            # the task dir rides at /local like the reference's task mounts
            binds.append(f"{cfg.task_dir}:/local")
        # group-volume mounts resolved by the task runner (host + CSI);
        # container paths must be absolute for the Docker API, so a
        # relative destination roots at / (the filesystem drivers root
        # theirs at the task dir)
        for m in getattr(cfg, "mounts", None) or []:
            mode = ":ro" if m.get("read_only") else ""
            dest = m["task_path"]
            if not dest.startswith("/"):
                dest = "/" + dest
            binds.append(f"{m['host_path']}:{dest}{mode}")
        host_config: dict[str, Any] = {
            "Binds": binds,
            "Memory": int(
                cfg.resources_memory_max_mb or cfg.resources_memory_mb
            ) * 1024 * 1024,
            "CpuShares": int(cfg.resources_cpu),
        }
        if conf.get("network_mode"):
            host_config["NetworkMode"] = conf["network_mode"]
        create: dict[str, Any] = {
            "Image": image,
            "Env": env,
            "HostConfig": host_config,
            "Labels": {
                "nomad_tpu.task_id": cfg.id,
                "nomad_tpu.alloc_id": cfg.alloc_id,
                **(conf.get("labels") or {}),
            },
        }
        if conf.get("entrypoint"):
            create["Entrypoint"] = list(conf["entrypoint"])
        cmd: list[str] = []
        if conf.get("command"):
            cmd.append(conf["command"])
        cmd.extend(conf.get("args") or [])
        if cmd:
            create["Cmd"] = cmd
        if conf.get("work_dir"):
            create["WorkingDir"] = conf["work_dir"]
        if cfg.user:
            create["User"] = cfg.user

        # Keep the FRONT of the id (the alloc uuid that makes it unique)
        # and add a digest suffix: tail-truncation could collide two
        # allocs of a long-named task and the 409 retry would then
        # force-remove a healthy container.
        import hashlib

        digest = hashlib.sha256(cfg.id.encode()).hexdigest()[:8]
        cname = f"nomad-{_NAME_RE.sub('-', cfg.id)[:46]}-{digest}"
        try:
            cid = self.api.container_create(cname, create)
        except DockerAPIError as e:
            if e.status == 409:
                # leftover from a crashed run: remove and retry once
                # (reference driver.go createContainer purge semantics)
                try:
                    self.api.container_remove(cname, force=True)
                except DriverError:
                    pass
                cid = self.api.container_create(cname, create)
            else:
                raise
        self.api.container_start(cid)

        task = _DockerTask(cfg, cid)
        with self._lock:
            self.tasks[cfg.id] = task
        self._spawn_waiter(task)
        self._spawn_log_pump(task, since=0)
        return TaskHandle(
            cfg.id,
            self.name,
            {
                "container_id": cid,
                "task_name": cfg.name,
                "stdout_path": cfg.stdout_path,
                "stderr_path": cfg.stderr_path,
            },
        )

    def _spawn_waiter(self, task: _DockerTask) -> None:
        def waiter():
            code = -1
            oom = False
            try:
                code = self.api.container_wait(task.cid)
                try:
                    st = self.api.container_inspect(task.cid)["State"]
                    oom = bool(st.get("OOMKilled"))
                except DriverError:
                    pass
            except DriverError as e:
                task.exit = ExitResult(exit_code=-1, err=str(e))
            if task.exit is None:
                task.exit = ExitResult(exit_code=code, oom_killed=oom)
            task.completed_ns = time.time_ns()
            task.done.set()

        threading.Thread(
            target=waiter, daemon=True, name=f"docker-wait-{task.cid[:12]}"
        ).start()

    def _spawn_log_pump(self, task: _DockerTask, since: int) -> None:
        """The docklog analog: follow the container's multiplexed log
        stream and append to the task's stdout/stderr files, where the
        existing logmon rotation + FS.logs streaming pick them up."""
        cfg = task.cfg
        if not cfg.stdout_path:
            return

        def pump():
            try:
                conn, resp = self.api.container_logs_stream(task.cid, since)
            except DriverError:
                return
            task._log_conn = conn
            try:
                with open(cfg.stdout_path, "ab") as out_f, open(
                    cfg.stderr_path or cfg.stdout_path, "ab"
                ) as err_f:
                    def w(f):
                        def write(b):
                            f.write(b)
                            f.flush()
                        return write

                    demux_stream(resp.read, w(out_f), w(err_f))
            except (OSError, ValueError, AttributeError):
                # AttributeError: destroy_task tore the connection down
                # under us (http.client nulls resp.fp on close)
                pass
            finally:
                conn.close()

        threading.Thread(
            target=pump, daemon=True, name=f"docker-log-{task.cid[:12]}"
        ).start()

    def _get(self, task_id: str) -> _DockerTask:
        with self._lock:
            task = self.tasks.get(task_id)
        if task is None:
            raise DriverError(f"unknown task {task_id}")
        return task

    def wait_task(
        self, task_id: str, timeout_s: Optional[float] = None
    ) -> Optional[ExitResult]:
        task = self._get(task_id)
        if not task.done.wait(timeout_s):
            return None
        return task.exit

    def stop_task(self, task_id: str, timeout_s: float, signal: str = "") -> None:
        task = self._get(task_id)
        try:
            if signal and signal not in ("SIGTERM", "TERM"):
                self.api.container_kill(task.cid, signal)
                if not task.done.wait(timeout_s):
                    self.api.container_kill(task.cid, "SIGKILL")
            else:
                # docker stop = SIGTERM, grace period, SIGKILL
                self.api.container_stop(task.cid, int(max(1, timeout_s)))
        except DockerAPIError as e:
            if e.status not in (304, 404, 409):  # already stopped/gone
                raise

    def destroy_task(self, task_id: str, force: bool = False) -> None:
        task = self._get(task_id)
        if not task.done.is_set() and not force:
            raise DriverError("task still running; use force")
        try:
            self.api.container_remove(task.cid, force=True)
        except DockerAPIError as e:
            if e.status != 404:
                raise
        self._close_log_conn(task)
        with self._lock:
            self.tasks.pop(task_id, None)

    @staticmethod
    def _close_log_conn(task: _DockerTask) -> None:
        """Force the follow-stream down: shut the raw socket first so a
        pump thread blocked mid-recv unblocks immediately (plain
        HTTPConnection.close() would wait for the response to drain)."""
        conn = task._log_conn
        if conn is None:
            return
        try:
            if conn.sock is not None:
                conn.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            conn.close()
        except OSError:
            pass

    def inspect_task(self, task_id: str) -> TaskStatus:
        task = self._get(task_id)
        state = TASK_STATE_UNKNOWN
        try:
            st = self.api.container_inspect(task.cid)["State"]
            state = TASK_STATE_RUNNING if st.get("Running") else TASK_STATE_EXITED
        except DriverError:
            if task.done.is_set():
                state = TASK_STATE_EXITED
        return TaskStatus(
            id=task_id,
            name=task.cfg.name,
            state=state,
            started_at_ns=task.started_ns,
            completed_at_ns=task.completed_ns,
            exit_result=task.exit,
        )

    def task_stats(self, task_id: str) -> dict[str, Any]:
        task = self._get(task_id)
        try:
            s = self.api.container_stats(task.cid) or {}
        except DriverError:
            return {}
        cpu = s.get("cpu_stats", {}).get("cpu_usage", {})
        mem = s.get("memory_stats", {})
        return {
            "cpu_user_s": cpu.get("usage_in_usermode", 0) / 1e9,
            "cpu_system_s": cpu.get("usage_in_kernelmode", 0) / 1e9,
            "memory_rss_bytes": mem.get("usage", 0),
            "memory_cgroup_bytes": mem.get("limit", -1),
        }

    def signal_task(self, task_id: str, signal: str) -> None:
        task = self._get(task_id)
        self.api.container_kill(task.cid, signal)

    # -- exec ------------------------------------------------------------

    def exec_task_streaming(self, task_id: str, cmd: list[str], tty: bool = False):
        task = self._get(task_id)
        exec_id = self.api.exec_create(task.cid, cmd, tty)
        sock = self.api.exec_start_socket(exec_id, tty)
        return sock

    def exec_task(
        self, task_id: str, cmd: list[str], timeout_s: float = 30.0
    ) -> tuple[bytes, int]:
        """One-shot exec. timeout_s is a WALL-CLOCK bound: on expiry the
        partial output returns with exit code 124 (the exec driver's
        convention), never a silent -1."""
        task = self._get(task_id)
        exec_id = self.api.exec_create(task.cid, cmd, tty=False)
        sock = self.api.exec_start_socket(exec_id, tty=False)
        out = bytearray()
        deadline = time.monotonic() + timeout_s
        timed_out = False
        try:
            def read_fn(n):
                nonlocal timed_out
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    timed_out = True
                    return b""
                sock.settimeout(remaining)
                try:
                    return sock.recv(n)
                except TimeoutError:
                    timed_out = True
                    return b""
                except OSError:
                    return b""

            demux_stream(read_fn, out.extend, out.extend)
        finally:
            sock.close()
        if timed_out:
            return bytes(out), 124
        poll_deadline = time.monotonic() + 5.0
        code = -1
        while time.monotonic() < poll_deadline:
            info = self.api.exec_inspect(exec_id)
            if not info.get("Running", False):
                code = int(info.get("ExitCode") or 0)
                break
            time.sleep(0.05)
        return bytes(out), code

    # -- recovery --------------------------------------------------------

    def recover_task(self, handle: TaskHandle) -> None:
        cid = handle.state.get("container_id")
        if not cid:
            raise DriverError("no container_id in handle")
        try:
            st = self.api.container_inspect(cid)["State"]
        except DriverError as e:
            raise DriverError(f"container {cid[:12]} is gone: {e}") from e
        cfg = TaskConfig(
            id=handle.task_id,
            name=handle.state.get("task_name", ""),
            stdout_path=handle.state.get("stdout_path", ""),
            stderr_path=handle.state.get("stderr_path", ""),
        )
        task = _DockerTask(cfg, cid)
        with self._lock:
            self.tasks[handle.task_id] = task
        if st.get("Running"):
            self._spawn_waiter(task)
            self._spawn_log_pump(task, since=int(time.time()))
        else:
            task.exit = ExitResult(exit_code=int(st.get("ExitCode", -1)))
            task.completed_ns = time.time_ns()
            task.done.set()
