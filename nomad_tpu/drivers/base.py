"""Task driver plugin interface.

Reference: plugins/drivers/driver.go:47-64 DriverPlugin — Fingerprint,
StartTask, WaitTask, StopTask, DestroyTask, InspectTask, TaskStats,
ExecTask, SignalTask, RecoverTask. The reference runs drivers out-of-process
over gRPC (hashicorp/go-plugin); round-1 drivers run in-process behind this
same interface so the gRPC boundary can be added underneath without
touching the task runner.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Any, Optional

HEALTH_STATE_HEALTHY = "healthy"
HEALTH_STATE_UNHEALTHY = "unhealthy"
HEALTH_STATE_UNDETECTED = "undetected"

TASK_STATE_RUNNING = "running"
TASK_STATE_EXITED = "exited"
TASK_STATE_UNKNOWN = "unknown"


@dataclass
class Fingerprint:
    attributes: dict[str, str] = field(default_factory=dict)
    health: str = HEALTH_STATE_HEALTHY
    health_description: str = ""


@dataclass
class TaskConfig:
    """What a driver needs to start a task (reference: drivers.TaskConfig)."""

    id: str = ""  # alloc_id/task_name
    name: str = ""
    alloc_id: str = ""
    env: dict[str, str] = field(default_factory=dict)
    config: dict[str, Any] = field(default_factory=dict)  # driver-specific
    resources_cpu: int = 0
    resources_memory_mb: int = 0
    # oversubscription hard cap (0 = cap at the reserve)
    resources_memory_max_mb: int = 0
    # dedicated core ids (reference LinuxResources.CpusetCpus): pinning
    # drivers restrict the task's cpu affinity to exactly these
    reserved_cores: list = field(default_factory=list)
    task_dir: str = ""
    stdout_path: str = ""
    stderr_path: str = ""
    user: str = ""
    # bridge mode: the alloc's network namespace path — drivers run the
    # task inside it (reference drivers' NetworkIsolationSpec)
    network_ns: str = ""
    # volume mounts: [{"host_path", "task_path", "read_only"}] —
    # bind-mounting drivers (docker) consume these; filesystem drivers
    # get a symlink placed by the task runner (reference: TaskConfig.Mounts)
    mounts: list = field(default_factory=list)


@dataclass
class ExitResult:
    exit_code: int = 0
    signal: int = 0
    oom_killed: bool = False
    err: Optional[str] = None

    def successful(self) -> bool:
        return self.exit_code == 0 and self.signal == 0 and self.err is None


@dataclass
class TaskStatus:
    id: str = ""
    name: str = ""
    state: str = TASK_STATE_UNKNOWN
    started_at_ns: int = 0
    completed_at_ns: int = 0
    exit_result: Optional[ExitResult] = None


class TaskHandle:
    """Opaque driver-side handle; serializable so a restarted client can
    reattach (reference: drivers.TaskHandle + RecoverTask)."""

    def __init__(self, task_id: str, driver: str, state: dict[str, Any]):
        self.task_id = task_id
        self.driver = driver
        self.state = state

    def to_dict(self) -> dict[str, Any]:
        return {"task_id": self.task_id, "driver": self.driver, "state": self.state}

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "TaskHandle":
        return cls(d["task_id"], d["driver"], d.get("state", {}))


class DriverError(Exception):
    pass


class Driver:
    """Base driver; subclasses implement the lifecycle verbs."""

    name = "base"

    def fingerprint(self) -> Fingerprint:
        raise NotImplementedError

    def start_task(self, cfg: TaskConfig) -> TaskHandle:
        raise NotImplementedError

    def wait_task(self, task_id: str, timeout_s: Optional[float] = None) -> Optional[ExitResult]:
        """Block until the task exits; None on timeout."""
        raise NotImplementedError

    def stop_task(self, task_id: str, timeout_s: float, signal: str = "") -> None:
        raise NotImplementedError

    def destroy_task(self, task_id: str, force: bool = False) -> None:
        raise NotImplementedError

    def inspect_task(self, task_id: str) -> TaskStatus:
        raise NotImplementedError

    def task_stats(self, task_id: str) -> dict[str, Any]:
        return {}

    def signal_task(self, task_id: str, signal: str) -> None:
        raise NotImplementedError

    def exec_task(self, task_id: str, cmd: list[str], timeout_s: float = 30.0) -> tuple[bytes, int]:
        raise DriverError(f"driver {self.name} does not support exec")

    def exec_task_streaming(self, task_id: str, cmd: list[str], tty: bool = False):
        """Interactive exec: returns a connected socket bridging the
        exec'd process's stdio (reference ExecTaskStreaming,
        plugins/drivers/execstreaming.go)."""
        raise DriverError(f"driver {self.name} does not support exec")

    def recover_task(self, handle: TaskHandle) -> None:
        raise DriverError(f"driver {self.name} cannot recover tasks")
