"""Userspace TCP relay, shared by bridge-mode port forwarding
(client/network.py PortProxy) and the connect sidecar's data plane
(connect/sidecar.py) — one implementation so accept-loop resilience and
half-close semantics cannot diverge between the two.

Semantics:
  * accept() errors are transient unless stopped — EMFILE/ECONNABORTED
    back off 50ms and keep serving; a relay must not die while its
    workload lives.
  * EOF on one direction propagates as shutdown(SHUT_WR) on the OTHER
    socket only (TCP half-close): a client that closes its write side
    after the request still receives the full response.
"""

from __future__ import annotations

import errno
import socket
import threading
import time
from typing import Callable, Optional


class TcpRelay:
    """Listener forwarding each connection to pick_target()'s choice.

    pick_target may return one ``(host, port)`` target or an ordered
    list of candidate targets; a later candidate is dialed ONLY when
    the earlier one fails with a no-route error (ENETUNREACH /
    EHOSTUNREACH) — a refused or timed-out dial means the primary was
    routable and falling through could deliver the stream to an
    unrelated service listening on the same port at the fallback
    address. The list form exists for the connect sidecar's gateway
    fallback (connect/sidecar.py): a netns'd dialer on a NAT-less host
    has NO ROUTE to a same-host advertised address and reaches the
    same listener through the bridge gateway; the fallback happens
    per-connection, inside the relay, so the unroutable primary never
    turns into a client-visible connection reset."""

    def __init__(
        self,
        listen_port: int,
        pick_target: Callable[[], Optional[tuple[str, int]]],
        listen_host: str = "0.0.0.0",
    ) -> None:
        self.pick_target = pick_target
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((listen_host, listen_port))
        self._srv.listen(64)
        self.port = self._srv.getsockname()[1]
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._accept_loop,
            daemon=True,
            name=f"tcprelay-{self.port}",
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        try:
            self._srv.close()
        except OSError:
            pass

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._srv.accept()
            except OSError:
                if self._stop.is_set():
                    return
                time.sleep(0.05)  # transient: keep serving
                continue
            threading.Thread(
                target=self._relay, args=(conn,),
                name="tcprelay-conn", daemon=True,
            ).start()

    def _relay(self, conn: socket.socket) -> None:
        target = self.pick_target()
        if target is None:
            conn.close()
            return
        candidates = [target] if isinstance(target, tuple) else list(target)
        upstream = None
        for cand in candidates:
            try:
                upstream = socket.create_connection(cand, timeout=10)
                break
            except OSError as e:
                # fall through ONLY when there was no route at all;
                # refused/timeout mean the primary was the right place
                # and merely unhealthy — never reroute those
                if e.errno not in (errno.ENETUNREACH, errno.EHOSTUNREACH):
                    break
        if upstream is None:
            conn.close()
            return

        def pump(src: socket.socket, dst: socket.socket) -> None:
            try:
                while True:
                    data = src.recv(1 << 16)
                    if not data:
                        # half-close: tell the peer this DIRECTION is
                        # done; the reverse stream stays open
                        try:
                            dst.shutdown(socket.SHUT_WR)
                        except OSError:
                            pass
                        break
                    dst.sendall(data)
            except OSError:
                for s in (src, dst):
                    try:
                        s.shutdown(socket.SHUT_RDWR)
                    except OSError:
                        pass

        t = threading.Thread(
            target=pump, args=(conn, upstream),
            name="tcprelay-pump", daemon=True,
        )
        t.start()
        pump(upstream, conn)
        t.join(timeout=30)
        for s in (conn, upstream):
            try:
                s.close()
            except OSError:
                pass
