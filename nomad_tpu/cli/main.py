"""`nomad-tpu` command set.

Reference: command/commands.go:57 registers ~140 subcommands; this is the
working core — agent, job (run/plan/status/stop/inspect/history/revert/
dispatch/periodic), node (status/drain/eligibility), alloc/eval/
deployment status, server members/join, system gc, version. Exit codes
follow the reference where they are load-bearing (`job plan`: 0 = no
changes, 1 = changes, 255 = error).

All commands talk to the HTTP API (NOMAD_ADDR / -address), exactly like
the reference CLI — never to the RPC fabric directly.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import time
from typing import Optional

from .. import codec
from ..api import APIError, NomadClient

VERSION = "0.1.0"


def _fmt_table(rows: list[list[str]], header: Optional[list[str]] = None) -> str:
    all_rows = ([header] if header else []) + rows
    if not all_rows:
        return ""
    widths = [
        max(len(str(r[i])) for r in all_rows) for i in range(len(all_rows[0]))
    ]
    lines = []
    for r in all_rows:
        lines.append(
            "  ".join(str(c).ljust(w) for c, w in zip(r, widths)).rstrip()
        )
    return "\n".join(lines)


def _conn_opts(args) -> tuple[str, str, str]:
    """(address, token, region) with env fallbacks — the single place
    connection defaults are resolved."""
    addr = args.address or os.environ.get(
        "NOMAD_ADDR", "http://127.0.0.1:4646"
    )
    region = getattr(args, "region", "") or os.environ.get(
        "NOMAD_REGION", ""
    )
    token = args.token or os.environ.get("NOMAD_TOKEN", "")
    return addr, token, region


def _client(args) -> NomadClient:
    addr, token, region = _conn_opts(args)
    return NomadClient(
        addr,
        token=token,
        region=region,
        # TLS against an internal CA (reference NOMAD_CACERT /
        # -tls-skip-verify)
        ca_cert=os.environ.get("NOMAD_CACERT", ""),
        tls_skip_verify=os.environ.get("NOMAD_SKIP_VERIFY", "").lower()
        in ("1", "true", "t", "yes"),
    )


def _parse_vars(pairs: list[str]) -> dict:
    out = {}
    for p in pairs or []:
        if "=" not in p:
            raise SystemExit(f"-var must be key=value, got {p!r}")
        k, v = p.split("=", 1)
        out[k] = v
    return out


def _load_jobfile(path: str, variables: dict):
    from ..jobspec import parse_job

    with open(path) as f:
        src = f.read()
    if path.endswith(".json"):
        data = json.loads(src)
        return codec.from_wire(data.get("Job", data))
    return parse_job(src, variables)


# ---------------------------------------------------------------------------
# agent


def cmd_agent(args) -> int:
    from ..agent import Agent, AgentConfig

    if args.config:
        cfg = _load_agent_config(args.config)
    else:
        cfg = AgentConfig()
    if args.dev:
        cfg.server_enabled = True
        cfg.client_enabled = True
        cfg.dev_mode = True  # ephemeral raft, like the reference's -dev
    if args.server:
        cfg.server_enabled = True
    if args.client:
        cfg.client_enabled = True
    if args.bootstrap_expect:
        cfg.bootstrap_expect = args.bootstrap_expect
    if args.join:
        cfg.server_join = [_addr(j) for j in args.join]
    if args.servers:
        cfg.client_servers = [_addr(j) for j in args.servers]
    if args.data_dir:
        cfg.data_dir = args.data_dir
    if args.node_name:
        cfg.node_name = args.node_name
    if args.http_port is not None:
        cfg.http_port = args.http_port
    if args.rpc_port is not None:
        cfg.rpc_port = args.rpc_port
    if args.tpu_scheduler:
        cfg.use_tpu_batch_worker = True

    agent = Agent(cfg)
    agent.start()
    if agent.http_addr:
        print(f"==> HTTP API: http://{agent.http_addr[0]}:{agent.http_addr[1]}")
    if agent.server:
        print(f"==> RPC: {agent.server.addr[0]}:{agent.server.addr[1]}")
    print("==> Agent started! Ctrl-C to stop.")
    stop = [False]
    hup = [False]

    def on_sig(sig, frame):
        stop[0] = True

    def on_hup(sig, frame):
        hup[0] = True  # handled on the main loop, not in the handler

    signal.signal(signal.SIGINT, on_sig)
    signal.signal(signal.SIGTERM, on_sig)
    # SIGHUP re-reads the config file and applies the reloadable subset
    # (TLS material, client meta, vault allowlist — Agent.reload);
    # reference command/agent/command.go handleSignals → handleReload.
    if hasattr(signal, "SIGHUP"):
        signal.signal(signal.SIGHUP, on_hup)
    try:
        while not stop[0]:
            if hup[0]:
                hup[0] = False
                if args.config:
                    try:
                        changed = agent.reload(_load_agent_config(args.config))
                        print(f"==> Config reloaded: {changed or 'no changes'}")
                    except Exception as e:
                        print(f"==> Config reload FAILED: {e}")
                else:
                    print("==> SIGHUP ignored: agent started without -config")
            time.sleep(0.2)
    finally:
        print("==> Shutting down")
        agent.shutdown()
    return 0


def _addr(s: str) -> tuple[str, int]:
    host, _, port = s.partition(":")
    return (host, int(port or 4647))


def _load_agent_config(path: str):
    from ..agent import AgentConfig

    # NB: `from ..jobspec import parse` would bind the parse SUBMODULE
    # (import machinery rebinds the package attr), not the hcl function.
    from ..jobspec.hcl import parse as parse_hcl

    with open(path) as f:
        src = f.read()
    cfg = AgentConfig()
    if path.endswith(".json"):
        data = json.loads(src)
        _apply_config_dict(cfg, data)
        return cfg
    body = parse_hcl(src)
    a = body.attrs()
    for k in (
        "region",
        "datacenter",
        "data_dir",
        "bind_addr",
        "node_name",
        "rpc_secret",
    ):
        if k in a:
            setattr(cfg, k, a[k])
    if "rpc_secret_window" in a:
        from ..jobspec.hcl import parse_duration

        cfg.rpc_secret_window_s = parse_duration(a["rpc_secret_window"])
    sb = body.block("server")
    if sb is not None:
        sa = sb.body.attrs()
        cfg.server_enabled = bool(sa.get("enabled", True))
        cfg.bootstrap_expect = int(sa.get("bootstrap_expect", 1))
        cfg.server_join = [_addr(s) for s in sa.get("server_join", [])]
    cb = body.block("client")
    if cb is not None:
        ca = cb.body.attrs()
        cfg.client_enabled = bool(ca.get("enabled", True))
        cfg.client_servers = [_addr(s) for s in ca.get("servers", [])]
        cfg.node_class = ca.get("node_class", "")
        cfg.csi_plugins = dict(ca.get("csi_plugins", {}))
        ce = cb.body.block("chroot_env")
        if ce is not None:
            cfg.chroot_env = {
                str(k): str(v) for k, v in ce.body.attrs().items()
            }
        mb2 = cb.body.block("meta")
        if mb2 is not None:
            cfg.node_meta = {
                str(k): str(v) for k, v in mb2.body.attrs().items()
            }
        rb2 = cb.body.block("reserved")
        if rb2 is not None:
            ra = rb2.body.attrs()
            cfg.reserved = {
                "cpu": int(ra.get("cpu", 0)),
                "memory": int(ra.get("memory", 0)),
                "disk": int(ra.get("disk", 0)),
            }
        for hv in cb.body.blocks("host_volume"):
            name = hv.labels[0] if hv.labels else ""
            a2 = hv.body.attrs()
            if name and a2.get("path"):
                cfg.host_volumes[name] = {
                    "path": str(a2["path"]),
                    "read_only": bool(a2.get("read_only", False)),
                }
    pb = body.block("ports")
    if pb is not None:
        pa = pb.body.attrs()
        cfg.http_port = int(pa.get("http", 0))
        cfg.rpc_port = int(pa.get("rpc", 0))
    ab = body.block("acl")
    if ab is not None:
        cfg.acl_enabled = bool(ab.body.attrs().get("enabled", False))
    vb = body.block("vault")
    if vb is not None:
        va = vb.body.attrs()
        if "allowed_policies" in va:
            cfg.vault_allowed_policies = [
                str(x) for x in va["allowed_policies"]
            ]
    tb = body.block("tls")
    if tb is not None:
        ta = tb.body.attrs()
        cfg.tls_http = bool(ta.get("http", False))
        cfg.tls_rpc = bool(ta.get("rpc", False))
        cfg.tls_cert_file = str(ta.get("cert_file", ""))
        cfg.tls_key_file = str(ta.get("key_file", ""))
        cfg.tls_ca_file = str(ta.get("ca_file", ""))
    teb = body.block("telemetry")
    if teb is not None:
        from ..jobspec.hcl import parse_duration

        tea = teb.body.attrs()
        cfg.telemetry_statsd_address = str(tea.get("statsd_address", ""))
        cfg.telemetry_datadog_address = str(tea.get("datadog_address", ""))
        if "collection_interval" in tea:
            cfg.telemetry_interval_s = parse_duration(
                tea["collection_interval"]
            )
        cfg.trace_enabled = bool(tea.get("trace_enabled", False))
        if "trace_buffer" in tea:
            cfg.trace_buffer = int(tea["trace_buffer"])
        if "host_profile" in tea:
            cfg.host_profile_enabled = bool(tea["host_profile"])
        if "host_profile_interval" in tea:
            cfg.host_profile_interval_ms = (
                parse_duration(tea["host_profile_interval"]) * 1e3
            )
        if "blackbox_enabled" in tea:
            cfg.blackbox_enabled = bool(tea["blackbox_enabled"])
        if "incident_dir" in tea:
            cfg.incident_dir = str(tea["incident_dir"])
        if "incident_max" in tea:
            cfg.incident_max = int(tea["incident_max"])
    brb = body.block("broker")
    if brb is not None:
        from ..jobspec.hcl import parse_duration

        bra = brb.body.attrs()
        if "delivery_limit" in bra:
            cfg.broker_delivery_limit = int(bra["delivery_limit"])
        if "nack_delay" in bra:
            cfg.broker_nack_delay_s = parse_duration(bra["nack_delay"])
        if "admission_depth" in bra:
            cfg.broker_admission_depth = int(bra["admission_depth"])
        if "namespace_cap" in bra:
            cfg.broker_namespace_cap = int(bra["namespace_cap"])
        if "blocked_cap" in bra:
            cfg.blocked_evals_cap = int(bra["blocked_cap"])
    lmb = body.block("limits")
    if lmb is not None:
        lma = lmb.body.attrs()
        cfg.http_rate_limit = float(lma.get("http_rate", 0) or 0)
        cfg.http_rate_burst = float(lma.get("http_burst", 0) or 0)
        cfg.rpc_rate_limit = float(lma.get("rpc_rate", 0) or 0)
        cfg.rpc_rate_burst = float(lma.get("rpc_burst", 0) or 0)
        cfg.node_register_rate = float(lma.get("node_register_rate", 0) or 0)
        cfg.node_register_burst = float(lma.get("node_register_burst", 0) or 0)
    spb = body.block("solver_pool")
    if spb is not None:
        from ..jobspec.hcl import parse_duration

        spa = spb.body.attrs()
        if "role" in spa:
            cfg.solver_pool_role = str(spa["role"])
        if "members" in spa:
            cfg.solver_pool_members = tuple(
                str(m) for m in (spa["members"] or [])
            )
        if "sync_interval" in spa:
            cfg.solver_pool_sync_interval_s = parse_duration(
                spa["sync_interval"]
            )
    for plug in body.blocks("plugin"):
        name = plug.labels[0] if plug.labels else ""
        ref = plug.body.attrs().get("factory", "")
        if name and ref:
            cfg.driver_plugins[name] = str(ref)
    for plug in body.blocks("device_plugin"):
        name = plug.labels[0] if plug.labels else ""
        pa = plug.body.attrs()
        ref = pa.get("factory", "")
        if name and ref:
            spec = {"factory": str(ref)}
            if pa.get("config"):
                spec["config"] = dict(pa["config"])
            cfg.device_plugins[name] = spec
    return cfg


def _apply_config_dict(cfg, data: dict) -> None:
    for k, v in data.items():
        if k == "server" and isinstance(v, dict):
            cfg.server_enabled = v.get("enabled", True)
            cfg.bootstrap_expect = v.get("bootstrap_expect", 1)
            cfg.server_join = [_addr(s) for s in v.get("server_join", [])]
        elif k == "client" and isinstance(v, dict):
            cfg.client_enabled = v.get("enabled", True)
            cfg.client_servers = [_addr(s) for s in v.get("servers", [])]
            cfg.csi_plugins = dict(v.get("csi_plugins", {}))
            cfg.chroot_env = dict(v.get("chroot_env", {}))
            cfg.host_volumes = {
                str(name): {
                    "path": str(hv.get("path", "")),
                    "read_only": bool(hv.get("read_only", False)),
                }
                for name, hv in (v.get("host_volumes") or {}).items()
                if hv.get("path")
            }
            cfg.node_meta = {
                str(k): str(vv) for k, vv in (v.get("meta") or {}).items()
            }
            if v.get("reserved"):
                cfg.reserved = {
                    "cpu": int(v["reserved"].get("cpu", 0)),
                    "memory": int(v["reserved"].get("memory", 0)),
                    "disk": int(v["reserved"].get("disk", 0)),
                }
        elif k == "device_plugins" and isinstance(v, dict):
            cfg.device_plugins = dict(v)
        elif k == "telemetry" and isinstance(v, dict):
            from ..jobspec.hcl import parse_duration

            cfg.telemetry_statsd_address = str(v.get("statsd_address", ""))
            cfg.telemetry_datadog_address = str(
                v.get("datadog_address", "")
            )
            cfg.trace_enabled = bool(v.get("trace_enabled", False))
            if "trace_buffer" in v:
                cfg.trace_buffer = int(v["trace_buffer"])
            if "collection_interval" in v:
                cfg.telemetry_interval_s = parse_duration(
                    v["collection_interval"]
                )
            if "host_profile" in v:
                cfg.host_profile_enabled = bool(v["host_profile"])
            if "host_profile_interval" in v:
                cfg.host_profile_interval_ms = (
                    parse_duration(v["host_profile_interval"]) * 1e3
                )
            if "blackbox_enabled" in v:
                cfg.blackbox_enabled = bool(v["blackbox_enabled"])
            if "incident_dir" in v:
                cfg.incident_dir = str(v["incident_dir"])
            if "incident_max" in v:
                cfg.incident_max = int(v["incident_max"])
        elif k == "broker" and isinstance(v, dict):
            from ..jobspec.hcl import parse_duration

            if "delivery_limit" in v:
                cfg.broker_delivery_limit = int(v["delivery_limit"])
            if "nack_delay" in v:
                cfg.broker_nack_delay_s = parse_duration(v["nack_delay"])
            if "admission_depth" in v:
                cfg.broker_admission_depth = int(v["admission_depth"])
            if "namespace_cap" in v:
                cfg.broker_namespace_cap = int(v["namespace_cap"])
            if "blocked_cap" in v:
                cfg.blocked_evals_cap = int(v["blocked_cap"])
        elif k == "limits" and isinstance(v, dict):
            cfg.http_rate_limit = float(v.get("http_rate", 0) or 0)
            cfg.http_rate_burst = float(v.get("http_burst", 0) or 0)
            cfg.rpc_rate_limit = float(v.get("rpc_rate", 0) or 0)
            cfg.rpc_rate_burst = float(v.get("rpc_burst", 0) or 0)
            cfg.node_register_rate = float(v.get("node_register_rate", 0) or 0)
            cfg.node_register_burst = float(v.get("node_register_burst", 0) or 0)
        elif k == "solver_pool" and isinstance(v, dict):
            from ..jobspec.hcl import parse_duration

            if "role" in v:
                cfg.solver_pool_role = str(v["role"])
            if "members" in v:
                cfg.solver_pool_members = tuple(
                    str(m) for m in (v["members"] or [])
                )
            if "sync_interval" in v:
                cfg.solver_pool_sync_interval_s = parse_duration(
                    v["sync_interval"]
                )
        elif k == "ports" and isinstance(v, dict):
            cfg.http_port = v.get("http", 0)
            cfg.rpc_port = v.get("rpc", 0)
        elif k == "acl" and isinstance(v, dict):
            cfg.acl_enabled = v.get("enabled", False)
        elif k == "tls" and isinstance(v, dict):
            cfg.tls_http = bool(v.get("http", False))
            cfg.tls_rpc = bool(v.get("rpc", False))
            cfg.tls_cert_file = str(v.get("cert_file", ""))
            cfg.tls_key_file = str(v.get("key_file", ""))
            cfg.tls_ca_file = str(v.get("ca_file", ""))
        elif hasattr(cfg, k):
            setattr(cfg, k, v)


# ---------------------------------------------------------------------------
# job


def cmd_job_run(args) -> int:
    api = _client(args)
    job = _load_jobfile(args.jobfile, _parse_vars(args.var))
    eval_id = api.jobs.register(job)
    print(f'==> Job "{job.id}" registered')
    if eval_id:
        print(f"    Evaluation ID: {eval_id}")
    if args.detach or not eval_id:
        return 0
    # monitor until the eval completes (reference: monitor.go)
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        ev = api.evaluations.get(eval_id)
        if ev.status in ("complete", "failed", "canceled"):
            print(f'    Evaluation status: "{ev.status}"')
            return 0 if ev.status == "complete" else 2
        time.sleep(0.3)
    print("    Evaluation still pending (timeout); detaching")
    return 0


_DIFF_MARK = {"Added": "+", "Deleted": "-", "Edited": "~", "None": " "}


def _render_diff(d, indent=0) -> None:
    if not d or d.get("Type") == "None":
        return
    pad = " " * indent
    mark = _DIFF_MARK.get(d.get("Type", "Edited"), "~")
    print(f"{pad}{mark} {d.get('Name', '')}")
    for f in d.get("Fields") or []:
        fm = _DIFF_MARK.get(f.get("Type", "Edited"), "~")
        old, new = f.get("Old", ""), f.get("New", "")
        if f["Type"] == "Added":
            print(f'{pad}  {fm} {f["Name"]}: "{new}"')
        elif f["Type"] == "Deleted":
            print(f'{pad}  {fm} {f["Name"]}: "{old}"')
        else:
            print(f'{pad}  {fm} {f["Name"]}: "{old}" => "{new}"')
    for o in d.get("Objects") or []:
        _render_diff(o, indent + 2)


def cmd_job_plan(args) -> int:
    """Server-side dry-run (reference command/job_plan.go): the REAL
    scheduler runs against a snapshot without committing; the CLI renders
    its per-group annotations and structural diff. Exit codes match the
    reference: 0 no changes, 1 changes, 255 error."""
    api = _client(args)
    try:
        job = _load_jobfile(args.jobfile, _parse_vars(args.var))
        resp = api.jobs.plan(job)
        _render_diff(resp.get("Diff"))
        updates = resp.get("Annotations", {}).get("DesiredTGUpdates", {})
        for tg, s in sorted(updates.items()):
            parts = []
            for key, label in (
                ("place", "create"),
                ("destructive", "create/destroy update"),
                ("in_place", "in-place update"),
                ("migrate", "migrate"),
                ("stop", "destroy"),
                ("canary", "canary"),
                ("ignore", "ignore"),
            ):
                n = s.get(key, 0)
                if n:
                    parts.append(f"{n} {label}")
            if parts:
                print(f'Task Group: "{tg}" ({", ".join(parts)})')
        failed = resp.get("FailedTGAllocs") or {}
        for tg, metric in failed.items():
            print(f'! Task Group "{tg}": placement would fail')
        if resp.get("JobModifyIndex") is not None:
            print(f"Job Modify Index: {resp['JobModifyIndex']}")
        if not resp.get("Changes"):
            print("No changes. Job is up to date.")
            return 0
        return 1
    except Exception as e:
        print(f"Error: {e}", file=sys.stderr)
        return 255


def cmd_job_status(args) -> int:
    api = _client(args)
    if not args.job_id:
        jobs = api.jobs.list()
        if not jobs:
            print("No running jobs")
            return 0
        print(
            _fmt_table(
                [
                    [j.id, j.type, str(j.priority), j.status]
                    for j in sorted(jobs, key=lambda j: j.id)
                ],
                header=["ID", "Type", "Priority", "Status"],
            )
        )
        return 0
    job = api.jobs.get(args.job_id)
    print(f"ID            = {job.id}")
    print(f"Name          = {job.name}")
    print(f"Type          = {job.type}")
    print(f"Priority      = {job.priority}")
    print(f"Status        = {job.status}")
    print(f"Datacenters   = {','.join(job.datacenters)}")
    print(f"Version       = {job.version}")
    try:
        summary = api.jobs.summary(job.id)
        print("\nSummary")
        rows = [
            [
                g,
                str(c.get("queued", 0)),
                str(c.get("starting", 0)),
                str(c.get("running", 0)),
                str(c.get("failed", 0)),
                str(c.get("complete", 0)),
                str(c.get("lost", 0)),
            ]
            for g, c in sorted(summary.summary.items())
        ]
        print(
            _fmt_table(
                rows,
                header=[
                    "Task Group",
                    "Queued",
                    "Starting",
                    "Running",
                    "Failed",
                    "Complete",
                    "Lost",
                ],
            )
        )
    except APIError:
        pass
    try:
        deps = api.jobs.deployments(args.job_id)
        active = [d for d in deps if d.active()]
        latest = max(
            active or deps, key=lambda d: d.job_version, default=None
        )
        if latest is not None:
            print("\nLatest Deployment")
            print(f"ID          = {latest.id[:8]}")
            print(f"Status      = {latest.status}")
            print(f"Description = {latest.status_description}")
    except APIError:
        pass
    allocs = api.jobs.allocations(args.job_id)
    if allocs:
        print("\nAllocations")
        print(
            _fmt_table(
                [
                    [
                        a.id[:8],
                        a.node_id[:8],
                        a.task_group,
                        a.desired_status,
                        a.client_status,
                    ]
                    for a in allocs
                ],
                header=["ID", "Node ID", "Task Group", "Desired", "Status"],
            )
        )
    return 0


def cmd_job_stop(args) -> int:
    api = _client(args)
    eval_id = api.jobs.deregister(args.job_id, purge=args.purge)
    print(f'==> Job "{args.job_id}" deregistered')
    if eval_id:
        print(f"    Evaluation ID: {eval_id}")
    return 0


def cmd_job_inspect(args) -> int:
    api = _client(args)
    job = api.jobs.get(args.job_id)
    print(json.dumps(codec.to_wire(job), indent=2, default=codec.json_default))
    return 0


def cmd_job_history(args) -> int:
    api = _client(args)
    versions = api.jobs.versions(args.job_id)
    rows = [
        [str(j.version), "true" if j.stable else "false", j.status]
        for j in versions
    ]
    print(_fmt_table(rows, header=["Version", "Stable", "Status"]))
    return 0


def cmd_job_revert(args) -> int:
    api = _client(args)
    api.jobs.revert(args.job_id, args.version)
    print(f'==> Job "{args.job_id}" reverted to version {args.version}')
    return 0


def cmd_job_dispatch(args) -> int:
    api = _client(args)
    meta = _parse_vars(args.meta)
    payload = None
    if args.payload_file:
        with open(args.payload_file) as f:
            payload = f.read()
    result = api.jobs.dispatch(args.job_id, meta=meta, payload=payload)
    print(f"Dispatched Job ID = {result}")
    return 0


def cmd_job_periodic_force(args) -> int:
    api = _client(args)
    out = api.jobs.periodic_force(args.job_id)
    print(f"Forced periodic launch: {out}")
    return 0


# ---------------------------------------------------------------------------
# node / alloc / eval / deployment


def cmd_node_status(args) -> int:
    api = _client(args)
    if not args.node_id:
        nodes = api.nodes.list()
        print(
            _fmt_table(
                [
                    [
                        n.id[:8],
                        n.datacenter,
                        n.name,
                        n.node_class or "<none>",
                        n.scheduling_eligibility,
                        n.status,
                    ]
                    for n in nodes
                ],
                header=["ID", "DC", "Name", "Class", "Eligibility", "Status"],
            )
        )
        return 0
    node = _find_by_prefix(api.nodes.list(), args.node_id)
    node = api.nodes.get(node.id)
    print(f"ID          = {node.id}")
    print(f"Name        = {node.name}")
    print(f"Class       = {node.node_class or '<none>'}")
    print(f"DC          = {node.datacenter}")
    print(f"Drain       = {node.drain_strategy is not None}")
    print(f"Eligibility = {node.scheduling_eligibility}")
    print(f"Status      = {node.status}")
    allocs = api.nodes.allocations(node.id)
    if allocs:
        print("\nAllocations")
        print(
            _fmt_table(
                [
                    [a.id[:8], a.job_id, a.task_group, a.client_status]
                    for a in allocs
                ],
                header=["ID", "Job ID", "Task Group", "Status"],
            )
        )
    return 0


def _find_by_prefix(items, prefix: str):
    return _find_by_prefix_attr(items, "id", prefix)


def cmd_node_drain(args) -> int:
    api = _client(args)
    node = _find_by_prefix(api.nodes.list(), args.node_id)
    if args.disable:
        api.nodes.drain(node.id, None, mark_eligible=True)
        print(f"Node {node.id[:8]} drain disabled")
        return 0
    from ..structs.structs import DrainStrategy

    spec = DrainStrategy(
        deadline_s=_duration(args.deadline),
        ignore_system_jobs=args.ignore_system,
    )
    api.nodes.drain(node.id, spec)
    print(f"Node {node.id[:8]} drain enabled (deadline {args.deadline})")
    return 0


def _duration(s: str) -> float:
    from ..jobspec import parse_duration

    return parse_duration(s)


def cmd_node_eligibility(args) -> int:
    api = _client(args)
    node = _find_by_prefix(api.nodes.list(), args.node_id)
    api.nodes.eligibility(node.id, args.enable)
    print(
        f"Node {node.id[:8]} marked "
        + ("eligible" if args.enable else "ineligible")
    )
    return 0


def cmd_alloc_logs(args) -> int:
    """Reference: command/alloc_logs.go."""
    import sys as _sys

    api = _client(args)
    alloc = _find_by_prefix(api.allocations.list(), args.alloc_id)
    task = args.task
    if not task:
        # single-task groups don't need -task
        a = api.allocations.get(alloc.id)
        tasks = list(a.task_states) or [a.task_group]
        task = tasks[0]
    try:
        for chunk in api.allocations.logs(
            alloc.id,
            task=task,
            log_type="stderr" if args.stderr else "stdout",
            follow=args.follow,
        ):
            _sys.stdout.buffer.write(chunk)
            _sys.stdout.buffer.flush()
    except KeyboardInterrupt:
        pass
    return 0


def cmd_alloc_fs(args) -> int:
    """Reference: command/alloc_fs.go — ls when the path is a directory,
    cat when it is a file."""
    import sys as _sys

    api = _client(args)
    alloc = _find_by_prefix(api.allocations.list(), args.alloc_id)
    path = args.path or ""
    st = api.allocations.fs_stat(alloc.id, path)
    if st and st.get("is_dir"):
        entries = api.allocations.fs_ls(alloc.id, path)
        rows = [
            [
                "dir" if e["is_dir"] else "file",
                str(e["size"]),
                e["name"],
            ]
            for e in entries
        ]
        print(_fmt_table(rows, ["Type", "Size", "Name"]))
    else:
        _sys.stdout.buffer.write(api.allocations.fs_cat(alloc.id, path))
    return 0


def cmd_alloc_exec(args) -> int:
    """Reference: command/alloc_exec.go — interactive exec into a task."""
    import os as _os
    import sys as _sys
    import threading as _threading

    api = _client(args)
    alloc = _find_by_prefix(api.allocations.list(), args.alloc_id)
    secret = args.rpc_secret or _os.environ.get("NOMAD_TPU_RPC_SECRET", "")
    # fabric TLS (tls { rpc = true }) is EXPLICIT opt-in (-fabric-tls):
    # inferring it from stray NOMAD_CLIENT_CERT would TLS-dial plaintext
    # fabrics. Creds come from the standard env vars; cert/key optional
    # against an encryption-only fabric, required together for mTLS.
    tls = None
    if args.fabric_tls:
        cert = _os.environ.get("NOMAD_CLIENT_CERT", "")
        key = _os.environ.get("NOMAD_CLIENT_KEY", "")
        if bool(cert) != bool(key):
            print(
                "alloc exec: NOMAD_CLIENT_CERT and NOMAD_CLIENT_KEY "
                "must both be set for fabric mTLS",
                file=_sys.stderr,
            )
            return 1
        ca = _os.environ.get("NOMAD_CACERT", "")
        if not ca:
            # no CA means verify_mode=CERT_NONE: the handshake succeeds
            # against ANY endpoint, and the rpc_secret preamble would go
            # to an unverified peer — loudly flag the downgrade
            print(
                "alloc exec: -fabric-tls without NOMAD_CACERT — server "
                "certificate will NOT be verified",
                file=_sys.stderr,
            )
        tls = (cert, key, ca)
    session = api.allocations.exec_session(
        alloc.id, args.cmd, task=args.task, tty=args.tty, rpc_secret=secret,
        tls=tls,
    )
    stop = _threading.Event()

    def pump_stdin() -> None:
        try:
            while not stop.is_set():
                data = _sys.stdin.buffer.raw.read(4096)
                if not data:
                    break
                session.send_stdin(data)
        except (OSError, ValueError):
            pass

    t = _threading.Thread(
        target=pump_stdin, name="exec-stdin-pump", daemon=True
    )
    t.start()
    try:
        while True:
            msg = session.recv(timeout_s=0.5)
            if msg is None:
                continue
            if msg.get("error"):
                print(f"exec error: {msg['error']}", file=_sys.stderr)
                return 1
            data = msg.get("data")
            if data:
                _sys.stdout.buffer.write(data)
                _sys.stdout.buffer.flush()
            if msg.get("eof"):
                return 0
    except KeyboardInterrupt:
        return 130
    finally:
        stop.set()
        session.close()


def cmd_alloc_status(args) -> int:
    api = _client(args)
    alloc = _find_by_prefix(api.allocations.list(), args.alloc_id)
    alloc = api.allocations.get(alloc.id)
    print(f"ID            = {alloc.id}")
    print(f"Job ID        = {alloc.job_id}")
    print(f"Node ID       = {alloc.node_id}")
    print(f"Task Group    = {alloc.task_group}")
    print(f"Desired       = {alloc.desired_status}")
    print(f"Client Status = {alloc.client_status}")
    # assigned device instances + live stats (reference: alloc status
    # shows Device Stats fed by the device plugin's Stats stream)
    if alloc.resources is not None:
        devlines = []
        for tname, tr in sorted(alloc.resources.tasks.items()):
            for dev in tr.devices or []:
                devlines.append(
                    f"  {tname}: {dev.get('id', '')} -> "
                    + ",".join(dev.get("device_ids", []))
                )
        if devlines:
            print("\nDevices")
            print("\n".join(devlines))
            try:
                stats = api.allocations.stats(alloc.id)
            except Exception:
                stats = {}
            for plugin, insts in sorted((stats.get("devices") or {}).items()):
                print(f"\nDevice Stats ({plugin})")
                for iid, s in sorted(insts.items()):
                    kv = ", ".join(f"{k}={v}" for k, v in sorted(s.items()))
                    print(f"  {iid}: {kv}")
    for task, state in sorted(alloc.task_states.items()):
        print(f"\nTask \"{task}\" is \"{state.state}\"")
        for ev in state.events[-5:]:
            etype = ev.get("type", "")
            msg = ev.get("display_message") or ev.get("message", "")
            print(f"  {etype}: {msg}")
    return 0


def cmd_eval_status(args) -> int:
    api = _client(args)
    ev = _find_by_prefix(api.evaluations.list(), args.eval_id)
    ev = api.evaluations.get(ev.id)
    print(f"ID           = {ev.id}")
    print(f"Status       = {ev.status}")
    print(f"Type         = {ev.type}")
    print(f"TriggeredBy  = {ev.triggered_by}")
    print(f"Job ID       = {ev.job_id}")
    print(f"Priority     = {ev.priority}")
    if ev.blocked_eval:
        print(f"Blocked Eval = {ev.blocked_eval}")
    return 0


def cmd_eval_list(args) -> int:
    api = _client(args)
    evals = api.evaluations.list()
    print(
        _fmt_table(
            [
                [e.id[:8], e.priority, e.triggered_by, e.job_id, e.status]
                for e in evals
            ],
            header=["ID", "Priority", "Triggered By", "Job ID", "Status"],
        )
    )
    return 0


def cmd_deployment_list(args) -> int:
    api = _client(args)
    deps = api.deployments.list()
    print(
        _fmt_table(
            [[d.id[:8], d.job_id, d.status, d.status_description] for d in deps],
            header=["ID", "Job ID", "Status", "Description"],
        )
    )
    return 0


def cmd_deployment_status(args) -> int:
    api = _client(args)
    d = _find_by_prefix(api.deployments.list(), args.deployment_id)
    d = api.deployments.get(d.id)
    print(f"ID          = {d.id}")
    print(f"Job ID      = {d.job_id}")
    print(f"Status      = {d.status}")
    print(f"Description = {d.status_description}")
    rows = []
    for g, s in sorted(d.task_groups.items()):
        rows.append(
            [
                g,
                str(s.desired_total),
                str(s.placed_allocs),
                str(s.healthy_allocs),
                str(s.unhealthy_allocs),
                str(s.desired_canaries),
                "true" if s.promoted else "false",
            ]
        )
    print(
        _fmt_table(
            rows,
            header=[
                "Group",
                "Desired",
                "Placed",
                "Healthy",
                "Unhealthy",
                "Canaries",
                "Promoted",
            ],
        )
    )
    return 0


def cmd_deployment_promote(args) -> int:
    api = _client(args)
    d = _find_by_prefix(api.deployments.list(), args.deployment_id)
    api.deployments.promote(d.id, groups=args.group or None)
    print(f"Deployment {d.id[:8]} promoted")
    return 0


def cmd_deployment_fail(args) -> int:
    api = _client(args)
    d = _find_by_prefix(api.deployments.list(), args.deployment_id)
    api.deployments.fail(d.id)
    print(f"Deployment {d.id[:8]} marked failed")
    return 0


def cmd_deployment_pause(args) -> int:
    api = _client(args)
    d = _find_by_prefix(api.deployments.list(), args.deployment_id)
    api.deployments.pause(d.id, pause=not args.resume)
    print(
        f"Deployment {d.id[:8]} " + ("resumed" if args.resume else "paused")
    )
    return 0


# ---------------------------------------------------------------------------
# server / status / misc


def cmd_acl_bootstrap(args) -> int:
    api = _client(args)
    token = api.acl.bootstrap()
    print(f"Accessor ID = {token.accessor_id}")
    print(f"Secret ID   = {token.secret_id}")
    print(f"Type        = {token.type}")
    return 0


def cmd_acl_policy_apply(args) -> int:
    api = _client(args)
    with open(args.rules_file) as f:
        rules = f.read()
    api.acl.policy_apply(args.name, rules, description=args.description or "")
    print(f'ACL policy "{args.name}" applied')
    return 0


def cmd_acl_policy_list(args) -> int:
    api = _client(args)
    pols = api.acl.policies()
    print(
        _fmt_table(
            [[p.name, p.description] for p in pols],
            header=["Name", "Description"],
        )
    )
    return 0


def cmd_acl_policy_delete(args) -> int:
    api = _client(args)
    api.acl.policy_delete(args.name)
    print(f'ACL policy "{args.name}" deleted')
    return 0


def cmd_acl_token_create(args) -> int:
    api = _client(args)
    token = api.acl.token_create(
        name=args.name or "", type=args.type, policies=args.policy or [],
        global_=getattr(args, "set_global", False),
    )
    print(f"Accessor ID = {token.accessor_id}")
    print(f"Secret ID   = {token.secret_id}")
    print(f"Type        = {token.type}")
    print(f"Policies    = {','.join(token.policies)}")
    return 0


def cmd_acl_token_list(args) -> int:
    api = _client(args)
    tokens = api.acl.tokens()
    print(
        _fmt_table(
            [
                [t.accessor_id[:8], t.name, t.type, ",".join(t.policies)]
                for t in tokens
            ],
            header=["Accessor", "Name", "Type", "Policies"],
        )
    )
    return 0


def cmd_acl_token_delete(args) -> int:
    api = _client(args)
    tokens = api.acl.tokens()
    match = _find_by_prefix_attr(tokens, "accessor_id", args.accessor_id)
    api.acl.token_delete(match.accessor_id)
    print(f"Token {match.accessor_id[:8]} deleted")
    return 0


def _print_token(t) -> None:
    print(f"Accessor ID = {t.accessor_id}")
    print(f"Secret ID   = {t.secret_id}")
    print(f"Name        = {t.name}")
    print(f"Type        = {t.type}")
    print(f"Global      = {t.global_}")
    print(f"Policies    = {','.join(t.policies)}")


def cmd_acl_policy_info(args) -> int:
    api = _client(args)
    p = api.acl.policy(args.name)
    print(f"Name        = {p.name}")
    print(f"Description = {p.description}")
    print("Rules:")
    print(p.rules)
    return 0


def cmd_acl_token_info(args) -> int:
    api = _client(args)
    tokens = api.acl.tokens()
    match = _find_by_prefix_attr(tokens, "accessor_id", args.accessor_id)
    _print_token(api.acl.token(match.accessor_id))
    return 0


def cmd_acl_token_self(args) -> int:
    api = _client(args)
    _print_token(api.acl.token_self())
    return 0


def cmd_acl_token_update(args) -> int:
    api = _client(args)
    fields = {}
    if args.name is not None:
        fields["name"] = args.name
    if args.policy:
        fields["policies"] = args.policy
    if args.type is not None:
        fields["type"] = args.type
    if args.set_global is not None:
        fields["global_"] = args.set_global == "true"
    t = api.acl.token_update(args.accessor_id, **fields)
    _print_token(t)
    return 0


def cmd_job_scaling_events(args) -> int:
    api = _client(args)
    st = api.jobs.scale_status(args.job_id)
    rows = []
    for group, events in sorted((st.get("ScalingEvents") or {}).items()):
        for e in events:
            when = time.strftime(
                "%Y-%m-%dT%H:%M:%S",
                time.localtime(e.get("Time", 0) / 1e9),
            )
            rows.append([
                when, group, e.get("PreviousCount", ""),
                e.get("Count", ""), str(e.get("EvalID", ""))[:8],
                e.get("Message", ""),
            ])
    if not rows:
        print("No scaling events")
        return 0
    print(_fmt_table(
        rows,
        header=["Time", "Group", "Previous", "Count", "Eval", "Message"],
    ))
    return 0


def cmd_namespace_inspect(args) -> int:
    api = _client(args)
    ns = next(
        (n for n in api.namespaces.list() if n.name == args.name), None
    )
    if ns is None:
        print(f"Namespace {args.name!r} not found", file=sys.stderr)
        return 1
    print(json.dumps(
        {"Name": ns.name, "Description": ns.description}, indent=2
    ))
    return 0


def cmd_server_join(args) -> int:
    api = _client(args)
    out = api.agent.join(*args.address)
    if out.get("error"):
        print(f"Join failed: {out['error']}", file=sys.stderr)
        return 1
    print(f"Joined {out['num_joined']} servers successfully")
    return 0


def cmd_check(args) -> int:
    """Agent health probe for external monitors (reference
    command/check.go): exit 0 healthy, 1 unhealthy/unreachable."""
    try:
        h = _client(args).agent.health()
    except Exception as e:
        print(f"unhealthy: {e}", file=sys.stderr)
        return 1
    ok = all(part.get("ok") for part in h.values())
    print("healthy" if ok else f"unhealthy: {h}")
    return 0 if ok else 1


VOLUME_INIT_TEMPLATE = """\
id        = "example-volume"
name      = "example-volume"
type      = "host"
node_id   = "<node-id>"
path      = "/srv/volumes/example"

capability {
  access_mode     = "single-node-writer"
  attachment_mode = "file-system"
}
"""


def cmd_volume_init(args) -> int:
    filename = args.filename or "volume.hcl"
    if os.path.exists(filename):
        print(f"File {filename} already exists", file=sys.stderr)
        return 1
    with open(filename, "w") as f:
        f.write(VOLUME_INIT_TEMPLATE)
    print(f"Example volume specification written to {filename}")
    return 0


def _find_by_prefix_attr(items, attr: str, prefix: str):
    matches = [i for i in items if getattr(i, attr).startswith(prefix)]
    if not matches:
        raise SystemExit(f"No object with ID prefix {prefix!r}")
    if len(matches) > 1:
        raise SystemExit(
            f"Ambiguous prefix {prefix!r} matches {len(matches)} objects"
        )
    return matches[0]


def cmd_operator_snapshot_save(args) -> int:
    """Reference: command/operator_snapshot_save.go."""
    api = _client(args)
    data = api.operator.snapshot_save()
    with open(args.file, "wb") as f:
        f.write(data)
    print(f"State file written to {args.file} ({len(data)} bytes)")
    return 0


def cmd_operator_snapshot_restore(args) -> int:
    """Reference: command/operator_snapshot_restore.go."""
    api = _client(args)
    with open(args.file, "rb") as f:
        data = f.read()
    api.operator.snapshot_restore(data)
    print("Snapshot restored")
    return 0


def cmd_namespace_list(args) -> int:
    api = _client(args)
    nss = api.namespaces.list()
    if not nss:
        print("No namespaces")
        return 0
    print(
        _fmt_table(
            [[n.name, n.description] for n in nss],
            header=["Name", "Description"],
        )
    )
    return 0


def cmd_namespace_apply(args) -> int:
    """Reference: command/namespace_apply.go."""
    from ..structs.structs import Namespace

    api = _client(args)
    api.namespaces.apply(
        Namespace(name=args.name, description=args.description or "")
    )
    print(f'Namespace "{args.name}" applied')
    return 0


def cmd_namespace_delete(args) -> int:
    api = _client(args)
    api.namespaces.delete(args.name)
    print(f'Namespace "{args.name}" deleted')
    return 0


def cmd_volume_register(args) -> int:
    """Reference: command/volume_register.go (host-volume shape)."""
    from ..structs.structs import Volume

    api = _client(args)
    vol = Volume(
        id=args.id,
        namespace=args.namespace or "default",
        name=args.name or args.id,
        type=args.type,
        node_id=args.node or "",
        path=args.path or "",
        access_mode=args.access_mode,
        plugin_id=args.plugin or "",
        external_id=args.external_id or "",
    )
    api.volumes.register(vol)
    print(f'Volume "{vol.id}" registered')
    return 0


def cmd_volume_create(args) -> int:
    """Reference: command/volume_create.go — provision via the CSI
    controller from an HCL volume spec, then register."""
    from ..jobspec.hcl import parse as parse_hcl
    from ..structs.structs import Volume

    with open(args.file) as f:
        body = parse_hcl(f.read())
    a = body.attrs()
    params = {}
    pb = body.block("parameters")
    if pb is not None:
        params = {k: str(v) for k, v in pb.body.attrs().items()}
    vol = Volume(
        id=a.get("id", ""),
        name=a.get("name", a.get("id", "")),
        namespace=a.get("namespace", args.namespace or "default"),
        type="csi",
        plugin_id=a.get("plugin_id", ""),
        access_mode=a.get(
            "access_mode", "multi-node-multi-writer"
        ),
        attachment_mode=a.get("attachment_mode", "file-system"),
        context=params,
    )
    if not vol.id or not vol.plugin_id:
        print("Error: volume spec requires id and plugin_id",
              file=sys.stderr)
        return 1
    api = _client(args)
    out = api.volumes.create(vol)
    print(f'Volume "{vol.id}" created (external id '
          f'"{getattr(out, "external_id", "")}")')
    return 0


def cmd_volume_delete(args) -> int:
    api = _client(args)
    api.volumes.delete(args.id, namespace=args.namespace)
    print(f'Volume "{args.id}" deleted')
    return 0


def cmd_volume_detach(args) -> int:
    api = _client(args)
    out = api.volumes.detach(
        args.volume_id, args.node_id, namespace=args.namespace
    )
    print(
        f"Volume {args.volume_id} detached from {args.node_id} "
        f"({out['released_claims']} claims released)"
    )
    return 0


def cmd_volume_snapshot_create(args) -> int:
    api = _client(args)
    out = api.volumes.snapshot_create(
        args.volume_id, name=args.name or "", namespace=args.namespace
    )
    print(f"Snapshot ID  = {out.get('snapshot_id')}")
    print(f"Volume ID    = {args.volume_id}")
    print(f"Size (MB)    = {out.get('size_mb')}")
    print(f"Ready        = {out.get('ready')}")
    return 0


def cmd_volume_snapshot_delete(args) -> int:
    api = _client(args)
    api.volumes.snapshot_delete(args.plugin_id, args.snapshot_id)
    print(f"Snapshot {args.snapshot_id} deleted")
    return 0


def cmd_volume_snapshot_list(args) -> int:
    api = _client(args)
    snaps = api.volumes.snapshot_list(args.plugin_id)
    print(_fmt_table(
        [
            [
                s.get("snapshot_id", ""),
                s.get("source_external_id", ""),
                s.get("size_mb", ""),
                "ready" if s.get("ready") else "pending",
            ]
            for s in snaps
        ],
        header=["Snapshot", "Volume", "Size MB", "Status"],
    ))
    return 0


def cmd_volume_status(args) -> int:
    api = _client(args)
    if args.id:
        vol = api.volumes.get(args.id, namespace=args.namespace)
        print(f"ID          = {vol.id}")
        print(f"Name        = {vol.name}")
        print(f"Namespace   = {vol.namespace}")
        print(f"Type        = {vol.type}")
        print(f"Access Mode = {vol.access_mode}")
        print(f"Claims      = {len(vol.claims)}")
        for c in vol.claims.values():
            mode = "read" if c.read_only else "write"
            print(f"  alloc {c.alloc_id[:8]} on {c.node_id[:8]} ({mode})")
        return 0
    vols = api.volumes.list(namespace=args.namespace)
    if not vols:
        print("No volumes")
        return 0
    print(
        _fmt_table(
            [
                [v.id, v.name, v.type, v.access_mode, str(len(v.claims))]
                for v in sorted(vols, key=lambda v: v.id)
            ],
            header=["ID", "Name", "Type", "Access Mode", "Claims"],
        )
    )
    return 0


def cmd_volume_deregister(args) -> int:
    api = _client(args)
    api.volumes.deregister(args.id, namespace=args.namespace)
    print(f'Volume "{args.id}" deregistered')
    return 0


def cmd_job_scale(args) -> int:
    """Reference: command/job_scale.go."""
    api = _client(args)
    out = api.jobs.scale(args.job_id, args.group, args.count)
    print(f'Job "{args.job_id}" group "{args.group}" scaled to {args.count}')
    if out.get("EvalID"):
        print(f"Evaluation ID: {out['EvalID']}")
    return 0


def cmd_monitor(args) -> int:
    """Reference: command/monitor.go — tail the agent's logs."""
    import urllib.request

    addr, tok, _ = _conn_opts(args)
    url = f"{addr}/v1/agent/monitor?log_level={args.log_level}"
    req = urllib.request.Request(url)
    if tok:
        req.add_header("X-Nomad-Token", tok)
    try:
        with urllib.request.urlopen(req) as resp:
            for line in resp:
                line = line.strip()
                if not line or line == b"{}":
                    continue
                rec = json.loads(line)
                print(f"[{rec['Level']}] {rec['Name']}: {rec['Message']}")
    except KeyboardInterrupt:
        pass
    return 0


def cmd_operator_raft_remove_peer(args) -> int:
    api = _client(args)
    api.operator.raft_remove_peer(args.peer_id)
    print(f'Removed raft peer "{args.peer_id}"')
    return 0


def cmd_alloc_restart(args) -> int:
    """Reference: command/alloc_restart.go."""
    api = _client(args)
    api.allocations.restart(args.alloc_id, task=args.task or "")
    print(f"Allocation {args.alloc_id[:8]} restarted")
    return 0


def cmd_alloc_signal(args) -> int:
    """Reference: command/alloc_signal.go."""
    api = _client(args)
    api.allocations.signal(args.alloc_id, args.signal, task=args.task or "")
    print(f"Signalled allocation {args.alloc_id[:8]} with {args.signal}")
    return 0


def cmd_alloc_stop(args) -> int:
    """Reference: command/alloc_stop.go — stop + reschedule."""
    api = _client(args)
    out = api.allocations.stop(args.alloc_id)
    print(f"Allocation {args.alloc_id[:8]} stopping")
    if out.get("EvalID"):
        print(f"Evaluation ID: {out['EvalID']}")
    return 0


def cmd_scaling_policy_list(args) -> int:
    """Reference: command/scaling_policy_list.go."""
    api = _client(args)
    pols = api.scaling.list_policies(namespace=args.namespace)
    if not pols:
        print("No scaling policies")
        return 0
    print(
        _fmt_table(
            [
                [p.id, p.job_id, p.group, str(p.min), str(p.max),
                 str(p.enabled)]
                for p in pols
            ],
            header=["ID", "Job", "Group", "Min", "Max", "Enabled"],
        )
    )
    return 0


def cmd_scaling_policy_info(args) -> int:
    """Reference: command/scaling_policy_info.go."""
    api = _client(args)
    p = api.scaling.get_policy(args.policy_id)
    print(f"ID      = {p.id}")
    print(f"Job     = {p.job_id}")
    print(f"Group   = {p.group}")
    print(f"Type    = {p.type}")
    print(f"Min     = {p.min}")
    print(f"Max     = {p.max}")
    print(f"Enabled = {p.enabled}")
    if p.policy:
        print("Policy:")
        for k in sorted(p.policy):
            print(f"  {k} = {p.policy[k]}")
    return 0


def cmd_job_eval(args) -> int:
    """Reference: command/job_eval.go — force a new evaluation."""
    api = _client(args)
    out = api.jobs.evaluate(args.job_id)
    print(f"Created eval {out['EvalID'][:8]} for job \"{args.job_id}\"")
    return 0


def cmd_job_deployments(args) -> int:
    """Reference: command/job_deployments.go."""
    api = _client(args)
    deps = api.jobs.deployments(args.job_id)
    if not deps:
        print("No deployments")
        return 0
    print(
        _fmt_table(
            [
                [d.id[:8], str(d.job_version), d.status,
                 d.status_description[:60]]
                for d in sorted(
                    deps, key=lambda d: d.job_version, reverse=True
                )
            ],
            header=["ID", "Job Version", "Status", "Description"],
        )
    )
    return 0


def cmd_job_promote(args) -> int:
    """Reference: command/job_promote.go — promote the job's latest
    deployment's canaries."""
    api = _client(args)
    deps = api.jobs.deployments(args.job_id)
    active = [d for d in deps if d.active()]
    if not active:
        print(f'No active deployment for job "{args.job_id}"',
              file=sys.stderr)
        return 1
    d = max(active, key=lambda d: d.job_version)
    api.deployments.promote(d.id)
    print(f"Deployment {d.id[:8]} promoted")
    return 0


def cmd_namespace_status(args) -> int:
    """Reference: command/namespace_status.go."""
    api = _client(args)
    ns = api.namespaces.get(args.name)
    print(f"Name        = {ns.name}")
    print(f"Description = {ns.description}")
    jobs = api.jobs.list(namespace=args.name)
    print(f"Jobs        = {len(jobs)}")
    return 0


def cmd_system_reconcile(args) -> int:
    """Reference: command/system_reconcile_summaries.go."""
    api = _client(args)
    out = api.system.reconcile_summaries()
    print(f"Reconciled {out['Reconciled']} job summaries")
    return 0


def cmd_server_force_leave(args) -> int:
    """Reference: command/server_force_leave.go."""
    api = _client(args)
    out = api.agent.force_leave(args.node)
    print(f'Member "{args.node}" force-left ({out["Acked"]} peers acked)')
    return 0


def cmd_operator_autopilot_get(args) -> int:
    api = _client(args)
    cfg = api.operator.autopilot_configuration()
    print(f"CleanupDeadServers = {cfg['CleanupDeadServers']}")
    return 0


def cmd_operator_autopilot_set(args) -> int:
    api = _client(args)
    cfg = {}
    if args.cleanup_dead_servers is not None:
        cfg["CleanupDeadServers"] = args.cleanup_dead_servers == "true"
    api.operator.autopilot_set_configuration(cfg)
    print("Autopilot configuration updated!")
    return 0


def cmd_operator_keygen(args) -> int:
    """Reference: command/operator_keygen.go — a random fabric secret
    (rpc_secret in agent config)."""
    import base64
    import secrets as _secrets

    print(base64.b64encode(_secrets.token_bytes(32)).decode())
    return 0


def _render_keyring_status(st: dict) -> None:
    print(f"Enabled          = {st.get('enabled')}")
    print(f"Generation       = {st.get('generation')}")
    print(f"Current Key      = {st.get('current_fingerprint') or '(none)'}")
    print(f"Key Age          = {st.get('age_s')}s")
    if st.get("dual_accept"):
        print(
            f"Dual-Accept      = open (previous "
            f"{st.get('previous_fingerprint')}, "
            f"{st.get('window_remaining_s')}s remaining)"
        )
    else:
        print("Dual-Accept      = closed")


def cmd_operator_keyring_status(args) -> int:
    """Reference: command/operator_keyring.go list — here the fabric
    rpc_secret keyring (rpc/keyring.py), fingerprints only."""
    api = _client(args)
    st = api.agent.keyring_status()
    if args.as_json:
        print(json.dumps(st, indent=2))
        return 0
    _render_keyring_status(st)
    return 0


def cmd_operator_keyring_rotate(args) -> int:
    """Rotate the TARGET AGENT's fabric secret live (the API analog of
    editing rpc_secret + SIGHUP; run against each agent in turn — the
    dual-accept window keeps the mixed cluster flowing)."""
    from ..jobspec.hcl import parse_duration

    api = _client(args)
    window = parse_duration(args.window) if args.window else None
    st = api.agent.keyring_rotate(args.secret, window_s=window)
    if args.as_json:
        print(json.dumps(st, indent=2))
        return 0
    if st.get("rotated"):
        print("Keyring rotated!")
    else:
        print("Keyring unchanged (secret already current)")
    _render_keyring_status(st)
    return 0


def cmd_operator_snapshot_inspect(args) -> int:
    """Reference: command/operator_snapshot_inspect.go."""
    from .. import codec

    with open(args.file, "rb") as f:
        raw = f.read()
    data = codec.unpack(raw)
    tables = data.get("tables", data) if isinstance(data, dict) else {}
    print(f"File    = {args.file}")
    print(f"Size    = {len(raw)} bytes")
    rows = []
    for name, t in sorted(tables.items()):
        try:
            rows.append([name, str(len(t))])
        except TypeError:
            rows.append([name, "?"])
    if rows:
        print(_fmt_table(rows, header=["Table", "Entries"]))
    return 0


def cmd_ui(args) -> int:
    """Reference: command/ui.go — print (and try to open) the web UI."""
    addr, _, _ = _conn_opts(args)
    url = f"{addr}/ui/"
    print(f"Opening URL {url}")
    try:
        import webbrowser

        webbrowser.open(url)
    except Exception:
        pass
    return 0


def cmd_eval_delete(args) -> int:
    """Reference: command/eval_delete.go."""
    api = _client(args)
    api.evaluations.delete(args.eval_id)
    print(f"Deleted evaluation {args.eval_id[:8]}")
    return 0


def cmd_node_purge(args) -> int:
    """Reference: command/node_status.go -purge path (Node.Purge)."""
    api = _client(args)
    api.put(f"/v1/node/{args.node_id}/purge")
    print(f"Node {args.node_id[:8]} purged")
    return 0


def cmd_system_gc(args) -> int:
    """Reference: command/system_gc.go."""
    api = _client(args)
    api.system.gc()
    print("System GC triggered")
    return 0


def cmd_operator_scheduler_get(args) -> int:
    api = _client(args)
    cfg = api.operator.scheduler_configuration()
    print(f"Scheduler Algorithm          = {cfg['SchedulerAlgorithm']}")
    pre = cfg["PreemptionConfig"]
    print(f"Preemption Service Enabled   = {pre['ServiceSchedulerEnabled']}")
    print(f"Preemption Batch Enabled     = {pre['BatchSchedulerEnabled']}")
    print(f"Preemption System Enabled    = {pre['SystemSchedulerEnabled']}")
    print(f"Preemption SysBatch Enabled  = {pre['SysBatchSchedulerEnabled']}")
    print(
        f"Memory Oversubscription      = "
        f"{cfg['MemoryOversubscriptionEnabled']}"
    )
    print(f"Placement Backend            = {cfg.get('Backend', 'host')}")
    return 0


def cmd_operator_scheduler_set(args) -> int:
    api = _client(args)
    cfg: dict = {}
    if args.scheduler_algorithm:
        cfg["SchedulerAlgorithm"] = args.scheduler_algorithm
    pre = {}
    for flag, key in (
        (args.preempt_service, "ServiceSchedulerEnabled"),
        (args.preempt_batch, "BatchSchedulerEnabled"),
        (args.preempt_system, "SystemSchedulerEnabled"),
        (args.preempt_sysbatch, "SysBatchSchedulerEnabled"),
    ):
        if flag is not None:
            pre[key] = flag == "true"
    if pre:
        cfg["PreemptionConfig"] = pre
    if args.memory_oversubscription is not None:
        cfg["MemoryOversubscriptionEnabled"] = (
            args.memory_oversubscription == "true"
        )
    api.operator.scheduler_set_configuration(cfg)
    print("Scheduler configuration updated!")
    return 0


def cmd_agent_info(args) -> int:
    """Reference: command/agent_info.go."""
    api = _client(args)
    info = api.get("/v1/agent/self")
    print(json.dumps(info, indent=2, default=codec.json_default))
    return 0


def cmd_job_validate(args) -> int:
    """Reference: command/job_validate.go — parse + validate locally,
    then server-side (/v1/validate/job) when a server is reachable."""
    try:
        job = _load_jobfile(args.jobfile, _parse_vars(args.var))
        job.canonicalize()
        job.validate()
    except Exception as e:
        print(f"Job validation errors:\n  {e}", file=sys.stderr)
        return 1
    try:
        out = _client(args).jobs.validate(job)
    except APIError as e:
        # a REACHABLE server's error (ACL denial, 500) must surface —
        # only an unreachable server downgrades to local-only checks
        print(f"Server-side validation failed: {e}", file=sys.stderr)
        return 1
    except Exception:
        out = None  # no server: local validation stands alone
    if out and out.get("Error"):
        print(f"Job validation errors:\n  {out['Error']}", file=sys.stderr)
        return 1
    print("Job validation successful")
    return 0


_EXAMPLE_JOB = """\
# Example jobspec (reference: command/job_init.go's example.nomad)
job "example" {
  datacenters = ["dc1"]
  type        = "service"

  group "cache" {
    count = 1

    task "redis" {
      driver = "rawexec"

      config {
        command = "/bin/sleep"
        args    = ["3600"]
      }

      resources {
        cpu    = 500
        memory = 256
      }
    }
  }
}
"""


def cmd_job_init(args) -> int:
    """Reference: command/job_init.go."""
    path = args.filename or "example.nomad"
    if os.path.exists(path):
        print(f"Error: {path} already exists", file=sys.stderr)
        return 1
    with open(path, "w") as f:
        f.write(_EXAMPLE_JOB)
    print(f"Example job file written to {path}")
    return 0


def cmd_node_meta(args) -> int:
    """Reference: command/node_meta_read.go."""
    api = _client(args)
    node = api.nodes.get(args.node_id)
    for k in sorted(node.meta):
        print(f"{k} = {node.meta[k]}")
    if not node.meta:
        print("No node metadata")
    return 0


def cmd_secret_put(args) -> int:
    api = _client(args)
    items = {}
    for kv in args.items:
        if "=" not in kv:
            print(f"Error: item {kv!r} must be key=value", file=sys.stderr)
            return 1
        k, _, v = kv.partition("=")
        items[k] = v
    api.secrets.put(args.path, items, namespace=args.namespace)
    print(f'Secret "{args.path}" written ({len(items)} keys)')
    return 0


def cmd_secret_get(args) -> int:
    api = _client(args)
    entry = api.secrets.get(args.path, namespace=args.namespace)
    for k in sorted(entry.items):
        print(f"{k} = {entry.items[k]}")
    return 0


def cmd_secret_list(args) -> int:
    api = _client(args)
    rows = api.secrets.list(namespace=args.namespace)
    if not rows:
        print("No secrets")
        return 0
    print(
        _fmt_table(
            [[r["path"], ",".join(r["keys"])] for r in rows],
            header=["Path", "Keys"],
        )
    )
    return 0


def cmd_secret_delete(args) -> int:
    api = _client(args)
    api.secrets.delete(args.path, namespace=args.namespace)
    print(f'Secret "{args.path}" deleted')
    return 0


def cmd_service_list(args) -> int:
    """Reference: command/service_list.go."""
    api = _client(args)
    rows = api.services.list(namespace=args.namespace)
    if not rows:
        print("No services")
        return 0
    print(
        _fmt_table(
            [
                [r["service_name"], ",".join(r["tags"]), str(r["instances"])]
                for r in rows
            ],
            header=["Service Name", "Tags", "Instances"],
        )
    )
    return 0


def cmd_service_info(args) -> int:
    """Reference: command/service_info.go."""
    api = _client(args)
    try:
        regs = api.services.get(args.name, namespace=args.namespace)
    except Exception as e:
        print(f"Error: {e}", file=sys.stderr)
        return 1
    print(
        _fmt_table(
            [
                [
                    f"{r.address}:{r.port}",
                    r.status or "-",
                    r.alloc_id[:8],
                    r.node_id[:8],
                    ",".join(r.tags),
                ]
                for r in regs
            ],
            header=["Address", "Status", "Alloc ID", "Node ID", "Tags"],
        )
    )
    return 0


def cmd_plugin_status(args) -> int:
    """Reference: command/plugin_status.go (CSI plugin health)."""
    api = _client(args)
    if args.id:
        p = api.plugins.get(args.id)
        print(f"ID                   = {p['id']}")
        print(f"Version              = {p.get('version', '')}")
        print(
            f"Controllers Healthy  = "
            f"{p['controllers_healthy']}/{p['controllers_expected']}"
        )
        print(
            f"Nodes Healthy        = "
            f"{p['nodes_healthy']}/{p['nodes_expected']}"
        )
        return 0
    plugins = api.plugins.list()
    if not plugins:
        print("No CSI plugins")
        return 0
    print(
        _fmt_table(
            [
                [
                    p["id"],
                    p.get("version", ""),
                    f"{p['controllers_healthy']}/{p['controllers_expected']}",
                    f"{p['nodes_healthy']}/{p['nodes_expected']}",
                ]
                for p in plugins
            ],
            header=["ID", "Version", "Controllers Healthy", "Nodes Healthy"],
        )
    )
    return 0


def cmd_operator_debug(args) -> int:
    """Reference: command/operator_debug.go — capture a support bundle
    (cluster state, metrics, thread dumps) into an archive."""
    import json as _json
    import tarfile
    import time as _time

    from .. import codec
    from ..agent.debug import debug_bundle

    api = _client(args)
    bundle = debug_bundle(api)
    out = args.output or f"nomad-debug-{_time.strftime('%Y%m%d-%H%M%S')}.tar.gz"
    with tarfile.open(out, "w:gz") as tar:
        for name, payload in bundle.items():
            data = _json.dumps(
                codec.to_wire(payload), indent=2, default=codec.json_default
            ).encode()
            info = tarfile.TarInfo(name=f"debug/{name}.json")
            info.size = len(data)
            import io as _io

            tar.addfile(info, _io.BytesIO(data))
    print(f"Debug capture written to {out}")
    return 0


def cmd_operator_metrics(args) -> int:
    """Reference: command/operator_metrics.go — dump agent telemetry."""
    import json as _json

    api = _client(args)
    snap = api.agent.metrics()
    if args.as_json:
        print(_json.dumps(snap, indent=2, sort_keys=True))
        return 0
    print(f"Uptime: {snap.get('uptime_seconds', 0):.0f}s")
    for section in ("counters", "gauges"):
        vals = snap.get(section) or {}
        if vals:
            print(f"\n{section.capitalize()}:")
            for k in sorted(vals):
                print(f"  {k} = {vals[k]}")
    samples = snap.get("samples") or {}
    if samples:
        print("\nSamples (count/mean/max):")
        for k in sorted(samples):
            s = samples[k]
            print(
                f"  {k} = {int(s['count'])} / {s['mean']:.6f} / "
                f"{s['max']:.6f}"
            )
    return 0


def _fmt_dur(s: float) -> str:
    """Compact duration: 840us / 12.5ms / 1.24s."""
    if s < 0.001:
        return f"{s * 1e6:.0f}us"
    if s < 1.0:
        return f"{s * 1e3:.1f}ms"
    return f"{s:.2f}s"


# `operator top` row order: the end-to-end pipeline first (enqueue →
# dequeue → solve → queue → verify/apply), then whatever else is hot
_TOP_STAGE_ORDER = [
    "nomad.eval.e2e_seconds",
    "nomad.broker.wait_seconds",
    "nomad.worker.invoke_seconds.service",
    "nomad.worker.invoke_seconds.batch",
    "nomad.worker.lane.interactive_seconds",
    "nomad.worker.lane.batch_seconds",
    "nomad.tpu.batch_dispatch_seconds",
    "nomad.tpu.micro_seconds",
    "nomad.tpu.host_prep_seconds",
    "nomad.tpu.device_seconds",
    "nomad.tpu.readback_seconds",
    "nomad.tpu.materialize_seconds",
    "nomad.tpu.commit_seconds",
    "nomad.plan.submit_seconds",
    "nomad.plan_queue.wait_seconds",
    "nomad.plan_apply.batch_seconds",
    "nomad.raft.apply_seconds",
]


def _render_top(
    snap: dict, prev, solver=None, profile=None, blackbox=None
) -> str:
    """One `operator top` frame from a /v1/metrics snapshot. prev is
    (monotonic_time, snapshot) of the previous frame (None on the
    first) — eval throughput is the e2e-count delta between frames,
    falling back to the last window's rate. solver is the optional
    /v1/solver/status payload feeding the solver panel row; profile the
    optional /v1/profile/status payload feeding the host row; blackbox
    the optional /v1/blackbox/status payload feeding the incidents
    row."""
    import time as _time

    gauges = snap.get("gauges") or {}
    samples = snap.get("samples") or {}
    e2e = samples.get("nomad.eval.e2e_seconds") or {}
    total_evals = int(e2e.get("count", 0))
    rate = None
    if prev is not None:
        prev_t, prev_snap = prev
        dt = _time.monotonic() - prev_t
        prev_count = int(
            (prev_snap.get("samples", {}).get("nomad.eval.e2e_seconds")
             or {}).get("count", 0)
        )
        if dt > 0:
            rate = (total_evals - prev_count) / dt
    if rate is None:
        win = e2e.get("window")
        if win and win.get("interval_s"):
            rate = win["count"] / max(win["interval_s"], 1e-9)
    lines = [
        f"nomad-tpu top — uptime {snap.get('uptime_seconds', 0):.0f}s",
        "",
        (
            f"Throughput  {rate:.1f} evals/s" if rate is not None
            else "Throughput  -"
        )
        + f"   total {total_evals} evals"
        + f"   failed {int(gauges.get('nomad.broker.failed', 0))}",
        (
            "Queues      broker ready "
            f"{int(gauges.get('nomad.broker.total_ready', 0))}"
            f"  unacked {int(gauges.get('nomad.broker.total_unacked', 0))}"
            f"  blocked {int(gauges.get('nomad.broker.total_blocked', 0))}"
            f"  waiting {int(gauges.get('nomad.broker.total_waiting', 0))}"
            f"   plan queue {int(gauges.get('nomad.plan_queue.depth', 0))}"
        ),
        (
            f"Workers     {int(gauges.get('nomad.workers.count', 0))}"
            " scheduler worker(s)"
            f"   processed {int(gauges.get('nomad.workers.processed', 0))}"
        ),
    ]
    # overload panel: admission shed / front-door throttle / backpressure
    # counters (docs/operations.md § Surviving overload). Rendered when
    # admission control is configured or any overload signal has fired —
    # an unconfigured quiet cluster keeps the compact layout.
    counters = snap.get("counters") or {}
    shed = int(counters.get("nomad.broker.shed", 0))
    rejected = int(counters.get("nomad.broker.rejected", 0))
    throttled = int(
        counters.get("nomad.http.throttled", 0)
        + counters.get("nomad.rpc.throttled", 0)
    )
    bp_level = gauges.get("nomad.worker.backpressure_level")
    if (
        shed or rejected or throttled or bp_level
        or gauges.get("nomad.broker.admission_depth")
    ):
        lines.append(
            f"Overload    shed {shed}   rejected(429) {rejected}"
            f"   throttled http+rpc {throttled}"
            f"   pending {int(gauges.get('nomad.broker.total_pending', 0))}"
            + (
                f"/{int(gauges.get('nomad.broker.admission_depth', 0))}"
                if gauges.get("nomad.broker.admission_depth")
                else ""
            )
            + (
                f"   backpressure {bp_level * 100:.0f}%"
                if bp_level is not None
                else ""
            )
        )
    # priority-lane panel (the interactive fast path, docs/pipeline.md):
    # rendered once the TPU worker has classified anything — lane
    # counters plus the two lanes' p50s side by side, so lane starvation
    # (interactive p50 drifting toward the batch cadence) reads straight
    # off the dashboard (docs/operations.md § Diagnosing a slow
    # interactive eval).
    ia_n = int(counters.get("nomad.worker.lane.interactive", 0))
    if ia_n:
        ia_s = samples.get("nomad.worker.lane.interactive_seconds") or {}
        b_s = samples.get("nomad.worker.lane.batch_seconds") or {}
        micro_n = int(counters.get("nomad.worker.lane.micro", 0))
        preempted = int(
            counters.get("nomad.worker.lane.drain_preempted", 0)
        )
        lines.append(
            f"Lanes       interactive {ia_n}"
            + (
                f" (p50 {_fmt_dur(ia_s['p50'])})"
                if ia_s.get("count") and "p50" in ia_s
                else ""
            )
            + f"   micro {micro_n}"
            + f"   drain preempted {preempted}"
            + (
                f"   batch p50 {_fmt_dur(b_s['p50'])}"
                if b_s.get("count") and "p50" in b_s
                else ""
            )
        )
    # solver panel: occupancy %, steady-state recompiles, device p95 —
    # /v1/solver/status for the ledger, /v1/metrics for the occupancy
    # histogram and the device-stage percentiles. Rendered only when a
    # solver actually exists here: a TPU batch worker is wired, or
    # batches have been solved (the snapshot itself is always truthy,
    # control-plane-only agents included).
    occ_s = samples.get("nomad.solver.occupancy")
    has_solver = solver is not None and (
        solver.get("worker") is not None
        or (solver.get("occupancy") or {}).get("batches")
    )
    if has_solver or (occ_s and occ_s.get("count")):
        ledger = (solver or {}).get("ledger") or {}
        steady = ledger.get("steady_recompiles", "-")
        dev = samples.get("nomad.tpu.device_seconds") or {}
        occ_txt = (
            f"{occ_s['last'] * 100:.1f}%"
            if occ_s and occ_s.get("count")
            else "-"
        )
        lines.append(
            f"Solver      occupancy {occ_txt}"
            f"   steady recompiles {steady}"
            + (
                f"   device p95 {_fmt_dur(dev['p95'])}"
                if dev.get("count") and "p95" in dev
                else "   device p95 -"
            )
        )
        # solver-pool row (only-when-nonzero, like the overload rows):
        # membership with per-member in-flight counts shown only for
        # members that actually hold a dispatched batch right now
        pool = (solver or {}).get("pool") or {}
        pmembers = [
            m for m in pool.get("members") or [] if not m.get("self")
        ]
        if pmembers or pool.get("dispatched"):
            mem_txt = " ".join(
                f"{m['id']}:{m['in_flight']}"
                if m.get("in_flight")
                else str(m["id"])
                for m in pmembers
            ) or "-"
            lines.append(
                f"SolverPool  members {len(pmembers)} [{mem_txt}]"
                f"   dispatched {pool.get('dispatched', 0)}"
                + (
                    f"   in-flight {pool['in_flight']}"
                    if pool.get("in_flight")
                    else ""
                )
                + (
                    f"   faults {pool['faults']}"
                    if pool.get("faults")
                    else ""
                )
            )
    # host-attribution row (always-on profiler, hostobs.py): rendered
    # only when the profiler has actually attributed something — busy
    # samples or GC activity (the only-render-when-nonzero pattern the
    # overload/solver rows follow); a quiet un-profiled agent keeps the
    # compact layout.
    if profile is not None:
        p_busy = profile.get("busy_seconds", 0.0)
        p_gc = (profile.get("gc") or {}).get("collections") or {}
        gc_n = sum(p_gc.values())
        if p_busy or gc_n:
            window = max(profile.get("window_seconds", 0.0), 1e-9)
            spans = profile.get("spans") or {}
            top_span = next(
                (s for s in spans if s != "-"), None
            ) or (next(iter(spans), None))
            gc_tot = (profile.get("gc") or {}).get(
                "pause_seconds_total", 0.0
            )
            lines.append(
                f"Host        busy {p_busy / window * 100:.1f}%"
                + (f"   top span {top_span}" if top_span else "")
                + f"   gc {gc_n} pauses"
                + (f" ({_fmt_dur(gc_tot)})" if gc_tot else "")
                + (
                    f"   rss {_fmt_bytes(profile['runtime']['rss_bytes'])}"
                    if (profile.get("runtime") or {}).get("rss_bytes")
                    else ""
                )
            )
    # fleet panel (heartbeat wheel + alloc-watch hub + node door,
    # docs/operations.md § Surviving a reconnect storm): rendered once
    # any node TTL is armed or a fleet signal has fired — a cluster
    # with no client nodes keeps the compact layout.
    armed = int(gauges.get("nomad.heartbeat.armed", 0))
    nodes_down = int(gauges.get("nomad.fleet.nodes_down", 0))
    expired = int(counters.get("nomad.heartbeat.expired", 0))
    node_throttled = int(counters.get("nomad.rpc.node_throttled", 0))
    if armed or nodes_down or expired or node_throttled:
        lines.append(
            f"Fleet       nodes ready "
            f"{int(gauges.get('nomad.fleet.nodes_ready', 0))}"
            f"  down {nodes_down}"
            f"   ttl armed {armed}"
            f" ({int(gauges.get('nomad.heartbeat.wheel_buckets', 0))}"
            " buckets)"
            f"   expired {expired}"
            f"   watchers "
            f"{int(gauges.get('nomad.fleet.watch_subscribers', 0))}"
            + (
                f"   node throttled(429) {node_throttled}"
                if node_throttled
                else ""
            )
        )
    # incidents row (flight recorder, blackbox.py): rendered only when
    # the recorder has fired a trigger or captured/suppressed an
    # incident — a healthy cluster keeps the compact layout, and the
    # row appearing at all is itself the signal (docs/incidents.md).
    if blackbox is not None:
        bstats = blackbox.get("stats") or {}
        fired = int(bstats.get("triggers_fired", 0))
        captured = int(bstats.get("incidents_captured", 0))
        suppressed = int(bstats.get("incidents_suppressed", 0))
        if fired or captured or suppressed:
            last = next(iter(blackbox.get("incidents") or []), None)
            lines.append(
                f"Incidents   captured {captured}"
                f" (stored {int(bstats.get('incidents_stored', 0))})"
                f"   triggers fired {fired}"
                f"  deduped {int(bstats.get('triggers_deduped', 0))}"
                + (
                    f"   suppressed {suppressed}" if suppressed else ""
                )
                + (
                    f"   last {last['id']}" if last else ""
                )
            )
    lines += [
        "",
        "Stage latencies (cumulative | last window):",
    ]
    ordered = [n for n in _TOP_STAGE_ORDER if n in samples]
    rest = sorted(
        (
            n for n in samples
            if "_seconds" in n and n not in _TOP_STAGE_ORDER
        ),
        key=lambda n: -samples[n].get("count", 0),
    )
    rows = []
    for name in ordered + rest:
        s = samples[name]
        if "p50" not in s:
            continue  # legacy-mode sample: no distribution to show
        win = s.get("window") or {}
        rows.append([
            name,
            str(int(s["count"])),
            _fmt_dur(s["p50"]), _fmt_dur(s["p95"]), _fmt_dur(s["p99"]),
            "|",
            str(int(win.get("count", 0))),
            _fmt_dur(win["p50"]) if win else "-",
            _fmt_dur(win["p95"]) if win else "-",
            _fmt_dur(win["p99"]) if win else "-",
        ])
    lines.append(_fmt_table(
        rows,
        ["STAGE", "COUNT", "P50", "P95", "P99",
         "|", "WCOUNT", "WP50", "WP95", "WP99"],
    ))
    return "\n".join(lines)


def _render_cluster_health(h: dict, prev=None) -> str:
    """Render one /v1/operator/cluster/health payload: per-server rows
    (raft indices, depths, host CPU/RSS, top source) + fleet totals.
    prev is (monotonic_time, health) of the previous frame — per-server
    CPU% is the cpu_seconds delta between frames (operator top
    -cluster); '-' on the first frame or for degraded members."""
    import time as _time

    servers = h.get("servers") or []
    n = len(servers)
    lines = [
        f"Cluster health — region {h.get('region', '-')}"
        f"   leader {h.get('leader') or '-'}"
        f"   {h.get('healthy', 0)}/{n} healthy"
        f"   queried via {h.get('queried_by', '-')}"
        f" in {h.get('elapsed_s', 0)}s",
        "",
    ]
    prev_cpu: dict = {}
    dt = None
    if prev is not None:
        prev_t, prev_h = prev
        dt = max(_time.monotonic() - prev_t, 1e-9)
        for s in prev_h.get("servers") or []:
            host = s.get("host") or {}
            if s.get("status") == "ok" and "cpu_seconds" in host:
                prev_cpu[s["id"]] = host["cpu_seconds"]
    rows = []
    for s in servers:
        if s.get("status") != "ok":
            rows.append([
                s.get("id", "?"), "degraded", "-", "-", "-", "-",
                "-", "-", (s.get("error") or "")[:40],
            ])
            continue
        raft = s.get("raft") or {}
        broker = s.get("broker") or {}
        host = s.get("host") or {}
        top_src = next(
            (r["source"] for r in (s.get("sources") or {}).get(
                "top", []
            )),
            "-",
        )
        cpu = host.get("cpu_seconds")
        cpu_txt = "-"
        if cpu is not None and s["id"] in prev_cpu and dt:
            cpu_txt = f"{(cpu - prev_cpu[s['id']]) / dt * 100:.0f}%"
        elif cpu is not None:
            cpu_txt = f"{cpu:.1f}s"
        rows.append([
            s["id"] + ("*" if s.get("leader") else ""),
            "ok",
            f"{raft.get('commit_index', 0)}/"
            f"{raft.get('applied_index', 0)}",
            str(int(broker.get("total_ready", 0))),
            str(int(broker.get("total_unacked", 0))),
            str(int(s.get("plan_queue_depth", 0))),
            cpu_txt,
            _fmt_bytes(host.get("rss_bytes", 0)),
            top_src,
        ])
    lines.append(_fmt_table(
        rows,
        ["SERVER", "STATUS", "RAFT C/A", "READY", "UNACKED",
         "PLANQ", "CPU", "RSS", "TOP SOURCE"],
    ))
    fleet = h.get("fleet") or {}
    lines += [
        "",
        (
            "Fleet totals"
            f"   broker ready {fleet.get('broker_ready', 0)}"
            f"  unacked {fleet.get('broker_unacked', 0)}"
            f"   plan queue {fleet.get('plan_queue_depth', 0)}"
            f"   cpu {fleet.get('cpu_seconds', 0.0):.1f}s"
            f"   rss {_fmt_bytes(fleet.get('rss_bytes', 0))}"
        ),
    ]
    src_rows = [
        [r["source"], str(r["calls"]), f"{r['seconds']:.3f}s"]
        for r in fleet.get("sources_top") or []
    ]
    if src_rows:
        lines += [
            "",
            "Top sources by handler seconds (fleet-wide):",
            _fmt_table(src_rows, ["SOURCE", "CALLS", "SECONDS"]),
        ]
    if h.get("degraded"):
        lines += ["", f"DEGRADED members: {', '.join(h['degraded'])}"]
    return "\n".join(lines)


def cmd_operator_cluster_health(args) -> int:
    """`operator cluster health` — the federated health surface
    (/v1/operator/cluster/health): every member's raft indices, queue
    depths, host CPU/RSS, and per-source cost top-K; partitioned
    members flagged degraded without blocking the response."""
    import json as _json

    api = _client(args)
    h = api.operator.cluster_health(
        timeout_s=args.timeout, top=args.top
    )
    if args.as_json:
        print(_json.dumps(h, indent=2, sort_keys=True))
    else:
        print(_render_cluster_health(h))
    # exit 1 when any member is degraded: scriptable like `check`
    return 1 if h.get("degraded") else 0


def cmd_operator_top(args) -> int:
    """Live telemetry dashboard: throughput, queue depths, worker
    utilization, and per-stage p50/p95/p99 (cumulative + last window)
    from /v1/metrics — the answer to "where is the batch spending its
    second", refreshed in place."""
    import time as _time

    api = _client(args)
    interval = max(0.2, float(args.interval))
    frames = 0
    prev = None
    try:
        while True:
            if getattr(args, "cluster", False):
                # -cluster: the federated per-server view — one health
                # pull renders every member's columns + fleet totals
                # (CPU% from the cpu_seconds delta between frames)
                health = api.operator.cluster_health(
                    timeout_s=max(0.5, interval / 2)
                )
                frame = _render_cluster_health(health, prev)
                prev = (_time.monotonic(), health)
                frames += 1
                last = args.once or (args.n and frames >= args.n)
                if not last and sys.stdout.isatty():
                    sys.stdout.write("\x1b[2J\x1b[H")
                print(frame)
                sys.stdout.flush()
                if last:
                    return 0
                _time.sleep(interval)
                continue
            snap = api.agent.metrics()
            try:
                solver = api.agent.solver_status()
            except Exception:
                solver = None  # older agent / route unavailable
            try:
                profile = api.agent.profile_status(top=1)
            except Exception:
                profile = None  # older agent / route unavailable
            try:
                bb = api.agent.blackbox_status()
            except Exception:
                bb = None  # older agent / route unavailable
            frame = _render_top(
                snap, prev, solver=solver, profile=profile, blackbox=bb
            )
            prev = (_time.monotonic(), snap)
            frames += 1
            last = args.once or (args.n and frames >= args.n)
            if not last and sys.stdout.isatty():
                sys.stdout.write("\x1b[2J\x1b[H")  # clear + home
            print(frame)
            sys.stdout.flush()
            if last:
                return 0
            _time.sleep(interval)
    except KeyboardInterrupt:
        return 0


def cmd_operator_trace(args) -> int:
    """Render eval-lifecycle traces from the agent's /v1/traces ring
    (trace.py): span tree with self-times for one trace, a listing when
    no id is given, and -summary for the critical-path analyzer (top
    span names by total self-time across the last N traces)."""
    from ..trace import critical_path, render_tree

    api = _client(args)
    if args.summary:
        summaries = api.traces.list(
            name=args.name, eval_id=args.eval_id, job_id=args.job_id,
            limit=args.n,
        )
        if not summaries:
            print("No traces recorded (is trace_enabled on?)")
            return 1
        traces = [api.traces.get(s["id"]) for s in summaries]
        total_ms = sum(t.get("duration_ms") or 0 for t in traces)
        print(
            f"Critical path over last {len(traces)} traces "
            f"({total_ms:.1f}ms total): top spans by self-time"
        )
        rows = [
            [name, f"{ns / 1e6:.3f}ms",
             f"{ns / max(total_ms * 1e6, 1) * 100:.1f}%"]
            for name, ns in critical_path(traces, top=args.top)
        ]
        print(_fmt_table(rows, ["Span", "Self Time", "Of Total"]))
        return 0
    if args.trace_id:
        trace_doc = api.traces.get(args.trace_id)
        print(render_tree(trace_doc))
        return 0
    summaries = api.traces.list(
        name=args.name, eval_id=args.eval_id, job_id=args.job_id,
        limit=args.n,
    )
    if not summaries:
        print("No traces recorded (is trace_enabled on?)")
        return 1
    rows = []
    for s in summaries:
        a = s.get("attrs") or {}
        rows.append(
            [
                s["id"],
                s["name"],
                f"{s.get('duration_ms', 0)}ms",
                str(s.get("num_spans", 0)),
                a.get("status", ""),
                a.get("eval_id", "") or ",".join(
                    (a.get("eval_ids") or [])[:2]
                ),
            ]
        )
    print(_fmt_table(
        rows, ["ID", "Name", "Duration", "Spans", "Status", "Evals"]
    ))
    return 0


def _fmt_wallclock(ts: float) -> str:
    """Wall-clock timestamp for incident/timeline rows (local time)."""
    import time as _time

    return _time.strftime("%Y-%m-%d %H:%M:%S", _time.localtime(ts))


def cmd_operator_incidents_list(args) -> int:
    """`operator incidents list` — the flight recorder's incident index
    (/v1/incidents): every anomaly-triggered capture with its trigger
    rule, observed value, and on-disk bundle path (docs/incidents.md)."""
    import json as _json

    api = _client(args)
    incidents = api.agent.incidents()
    if args.as_json:
        print(_json.dumps(incidents, indent=2, sort_keys=True))
        return 0
    if not incidents:
        print("No incidents captured (the blackbox is quiet).")
        return 0
    rows = []
    for rec in incidents:
        d = rec.get("detail") or {}
        rows.append([
            rec["id"],
            _fmt_wallclock(rec.get("ts", 0)),
            d.get("rule", rec.get("reason", "")),
            str(d.get("value", "-")),
            str(d.get("threshold", "-")),
            rec.get("path") or "(memory only)",
        ])
    print(_fmt_table(
        rows,
        ["ID", "CAPTURED", "RULE", "VALUE", "THRESHOLD", "BUNDLE"],
    ))
    return 0


def cmd_operator_incidents_show(args) -> int:
    """`operator incidents show <id>` — one incident's capture record:
    trigger detail, bundle path, and the files the capture wrote."""
    import json as _json

    api = _client(args)
    rec = api.agent.incident(args.incident_id)
    if args.as_json:
        print(_json.dumps(rec, indent=2, sort_keys=True))
        return 0
    d = rec.get("detail") or {}
    print(f"Incident  {rec['id']}")
    print(f"Captured  {_fmt_wallclock(rec.get('ts', 0))}")
    print(f"Rule      {d.get('rule', rec.get('reason', '-'))}")
    if d.get("reason"):
        print(f"Reason    {d['reason']}")
    if "value" in d:
        print(
            f"Observed  {d.get('value')}"
            f" (threshold {d.get('threshold', '-')},"
            f" source {d.get('source', '-')})"
        )
    print(f"Bundle    {rec.get('path') or '(memory only)'}")
    files = rec.get("files") or []
    if files:
        print("Files:")
        for name in files:
            print(f"  {name}")
    return 0


def cmd_operator_timeline(args) -> int:
    """`operator timeline <kind> <id>` — the causal timeline for one
    object (/v1/timeline): flight-recorder journal rows + finished
    traces that touch the object or anything reachable from it within
    two relation hops, merged onto one wall-clock axis."""
    import json as _json

    api = _client(args)
    tl = api.agent.timeline(args.kind, args.object_id)
    if args.as_json:
        print(_json.dumps(tl, indent=2, sort_keys=True))
        return 0
    related = tl.get("related") or []
    print(
        f"Timeline for {tl.get('kind')}:{tl.get('id')}"
        f" — {len(tl.get('rows') or [])} row(s),"
        f" {len(related)} related object(s)"
    )
    if related:
        print("Related: " + " ".join(sorted(related)))
    rows = []
    for row in tl.get("rows") or []:
        d = row.get("detail") or {}
        extra = " ".join(
            f"{k}={d[k]}" for k in sorted(d)
            if k != "rel" and not isinstance(d[k], (dict, list))
        )
        rows.append([
            _fmt_wallclock(row.get("ts", 0)),
            row.get("kind", ""),
            row.get("key", ""),
            extra[:60],
        ])
    print(_fmt_table(rows, ["TIME", "KIND", "KEY", "DETAIL"]))
    if tl.get("truncated"):
        print("(truncated — raise the journal capacity for more)")
    return 0


def _fmt_bytes(n) -> str:
    """Compact byte count: 512B / 3.2KB / 1.5MB / 2.1GB."""
    n = float(n or 0)
    for unit in ("B", "KB", "MB", "GB"):
        if n < 1024 or unit == "GB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{int(n)}B"
        n /= 1024
    return f"{n:.1f}GB"


def _render_solver_status(snap: dict) -> str:
    """One `operator solver status` frame from /v1/solver/status."""
    lines = ["nomad-tpu solver status", ""]
    w = snap.get("worker")
    if w:
        lines.append(
            f"Worker      batch_size {w['batch_size']}"
            f"  pipeline {'on' if w.get('pipeline') else 'off'}"
            f"  processed {w.get('processed', 0)} evals"
        )
    occ = snap.get("occupancy") or {}
    last = occ.get("last_batch") or {}
    asks = occ.get("last_asks") or {}
    mean = occ.get("mean")
    lines.append(
        "Occupancy   "
        + (
            f"last {last['occupancy'] * 100:.1f}% "
            f"({last['n']}x{last['g']} real in "
            f"{last['pad_n']}x{last['pad_g']} padded, "
            f"waste {last['pad_waste'] * 100:.1f}%)"
            if last
            else "no batches solved yet"
        )
        + (f"   mean {mean * 100:.1f}%" if mean is not None else "")
        + (
            f"   asks {asks['groups']} groups / "
            f"{asks['requests']} requests"
            if asks
            else ""
        )
    )
    tr = snap.get("transfers") or {}
    lines.append(
        f"Transfers   h2d {_fmt_bytes(tr.get('h2d_bytes'))}"
        f"   d2h {_fmt_bytes(tr.get('d2h_bytes'))}"
        + (
            f"   allgather {_fmt_bytes(tr.get('allgather_bytes'))}"
            f"   scatter {_fmt_bytes(tr.get('scatter_bytes'))}"
            if tr.get("allgather_bytes") or tr.get("scatter_bytes")
            else ""
        )
        + " (cumulative)"
    )
    mem = snap.get("device_memory")
    lines.append(
        "Device mem  "
        + (
            f"in use {_fmt_bytes(mem.get('bytes_in_use'))}"
            + (
                f" / limit {_fmt_bytes(mem['bytes_limit'])}"
                if mem.get("bytes_limit")
                else ""
            )
            if mem
            else "unreported by backend (CPU fallback reports none)"
        )
        + f"   live arrays {_fmt_bytes(snap.get('live_array_bytes'))}"
        + f" (highwater {_fmt_bytes(snap.get('live_array_highwater_bytes'))})"
    )
    sharding = snap.get("sharding") or {}
    shards = sharding.get("last_shards")
    if shards:
        lines.append("")
        lines.append(
            f"Mesh        {sharding.get('devices', len(shards))} devices, "
            "node axis sharded (docs/sharding.md)"
        )
        lines.append(_fmt_table(
            [
                [
                    str(s.get("shard")),
                    str(s.get("rows")),
                    str(s.get("real_rows")),
                    f"{(s.get('occupancy') or 0) * 100:.1f}%",
                ]
                for s in shards
            ],
            ["SHARD", "ROWS", "REAL", "OCCUPANCY"],
        ))
    ledger = snap.get("ledger") or {}
    lines.append("")
    lines.append(
        f"Compile ledger: {ledger.get('compiles', 0)} compiles, "
        f"{ledger.get('cache_hits', 0)} cache hits, "
        f"{ledger.get('steady_recompiles', 0)} steady-state recompiles"
    )
    rows = []
    for name, k in sorted((ledger.get("kernels") or {}).items()):
        rows.append([
            name,
            str(k["compiles"]),
            str(k["steady_recompiles"]),
            str(k["cache_hits"]),
            f"{k['first_compile_ms']:.1f}ms",
            f"{k['steady_compile_ms']:.1f}ms",
            str(k["signatures"]),
        ])
    if rows:
        lines.append(_fmt_table(
            rows,
            ["KERNEL", "COMPILES", "RECOMPILES", "HITS",
             "FIRST-COMPILE", "STEADY-COMPILE", "SHAPES"],
        ))
    jit = snap.get("jit_cache_sizes")
    if jit:
        lines.append(
            "jit cache (jax ground truth): "
            + "  ".join(f"{k}={v}" for k, v in sorted(jit.items()))
        )
    pool = snap.get("pool") or {}
    if (pool.get("members") or pool.get("dispatched")
            or pool.get("role")):
        lines.append("")
        lines.append(_render_solver_pool(pool))
    return "\n".join(lines)


def _render_solver_pool(pool: dict) -> str:
    """The solver-pool section shared by `operator solver status` and
    `operator solver pool status` (docs/solver-pool.md)."""
    lines = [
        f"Solver pool role {pool.get('role') or '-'}"
        f"   dispatched {pool.get('dispatched', 0)}"
        f"   completed {pool.get('completed', 0)}"
        f"   fallback-local {pool.get('fallback_local', 0)}"
        + (
            f"   faults {pool['faults']}" if pool.get("faults") else ""
        )
        + (
            f"   aborted {pool['aborted']}" if pool.get("aborted") else ""
        )
    ]
    rows = []
    for m in pool.get("members") or []:
        remote = m.get("remote") or {}
        rows.append([
            str(m["id"]) + (" (self)" if m.get("self") else ""),
            m.get("status", "-"),
            str(m.get("in_flight", 0)),
            str(m.get("dispatched", 0)),
            str(m.get("faults", 0)),
            str(remote.get("warmups", "-")),
            str(remote.get("solves", "-")),
            str(remote.get("last_sync", "-")),
        ])
    if rows:
        lines.append(_fmt_table(
            rows,
            ["MEMBER", "STATUS", "IN-FLIGHT", "DISPATCHED", "FAULTS",
             "WARMUPS", "SOLVES", "LAST-SYNC"],
        ))
    else:
        lines.append("no pool members advertised (serf tag solver=1)")
    local = pool.get("local")
    if local:
        lines.append(
            f"local solver: warmups {local.get('warmups', 0)}"
            f"  solves {local.get('solves', 0)}"
            f"  syncs {local.get('syncs', 0)}"
            f"  last sync {local.get('last_sync', 'cold')}"
        )
    return "\n".join(lines)


def cmd_operator_solver_pool_status(args) -> int:
    """Render /v1/solver/pool: pool membership + health, leader-side
    dispatch stats, and each member's own warm-solver counters
    (docs/solver-pool.md; runbook operations.md § Scaling the placement
    plane)."""
    import json as _json

    api = _client(args)
    snap = api.agent.solver_pool()
    if args.as_json:
        print(_json.dumps(snap, indent=2, sort_keys=True))
        return 0
    print("nomad-tpu solver pool")
    print("")
    print(_render_solver_pool(snap))
    return 0


def cmd_operator_solver_status(args) -> int:
    """Render /v1/solver/status: the compile ledger (bucket recompiles
    vs cache hits), batch occupancy vs padding waste, host<->device
    transfer bytes, and device memory — the triage surface for a slow
    solve (operations.md § Diagnosing a slow solve)."""
    import json as _json

    api = _client(args)
    snap = api.agent.solver_status()
    if args.as_json:
        print(_json.dumps(snap, indent=2, sort_keys=True))
        return 0
    print(_render_solver_status(snap))
    return 0


def cmd_operator_solver_top(args) -> int:
    """Refresh-loop solver dashboard: occupancy, recompile rate, and
    transfer rates from /v1/solver/status, beside the device-stage
    percentiles from /v1/metrics."""
    import time as _time

    api = _client(args)
    interval = max(0.2, float(args.interval))
    frames = 0
    prev = None
    try:
        while True:
            snap = api.agent.solver_status()
            msnap = api.agent.metrics()
            lines = [_render_solver_status(snap)]
            ledger = snap.get("ledger") or {}
            tr = snap.get("transfers") or {}
            if prev is not None:
                prev_t, prev_ledger, prev_tr = prev
                dt = max(_time.monotonic() - prev_t, 1e-9)
                # clamp at 0: an agent restart between frames resets
                # the cumulative counters and would render negatives
                compiled = max(0, ledger.get("compiles", 0) - prev_ledger)
                h2d_rate = max(0, tr.get("h2d_bytes", 0) - prev_tr[0]) / dt
                d2h_rate = max(0, tr.get("d2h_bytes", 0) - prev_tr[1]) / dt
                lines.append(
                    f"\nRates       compiles {compiled} in {dt:.1f}s"
                    f"   h2d {_fmt_bytes(h2d_rate)}/s"
                    f"   d2h {_fmt_bytes(d2h_rate)}/s"
                )
            samples = msnap.get("samples") or {}
            rows = []
            for name in (
                "nomad.tpu.host_prep_seconds",
                "nomad.tpu.device_seconds",
                "nomad.tpu.readback_seconds",
                "nomad.tpu.materialize_seconds",
                "nomad.solver.compile_seconds",
            ):
                s = samples.get(name)
                if not s or "p50" not in s:
                    continue
                rows.append([
                    name, str(int(s["count"])),
                    _fmt_dur(s["p50"]), _fmt_dur(s["p95"]),
                    _fmt_dur(s["p99"]),
                ])
            if rows:
                lines.append("")
                lines.append(_fmt_table(
                    rows, ["DEVICE STAGE", "COUNT", "P50", "P95", "P99"]
                ))
            prev = (
                _time.monotonic(), ledger.get("compiles", 0),
                (tr.get("h2d_bytes", 0), tr.get("d2h_bytes", 0)),
            )
            frames += 1
            last = args.once or (args.n and frames >= args.n)
            if not last and sys.stdout.isatty():
                sys.stdout.write("\x1b[2J\x1b[H")
            print("\n".join(lines))
            sys.stdout.flush()
            if last:
                return 0
            _time.sleep(interval)
    except KeyboardInterrupt:
        return 0


def _render_profile_status(snap: dict) -> str:
    """One `operator profile status` frame from /v1/profile/status."""
    lines = ["nomad-tpu host profile", ""]
    samples = snap.get("samples", 0)
    busy = snap.get("busy_seconds", 0.0)
    window = max(snap.get("window_seconds", 0.0), 1e-9)
    overhead = snap.get("overhead") or {}
    lines.append(
        f"Sampler     {samples} samples over {window:.0f}s"
        f"  ({snap.get('interval_ms', 0):.0f}ms interval"
        f"{'' if snap.get('running') else ', STOPPED'})"
        f"   busy {busy:.1f}s ({busy / window * 100:.1f}% of window)"
        f"   overhead {overhead.get('duty_cycle', 0) * 100:.2f}%"
    )
    gc_s = snap.get("gc") or {}
    cols = gc_s.get("collections") or {}
    lines.append(
        "GC          "
        + " ".join(f"{g} {n}" for g, n in sorted(cols.items()))
        + f"   pauses {_fmt_dur(gc_s.get('pause_seconds_total', 0.0))}"
        + f" (max {_fmt_dur(gc_s.get('pause_max_s', 0.0))})"
        + f"   paused sections {gc_s.get('paused_sections', 0)}"
        + f" ({_fmt_dur(gc_s.get('paused_section_seconds', 0.0))})"
    )
    rt = snap.get("runtime") or {}
    lines.append(
        f"Runtime     rss {_fmt_bytes(rt.get('rss_bytes'))}"
        f"   threads {rt.get('threads', 0)}"
        f"   fds {rt.get('fds', '-')}"
    )
    locks = snap.get("locks") or {}
    hot = [
        (name, s) for name, s in sorted(locks.items())
        if s.get("contended")
    ]
    if hot:
        lines.append(
            "Locks       "
            + "   ".join(
                f"{name}: {s['contended']} contended, "
                f"{_fmt_dur(s['wait_seconds_total'])} waited "
                f"(max {_fmt_dur(s['max_wait_s'])})"
                for name, s in hot
            )
        )
    lines.append("")
    by_role = snap.get("threads") or {}
    if by_role:
        busy_roles = {
            r: s for r, s in by_role.items() if s.get("busy_seconds")
        }
        if busy_roles:
            lines.append(
                "Busy by role: "
                + "  ".join(
                    f"{r} {s['busy_seconds']:.2f}s"
                    for r, s in sorted(
                        busy_roles.items(),
                        key=lambda kv: -kv[1]["busy_seconds"],
                    )
                )
            )
    sites = snap.get("top_sites") or []
    rows = [
        [
            s["role"],
            s["span"],
            s["site"],
            f"{s['seconds']:.3f}s",
            f"{s['seconds'] / max(busy, 1e-9) * 100:.1f}%",
            str(s["samples"]),
        ]
        for s in sites[:15]
    ]
    if rows:
        lines.append("")
        lines.append("Top self-time sites (role x span x function):")
        lines.append(_fmt_table(
            rows,
            ["ROLE", "SPAN", "SITE", "SELF", "OF-BUSY", "SAMPLES"],
        ))
    else:
        lines.append("")
        lines.append(
            "No busy samples yet (an idle agent profiles as idle; "
            "span names appear once tracing is enabled)."
        )
    dropped = snap.get("sites_evicted", 0) + snap.get("stacks_dropped", 0)
    if dropped:
        lines.append(
            f"NOTE: bounded ledgers overflowed "
            f"({snap.get('sites_evicted', 0)} site samples -> (other), "
            f"{snap.get('stacks_dropped', 0)} stacks dropped)"
        )
    return "\n".join(lines)


def cmd_operator_profile_status(args) -> int:
    """Render /v1/profile/status: the always-on host profiler's
    span-correlated CPU attribution, GC/runtime telemetry, and lock-wait
    ledger — the triage surface for "where does the host second go"
    (docs/operations.md)."""
    import json as _json

    api = _client(args)
    snap = api.agent.profile_status()
    if args.as_json:
        print(_json.dumps(snap, indent=2, sort_keys=True))
        return 0
    print(_render_profile_status(snap))
    return 0


def cmd_operator_profile_top(args) -> int:
    """Refresh-loop host-profile dashboard: /v1/profile/status rendered
    in place, plus busy-rate deltas between frames."""
    import time as _time

    api = _client(args)
    interval = max(0.2, float(args.interval))
    frames = 0
    prev = None
    try:
        while True:
            snap = api.agent.profile_status()
            lines = [_render_profile_status(snap)]
            if prev is not None:
                prev_t, prev_busy, prev_gc = prev
                dt = max(_time.monotonic() - prev_t, 1e-9)
                busy_rate = max(
                    0.0, snap.get("busy_seconds", 0.0) - prev_busy
                ) / dt
                gc_now = (snap.get("gc") or {}).get(
                    "pause_seconds_total", 0.0
                )
                lines.append(
                    f"\nRates       busy {busy_rate * 100:.1f}% of wall"
                    f"   gc {_fmt_dur(max(0.0, gc_now - prev_gc))} paused"
                    f" in {dt:.1f}s"
                )
            prev = (
                _time.monotonic(),
                snap.get("busy_seconds", 0.0),
                (snap.get("gc") or {}).get("pause_seconds_total", 0.0),
            )
            frames += 1
            last = args.once or (args.n and frames >= args.n)
            if not last and sys.stdout.isatty():
                sys.stdout.write("\x1b[2J\x1b[H")
            print("\n".join(lines))
            sys.stdout.flush()
            if last:
                return 0
            _time.sleep(interval)
    except KeyboardInterrupt:
        return 0


def cmd_operator_profile_stacks(args) -> int:
    """Download the collapsed-stack flamegraph text
    (/v1/profile/collapsed): `role;span;frame;...;leaf count` per line —
    pipe into flamegraph.pl or load into speedscope as-is."""
    api = _client(args)
    text = api.agent.profile_collapsed()
    if args.output:
        with open(args.output, "w") as f:
            f.write(text)
        print(
            f"Collapsed stacks written to {args.output} "
            f"({len(text.splitlines())} unique stacks)"
        )
        return 0
    sys.stdout.write(text)
    return 0


def cmd_operator_vet(args) -> int:
    """nomad-vet: the AST-level concurrency & layering analyzer
    (nomad_tpu/analysis; docs/static-analysis.md). Purely local — it
    walks this checkout's production tree, no running agent needed.
    Exit 1 on any unsuppressed finding, stale baseline entry, or
    ledger defect: the same zero-findings contract CI enforces."""
    import json as _json

    from ..analysis import dynamic_edges_from_json, run_vet

    dyn = None
    try:
        if args.dynamic_edges:
            with open(args.dynamic_edges, encoding="utf-8") as f:
                dyn = dynamic_edges_from_json(f.read())
        report = run_vet(
            rules=args.rules or None,
            baseline_path=args.baseline,
            dynamic_edges=dyn,
        )
    except (OSError, ValueError) as e:
        # unknown -rule, unreadable -dynamic-edges/-baseline file, or
        # malformed JSON: a one-line operator error, distinct from the
        # exit-1 findings contract
        print(f"Error: {e}", file=sys.stderr)
        return 2
    if args.as_json:
        print(_json.dumps(report.to_dict(), indent=2))
    else:
        print(report.render(advisories=args.advisory))
    return 1 if report.gate_count else 0


def cmd_event_stream(args) -> int:
    """Follow /v1/event/stream as NDJSON (reference api/event_stream.go
    + `nomad event` tooling): one frame per line, payloads wire-lowered.
    -topic Topic[:Key] filters (repeatable); -index resumes from an
    index; interrupt to stop."""
    import json as _json

    from .. import codec
    from ..api.client import event_stream

    api = _client(args)
    topics: dict[str, list[str]] = {}
    for t in args.topic:
        topic, sep, key = t.partition(":")
        topics.setdefault(topic, []).append(key if sep else "*")
    try:
        for frame in event_stream(
            api, topics=topics, index=args.index, namespace=args.namespace
        ):
            print(_json.dumps(
                codec.to_wire(frame), default=codec.json_default
            ))
            sys.stdout.flush()
    except KeyboardInterrupt:
        return 0
    return 0


def cmd_operator_raft_list_peers(args) -> int:
    """Reference: command/operator_raft_list.go."""
    api = _client(args)
    peers = api.operator.raft_configuration()
    print(
        _fmt_table(
            [
                [
                    p["id"],
                    f"{p['address'][0]}:{p['address'][1]}",
                    "leader" if p["leader"] else "follower",
                ]
                for p in peers
            ],
            ["Node", "Address", "State"],
        )
    )
    return 0


def cmd_server_members(args) -> int:
    api = _client(args)
    members = api.agent.members()
    print(
        _fmt_table(
            [
                [
                    m["id"],
                    f"{m['addr'][0]}:{m['addr'][1]}",
                    m["status"],
                    m["tags"].get("region", ""),
                ]
                for m in members
            ],
            header=["Name", "Address", "Status", "Region"],
        )
    )
    return 0


def cmd_status(args) -> int:
    """Reference command/status.go: a bare id resolves by prefix search
    across every context; unambiguous hits print the object's status."""
    if not args.job_id:
        return cmd_job_status(args)
    api = _client(args)
    try:
        result = api.search.prefix(args.job_id)
    except APIError:
        return cmd_job_status(args)
    matches = result.get("Matches") or {}
    flat = [(ctx, i) for ctx, ids in matches.items() for i in ids]
    if not flat:
        print(f'No matches for "{args.job_id}"')
        return 1
    if len(flat) > 1:
        print(f'Multiple matches for "{args.job_id}":\n')
        for ctx, ident in flat:
            print(f"  {ctx[:-1] if ctx.endswith('s') else ctx}: {ident}")
        return 1
    ctx, ident = flat[0]
    args.job_id = ident
    if ctx == "jobs":
        return cmd_job_status(args)
    if ctx == "nodes":
        args.node_id = ident
        return cmd_node_status(args)
    if ctx == "allocs":
        args.alloc_id = ident
        return cmd_alloc_status(args)
    if ctx == "evals":
        args.eval_id = ident
        return cmd_eval_status(args)
    print(f"{ctx[:-1]}: {ident}")
    return 0


def cmd_version(args) -> int:
    print(f"nomad-tpu v{VERSION}")
    return 0


# ---------------------------------------------------------------------------


def _args_job_run(p):
    p.add_argument("jobfile")
    p.add_argument("-var", action="append", default=[])
    p.add_argument("-detach", action="store_true")
    p.set_defaults(fn=cmd_job_run)


def _args_job_stop(p):
    p.add_argument("job_id")
    p.add_argument("-purge", action="store_true")
    p.set_defaults(fn=cmd_job_stop)


def _args_job_plan(p):
    p.add_argument("jobfile")
    p.add_argument("-var", action="append", default=[])
    p.set_defaults(fn=cmd_job_plan)


def _args_job_validate(p):
    p.add_argument("jobfile")
    p.add_argument("-var", action="append", default=[])
    p.set_defaults(fn=cmd_job_validate)


def _args_job_init(p):
    p.add_argument("filename", nargs="?")
    p.set_defaults(fn=cmd_job_init)


def _args_job_inspect(p):
    p.add_argument("job_id")
    p.set_defaults(fn=cmd_job_inspect)


def _args_alloc_exec(p):
    p.add_argument("-t", "-tty", dest="tty", action="store_true")
    p.add_argument("-task", default="")
    p.add_argument("-rpc-secret", dest="rpc_secret", default="")
    p.add_argument(
        "-fabric-tls", dest="fabric_tls", action="store_true",
        help="dial the RPC fabric over TLS (tls { rpc = true }); "
        "creds from NOMAD_CLIENT_CERT/KEY + NOMAD_CACERT",
    )
    p.add_argument("alloc_id")
    p.add_argument("cmd", nargs=argparse.REMAINDER)
    p.set_defaults(fn=cmd_alloc_exec)


def _args_alloc_logs(p):
    p.add_argument("-f", "-follow", dest="follow", action="store_true")
    p.add_argument("-stderr", action="store_true")
    p.add_argument("-task", default="")
    p.add_argument("alloc_id")
    p.set_defaults(fn=cmd_alloc_logs)


def _args_alloc_fs(p):
    p.add_argument("alloc_id")
    p.add_argument("path", nargs="?", default="")
    p.set_defaults(fn=cmd_alloc_fs)


def _args_alloc_status(p):
    p.add_argument("alloc_id")
    p.set_defaults(fn=cmd_alloc_status)


def _args_eval_status(p):
    p.add_argument("eval_id")
    p.set_defaults(fn=cmd_eval_status)


def _args_node_status(p):
    p.add_argument("node_id", nargs="?")
    p.set_defaults(fn=cmd_node_status)


def _args_node_drain(p):
    p.add_argument("node_id")
    p.add_argument("-enable", action="store_true")
    p.add_argument("-disable", action="store_true")
    p.add_argument("-deadline", default="1h")
    p.add_argument("-ignore-system", dest="ignore_system",
                   action="store_true")
    p.set_defaults(fn=cmd_node_drain)


def _args_server_join(p):
    p.add_argument("address", nargs="+")
    p.set_defaults(fn=cmd_server_join)


def _args_server_force_leave(p):
    p.add_argument("node")
    p.set_defaults(fn=cmd_server_force_leave)


def _args_operator_debug(p):
    p.add_argument("-output", default="")
    p.set_defaults(fn=cmd_operator_debug)


def _args_conn(sp) -> None:
    """Accept -address/-token AFTER the subcommand too (the natural
    spelling when pointing a dashboard at a specific server: `operator
    top -address http://s2:4646`). The top-level flags keep working:
    SUPPRESS means an absent subcommand flag never clobbers a value the
    top-level parse already set, while a present one wins."""
    sp.add_argument(
        "-address", default=argparse.SUPPRESS,
        help="HTTP API address of the target agent",
    )
    sp.add_argument(
        "-token", default=argparse.SUPPRESS, help="ACL token"
    )


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="nomad-tpu")
    p.add_argument("-address", default=None, help="HTTP API address")
    p.add_argument("-token", default=None, help="ACL token")
    p.add_argument(
        "-region", default=None,
        help="federated region to address (default: the server's own)",
    )
    sub = p.add_subparsers(dest="cmd")

    ag = sub.add_parser("agent", help="run an agent")
    ag.add_argument("-dev", action="store_true")
    ag.add_argument("-server", action="store_true")
    ag.add_argument("-client", action="store_true")
    ag.add_argument("-config", default=None)
    ag.add_argument("-bootstrap-expect", dest="bootstrap_expect", type=int)
    ag.add_argument("-join", action="append", default=[])
    ag.add_argument("-servers", action="append", default=[])
    ag.add_argument("-data-dir", dest="data_dir", default=None)
    ag.add_argument("-node-name", dest="node_name", default=None)
    ag.add_argument("-http-port", dest="http_port", type=int, default=None)
    ag.add_argument("-rpc-port", dest="rpc_port", type=int, default=None)
    ag.add_argument("-tpu-scheduler", action="store_true", dest="tpu_scheduler")
    ag.set_defaults(fn=cmd_agent)

    job = sub.add_parser("job", help="job commands")
    jsub = job.add_subparsers(dest="subcmd")
    _args_job_run(jsub.add_parser("run"))
    _args_job_plan(jsub.add_parser("plan"))
    js = jsub.add_parser("status")
    js.add_argument("job_id", nargs="?")
    js.set_defaults(fn=cmd_job_status)
    _args_job_stop(jsub.add_parser("stop"))
    jev = jsub.add_parser("eval")
    jev.add_argument("job_id")
    jev.set_defaults(fn=cmd_job_eval)
    jdp = jsub.add_parser("deployments")
    jdp.add_argument("job_id")
    jdp.set_defaults(fn=cmd_job_deployments)
    jpr = jsub.add_parser("promote")
    jpr.add_argument("job_id")
    jpr.set_defaults(fn=cmd_job_promote)
    jsc = jsub.add_parser("scale")
    jsc.add_argument("job_id")
    jsc.add_argument("group")
    jsc.add_argument("count", type=int)
    jsc.set_defaults(fn=cmd_job_scale)
    jse = jsub.add_parser("scaling-events")
    jse.add_argument("job_id")
    jse.set_defaults(fn=cmd_job_scaling_events)
    _args_job_validate(jsub.add_parser("validate"))
    _args_job_init(jsub.add_parser("init"))
    _args_job_inspect(jsub.add_parser("inspect"))
    jh = jsub.add_parser("history")
    jh.add_argument("job_id")
    jh.set_defaults(fn=cmd_job_history)
    jv = jsub.add_parser("revert")
    jv.add_argument("job_id")
    jv.add_argument("version", type=int)
    jv.set_defaults(fn=cmd_job_revert)
    jd = jsub.add_parser("dispatch")
    jd.add_argument("job_id")
    jd.add_argument("-meta", action="append", default=[])
    jd.add_argument("-payload-file", dest="payload_file", default=None)
    jd.set_defaults(fn=cmd_job_dispatch)
    jpf = jsub.add_parser("periodic")
    jpfsub = jpf.add_subparsers(dest="subsubcmd")
    jpff = jpfsub.add_parser("force")
    jpff.add_argument("job_id")
    jpff.set_defaults(fn=cmd_job_periodic_force)

    node = sub.add_parser("node", help="node commands")
    nsub = node.add_subparsers(dest="subcmd")
    _args_node_status(nsub.add_parser("status"))
    _args_node_drain(nsub.add_parser("drain"))
    ne = nsub.add_parser("eligibility")
    ne.add_argument("node_id")
    ne.add_argument("-enable", action="store_true")
    ne.add_argument("-disable", action="store_true")
    ne.set_defaults(fn=lambda a: cmd_node_eligibility(_elig_fix(a)))
    nm = nsub.add_parser("meta")
    nm.add_argument("node_id")
    nm.set_defaults(fn=cmd_node_meta)
    np_ = nsub.add_parser("purge")
    np_.add_argument("node_id")
    np_.set_defaults(fn=cmd_node_purge)

    alloc = sub.add_parser("alloc", help="alloc commands")
    asub = alloc.add_subparsers(dest="subcmd")
    _args_alloc_status(asub.add_parser("status"))
    _args_alloc_logs(asub.add_parser("logs"))
    _args_alloc_fs(asub.add_parser("fs"))
    arst = asub.add_parser("restart")
    arst.add_argument("alloc_id")
    arst.add_argument("-task", default="")
    arst.set_defaults(fn=cmd_alloc_restart)
    asig = asub.add_parser("signal")
    asig.add_argument("alloc_id")
    asig.add_argument("-s", dest="signal", default="SIGTERM")
    asig.add_argument("-task", default="")
    asig.set_defaults(fn=cmd_alloc_signal)
    astp = asub.add_parser("stop")
    astp.add_argument("alloc_id")
    astp.set_defaults(fn=cmd_alloc_stop)
    # REMAINDER semantics (everything after the alloc id belongs to the
    # command, its own dashed flags included) live in _args_alloc_exec
    _args_alloc_exec(asub.add_parser("exec"))

    ev = sub.add_parser("eval", help="eval commands")
    esub = ev.add_subparsers(dest="subcmd")
    _args_eval_status(esub.add_parser("status"))
    el = esub.add_parser("list")
    el.set_defaults(fn=cmd_eval_list)
    edel = esub.add_parser("delete")
    edel.add_argument("eval_id")
    edel.set_defaults(fn=cmd_eval_delete)

    evt = sub.add_parser("event", help="event stream commands")
    evtsub = evt.add_subparsers(dest="subcmd")
    evst = evtsub.add_parser(
        "stream", help="follow /v1/event/stream as NDJSON"
    )
    evst.add_argument(
        "-topic", action="append", default=[],
        help="Topic[:Key] filter, repeatable (e.g. Job:web)",
    )
    evst.add_argument("-index", type=int, default=0,
                      help="resume from this index")
    evst.add_argument("-namespace", default="")
    evst.set_defaults(fn=cmd_event_stream)

    dep = sub.add_parser("deployment", help="deployment commands")
    dsub = dep.add_subparsers(dest="subcmd")
    dl = dsub.add_parser("list")
    dl.set_defaults(fn=cmd_deployment_list)
    dst = dsub.add_parser("status")
    dst.add_argument("deployment_id")
    dst.set_defaults(fn=cmd_deployment_status)
    dpr = dsub.add_parser("promote")
    dpr.add_argument("deployment_id")
    dpr.add_argument("-group", action="append", default=[])
    dpr.set_defaults(fn=cmd_deployment_promote)
    dfa = dsub.add_parser("fail")
    dfa.add_argument("deployment_id")
    dfa.set_defaults(fn=cmd_deployment_fail)
    dpa = dsub.add_parser("pause")
    dpa.add_argument("deployment_id")
    dpa.add_argument("-resume", action="store_true")
    dpa.set_defaults(fn=cmd_deployment_pause)
    dre = dsub.add_parser("resume")
    dre.add_argument("deployment_id")
    dre.set_defaults(
        fn=lambda a: cmd_deployment_pause(_set_resume(a))
    )

    acl = sub.add_parser("acl", help="ACL commands")
    aclsub = acl.add_subparsers(dest="subcmd")
    ab = aclsub.add_parser("bootstrap")
    ab.set_defaults(fn=cmd_acl_bootstrap)
    ap_ = aclsub.add_parser("policy")
    apsub = ap_.add_subparsers(dest="subsubcmd")
    apa = apsub.add_parser("apply")
    apa.add_argument("name")
    apa.add_argument("rules_file")
    apa.add_argument("-description", default=None)
    apa.set_defaults(fn=cmd_acl_policy_apply)
    apl = apsub.add_parser("list")
    apl.set_defaults(fn=cmd_acl_policy_list)
    apd = apsub.add_parser("delete")
    apd.add_argument("name")
    apd.set_defaults(fn=cmd_acl_policy_delete)
    api_ = apsub.add_parser("info")
    api_.add_argument("name")
    api_.set_defaults(fn=cmd_acl_policy_info)
    at = aclsub.add_parser("token")
    atsub = at.add_subparsers(dest="subsubcmd")
    atc = atsub.add_parser("create")
    atc.add_argument("-name", default=None)
    atc.add_argument("-type", default="client")
    atc.add_argument("-policy", action="append", default=[])
    atc.add_argument("-global", dest="set_global", action="store_true")
    atc.set_defaults(fn=cmd_acl_token_create)
    atl = atsub.add_parser("list")
    atl.set_defaults(fn=cmd_acl_token_list)
    atd = atsub.add_parser("delete")
    atd.add_argument("accessor_id")
    atd.set_defaults(fn=cmd_acl_token_delete)
    ati = atsub.add_parser("info")
    ati.add_argument("accessor_id")
    ati.set_defaults(fn=cmd_acl_token_info)
    ats = atsub.add_parser("self")
    ats.set_defaults(fn=cmd_acl_token_self)
    atu = atsub.add_parser("update")
    atu.add_argument("accessor_id")
    atu.add_argument("-name", default=None)
    atu.add_argument("-type", default=None)
    atu.add_argument("-policy", action="append", default=[])
    atu.add_argument("-global", dest="set_global", choices=["true", "false"],
                     default=None)
    atu.set_defaults(fn=cmd_acl_token_update)

    srv = sub.add_parser("server", help="server commands")
    ssub = srv.add_subparsers(dest="subcmd")
    sm = ssub.add_parser("members")
    sm.set_defaults(fn=cmd_server_members)
    _args_server_force_leave(ssub.add_parser("force-leave"))
    _args_server_join(ssub.add_parser("join"))

    nsp = sub.add_parser("namespace", help="namespace commands")
    nssub = nsp.add_subparsers(dest="subcmd")
    nst = nssub.add_parser("status")
    nst.add_argument("name")
    nst.set_defaults(fn=cmd_namespace_status)
    nsl = nssub.add_parser("list")
    nsl.set_defaults(fn=cmd_namespace_list)
    nsa = nssub.add_parser("apply")
    nsa.add_argument("name")
    nsa.add_argument("-description", default="")
    nsa.set_defaults(fn=cmd_namespace_apply)
    nsd = nssub.add_parser("delete")
    nsd.add_argument("name")
    nsd.set_defaults(fn=cmd_namespace_delete)
    nsi = nssub.add_parser("inspect")
    nsi.add_argument("name")
    nsi.set_defaults(fn=cmd_namespace_inspect)

    vol = sub.add_parser("volume", help="volume commands")
    volsub = vol.add_subparsers(dest="subcmd")
    vreg = volsub.add_parser("register")
    vreg.add_argument("id")
    vreg.add_argument("-name", default="")
    vreg.add_argument("-namespace", default="default")
    vreg.add_argument("-node", default="")
    vreg.add_argument("-path", default="")
    vreg.add_argument(
        "-access-mode", dest="access_mode", default="multi-node-multi-writer"
    )
    vreg.add_argument("-type", default="host", choices=["host", "csi"])
    vreg.add_argument("-plugin", default="")
    vreg.add_argument("-external-id", dest="external_id", default="")
    vreg.set_defaults(fn=cmd_volume_register)
    vinit = volsub.add_parser("init")
    vinit.add_argument("filename", nargs="?")
    vinit.set_defaults(fn=cmd_volume_init)
    vdet = volsub.add_parser("detach")
    vdet.add_argument("volume_id")
    vdet.add_argument("node_id")
    vdet.add_argument("-namespace", default="default")
    vdet.set_defaults(fn=cmd_volume_detach)
    vsnap = volsub.add_parser("snapshot")
    vsnapsub = vsnap.add_subparsers(dest="subsubcmd")
    vsc = vsnapsub.add_parser("create")
    vsc.add_argument("volume_id")
    vsc.add_argument("name", nargs="?")
    vsc.add_argument("-namespace", default="default")
    vsc.set_defaults(fn=cmd_volume_snapshot_create)
    vsd = vsnapsub.add_parser("delete")
    vsd.add_argument("plugin_id")
    vsd.add_argument("snapshot_id")
    vsd.set_defaults(fn=cmd_volume_snapshot_delete)
    vsl = vsnapsub.add_parser("list")
    vsl.add_argument("-plugin", dest="plugin_id", required=True)
    vsl.set_defaults(fn=cmd_volume_snapshot_list)
    vstat = volsub.add_parser("status")
    vstat.add_argument("id", nargs="?")
    vstat.add_argument("-namespace", default="default")
    vstat.set_defaults(fn=cmd_volume_status)
    vcre = volsub.add_parser("create")
    vcre.add_argument("file")
    vcre.add_argument("-namespace", default="default")
    vcre.set_defaults(fn=cmd_volume_create)
    vdel = volsub.add_parser("delete")
    vdel.add_argument("id")
    vdel.add_argument("-namespace", default="default")
    vdel.set_defaults(fn=cmd_volume_delete)
    vdereg = volsub.add_parser("deregister")
    vdereg.add_argument("id")
    vdereg.add_argument("-namespace", default="default")
    vdereg.set_defaults(fn=cmd_volume_deregister)

    system = sub.add_parser("system", help="system maintenance commands")
    syssub = system.add_subparsers(dest="subcmd")
    sgc = syssub.add_parser("gc")
    sgc.set_defaults(fn=cmd_system_gc)
    srec = syssub.add_parser("reconcile")
    srecsub = srec.add_subparsers(dest="subsubcmd")
    srs = srecsub.add_parser("summaries")
    srs.set_defaults(fn=cmd_system_reconcile)

    sec = sub.add_parser("secret", help="embedded secrets store commands")
    secsub = sec.add_subparsers(dest="subcmd")
    sput = secsub.add_parser("put")
    sput.add_argument("path")
    sput.add_argument("items", nargs="+", help="key=value ...")
    sput.add_argument("-namespace", default="default")
    sput.set_defaults(fn=cmd_secret_put)
    sget = secsub.add_parser("get")
    sget.add_argument("path")
    sget.add_argument("-namespace", default="default")
    sget.set_defaults(fn=cmd_secret_get)
    sls = secsub.add_parser("list")
    sls.add_argument("-namespace", default="default")
    sls.set_defaults(fn=cmd_secret_list)
    sdel = secsub.add_parser("delete")
    sdel.add_argument("path")
    sdel.add_argument("-namespace", default="default")
    sdel.set_defaults(fn=cmd_secret_delete)

    uic = sub.add_parser("ui", help="open the web UI")
    uic.set_defaults(fn=cmd_ui)

    scal = sub.add_parser("scaling", help="scaling policy commands")
    scalsub = scal.add_subparsers(dest="subcmd")
    scp = scalsub.add_parser("policy")
    scpsub = scp.add_subparsers(dest="subsubcmd")
    scpl = scpsub.add_parser("list")
    scpl.add_argument("-namespace", default="default")
    scpl.set_defaults(fn=cmd_scaling_policy_list)
    scpi = scpsub.add_parser("info")
    scpi.add_argument("policy_id")
    scpi.set_defaults(fn=cmd_scaling_policy_info)

    svc = sub.add_parser("service", help="service discovery commands")
    svcsub = svc.add_subparsers(dest="subcmd")
    slist = svcsub.add_parser("list")
    slist.add_argument("-namespace", default="default")
    slist.set_defaults(fn=cmd_service_list)
    sinfo = svcsub.add_parser("info")
    sinfo.add_argument("name")
    sinfo.add_argument("-namespace", default="default")
    sinfo.set_defaults(fn=cmd_service_info)

    plug = sub.add_parser("plugin", help="CSI plugin commands")
    plugsub = plug.add_subparsers(dest="subcmd")
    pstat = plugsub.add_parser("status")
    pstat.add_argument("id", nargs="?")
    pstat.set_defaults(fn=cmd_plugin_status)

    op = sub.add_parser("operator", help="operator commands")
    opsub = op.add_subparsers(dest="subcmd")
    opsnap = opsub.add_parser("snapshot")
    opsnapsub = opsnap.add_subparsers(dest="subsubcmd")
    opss = opsnapsub.add_parser("save")
    opss.add_argument("file")
    opss.set_defaults(fn=cmd_operator_snapshot_save)
    opsi = opsnapsub.add_parser("inspect")
    opsi.add_argument("file")
    opsi.set_defaults(fn=cmd_operator_snapshot_inspect)
    opsr = opsnapsub.add_parser("restore")
    opsr.add_argument("file")
    opsr.set_defaults(fn=cmd_operator_snapshot_restore)
    opraft = opsub.add_parser("raft")
    opraftsub = opraft.add_subparsers(dest="subsubcmd")
    oplp = opraftsub.add_parser("list-peers")
    oplp.set_defaults(fn=cmd_operator_raft_list_peers)
    oprm = opraftsub.add_parser("remove-peer")
    oprm.add_argument("peer_id")
    oprm.set_defaults(fn=cmd_operator_raft_remove_peer)
    opap = opsub.add_parser("autopilot")
    opapsub = opap.add_subparsers(dest="subsubcmd")
    opag = opapsub.add_parser("get-config")
    opag.set_defaults(fn=cmd_operator_autopilot_get)
    opas = opapsub.add_parser("set-config")
    opas.add_argument(
        "-cleanup-dead-servers", dest="cleanup_dead_servers",
        default=None, choices=["true", "false"],
    )
    opas.set_defaults(fn=cmd_operator_autopilot_set)
    opkg = opsub.add_parser("keygen")
    opkg.set_defaults(fn=cmd_operator_keygen)
    opkr = opsub.add_parser(
        "keyring", help="fabric rpc_secret keyring (dual-accept rotation)"
    )
    opkrsub = opkr.add_subparsers(dest="subsubcmd")
    opkrs = opkrsub.add_parser(
        "status", help="keyring generation/age/window (/v1/agent/keyring)"
    )
    opkrs.add_argument("-json", action="store_true", dest="as_json")
    opkrs.set_defaults(fn=cmd_operator_keyring_status)
    opkrr = opkrsub.add_parser(
        "rotate", help="install a new secret on the target agent, live"
    )
    opkrr.add_argument(
        "-secret", required=True,
        help="the new cluster secret (see `operator keygen`)",
    )
    opkrr.add_argument(
        "-window", default="",
        help="dual-accept window for the old secret (e.g. 60s; "
        "default: the agent's rpc_secret_window)",
    )
    opkrr.add_argument("-json", action="store_true", dest="as_json")
    opkrr.set_defaults(fn=cmd_operator_keyring_rotate)
    opmet = opsub.add_parser("metrics")
    opmet.add_argument("-json", action="store_true", dest="as_json")
    _args_conn(opmet)
    opmet.set_defaults(fn=cmd_operator_metrics)
    optop = opsub.add_parser(
        "top", help="live telemetry dashboard (/v1/metrics)"
    )
    optop.add_argument("-interval", type=float, default=2.0,
                       help="seconds between refreshes")
    optop.add_argument("-n", type=int, default=0,
                       help="frames to render (0 = until interrupted)")
    optop.add_argument("-once", action="store_true",
                       help="render a single frame and exit")
    optop.add_argument(
        "-cluster", action="store_true",
        help="federated per-server columns + fleet totals "
        "(/v1/operator/cluster/health)",
    )
    _args_conn(optop)
    optop.set_defaults(fn=cmd_operator_top)
    opcl = opsub.add_parser(
        "cluster", help="cluster-scope observability"
    )
    opclsub = opcl.add_subparsers(dest="subsubcmd")
    opclh = opclsub.add_parser(
        "health",
        help="federated member health: raft indices, depths, host "
        "CPU/RSS, per-source cost (/v1/operator/cluster/health)",
    )
    opclh.add_argument("-json", action="store_true", dest="as_json")
    opclh.add_argument(
        "-timeout", type=float, default=2.0,
        help="per-peer deadline in seconds (slow members go degraded)",
    )
    opclh.add_argument("-top", type=int, default=5,
                       help="per-source top-K rows per member")
    _args_conn(opclh)
    opclh.set_defaults(fn=cmd_operator_cluster_health)
    optr = opsub.add_parser(
        "trace", help="render eval-lifecycle traces (/v1/traces)"
    )
    optr.add_argument("trace_id", nargs="?", default="")
    optr.add_argument("-summary", action="store_true",
                      help="critical-path: top spans by total self-time")
    optr.add_argument("-n", type=int, default=20,
                      help="how many recent traces to list/summarize")
    optr.add_argument("-top", type=int, default=5,
                      help="how many span names in the summary")
    optr.add_argument("-name", default="",
                      help="filter by trace name (eval, tpu.batch, http)")
    optr.add_argument("-eval-id", dest="eval_id", default="")
    optr.add_argument("-job-id", dest="job_id", default="")
    optr.set_defaults(fn=cmd_operator_trace)
    opinc = opsub.add_parser(
        "incidents",
        help="flight-recorder incident captures (/v1/incidents)",
    )
    opincsub = opinc.add_subparsers(dest="subsubcmd")
    opincl = opincsub.add_parser(
        "list", help="anomaly-triggered capture index"
    )
    opincl.add_argument("-json", action="store_true", dest="as_json")
    _args_conn(opincl)
    opincl.set_defaults(fn=cmd_operator_incidents_list)
    opincs = opincsub.add_parser(
        "show", help="one incident's trigger detail + bundle files"
    )
    opincs.add_argument("incident_id")
    opincs.add_argument("-json", action="store_true", dest="as_json")
    _args_conn(opincs)
    opincs.set_defaults(fn=cmd_operator_incidents_show)
    optl = opsub.add_parser(
        "timeline",
        help="causal timeline for one object (/v1/timeline)",
    )
    optl.add_argument(
        "kind", help="eval | alloc | node | job | deployment | plan"
    )
    optl.add_argument("object_id")
    optl.add_argument("-json", action="store_true", dest="as_json")
    _args_conn(optl)
    optl.set_defaults(fn=cmd_operator_timeline)
    opsol = opsub.add_parser(
        "solver", help="solver device observability (/v1/solver/status)"
    )
    opsolsub = opsol.add_subparsers(dest="subsubcmd")
    opsst = opsolsub.add_parser(
        "status", help="compile ledger, occupancy, transfers, device memory"
    )
    opsst.add_argument("-json", action="store_true", dest="as_json")
    opsst.set_defaults(fn=cmd_operator_solver_status)
    opstp = opsolsub.add_parser(
        "top", help="refresh-loop solver dashboard"
    )
    opstp.add_argument("-interval", type=float, default=2.0,
                       help="seconds between refreshes")
    opstp.add_argument("-n", type=int, default=0,
                       help="frames to render (0 = until interrupted)")
    opstp.add_argument("-once", action="store_true",
                       help="render a single frame and exit")
    opstp.set_defaults(fn=cmd_operator_solver_top)
    oppool = opsolsub.add_parser(
        "pool", help="solver-pool tier (/v1/solver/pool)"
    )
    oppoolsub = oppool.add_subparsers(dest="subsubsubcmd")
    opplst = oppoolsub.add_parser(
        "status",
        help="pool membership, dispatch stats, per-member warm solvers",
    )
    opplst.add_argument("-json", action="store_true", dest="as_json")
    opplst.set_defaults(fn=cmd_operator_solver_pool_status)
    opprof = opsub.add_parser(
        "profile", help="continuous host profiler (/v1/profile/status)"
    )
    opprofsub = opprof.add_subparsers(dest="subsubcmd")
    oppst = opprofsub.add_parser(
        "status",
        help="span-correlated CPU self-time, GC/lock/runtime telemetry",
    )
    oppst.add_argument("-json", action="store_true", dest="as_json")
    oppst.set_defaults(fn=cmd_operator_profile_status)
    opptp = opprofsub.add_parser(
        "top", help="refresh-loop host-profile dashboard"
    )
    opptp.add_argument("-interval", type=float, default=2.0,
                       help="seconds between refreshes")
    opptp.add_argument("-n", type=int, default=0,
                       help="frames to render (0 = until interrupted)")
    opptp.add_argument("-once", action="store_true",
                       help="render a single frame and exit")
    opptp.set_defaults(fn=cmd_operator_profile_top)
    oppsk = opprofsub.add_parser(
        "stacks",
        help="collapsed-stack flamegraph text (/v1/profile/collapsed)",
    )
    oppsk.add_argument("-output", default="",
                       help="write to a file instead of stdout")
    oppsk.set_defaults(fn=cmd_operator_profile_stacks)
    opvet = opsub.add_parser(
        "vet",
        help="static concurrency & layering analyzer (nomad-vet)",
    )
    opvet.add_argument("-json", action="store_true", dest="as_json")
    opvet.add_argument(
        "-rule", action="append", dest="rules", metavar="RULE",
        help="run only this rule id (repeatable; e.g. NV-lock-blocking)",
    )
    opvet.add_argument(
        "-baseline", default=None,
        help="suppression ledger (default: analysis/baseline.toml)",
    )
    opvet.add_argument(
        "-dynamic-edges", dest="dynamic_edges", default=None,
        help="racecheck edges() JSON for the NV-lock-order cross-check",
    )
    opvet.add_argument(
        "-advisory", action="store_true",
        help="also print advisories (dynamic-coverage gaps)",
    )
    opvet.set_defaults(fn=cmd_operator_vet)
    _args_operator_debug(opsub.add_parser("debug"))
    opsch = opsub.add_parser("scheduler")
    opschsub = opsch.add_subparsers(dest="subsubcmd")
    opsg = opschsub.add_parser("get-config")
    opsg.set_defaults(fn=cmd_operator_scheduler_get)
    opss2 = opschsub.add_parser("set-config")
    opss2.add_argument(
        "-scheduler-algorithm", dest="scheduler_algorithm", default=None,
        choices=["binpack", "spread"],
    )
    for flag, dest in (
        ("-preempt-service-scheduler", "preempt_service"),
        ("-preempt-batch-scheduler", "preempt_batch"),
        ("-preempt-system-scheduler", "preempt_system"),
        ("-preempt-sysbatch-scheduler", "preempt_sysbatch"),
        ("-memory-oversubscription", "memory_oversubscription"),
    ):
        opss2.add_argument(
            flag, dest=dest, default=None, choices=["true", "false"]
        )
    opss2.set_defaults(fn=cmd_operator_scheduler_set)

    ai = sub.add_parser("agent-info", help="agent runtime info")
    ai.set_defaults(fn=cmd_agent_info)

    mon = sub.add_parser("monitor", help="stream agent logs")
    mon.add_argument("-log-level", dest="log_level", default="INFO")
    mon.set_defaults(fn=cmd_monitor)

    st = sub.add_parser("status", help="list jobs")
    st.add_argument("job_id", nargs="?")
    st.set_defaults(fn=cmd_status)

    # -- top-level aliases (reference commands.go registers these
    # shortcuts alongside the namespaced forms: run == job run, etc.) —
    # each shares its canonical subcommand's argument-registration
    # helper, so flags can never drift between the two spellings
    _args_job_run(sub.add_parser("run", help="alias of `job run`"))
    _args_job_stop(sub.add_parser("stop", help="alias of `job stop`"))
    _args_job_plan(sub.add_parser("plan", help="alias of `job plan`"))
    _args_job_validate(
        sub.add_parser("validate", help="alias of `job validate`")
    )
    _args_job_init(sub.add_parser("init", help="alias of `job init`"))
    _args_job_inspect(
        sub.add_parser("inspect", help="alias of `job inspect`")
    )
    _args_alloc_exec(sub.add_parser("exec", help="alias of `alloc exec`"))
    _args_alloc_logs(sub.add_parser("logs", help="alias of `alloc logs`"))
    _args_alloc_fs(sub.add_parser("fs", help="alias of `alloc fs`"))
    _args_alloc_status(
        sub.add_parser("alloc-status", help="alias of `alloc status`")
    )
    _args_eval_status(
        sub.add_parser("eval-status", help="alias of `eval status`")
    )
    _args_node_status(
        sub.add_parser("node-status", help="alias of `node status`")
    )
    _args_node_drain(
        sub.add_parser("node-drain", help="alias of `node drain`")
    )
    al_sm = sub.add_parser("server-members", help="alias of `server members`")
    al_sm.set_defaults(fn=cmd_server_members)
    _args_server_join(
        sub.add_parser("server-join", help="alias of `server join`")
    )
    _args_server_force_leave(
        sub.add_parser(
            "server-force-leave", help="alias of `server force-leave`"
        )
    )
    al_kg = sub.add_parser("keygen", help="alias of `operator keygen`")
    al_kg.set_defaults(fn=cmd_operator_keygen)
    _args_operator_debug(
        sub.add_parser("debug", help="alias of `operator debug`")
    )
    chk = sub.add_parser("check", help="agent health probe")
    chk.set_defaults(fn=cmd_check)

    ver = sub.add_parser("version")
    ver.set_defaults(fn=cmd_version)

    return p


def _set_resume(a):
    a.resume = True
    return a


def _elig_fix(a):
    if a.disable:
        a.enable = False
    elif not a.enable:
        raise SystemExit("one of -enable / -disable required")
    return a


def main(argv: Optional[list[str]] = None) -> int:
    parser = build_parser()
    args = parser.parse_args(argv)
    fn = getattr(args, "fn", None)
    if fn is None:
        parser.print_help()
        return 127
    try:
        ret = fn(args)
        # Flush inside the try: small outputs sit in the stdio buffer until
        # interpreter exit, where an EPIPE would bypass this handler.
        sys.stdout.flush()
        return ret
    except BrokenPipeError:
        # stdout consumer (a pager, `head`) closed early — exit quietly
        # like standard unix tools; suppress the interpreter's flush error.
        devnull = os.open(os.devnull, os.O_WRONLY)
        os.dup2(devnull, sys.stdout.fileno())
        return 0
    except APIError as e:
        print(f"Error: {e}", file=sys.stderr)
        return 1
    except SystemExit as e:
        if isinstance(e.code, str):
            print(f"Error: {e.code}", file=sys.stderr)
            return 1
        raise


if __name__ == "__main__":
    sys.exit(main())
