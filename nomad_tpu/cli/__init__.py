"""CLI (reference: command/ — ~140 subcommands; the core set here)."""

from .main import main

__all__ = ["main"]
