"""nomad_tpu — a TPU-native distributed workload orchestrator.

A ground-up rebuild of the capability set of HashiCorp Nomad (reference at
/root/reference) with one deliberate architectural departure: placement is
solved on TPU. The per-evaluation iterator scheduler is replaced by a batched
JAX solver over dense (alloc x node x resource) tensors; everything around it
(Raft-style replicated state, eval broker, optimistic plan apply, client
agents, drivers) keeps the reference's semantics.

Layer map (mirrors SURVEY.md §1):
  structs/    shared vocabulary (Job, Node, Allocation, Evaluation, Plan)
  state/      MVCC state store with watch channels
  scheduler/  host oracle scheduler + the TPU batch solver (scheduler/tpu)
  server/     eval broker, workers, plan queue/applier, FSM, leadership
  client/     node agent, alloc/task runners
  drivers/    task execution drivers (mock, rawexec, exec)
  api/ cli/   HTTP API + SDK + command line surface
"""

__version__ = "0.1.0"
