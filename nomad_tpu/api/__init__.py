"""Python SDK over the HTTP API (reference: api/ Go SDK, 19k LoC —
one resource group per class here like one file per resource there)."""

from .client import APIError, NomadClient

__all__ = ["APIError", "NomadClient"]
