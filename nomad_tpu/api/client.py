"""Typed HTTP client.

Reference: api/api.go (Client, QueryOptions/WriteOptions, blocking
queries), api/jobs.go, api/nodes.go, api/allocations.go,
api/evaluations.go, api/deployments.go, api/event_stream.go.

Decodes codec wire payloads back into the shared typed structs, so
`client.jobs.get("x")` returns a real Job dataclass, like the Go SDK's
typed structs.
"""

from __future__ import annotations

import json
import urllib.error
import urllib.parse
import urllib.request
from typing import Iterator, Optional

from .. import codec


class APIError(Exception):
    def __init__(self, status: int, message: str,
                 retry_after: Optional[float] = None) -> None:
        self.status = status
        # 429/503 backoff hint from the Retry-After header or the error
        # body's retry_after_s field (sub-second precision wins).
        # retry.py's call_with_retry honors the same attribute name as
        # a backoff floor.
        self.retry_after = retry_after
        self.retry_after_s = retry_after
        super().__init__(f"HTTP {status}: {message}")


class NomadClient:
    def __init__(
        self,
        address: str = "http://127.0.0.1:4646",
        token: str = "",
        namespace: str = "default",
        region: str = "",
        timeout_s: float = 35.0,
        ca_cert: str = "",  # PEM bundle verifying an https:// server
        tls_skip_verify: bool = False,
        retry_429: int = 0,  # max automatic retries of throttled requests
        retry_429_max_wait_s: float = 30.0,
    ) -> None:
        self.address = address.rstrip("/")
        self.token = token
        self.namespace = namespace
        self.region = region  # "" = the contacted server's own region
        self.timeout_s = timeout_s
        # With retry_429 > 0, a 429 whose Retry-After (header or JSON
        # retry_after_s) fits under retry_429_max_wait_s is slept out
        # and retried, up to retry_429 times — the client half of the
        # server's admission control (it TOLD us when to come back).
        self.retry_429 = retry_429
        self.retry_429_max_wait_s = retry_429_max_wait_s
        self._ssl_ctx = None
        if address.startswith("https://"):
            import ssl

            if tls_skip_verify:
                self._ssl_ctx = ssl._create_unverified_context()
            elif ca_cert:
                self._ssl_ctx = ssl.create_default_context(cafile=ca_cert)
            else:
                self._ssl_ctx = ssl.create_default_context()
        self.jobs = Jobs(self)
        self.nodes = Nodes(self)
        self.allocations = Allocations(self)
        self.evaluations = Evaluations(self)
        self.deployments = Deployments(self)
        self.agent = AgentAPI(self)
        self.status = Status(self)
        self.acl = ACLAPI(self)
        self.operator = Operator(self)
        self.volumes = Volumes(self)
        self.plugins = Plugins(self)
        self.services = Services(self)
        self.secrets = Secrets(self)
        self.namespaces = Namespaces(self)
        self.search = Search(self)
        self.system = SystemAPI(self)
        self.scaling = Scaling(self)
        self.traces = Traces(self)

    # -- plumbing ------------------------------------------------------

    def _request(
        self,
        method: str,
        path: str,
        params: Optional[dict] = None,
        body=None,
        raw: bool = False,
        with_index: bool = False,
        timeout_s: Optional[float] = None,
    ):
        params = {k: v for k, v in (params or {}).items() if v not in (None, "")}
        if self.region and "region" not in params:
            params["region"] = self.region
        url = self.address + path
        if params:
            url += "?" + urllib.parse.urlencode(params, doseq=True)
        data = None
        if body is not None:
            data = json.dumps(body, default=codec.json_default).encode()
        req = urllib.request.Request(url, data=data, method=method)
        req.add_header("Content-Type", "application/json")
        if self.token:
            req.add_header("X-Nomad-Token", self.token)
        attempts_left = self.retry_429
        while True:
            try:
                resp = urllib.request.urlopen(
                    req,
                    timeout=timeout_s or self.timeout_s,
                    context=self._ssl_ctx,
                )
                break
            except urllib.error.HTTPError as e:
                retry_after = None
                hdr = e.headers.get("Retry-After") if e.headers else None
                if hdr:
                    try:
                        retry_after = float(hdr)
                    except ValueError:
                        pass
                try:
                    body = json.loads(e.read())
                    msg = body.get("error", str(e))
                    # sub-second precision beats the integral header
                    if body.get("retry_after_s") is not None:
                        retry_after = float(body["retry_after_s"])
                except Exception:
                    msg = str(e)
                if (
                    e.code == 429
                    and attempts_left > 0
                    and retry_after is not None
                    and retry_after <= self.retry_429_max_wait_s
                ):
                    attempts_left -= 1
                    import time as _time

                    _time.sleep(max(0.0, retry_after))
                    continue
                raise APIError(e.code, msg, retry_after=retry_after) from None
        if raw:
            return resp
        payload = json.loads(resp.read() or b"null")
        index = resp.headers.get("X-Nomad-Index")
        decoded = codec.from_wire(payload)
        if with_index:
            return decoded, int(index) if index is not None else 0
        return decoded

    def get(self, path, **kw):
        return self._request("GET", path, **kw)

    def get_with_index(self, path, **kw):
        """Blocking-query form: returns (decoded, X-Nomad-Index)."""
        return self._request("GET", path, with_index=True, **kw)

    def put(self, path, body=None, **kw):
        return self._request("PUT", path, body=body, **kw)

    def delete(self, path, **kw):
        return self._request("DELETE", path, **kw)


class _Resource:
    def __init__(self, c: NomadClient) -> None:
        self.c = c


class Jobs(_Resource):
    def list(self, prefix: str = "", namespace: Optional[str] = None):
        return self.c.get(
            "/v1/jobs",
            params={
                "prefix": prefix,
                "namespace": namespace or self.c.namespace,
            },
        )

    def register(self, job) -> str:
        """Returns the eval id (reference api/jobs.go Register)."""
        return self.c.put("/v1/jobs", body={"Job": codec.to_wire(job)})

    def plan(self, job, diff: bool = True):
        """Server-side dry-run: scheduler annotations + structural diff +
        placement failures, nothing committed (reference api/jobs.go Plan)."""
        return self.c.put(
            f"/v1/job/{job.id}/plan",
            body={"Job": codec.to_wire(job), "Diff": diff},
        )

    def get(self, job_id: str, namespace: Optional[str] = None):
        return self.c.get(
            f"/v1/job/{job_id}",
            params={"namespace": namespace or self.c.namespace},
        )

    def deregister(
        self, job_id: str, purge: bool = False, namespace: Optional[str] = None
    ) -> str:
        return self.c.delete(
            f"/v1/job/{job_id}",
            params={
                "purge": "true" if purge else "false",
                "namespace": namespace or self.c.namespace,
            },
        )

    def allocations(self, job_id: str, namespace: Optional[str] = None):
        return self.c.get(
            f"/v1/job/{job_id}/allocations",
            params={"namespace": namespace or self.c.namespace},
        )

    def evaluations(self, job_id: str, namespace: Optional[str] = None):
        return self.c.get(
            f"/v1/job/{job_id}/evaluations",
            params={"namespace": namespace or self.c.namespace},
        )

    def summary(self, job_id: str, namespace: Optional[str] = None):
        return self.c.get(
            f"/v1/job/{job_id}/summary",
            params={"namespace": namespace or self.c.namespace},
        )

    def scale(self, job_id: str, group: str, count: int,
              message: str = "", namespace: Optional[str] = None):
        return self.c.put(
            f"/v1/job/{job_id}/scale",
            params={"namespace": namespace or self.c.namespace},
            body={
                "Target": {"Group": group},
                "Count": count,
                "Message": message,
            },
        )

    def validate(self, job):
        """Server-side validation; returns {Error, ValidationErrors,
        Warnings} (reference api/jobs.go Validate)."""
        return self.c.put(
            "/v1/validate/job", body={"Job": codec.to_wire(job)}
        )

    def evaluate(self, job_id: str, namespace: Optional[str] = None):
        """Force a new evaluation (reference api/jobs.go ForceEvaluate)."""
        return self.c.put(
            f"/v1/job/{job_id}/evaluate",
            params={"namespace": namespace or self.c.namespace},
        )

    def deployments(self, job_id: str, namespace: Optional[str] = None):
        return self.c.get(
            f"/v1/job/{job_id}/deployments",
            params={"namespace": namespace or self.c.namespace},
        )

    def scale_status(self, job_id: str, namespace: Optional[str] = None):
        return self.c.get(
            f"/v1/job/{job_id}/scale",
            params={"namespace": namespace or self.c.namespace},
        )

    def versions(self, job_id: str, namespace: Optional[str] = None):
        return self.c.get(
            f"/v1/job/{job_id}/versions",
            params={"namespace": namespace or self.c.namespace},
        )

    def revert(self, job_id: str, version: int, namespace: Optional[str] = None):
        return self.c.put(
            f"/v1/job/{job_id}/revert",
            body={
                "JobVersion": version,
                "Namespace": namespace or self.c.namespace,
            },
        )

    def dispatch(
        self,
        job_id: str,
        meta: Optional[dict] = None,
        payload: Optional[str] = None,
        namespace: Optional[str] = None,
    ):
        return self.c.put(
            f"/v1/job/{job_id}/dispatch",
            params={"namespace": namespace or self.c.namespace},
            body={"Meta": meta or {}, "Payload": payload},
        )

    def periodic_force(self, job_id: str, namespace: Optional[str] = None):
        return self.c.put(
            f"/v1/job/{job_id}/periodic/force",
            params={"namespace": namespace or self.c.namespace},
        )


class Nodes(_Resource):
    def list(self, prefix: str = ""):
        return self.c.get("/v1/nodes", params={"prefix": prefix})

    def get(self, node_id: str):
        return self.c.get(f"/v1/node/{node_id}")

    def allocations(self, node_id: str):
        return self.c.get(f"/v1/node/{node_id}/allocations")

    def drain(self, node_id: str, spec=None, mark_eligible: bool = False):
        return self.c.put(
            f"/v1/node/{node_id}/drain",
            body={
                "DrainSpec": codec.to_wire(spec) if spec is not None else None,
                "MarkEligible": mark_eligible,
            },
        )

    def eligibility(self, node_id: str, eligible: bool):
        return self.c.put(
            f"/v1/node/{node_id}/eligibility",
            body={"Eligibility": "eligible" if eligible else "ineligible"},
        )

    def purge(self, node_id: str):
        return self.c.put(f"/v1/node/{node_id}/purge")


class Allocations(_Resource):
    def restart(self, alloc_id: str, task: str = ""):
        return self.c.put(
            f"/v1/client/allocation/{alloc_id}/restart",
            body={"TaskName": task},
        )

    def signal(self, alloc_id: str, signal: str, task: str = ""):
        return self.c.put(
            f"/v1/client/allocation/{alloc_id}/signal",
            body={"Signal": signal, "TaskName": task},
        )

    def stop(self, alloc_id: str):
        return self.c.put(f"/v1/allocation/{alloc_id}/stop")

    def stats(self, alloc_id: str):
        """Live resource usage incl. device stats (reference:
        GET /v1/client/allocation/:id/stats)."""
        return self.c.get(f"/v1/client/allocation/{alloc_id}/stats")

    def list(self):
        return self.c.get("/v1/allocations")

    def get(self, alloc_id: str):
        return self.c.get(f"/v1/allocation/{alloc_id}")

    # -- streaming alloc surface (reference api/fs.go, allocations_exec) --

    def logs(
        self,
        alloc_id: str,
        task: str = "",
        log_type: str = "stdout",
        follow: bool = False,
        origin: str = "start",
        offset: int = 0,
    ):
        """Yields raw log chunks; with follow=True, blocks for more."""
        resp = self.c.get(
            f"/v1/client/fs/logs/{alloc_id}",
            params={
                "task": task,
                "type": log_type,
                "follow": "true" if follow else "false",
                "origin": origin,
                "offset": offset or None,
            },
            raw=True,
            timeout_s=None if follow else 30,
        )
        while True:
            chunk = resp.read1(65536)
            if not chunk:
                return
            yield chunk

    def fs_ls(self, alloc_id: str, path: str = ""):
        return self.c.get(
            f"/v1/client/fs/ls/{alloc_id}", params={"path": path}
        )

    def fs_stat(self, alloc_id: str, path: str = ""):
        return self.c.get(
            f"/v1/client/fs/stat/{alloc_id}", params={"path": path}
        )

    def fs_cat(self, alloc_id: str, path: str) -> bytes:
        resp = self.c.get(
            f"/v1/client/fs/cat/{alloc_id}", params={"path": path}, raw=True
        )
        return resp.read()

    def exec_session(
        self,
        alloc_id: str,
        cmd: list,
        task: str = "",
        tty: bool = False,
        rpc_secret: str = "",
        tls=None,  # (cert_file, key_file, ca_file) when tls { rpc }
    ):
        """Open an interactive exec session over the RPC fabric.

        Returns an ExecSession: .recv() yields output frames, .send_stdin()
        writes input, .close() ends it. The fabric address comes from
        /v1/agent/self; a cluster rpc_secret must be supplied when the
        fabric requires one, and `tls` (cert/key/ca paths) when the
        fabric runs TLS (rpc/tls.py).
        """
        from ..rpc import ConnPool

        tls_ctx = None
        if tls:
            from ..rpc.tls import client_context

            cert, key, ca = tls
            tls_ctx = client_context(ca, cert, key)
        info = self.c.get("/v1/agent/self")
        host, port = info["rpc_addr"]
        pool = ConnPool(secret=rpc_secret, tls_context=tls_ctx)
        session = pool.stream(
            (host, int(port)),
            "ClientExec.exec",
            {
                "alloc_id": alloc_id,
                "task": task,
                "cmd": cmd,
                "tty": tty,
                "token": self.c.token,
            },
        )
        first = session.recv(timeout_s=30)
        if first.get("error"):
            session.close()
            pool.shutdown()
            raise APIError(500, first["error"])
        return ExecSession(session, pool)


class ExecSession:
    """Client half of an interactive exec (reference api/allocations_exec)."""

    def __init__(self, session, pool) -> None:
        self._session = session
        self._pool = pool

    def recv(self, timeout_s=None):
        """Next output frame: {'data': bytes} | {'eof': True} |
        {'error': str}; None on timeout."""
        try:
            return self._session.recv(timeout_s=timeout_s)
        except TimeoutError:
            return None

    def send_stdin(self, data: bytes) -> None:
        self._session.send({"stdin": data})

    def close(self) -> None:
        try:
            self._session.send({"eof": True})
        except (ConnectionError, OSError):
            pass
        self._session.close()
        self._pool.shutdown()


class Scaling(_Resource):
    """Reference: api/scaling.go."""

    def list_policies(self, namespace: Optional[str] = None):
        return self.c.get(
            "/v1/scaling/policies",
            params={"namespace": namespace or self.c.namespace},
        )

    def get_policy(self, policy_id: str):
        return self.c.get(f"/v1/scaling/policy/{policy_id}")


class Traces(_Resource):
    """The agent's eval-lifecycle tracing ring (/v1/traces, trace.py)."""

    def list(self, name: str = "", eval_id: str = "", job_id: str = "",
             limit: int = 50):
        return self.c.get(
            "/v1/traces",
            params={
                "name": name,
                "eval_id": eval_id,
                "job_id": job_id,
                "limit": limit,
            },
        )

    def get(self, trace_id: str):
        return self.c.get(f"/v1/traces/{trace_id}")


class SystemAPI(_Resource):
    def gc(self):
        return self.c.put("/v1/system/gc")

    def reconcile_summaries(self):
        return self.c.put("/v1/system/reconcile/summaries")


class Evaluations(_Resource):
    def list(self):
        return self.c.get("/v1/evaluations")

    def delete(self, eval_id: str):
        return self.c.delete(f"/v1/evaluation/{eval_id}")

    def get(self, eval_id: str):
        return self.c.get(f"/v1/evaluation/{eval_id}")

    def allocations(self, eval_id: str):
        return self.c.get(f"/v1/evaluation/{eval_id}/allocations")


class Deployments(_Resource):
    def list(self):
        return self.c.get("/v1/deployments")

    def get(self, deployment_id: str):
        return self.c.get(f"/v1/deployment/{deployment_id}")

    def allocations(self, deployment_id: str):
        return self.c.get(f"/v1/deployment/allocations/{deployment_id}")

    def promote(self, deployment_id: str, groups=None):
        return self.c.put(
            f"/v1/deployment/promote/{deployment_id}",
            body={"Groups": groups},
        )

    def pause(self, deployment_id: str, pause: bool = True):
        return self.c.put(
            f"/v1/deployment/pause/{deployment_id}", body={"Pause": pause}
        )

    def fail(self, deployment_id: str):
        return self.c.put(f"/v1/deployment/fail/{deployment_id}")


class Search(_Resource):
    def prefix(self, prefix: str, context: str = "all",
               namespace: Optional[str] = None):
        return self.c.put(
            "/v1/search",
            body={
                "Prefix": prefix,
                "Context": context,
                "Namespace": namespace or self.c.namespace,
            },
        )

    def fuzzy(self, text: str, context: str = "all",
              namespace: Optional[str] = None):
        return self.c.put(
            "/v1/search/fuzzy",
            body={
                "Text": text,
                "Context": context,
                "Namespace": namespace or self.c.namespace,
            },
        )


class Namespaces(_Resource):
    def list(self):
        return self.c.get("/v1/namespaces")

    def apply(self, namespace):
        return self.c.put(
            "/v1/namespaces", body={"Namespace": codec.to_wire(namespace)}
        )

    def get(self, name: str):
        return self.c.get(f"/v1/namespace/{name}")

    def delete(self, name: str):
        return self.c.delete(f"/v1/namespace/{name}")


class Volumes(_Resource):
    def list(self, namespace: Optional[str] = None):
        return self.c.get(
            "/v1/volumes",
            params={"namespace": namespace or self.c.namespace},
        )

    def register(self, volume):
        return self.c.put("/v1/volumes", body={"Volume": codec.to_wire(volume)})

    def get(self, vol_id: str, namespace: Optional[str] = None):
        return self.c.get(
            f"/v1/volume/{vol_id}",
            params={"namespace": namespace or self.c.namespace},
        )

    def deregister(self, vol_id: str, namespace: Optional[str] = None):
        return self.c.delete(
            f"/v1/volume/{vol_id}",
            params={"namespace": namespace or self.c.namespace},
        )

    def detach(self, volume_id: str, node_id: str,
               namespace: Optional[str] = None):
        """Release a node's claims + controller-unpublish (reference
        api/csi.go Detach)."""
        return self.c.delete(
            f"/v1/volume/{volume_id}/detach",
            params={
                "node": node_id,
                "namespace": namespace or self.c.namespace,
            },
        )

    def snapshot_create(self, volume_id: str, name: str = "",
                        namespace: Optional[str] = None):
        """Point-in-time snapshot via the CSI controller (reference
        api/csi.go CreateSnapshot)."""
        return self.c.put(
            "/v1/volumes/snapshot",
            body={
                "VolumeID": volume_id,
                "Name": name,
                "Namespace": namespace or self.c.namespace,
            },
        )

    def snapshot_delete(self, plugin_id: str, snapshot_id: str):
        return self.c.delete(
            "/v1/volumes/snapshot",
            params={"plugin_id": plugin_id, "snapshot_id": snapshot_id},
        )

    def snapshot_list(self, plugin_id: str):
        return self.c.get(
            "/v1/volumes/snapshot", params={"plugin_id": plugin_id}
        )

    def create(self, volume):
        """Provision through the CSI controller then register
        (reference api/csi.go Create)."""
        return self.c.put(
            "/v1/volumes/create", body={"Volume": codec.to_wire(volume)}
        )

    def delete(self, vol_id: str, namespace: Optional[str] = None):
        """Deregister + deprovision (reference api/csi.go Delete)."""
        return self.c.delete(
            f"/v1/volume/{vol_id}/delete",
            params={"namespace": namespace or self.c.namespace},
        )


class Secrets(_Resource):
    """Embedded secrets store (the Vault-analog surface)."""

    def list(self, namespace: Optional[str] = None):
        return self.c.get(
            "/v1/secrets",
            params={"namespace": namespace or self.c.namespace},
        )

    def get(self, path: str, namespace: Optional[str] = None):
        return self.c.get(
            f"/v1/secret/{path}",
            params={"namespace": namespace or self.c.namespace},
        )

    def put(self, path: str, items: dict, namespace: Optional[str] = None):
        return self.c.put(
            f"/v1/secret/{path}",
            params={"namespace": namespace or self.c.namespace},
            body={"Items": items},
        )

    def delete(self, path: str, namespace: Optional[str] = None):
        return self.c.delete(
            f"/v1/secret/{path}",
            params={"namespace": namespace or self.c.namespace},
        )


class Services(_Resource):
    """Native service discovery (reference: api/services.go)."""

    def list(self, namespace: Optional[str] = None):
        return self.c.get(
            "/v1/services",
            params={"namespace": namespace or self.c.namespace},
        )

    def get(self, name: str, namespace: Optional[str] = None):
        return self.c.get(
            f"/v1/service/{name}",
            params={"namespace": namespace or self.c.namespace},
        )

    def delete(self, name: str, reg_id: str):
        return self.c.delete(f"/v1/service/{name}/{reg_id}")


class Plugins(_Resource):
    """CSI plugin health aggregation (reference: api/csi.go CSIPlugins)."""

    def list(self):
        return self.c.get("/v1/plugins")

    def get(self, plugin_id: str):
        return self.c.get(f"/v1/plugin/csi/{plugin_id}")


class Operator(_Resource):
    def autopilot_configuration(self):
        return self.c.get("/v1/operator/autopilot/configuration")

    def autopilot_set_configuration(self, config: dict):
        return self.c.put(
            "/v1/operator/autopilot/configuration", body=config
        )

    def raft_remove_peer(self, peer_id: str):
        return self.c.delete(
            "/v1/operator/raft/peer", params={"id": peer_id}
        )

    def scheduler_configuration(self):
        return self.c.get("/v1/operator/scheduler/configuration")

    def scheduler_set_configuration(self, config: dict):
        return self.c.put(
            "/v1/operator/scheduler/configuration", body=config
        )

    def snapshot_save(self) -> bytes:
        import base64

        resp = self.c.get("/v1/operator/snapshot")
        return base64.b64decode(resp["Snapshot"])

    def snapshot_restore(self, data: bytes):
        import base64

        return self.c.put(
            "/v1/operator/snapshot",
            body={"Snapshot": base64.b64encode(data).decode()},
        )

    def raft_configuration(self):
        return self.c.get("/v1/operator/raft/configuration")

    def cluster_health(self, timeout_s=None, top=None):
        """Leader-side telemetry federation (GET
        /v1/operator/cluster/health): every member's raft indices,
        broker/plan-queue depths, host CPU/RSS, and per-source cost
        top-K; partitioned members flagged `degraded` under a bounded
        per-peer deadline."""
        params = {}
        if timeout_s is not None:
            params["timeout"] = str(timeout_s)
        if top is not None:
            params["top"] = str(top)
        return self.c.get(
            "/v1/operator/cluster/health", params=params or None
        )


class AgentAPI(_Resource):
    def force_leave(self, node: str):
        return self.c.put("/v1/agent/force-leave", params={"node": node})

    def members(self):
        return self.c.get("/v1/agent/members")

    def metrics(self):
        """Telemetry snapshot (reference api/operator_metrics.go):
        counters, gauges, and histogram samples with cumulative and
        last-window p50/p90/p95/p99 (metrics.py)."""
        return self.c.get("/v1/metrics")

    def metrics_prometheus(self) -> str:
        """The Prometheus text exposition (?format=prometheus) verbatim
        — what a scraper sees, histogram buckets included."""
        resp = self.c.get(
            "/v1/metrics", params={"format": "prometheus"}, raw=True
        )
        return resp.read().decode()

    def solver_status(self):
        """Solver observability snapshot (/v1/solver/status): compile
        ledger, batch occupancy/padding waste, host<->device transfer
        bytes, device memory (nomad_tpu/solverobs.py); rendered by
        `operator solver status|top`."""
        return self.c.get("/v1/solver/status")

    def solver_pool(self):
        """Solver-pool tier snapshot (/v1/solver/pool): membership +
        health, leader-side dispatch stats, and each member's own warm
        solver counters (nomad_tpu/server/solver_pool.py); rendered by
        `operator solver pool status`."""
        return self.c.get("/v1/solver/pool")

    def profile_status(self, top: int = 50):
        """Host profiler summary (/v1/profile/status): span-correlated
        CPU self-time sites, GC pause/collection telemetry, lock-wait
        ledger, runtime gauges (nomad_tpu/hostobs.py); rendered by
        `operator profile status|top`."""
        return self.c.get("/v1/profile/status", params={"top": top})

    def profile_collapsed(self, limit: int = 0) -> str:
        """Collapsed-stack flamegraph text (/v1/profile/collapsed)
        verbatim — feed to flamegraph.pl / speedscope."""
        resp = self.c.get(
            "/v1/profile/collapsed", params={"limit": limit}, raw=True
        )
        return resp.read().decode()

    def blackbox_status(self, journal: int = 0):
        """Flight-recorder summary (/v1/blackbox/status): journal
        occupancy and per-kind counts, the trigger catalogue with
        last-fired ages, and recent incidents (nomad_tpu/blackbox.py);
        journal=N appends the newest N journal rows. Rendered by
        `operator incidents list` and the `operator top` panel."""
        params = {"journal": journal} if journal else None
        return self.c.get("/v1/blackbox/status", params=params)

    def incidents(self):
        """Captured-incident index (/v1/incidents), newest first; each
        record's `path` is the on-disk bundle directory."""
        return self.c.get("/v1/incidents")

    def incident(self, incident_id: str):
        """One incident's record + its bundle file inventory."""
        return self.c.get(f"/v1/incidents/{incident_id}")

    def timeline(self, kind: str, obj_id: str):
        """Causal cross-object timeline (/v1/timeline/<kind>/<id>):
        journal rows + finished traces merged and expanded through
        their cross-object links (eval -> plan -> alloc -> node).
        Rendered by `operator timeline <kind> <id>`."""
        return self.c.get(f"/v1/timeline/{kind}/{obj_id}")

    def self(self):
        return self.c.get("/v1/agent/self")

    def keyring_status(self):
        """Fabric-auth keyring state (/v1/agent/keyring): generation,
        key age, dual-accept window — fingerprints only, never the
        secrets. Rendered by `operator keyring status`."""
        return self.c.get("/v1/agent/keyring")

    def keyring_rotate(self, secret: str, window_s=None):
        """Rotate this agent's fabric secret live (the API analog of
        editing rpc_secret + SIGHUP); old secret stays accepted for the
        dual-accept window."""
        body = {"Secret": secret}
        if window_s is not None:
            body["Window"] = window_s
        return self.c.put("/v1/agent/keyring/rotate", body)

    def health(self):
        return self.c.get("/v1/agent/health")

    def join(self, *addresses: str):
        return self.c.put(
            "/v1/agent/join", params={"address": list(addresses)}
        )


class Status(_Resource):
    def leader(self):
        return self.c.get("/v1/status/leader")

    def peers(self):
        return self.c.get("/v1/status/peers")

    def regions(self):
        return self.c.get("/v1/regions")


class ACLAPI(_Resource):
    def bootstrap(self):
        return self.c.put("/v1/acl/bootstrap")

    def policies(self):
        return self.c.get("/v1/acl/policies")

    def policy(self, name: str):
        return self.c.get(f"/v1/acl/policy/{name}")

    def policy_apply(self, name: str, rules: str, description: str = ""):
        return self.c.put(
            f"/v1/acl/policy/{name}",
            body={"Rules": rules, "Description": description},
        )

    def policy_delete(self, name: str):
        return self.c.delete(f"/v1/acl/policy/{name}")

    def tokens(self):
        return self.c.get("/v1/acl/tokens")

    def token(self, accessor_id: str):
        return self.c.get(f"/v1/acl/token/{accessor_id}")

    def token_self(self):
        return self.c.get("/v1/acl/token/self")

    def token_create(
        self, name: str = "", type: str = "client", policies=None,
        global_: bool = False,
    ):
        return self.c.put(
            "/v1/acl/token",
            body={
                "Name": name, "Type": type, "Policies": policies or [],
                "Global": global_,
            },
        )

    def token_update(self, accessor_id: str, **fields):
        """Update mutable fields of an existing token (reference
        acl token update): Name, Policies, Type, Global."""
        body = {"AccessorID": accessor_id}
        for k_api, k_py in (
            ("Name", "name"), ("Policies", "policies"),
            ("Type", "type"), ("Global", "global_"),
        ):
            if k_py in fields:
                body[k_api] = fields[k_py]
        return self.c.put("/v1/acl/token", body=body)

    def token_delete(self, accessor_id: str):
        return self.c.delete(f"/v1/acl/token/{accessor_id}")


def event_stream(
    client: NomadClient,
    topics: Optional[dict] = None,
    index: int = 0,
    namespace: str = "",
) -> Iterator[dict]:
    """Generator over /v1/event/stream NDJSON frames (reference
    api/event_stream.go). Yields {"Index": n, "Events": [...]} dicts with
    decoded payloads; skips heartbeats."""
    params: list[tuple[str, str]] = []
    for topic, keys in (topics or {}).items():
        for k in keys:
            params.append(("topic", f"{topic}:{k}"))
    if index:
        params.append(("index", str(index)))
    if namespace:
        params.append(("namespace", namespace))
    url = client.address + "/v1/event/stream"
    if params:
        url += "?" + urllib.parse.urlencode(params)
    req = urllib.request.Request(url)
    if client.token:
        req.add_header("X-Nomad-Token", client.token)
    resp = urllib.request.urlopen(req, context=client._ssl_ctx)
    for line in resp:
        line = line.strip()
        if not line or line == b"{}":
            continue
        frame = json.loads(line)
        for ev in frame.get("Events", []):
            ev["Payload"] = codec.from_wire(ev["Payload"])
        yield frame
