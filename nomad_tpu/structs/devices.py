"""Device instance accounting (reference: nomad/structs/devices.go).

Tracks which device instances on a node are in use by which allocs, used by
AllocsFit's oversubscription check and the scheduler's device allocator.
"""

from __future__ import annotations

from typing import Iterable

from .structs import Allocation, Node, NodeDeviceResource


class DeviceAccounterInstance:
    def __init__(self, device: NodeDeviceResource) -> None:
        self.device = device
        # instance id -> number of users (healthy instances only are usable)
        self.instances: dict[str, int] = {i.id: 0 for i in device.instances}

    def free_count(self) -> int:
        healthy = {i.id for i in self.device.instances if i.healthy}
        return sum(1 for iid, users in self.instances.items() if users == 0 and iid in healthy)


class DeviceAccounter:
    def __init__(self, node: Node) -> None:
        self.devices: dict[str, DeviceAccounterInstance] = {
            d.id_string(): DeviceAccounterInstance(d) for d in node.resources.devices
        }

    def add_allocs(self, allocs: Iterable[Allocation]) -> bool:
        """Track device use by allocs; True if an instance is oversubscribed."""
        collision = False
        for alloc in allocs:
            if alloc.terminal_status() or alloc.resources is None:
                continue
            for tr in alloc.resources.tasks.values():
                for dev in tr.devices:
                    key = dev.get("id", "")
                    ids = dev.get("device_ids", [])
                    acc = self.devices.get(key)
                    if acc is None:
                        continue
                    for iid in ids:
                        if iid in acc.instances:
                            acc.instances[iid] += 1
                            if acc.instances[iid] > 1:
                                collision = True
        return collision

    def add_reserved(self, key: str, instance_ids: list[str]) -> bool:
        acc = self.devices.get(key)
        if acc is None:
            return False
        collision = False
        for iid in instance_ids:
            if iid in acc.instances:
                acc.instances[iid] += 1
                if acc.instances[iid] > 1:
                    collision = True
        return collision
