"""Port/bandwidth accounting per node — bin-packing within bin-packing.

Reference: nomad/structs/network.go NetworkIndex. Kept host-side (SURVEY.md §7
hard part 5): the TPU solver sees network only as a scalar capacity column;
exact port selection happens here during plan construction and verification.
"""

from __future__ import annotations

import random
from typing import Iterable, Optional

from .structs import Allocation, NetworkResource, Node, Port

MIN_DYNAMIC_PORT = 20000
MAX_DYNAMIC_PORT = 32000
MAX_RAND_PORT_ATTEMPTS = 20


class NetworkIndex:
    """Tracks used ports and bandwidth on one node."""

    def __init__(self) -> None:
        self.avail_networks: list[NetworkResource] = []
        self.avail_bandwidth: dict[str, int] = {}  # device -> mbits
        self.used_ports: dict[str, set[int]] = {}  # ip -> ports
        self.used_bandwidth: dict[str, int] = {}  # device -> mbits

    def set_node(self, node: Node) -> bool:
        """Index the node's networks; True on reserved-port collision."""
        collide = False
        for n in node.resources.networks:
            if n.device:
                self.avail_networks.append(n)
                self.avail_bandwidth[n.device] = n.mbits
        for port in node.reserved.reserved_ports:
            for n in self.avail_networks:
                if self._add_reserved_port(n.ip, port):
                    collide = True
        return collide

    def add_allocs(self, allocs: Iterable[Allocation]) -> bool:
        """Track the port/bandwidth usage of existing allocs; True on collision."""
        collide = False
        for alloc in allocs:
            if alloc.terminal_status():
                continue
            if alloc.resources is not None:
                for net in alloc.resources.shared_networks:
                    if self.add_reserved(net):
                        collide = True
                for tr in alloc.resources.tasks.values():
                    for net in tr.networks:
                        if self.add_reserved(net):
                            collide = True
        return collide

    def add_reserved(self, net: NetworkResource) -> bool:
        collide = False
        for port in list(net.reserved_ports) + list(net.dynamic_ports):
            if port.value and self._add_reserved_port(net.ip, port.value):
                collide = True
        if net.device:
            self.used_bandwidth[net.device] = (
                self.used_bandwidth.get(net.device, 0) + net.mbits
            )
        return collide

    def remove_reserved(self, net: NetworkResource) -> None:
        """Undo add_reserved — rollback for a partially-built placement
        whose later asks failed (the batch solver shares one index per
        node across the whole solve)."""
        for port in list(net.reserved_ports) + list(net.dynamic_ports):
            if port.value:
                self.used_ports.get(net.ip, set()).discard(port.value)
        if net.device:
            self.used_bandwidth[net.device] = max(
                0, self.used_bandwidth.get(net.device, 0) - net.mbits
            )

    def _add_reserved_port(self, ip: str, port: int) -> bool:
        used = self.used_ports.setdefault(ip, set())
        if port in used:
            return True
        used.add(port)
        return False

    def overcommitted(self) -> bool:
        for device, used in self.used_bandwidth.items():
            if used > self.avail_bandwidth.get(device, 0):
                return True
        return False

    def yield_ip(self) -> Optional[NetworkResource]:
        for n in self.avail_networks:
            return n
        return None

    def assign_network(self, ask: NetworkResource) -> Optional[NetworkResource]:
        """Satisfy a network ask: pick a device/IP, reserve static ports,
        allocate dynamic ports. Returns the granted offer or None."""
        if not self.avail_networks:
            # Node advertises no networks: only satisfiable with no port asks.
            if not ask.reserved_ports and not ask.dynamic_ports and ask.mbits == 0:
                return NetworkResource(mode=ask.mode)
            return None

        for n in self.avail_networks:
            if ask.mbits + self.used_bandwidth.get(n.device, 0) > self.avail_bandwidth.get(
                n.device, 0
            ):
                continue
            used = self.used_ports.get(n.ip, set())
            # Static ports must be free.
            if any(p.value in used for p in ask.reserved_ports):
                continue
            offer = NetworkResource(
                mode=ask.mode,
                device=n.device,
                ip=n.ip,
                cidr=n.cidr,
                mbits=ask.mbits,
                reserved_ports=[
                    Port(p.label, p.value, p.to, p.host_network)
                    for p in ask.reserved_ports
                ],
            )
            taken = set(used) | {p.value for p in ask.reserved_ports}
            got = pick_dynamic_ports(taken, len(ask.dynamic_ports))
            if got is not None:
                for p, port in zip(ask.dynamic_ports, got):
                    offer.dynamic_ports.append(
                        Port(p.label, port, p.to, p.host_network)
                    )
                return offer
        return None


_MASK64 = (1 << 64) - 1
_LCG_MUL = 6364136223846793005
_LCG_ADD = 1442695040888963407


def _pick_ports_py(taken: set[int], k: int, seed: int) -> Optional[list[int]]:
    """Pure-Python twin of fastpack.pick_ports: the SAME LCG draw
    sequence and linear-scan fallback, so native and fallback pick
    identical ports for one seed (behavior can never diverge — only
    speed, the fastpack contract)."""
    span = MAX_DYNAMIC_PORT - MIN_DYNAMIC_PORT + 1
    bits = {
        p - MIN_DYNAMIC_PORT
        for p in taken
        if MIN_DYNAMIC_PORT <= p <= MAX_DYNAMIC_PORT
    }
    x = seed & _MASK64
    out: list[int] = []
    for _ in range(k):
        got = -1
        for _attempt in range(MAX_RAND_PORT_ATTEMPTS):
            x = (x * _LCG_MUL + _LCG_ADD) & _MASK64
            off = (x >> 33) % span
            if off not in bits:
                got = off
                break
        if got < 0:
            for off in range(span):
                if off not in bits:
                    got = off
                    break
        if got < 0:
            return None  # range exhausted
        bits.add(got)
        out.append(MIN_DYNAMIC_PORT + got)
    return out


def pick_dynamic_ports(taken: set[int], k: int) -> Optional[list[int]]:
    """k distinct free dynamic ports in one draw (bulk port-picking for
    the data plane): native fastpack.pick_ports over a free-port bitmap
    when the extension is resolved, the identical-LCG Python fallback
    otherwise. One entropy draw seeds the whole batch."""
    if k == 0:
        return []
    seed = random.getrandbits(64)
    from .. import codec

    fp = codec.native_module()
    if fp is not None and hasattr(fp, "pick_ports"):
        span = MAX_DYNAMIC_PORT - MIN_DYNAMIC_PORT + 1
        bitmap = bytearray((span + 7) // 8)
        for p in taken:
            if MIN_DYNAMIC_PORT <= p <= MAX_DYNAMIC_PORT:
                off = p - MIN_DYNAMIC_PORT
                bitmap[off >> 3] |= 1 << (off & 7)
        try:
            return fp.pick_ports(
                bytes(bitmap), k, MIN_DYNAMIC_PORT, MAX_DYNAMIC_PORT, seed
            )
        except Exception:
            pass
    return _pick_ports_py(taken, k, seed)
