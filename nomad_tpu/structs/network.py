"""Port/bandwidth accounting per node — bin-packing within bin-packing.

Reference: nomad/structs/network.go NetworkIndex. Kept host-side (SURVEY.md §7
hard part 5): the TPU solver sees network only as a scalar capacity column;
exact port selection happens here during plan construction and verification.
"""

from __future__ import annotations

import random
from typing import Iterable, Optional

from .structs import Allocation, NetworkResource, Node, Port

MIN_DYNAMIC_PORT = 20000
MAX_DYNAMIC_PORT = 32000
MAX_RAND_PORT_ATTEMPTS = 20


class NetworkIndex:
    """Tracks used ports and bandwidth on one node."""

    def __init__(self) -> None:
        self.avail_networks: list[NetworkResource] = []
        self.avail_bandwidth: dict[str, int] = {}  # device -> mbits
        self.used_ports: dict[str, set[int]] = {}  # ip -> ports
        self.used_bandwidth: dict[str, int] = {}  # device -> mbits

    def set_node(self, node: Node) -> bool:
        """Index the node's networks; True on reserved-port collision."""
        collide = False
        for n in node.resources.networks:
            if n.device:
                self.avail_networks.append(n)
                self.avail_bandwidth[n.device] = n.mbits
        for port in node.reserved.reserved_ports:
            for n in self.avail_networks:
                if self._add_reserved_port(n.ip, port):
                    collide = True
        return collide

    def add_allocs(self, allocs: Iterable[Allocation]) -> bool:
        """Track the port/bandwidth usage of existing allocs; True on collision."""
        collide = False
        for alloc in allocs:
            if alloc.terminal_status():
                continue
            if alloc.resources is not None:
                for net in alloc.resources.shared_networks:
                    if self.add_reserved(net):
                        collide = True
                for tr in alloc.resources.tasks.values():
                    for net in tr.networks:
                        if self.add_reserved(net):
                            collide = True
        return collide

    def add_reserved(self, net: NetworkResource) -> bool:
        collide = False
        for port in list(net.reserved_ports) + list(net.dynamic_ports):
            if port.value and self._add_reserved_port(net.ip, port.value):
                collide = True
        if net.device:
            self.used_bandwidth[net.device] = (
                self.used_bandwidth.get(net.device, 0) + net.mbits
            )
        return collide

    def remove_reserved(self, net: NetworkResource) -> None:
        """Undo add_reserved — rollback for a partially-built placement
        whose later asks failed (the batch solver shares one index per
        node across the whole solve)."""
        for port in list(net.reserved_ports) + list(net.dynamic_ports):
            if port.value:
                self.used_ports.get(net.ip, set()).discard(port.value)
        if net.device:
            self.used_bandwidth[net.device] = max(
                0, self.used_bandwidth.get(net.device, 0) - net.mbits
            )

    def _add_reserved_port(self, ip: str, port: int) -> bool:
        used = self.used_ports.setdefault(ip, set())
        if port in used:
            return True
        used.add(port)
        return False

    def overcommitted(self) -> bool:
        for device, used in self.used_bandwidth.items():
            if used > self.avail_bandwidth.get(device, 0):
                return True
        return False

    def yield_ip(self) -> Optional[NetworkResource]:
        for n in self.avail_networks:
            return n
        return None

    def assign_network(self, ask: NetworkResource) -> Optional[NetworkResource]:
        """Satisfy a network ask: pick a device/IP, reserve static ports,
        allocate dynamic ports. Returns the granted offer or None."""
        if not self.avail_networks:
            # Node advertises no networks: only satisfiable with no port asks.
            if not ask.reserved_ports and not ask.dynamic_ports and ask.mbits == 0:
                return NetworkResource(mode=ask.mode)
            return None

        for n in self.avail_networks:
            if ask.mbits + self.used_bandwidth.get(n.device, 0) > self.avail_bandwidth.get(
                n.device, 0
            ):
                continue
            used = self.used_ports.get(n.ip, set())
            # Static ports must be free.
            if any(p.value in used for p in ask.reserved_ports):
                continue
            offer = NetworkResource(
                mode=ask.mode,
                device=n.device,
                ip=n.ip,
                cidr=n.cidr,
                mbits=ask.mbits,
                reserved_ports=[
                    Port(p.label, p.value, p.to, p.host_network)
                    for p in ask.reserved_ports
                ],
            )
            taken = set(used) | {p.value for p in ask.reserved_ports}
            ok = True
            for p in ask.dynamic_ports:
                got = self._pick_dynamic_port(taken)
                if got is None:
                    ok = False
                    break
                taken.add(got)
                offer.dynamic_ports.append(Port(p.label, got, p.to, p.host_network))
            if ok:
                return offer
        return None

    def _pick_dynamic_port(self, taken: set[int]) -> Optional[int]:
        for _ in range(MAX_RAND_PORT_ATTEMPTS):
            port = random.randint(MIN_DYNAMIC_PORT, MAX_DYNAMIC_PORT)
            if port not in taken:
                return port
        # Linear fallback scan
        for port in range(MIN_DYNAMIC_PORT, MAX_DYNAMIC_PORT + 1):
            if port not in taken:
                return port
        return None
