"""Structural job diffing for plan dry-runs.

Reference: nomad/structs/diff.go — Job.Diff builds a tree of ObjectDiff /
FieldDiff nodes (Added/Deleted/Edited/None) that the CLI renders and the
scheduler's annotations ride alongside. This is a generic dataclass walker
rather than the reference's per-struct hand-rolled methods: nomad_tpu
structs are plain dataclasses, so one recursive differ covers the whole
tree and can't drift from the struct definitions.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

DIFF_NONE = "None"
DIFF_ADDED = "Added"
DIFF_DELETED = "Deleted"
DIFF_EDITED = "Edited"

# Fields that are bookkeeping, not user intent — never part of a diff.
_IGNORED_FIELDS = {
    "create_index",
    "modify_index",
    "job_modify_index",
    "submit_time",
    "version",
    "status",
    "stable",
    "modify_time",
    "create_time",
    "id",  # object identity compared by name/key, not uuid
}


def _is_struct(v: Any) -> bool:
    return dataclasses.is_dataclass(v) and not isinstance(v, type)


def _scalar(v: Any) -> str:
    if v is None:
        return ""
    if isinstance(v, bool):
        return "true" if v else "false"
    return str(v)


def _name_of(v: Any, fallback: str) -> str:
    for attr in ("name", "label", "attribute", "ltarget"):
        n = getattr(v, attr, None)
        if n:
            return str(n)
    return fallback


def _empty(v: Any) -> bool:
    # bools are never "empty": False == 0 would otherwise make a
    # False -> True flip render as Added instead of Edited.
    if isinstance(v, bool):
        return False
    return v in (None, "", 0, [], {})


def field_diff(name: str, old: Any, new: Any) -> Optional[dict]:
    if old == new:
        return None
    if _empty(old) and not _empty(new):
        kind = DIFF_ADDED
    elif _empty(new) and not _empty(old):
        kind = DIFF_DELETED
    else:
        kind = DIFF_EDITED
    return {"Type": kind, "Name": name, "Old": _scalar(old), "New": _scalar(new)}


def object_diff(name: str, old: Any, new: Any) -> Optional[dict]:
    """Diff two same-shaped dataclasses (either may be None)."""
    if old is None and new is None:
        return None
    kind = DIFF_EDITED
    if old is None:
        kind = DIFF_ADDED
    elif new is None:
        kind = DIFF_DELETED
    ref = new if new is not None else old
    fields: list[dict] = []
    objects: list[dict] = []
    for f in dataclasses.fields(ref):
        if f.name in _IGNORED_FIELDS:
            continue
        ov = getattr(old, f.name, None) if old is not None else None
        nv = getattr(new, f.name, None) if new is not None else None
        d = _value_diff(f.name, ov, nv)
        if d is None:
            continue
        if isinstance(d, list):
            objects.extend(d)
        elif "Fields" in d or "Objects" in d:
            objects.append(d)
        else:
            fields.append(d)
    if not fields and not objects and kind == DIFF_EDITED:
        return None
    return {
        "Type": kind,
        "Name": name,
        "Fields": fields,
        "Objects": objects,
    }


def _value_diff(name: str, old: Any, new: Any):
    if _is_struct(old) or _is_struct(new):
        return object_diff(name, old, new)
    if isinstance(old, dict) or isinstance(new, dict):
        old, new = old or {}, new or {}
        out = []
        for k in sorted(set(old) | set(new), key=str):
            d = _value_diff(f"{name}[{k}]", old.get(k), new.get(k))
            if d is None:
                continue
            out.extend(d if isinstance(d, list) else [d])
        return out or None
    if isinstance(old, (list, tuple)) or isinstance(new, (list, tuple)):
        old, new = list(old or []), list(new or [])
        if old and _is_struct(old[0]) or new and _is_struct(new[0]):
            olds = {_name_of(v, str(i)): v for i, v in enumerate(old)}
            news = {_name_of(v, str(i)): v for i, v in enumerate(new)}
            out = []
            for k in sorted(set(olds) | set(news)):
                d = object_diff(f"{name}[{k}]", olds.get(k), news.get(k))
                if d is not None:
                    out.append(d)
            return out or None
        if old != new:
            return field_diff(name, old, new)
        return None
    return field_diff(name, old, new)


def job_diff(old, new) -> dict:
    """Top-level diff between two Job versions (reference diff.go:38).

    Task groups are matched by name and diffed as first-class objects so
    the CLI can render create/destroy/edit per group; the scheduler's
    annotations (in-place vs destructive) ride separately.
    """
    if old is None:
        d = object_diff(new.id, None, new) or {
            "Type": DIFF_ADDED, "Name": new.id, "Fields": [], "Objects": [],
        }
        d["Type"] = DIFF_ADDED
        return d
    d = object_diff(new.id, old, new)
    if d is None:
        return {"Type": DIFF_NONE, "Name": new.id, "Fields": [], "Objects": []}
    return d
