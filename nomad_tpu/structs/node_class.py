"""Computed node classes — feasibility memoization key.

Reference: nomad/structs/node_class.go ComputeClass :31. Nodes with identical
non-unique attributes/resources hash to the same class; the scheduler then
checks feasibility once per class instead of once per node. The TPU solver
uses the same classes to deduplicate rows of the feasibility-mask tensor.
"""

from __future__ import annotations

import hashlib

from .structs import Node

# Attribute/meta keys that are unique per node and must not enter the hash.
_UNIQUE_PREFIX = "unique."


def _escaped(key: str) -> bool:
    return key.startswith(_UNIQUE_PREFIX) or f".{_UNIQUE_PREFIX}" in key


def compute_node_class(node: Node) -> str:
    """Deterministic hash over the scheduling-relevant, non-unique fields."""
    h = hashlib.blake2b(digest_size=8)

    def put(*parts: object) -> None:
        for p in parts:
            h.update(str(p).encode())
            h.update(b"\x00")

    put("dc", node.datacenter)
    put("class", node.node_class)
    r = node.resources
    put("res", r.cpu, r.memory_mb, r.disk_mb)
    for net in sorted(r.networks, key=lambda n: n.device):
        put("net", net.device, net.mbits)
    for dev in sorted(r.devices, key=lambda d: d.id_string()):
        put("dev", dev.id_string(), len(dev.instances))
        for k in sorted(dev.attributes):
            put("devattr", k, dev.attributes[k])
    rv = node.reserved
    put("reserved", rv.cpu, rv.memory_mb, rv.disk_mb)
    for name in sorted(node.host_volumes):
        hv = node.host_volumes[name]
        put("hostvol", name, hv.read_only)
    for pid in sorted(node.csi_plugins):
        info = node.csi_plugins[pid]
        # health/capability must be part of the class: feasibility is
        # memoized per computed_class, and CSIVolumeChecker reads these
        put(
            "csiplugin", pid,
            bool(info.get("healthy")),
            bool(info.get("controller")),
            bool(info.get("node", True)),
        )
    for k in sorted(node.attributes):
        if not _escaped(k):
            put("attr", k, node.attributes[k])
    for k in sorted(node.meta):
        if not _escaped(k):
            put("meta", k, node.meta[k])
    for name in sorted(node.drivers):
        d = node.drivers[name]
        put("driver", name, d.detected, d.healthy)
    return "v1:" + h.hexdigest()


def escaped_constraint_target(target: str) -> bool:
    """Does a constraint target reference node-unique state? Such constraints
    escape class-level memoization (reference: EscapedConstraints)."""
    return _escaped(target)
