"""Computed node classes — feasibility memoization key.

Reference: nomad/structs/node_class.go ComputeClass :31. Nodes with identical
non-unique attributes/resources hash to the same class; the scheduler then
checks feasibility once per class instead of once per node. The TPU solver
uses the same classes to deduplicate rows of the feasibility-mask tensor.

The blake2b re-hash of every node's full attribute set per pass measured
7-13% of c2m wall (round-12 profiler), so the hash is memoized on the
CONTENT key — the exact tuple of scheduling-relevant parts the digest
covers. A fleet has a handful of distinct classes, so steady state is
one tuple build + dict hit per call, no digest. Content keying makes
invalidation automatic and exact: a node upsert (or any in-place
mutation before upsert — the client fingerprint path) changes the key
and recomputes; keying on (id, modify_index) instead would serve stale
classes to pre-upsert mutations, which both the bench builder and the
client fingerprinters perform.
"""

from __future__ import annotations

import hashlib

from .structs import Node

# Attribute/meta keys that are unique per node and must not enter the hash.
_UNIQUE_PREFIX = "unique."

# digest-stream -> class string; bounded (a class universe anywhere
# near the cap means the memo is not earning its memory — start over).
_MEMO: dict[str, str] = {}
_MEMO_CAP = 65536


def _escaped(key: str) -> bool:
    return key.startswith(_UNIQUE_PREFIX) or f".{_UNIQUE_PREFIX}" in key


def _class_parts(node: Node) -> list:
    """The scheduling-relevant, non-unique parts, in digest order."""
    parts: list = ["dc", node.datacenter, "class", node.node_class]
    ap = parts.append
    r = node.resources
    parts += ("res", r.cpu, r.memory_mb, r.disk_mb)
    for net in sorted(r.networks, key=lambda n: n.device):
        parts += ("net", net.device, net.mbits)
    for dev in sorted(r.devices, key=lambda d: d.id_string()):
        parts += ("dev", dev.id_string(), len(dev.instances))
        for k in sorted(dev.attributes):
            parts += ("devattr", k, dev.attributes[k])
    rv = node.reserved
    parts += ("reserved", rv.cpu, rv.memory_mb, rv.disk_mb)
    for name in sorted(node.host_volumes):
        hv = node.host_volumes[name]
        parts += ("hostvol", name, hv.read_only)
    for pid in sorted(node.csi_plugins):
        info = node.csi_plugins[pid]
        # health/capability must be part of the class: feasibility is
        # memoized per computed_class, and CSIVolumeChecker reads these
        parts += (
            "csiplugin", pid,
            bool(info.get("healthy")),
            bool(info.get("controller")),
            bool(info.get("node", True)),
        )
    for k in sorted(node.attributes):
        if not _escaped(k):
            parts += ("attr", k, node.attributes[k])
    for k in sorted(node.meta):
        if not _escaped(k):
            parts += ("meta", k, node.meta[k])
    for name in sorted(node.drivers):
        d = node.drivers[name]
        parts += ("driver", name, d.detected, d.healthy)
    return parts


def compute_node_class(node: Node) -> str:
    """Deterministic hash over the scheduling-relevant, non-unique fields.

    Digest-compatible with the original per-part put() loop: the byte
    stream is str(part) + NUL per part, so existing stored
    computed_class values stay valid across this memoization.
    """
    parts = _class_parts(node)
    # the memo key IS the digest input stream: a tuple of raw parts
    # would conflate values that compare equal but stringify differently
    # (True == 1, 1 == 1.0) and serve a class the digest would not have
    # produced — keying on the stream makes cache hits exact by
    # construction. The blake2b work (init + ~100 update calls) is what
    # the memo elides; the str/join pass is the irreducible key cost.
    key = "\x00".join(str(p) for p in parts) + "\x00"
    cls = _MEMO.get(key)
    if cls is None:
        h = hashlib.blake2b(key.encode(), digest_size=8)
        cls = "v1:" + h.hexdigest()
        if len(_MEMO) >= _MEMO_CAP:
            _MEMO.clear()
        _MEMO[key] = cls
    return cls


def escaped_constraint_target(target: str) -> bool:
    """Does a constraint target reference node-unique state? Such constraints
    escape class-level memoization (reference: EscapedConstraints)."""
    return _escaped(target)
