"""Core vocabulary shared by every layer of the framework.

This is the TPU-native re-design of the reference's shared struct vocabulary
(reference: nomad/structs/structs.go — Job :3958, TaskGroup :5923, Task :6652,
Node :1812, Allocation :9110, Evaluation :10211, Plan :10505, Resources :2191).

Design departures from the reference (deliberate, TPU-first):
  * Resources are a flat numeric vector (cpu MHz, memory MB, disk MB,
    network mbits) so that lowering node/alloc state into dense
    ``(alloc x node x resource)`` tensors for the JAX placement solver is a
    simple gather, not a tree walk.
  * All structs are plain dataclasses with explicit ``copy()`` — the state
    store relies on copy-on-write discipline exactly like the reference's
    immutable-radix MemDB store.
"""

from __future__ import annotations

import dataclasses
import threading
import time
import os
from dataclasses import dataclass, field
from typing import Any, Optional

# ---------------------------------------------------------------------------
# Constants (reference: nomad/structs/structs.go:1659,3916,9096,10140)
# ---------------------------------------------------------------------------

JOB_TYPE_CORE = "_core"
JOB_TYPE_SERVICE = "service"
JOB_TYPE_BATCH = "batch"
JOB_TYPE_SYSTEM = "system"
JOB_TYPE_SYSBATCH = "sysbatch"

JOB_STATUS_PENDING = "pending"
JOB_STATUS_RUNNING = "running"
JOB_STATUS_DEAD = "dead"

JOB_MIN_PRIORITY = 1
JOB_DEFAULT_PRIORITY = 50
JOB_MAX_PRIORITY = 100

CORE_JOB_PRIORITY = JOB_MAX_PRIORITY * 2

NODE_STATUS_INIT = "initializing"
NODE_STATUS_READY = "ready"
NODE_STATUS_DOWN = "down"

NODE_SCHEDULING_ELIGIBLE = "eligible"
NODE_SCHEDULING_INELIGIBLE = "ineligible"

ALLOC_DESIRED_STATUS_RUN = "run"
ALLOC_DESIRED_STATUS_STOP = "stop"
ALLOC_DESIRED_STATUS_EVICT = "evict"

ALLOC_CLIENT_STATUS_PENDING = "pending"
ALLOC_CLIENT_STATUS_RUNNING = "running"
ALLOC_CLIENT_STATUS_COMPLETE = "complete"
ALLOC_CLIENT_STATUS_FAILED = "failed"
ALLOC_CLIENT_STATUS_LOST = "lost"

EVAL_STATUS_BLOCKED = "blocked"
EVAL_STATUS_PENDING = "pending"
EVAL_STATUS_COMPLETE = "complete"
EVAL_STATUS_FAILED = "failed"
EVAL_STATUS_CANCELLED = "canceled"

EVAL_TRIGGER_JOB_REGISTER = "job-register"
EVAL_TRIGGER_JOB_DEREGISTER = "job-deregister"
EVAL_TRIGGER_PERIODIC_JOB = "periodic-job"
EVAL_TRIGGER_NODE_DRAIN = "node-drain"
EVAL_TRIGGER_NODE_UPDATE = "node-update"
EVAL_TRIGGER_ALLOC_STOP = "alloc-stop"
EVAL_TRIGGER_SCHEDULED = "scheduled"
EVAL_TRIGGER_ROLLING_UPDATE = "rolling-update"
EVAL_TRIGGER_DEPLOYMENT_WATCHER = "deployment-watcher"
EVAL_TRIGGER_FAILED_FOLLOWUP = "failed-follow-up"
EVAL_TRIGGER_MAX_PLANS = "max-plan-attempts"
EVAL_TRIGGER_RETRY_FAILED_ALLOC = "alloc-failure"
EVAL_TRIGGER_QUEUED_ALLOCS = "queued-allocs"
EVAL_TRIGGER_PREEMPTION = "preemption"
EVAL_TRIGGER_SCALING = "job-scaling"
EVAL_TRIGGER_FORCE_EVAL = "job-eval"

# Constraint operands (reference: nomad/structs/structs.go:8248-8258)
CONSTRAINT_DISTINCT_PROPERTY = "distinct_property"
CONSTRAINT_DISTINCT_HOSTS = "distinct_hosts"
CONSTRAINT_REGEX = "regexp"
CONSTRAINT_VERSION = "version"
CONSTRAINT_SEMVER = "semver"
CONSTRAINT_SET_CONTAINS = "set_contains"
CONSTRAINT_SET_CONTAINS_ALL = "set_contains_all"
CONSTRAINT_SET_CONTAINS_ANY = "set_contains_any"
CONSTRAINT_IS_SET = "is_set"
CONSTRAINT_IS_NOT_SET = "is_not_set"

COMPARISON_OPERANDS = ("=", "==", "is", "!=", "not", "<", "<=", ">", ">=")

DEPLOYMENT_STATUS_RUNNING = "running"
DEPLOYMENT_STATUS_PAUSED = "paused"
DEPLOYMENT_STATUS_FAILED = "failed"
DEPLOYMENT_STATUS_SUCCESSFUL = "successful"
DEPLOYMENT_STATUS_CANCELLED = "cancelled"

DEPLOYMENT_STATUSES_TERMINAL = (
    DEPLOYMENT_STATUS_FAILED,
    DEPLOYMENT_STATUS_SUCCESSFUL,
    DEPLOYMENT_STATUS_CANCELLED,
)

ALLOC_HEALTH_DESC_NO_TASKS = "Task not running by deadline"

# Reschedule/restart
RESTART_POLICY_MODE_DELAY = "delay"
RESTART_POLICY_MODE_FAIL = "fail"

DEFAULT_NAMESPACE = "default"


# Per-thread id pool behind generate_uuid: the urandom syscall AND the
# per-id hex/dash formatting are the cost (the round-12 profiler put
# generate_uuid + generate_uuids together at ~20% of c2m wall). The pool
# now holds PRE-FORMATTED ids minted in bulk — one urandom syscall and
# one formatting pass (native fastpack.uuid_hex when present) serve 256
# ids — so every per-id call site is bulk minting under the hood.
# Thread-local so no lock rides the hot path. NOT fork-safe by design:
# this codebase spawns subprocesses (fresh interpreter), never forks a
# live server.
_UUID_POOL_IDS = 256


class _UuidPool(threading.local):
    def __init__(self) -> None:
        self.ids: list[str] = []
        self.off = 0
        # raw entropy pool for bulk minting: one 64KiB urandom read
        # serves ~16 c2m-sized generate_uuids calls (the per-call
        # syscall was ~0.2s of a c2m pass)
        self.raw = b""
        self.raw_off = 0


_uuid_pool = _UuidPool()

_RAW_POOL_BYTES = 1 << 16


def _pool_entropy(n: int) -> bytes:
    pool = _uuid_pool
    off = pool.raw_off
    if off + n > len(pool.raw):
        pool.raw = os.urandom(max(_RAW_POOL_BYTES, n))
        off = 0
    pool.raw_off = off + n
    return pool.raw[off : off + n]


def generate_uuid() -> str:
    # uuid4-shaped from the bulk-minted pool: same entropy per id as
    # uuid.uuid4(), one syscall + one format pass per _UUID_POOL_IDS ids
    pool = _uuid_pool
    off = pool.off
    if off >= len(pool.ids):
        pool.ids = generate_uuids(_UUID_POOL_IDS)
        off = 0
    pool.off = off + 1
    return pool.ids[off]


def _uuid_hex_py(raw: bytes) -> list[str]:
    h = raw.hex()
    return [
        f"{b[:8]}-{b[8:12]}-{b[12:16]}-{b[16:20]}-{b[20:]}"
        for b in (h[i : i + 32] for i in range(0, len(h), 32))
    ]


def generate_uuids(k: int) -> list[str]:
    """Bulk uuid4-shaped ids: one urandom syscall + one formatting pass
    for the whole batch (the batched solver mints 100k+ allocation ids
    per solve). Formatting runs in the fastpack extension when it is
    already resolved (codec.warm_native — this function must never
    trigger the C build itself), with the pure-Python hex pass as the
    behavior-identical fallback."""
    raw = _pool_entropy(16 * k)
    from .. import codec

    fp = codec.native_module()
    if fp is not None:
        try:
            return fp.uuid_hex(raw)
        except Exception:
            pass
    return _uuid_hex_py(raw)


def now_ns() -> int:
    return time.time_ns()


# ---------------------------------------------------------------------------
# Resources
# ---------------------------------------------------------------------------

# Fixed resource vector layout used by the TPU solver lowering
# (nomad_tpu/scheduler/tpu/lower.py): indices into the dense resource axis.
RES_CPU = 0
RES_MEM = 1
RES_DISK = 2
NUM_CORE_RESOURCES = 3


@dataclass(slots=True)
class Port:
    label: str = ""
    value: int = 0
    to: int = 0
    host_network: str = "default"


@dataclass(slots=True)
class NetworkResource:
    """A network ask/offer (reference: structs.go NetworkResource :2441)."""

    mode: str = "host"
    device: str = ""
    cidr: str = ""
    ip: str = ""
    mbits: int = 0
    reserved_ports: list[Port] = field(default_factory=list)
    dynamic_ports: list[Port] = field(default_factory=list)

    def copy(self) -> "NetworkResource":
        return NetworkResource(
            mode=self.mode,
            device=self.device,
            cidr=self.cidr,
            ip=self.ip,
            mbits=self.mbits,
            reserved_ports=[dataclasses.replace(p) for p in self.reserved_ports],
            dynamic_ports=[dataclasses.replace(p) for p in self.dynamic_ports],
        )

    def port_labels(self) -> dict[str, int]:
        out: dict[str, int] = {}
        for p in self.reserved_ports:
            out[p.label] = p.value
        for p in self.dynamic_ports:
            out[p.label] = p.value
        return out


@dataclass(slots=True)
class RequestedDevice:
    """A device ask (reference: structs.go RequestedDevice :3035)."""

    name: str = ""  # e.g. "gpu", "nvidia/gpu", "nvidia/gpu/1080ti"
    count: int = 1
    constraints: list["Constraint"] = field(default_factory=list)
    affinities: list["Affinity"] = field(default_factory=list)

    def copy(self) -> "RequestedDevice":
        return RequestedDevice(
            name=self.name,
            count=self.count,
            constraints=[c.copy() for c in self.constraints],
            affinities=[a.copy() for a in self.affinities],
        )

    def id_tuple(self) -> tuple[str, ...]:
        """vendor/type/name triple, any suffix may be absent."""
        return tuple(self.name.split("/"))


@dataclass(slots=True)
class Resources:
    """A task's resource ask, flattened to the solver's core vector.

    Reference: structs.go Resources :2191. cpu is MHz shares, memory/disk MB.
    """

    cpu: int = 100
    memory_mb: int = 300
    # memory oversubscription (reference MemoryMaxMB, 1.1+): the cgroup
    # hard cap when the operator enables oversubscription; scheduling
    # still packs on memory_mb (the reserve). 0 = no excess.
    memory_max_mb: int = 0
    disk_mb: int = 0
    networks: list[NetworkResource] = field(default_factory=list)
    devices: list[RequestedDevice] = field(default_factory=list)
    cores: int = 0  # reserved whole cores (0 = share)

    def copy(self) -> "Resources":
        return Resources(
            cpu=self.cpu,
            memory_mb=self.memory_mb,
            memory_max_mb=self.memory_max_mb,
            disk_mb=self.disk_mb,
            networks=[n.copy() for n in self.networks],
            devices=[d.copy() for d in self.devices],
            cores=self.cores,
        )

    def vector(self) -> list[float]:
        return [float(self.cpu), float(self.memory_mb), float(self.disk_mb)]

    def add(self, other: "Resources") -> None:
        self.cpu += other.cpu
        self.memory_mb += other.memory_mb
        self.disk_mb += other.disk_mb
        self.networks.extend(n.copy() for n in other.networks)

    def superset(self, other: "Resources") -> tuple[bool, str]:
        if self.cpu < other.cpu:
            return False, "cpu"
        if self.memory_mb < other.memory_mb:
            return False, "memory"
        if self.disk_mb < other.disk_mb:
            return False, "disk"
        return True, ""

    def validate(self) -> None:
        if self.cpu < 0:
            raise ValueError("resources: cpu must be >= 0")
        if self.memory_mb < 0:
            raise ValueError("resources: memory must be >= 0")
        if self.memory_max_mb and self.memory_max_mb < self.memory_mb:
            raise ValueError(
                "resources: memory_max must be >= memory (the reserve)"
            )


@dataclass(slots=True)
class NodeDeviceInstance:
    id: str = ""
    healthy: bool = True
    locality: str = ""


@dataclass(slots=True)
class NodeDeviceResource:
    """A device group present on a node (reference: structs.go NodeDeviceResource :3230)."""

    vendor: str = ""
    type: str = ""
    name: str = ""
    instances: list[NodeDeviceInstance] = field(default_factory=list)
    attributes: dict[str, Any] = field(default_factory=dict)

    def copy(self) -> "NodeDeviceResource":
        return NodeDeviceResource(
            vendor=self.vendor,
            type=self.type,
            name=self.name,
            instances=[dataclasses.replace(i) for i in self.instances],
            attributes=dict(self.attributes),
        )

    def id_string(self) -> str:
        return f"{self.vendor}/{self.type}/{self.name}"

    def matches(self, ask: RequestedDevice) -> bool:
        parts = ask.id_tuple()
        if len(parts) == 1:
            return parts[0] == self.type
        if len(parts) == 2:
            return parts == (self.vendor, self.type)
        if len(parts) == 3:
            return parts == (self.vendor, self.type, self.name)
        return False


@dataclass(slots=True)
class NodeResources:
    """What a node offers (reference: structs.go NodeResources :2797)."""

    cpu: int = 4000
    memory_mb: int = 8192
    disk_mb: int = 100 * 1024
    networks: list[NetworkResource] = field(default_factory=list)
    devices: list[NodeDeviceResource] = field(default_factory=list)
    total_cores: int = 0

    def copy(self) -> "NodeResources":
        return NodeResources(
            cpu=self.cpu,
            memory_mb=self.memory_mb,
            disk_mb=self.disk_mb,
            networks=[n.copy() for n in self.networks],
            devices=[d.copy() for d in self.devices],
            total_cores=self.total_cores,
        )

    def vector(self) -> list[float]:
        return [float(self.cpu), float(self.memory_mb), float(self.disk_mb)]


@dataclass(slots=True)
class NodeReservedResources:
    """Resources the node holds back from scheduling (reference :2977)."""

    cpu: int = 0
    memory_mb: int = 0
    disk_mb: int = 0
    reserved_ports: list[int] = field(default_factory=list)

    def copy(self) -> "NodeReservedResources":
        return NodeReservedResources(
            cpu=self.cpu,
            memory_mb=self.memory_mb,
            disk_mb=self.disk_mb,
            reserved_ports=list(self.reserved_ports),
        )

    def vector(self) -> list[float]:
        return [float(self.cpu), float(self.memory_mb), float(self.disk_mb)]


# ---------------------------------------------------------------------------
# Constraints / affinities / spread
# ---------------------------------------------------------------------------


@dataclass(slots=True)
class Constraint:
    """Hard placement restriction (reference: structs.go Constraint :8262)."""

    ltarget: str = ""
    rtarget: str = ""
    operand: str = "="

    def copy(self) -> "Constraint":
        return Constraint(self.ltarget, self.rtarget, self.operand)

    def __str__(self) -> str:
        return f"{self.ltarget} {self.operand} {self.rtarget}"

    def key(self) -> tuple[str, str, str]:
        return (self.ltarget, self.operand, self.rtarget)

    def validate(self) -> None:
        if not self.operand:
            raise ValueError("constraint: missing operand")
        if self.operand in (CONSTRAINT_REGEX, CONSTRAINT_VERSION, CONSTRAINT_SEMVER):
            if not self.ltarget:
                raise ValueError(f"constraint: {self.operand} requires ltarget")
            if not self.rtarget:
                raise ValueError(f"constraint: {self.operand} requires rtarget")


@dataclass(slots=True)
class Affinity:
    """Soft placement preference with weight in [-100, 100] (reference :8382)."""

    ltarget: str = ""
    rtarget: str = ""
    operand: str = "="
    weight: int = 50

    def copy(self) -> "Affinity":
        return Affinity(self.ltarget, self.rtarget, self.operand, self.weight)

    def validate(self) -> None:
        if self.weight == 0:
            raise ValueError("affinity: weight cannot be zero")
        if not -100 <= self.weight <= 100:
            raise ValueError("affinity: weight must be within [-100, 100]")


@dataclass(slots=True)
class SpreadTarget:
    value: str = ""
    percent: int = 0


@dataclass(slots=True)
class Spread:
    """Spread allocs across attribute values (reference: structs.go Spread :8468)."""

    attribute: str = ""
    weight: int = 50
    targets: list[SpreadTarget] = field(default_factory=list)

    def copy(self) -> "Spread":
        return Spread(
            attribute=self.attribute,
            weight=self.weight,
            targets=[dataclasses.replace(t) for t in self.targets],
        )

    def validate(self) -> None:
        if not self.attribute:
            raise ValueError("spread: missing attribute")
        if not 0 < self.weight <= 100:
            raise ValueError("spread: weight must be within (0, 100]")
        total = sum(t.percent for t in self.targets)
        if total > 100:
            raise ValueError("spread: target percentages sum over 100")


# ---------------------------------------------------------------------------
# Policies (restart / reschedule / update / migrate / ephemeral disk)
# ---------------------------------------------------------------------------


@dataclass(slots=True)
class RestartPolicy:
    """Client-side restart policy (reference: structs.go RestartPolicy :4602)."""

    attempts: int = 2
    interval_s: float = 1800.0
    delay_s: float = 15.0
    mode: str = RESTART_POLICY_MODE_FAIL

    def copy(self) -> "RestartPolicy":
        return dataclasses.replace(self)


@dataclass(slots=True)
class ReschedulePolicy:
    """Server-side reschedule policy (reference: structs.go ReschedulePolicy :4672)."""

    attempts: int = 0
    interval_s: float = 0.0
    delay_s: float = 30.0
    delay_function: str = "exponential"  # constant | exponential | fibonacci
    max_delay_s: float = 3600.0
    unlimited: bool = True

    def copy(self) -> "ReschedulePolicy":
        return dataclasses.replace(self)

    def enabled(self) -> bool:
        return self.unlimited or (self.attempts > 0 and self.interval_s > 0)


@dataclass(slots=True)
class UpdateStrategy:
    """Rolling-update / deployment strategy (reference: structs.go :4369)."""

    stagger_s: float = 30.0
    max_parallel: int = 1
    health_check: str = "checks"  # checks | task_states | manual
    min_healthy_time_s: float = 10.0
    healthy_deadline_s: float = 300.0
    progress_deadline_s: float = 600.0
    auto_revert: bool = False
    auto_promote: bool = False
    canary: int = 0

    def copy(self) -> "UpdateStrategy":
        return dataclasses.replace(self)

    def rolling(self) -> bool:
        return self.stagger_s > 0 and self.max_parallel > 0

    def requires_promotion(self) -> bool:
        return self.canary > 0 and not self.auto_promote


@dataclass(slots=True)
class MigrateStrategy:
    """Drain migration rate limits (reference: structs.go MigrateStrategy :4527)."""

    max_parallel: int = 1
    health_check: str = "checks"
    min_healthy_time_s: float = 10.0
    healthy_deadline_s: float = 300.0

    def copy(self) -> "MigrateStrategy":
        return dataclasses.replace(self)


@dataclass(slots=True)
class EphemeralDisk:
    sticky: bool = False
    size_mb: int = 300
    migrate: bool = False

    def copy(self) -> "EphemeralDisk":
        return dataclasses.replace(self)


@dataclass(slots=True)
class PeriodicConfig:
    """Cron-style launch config (reference: structs.go PeriodicConfig :4862)."""

    enabled: bool = False
    spec: str = ""
    spec_type: str = "cron"
    prohibit_overlap: bool = False
    timezone: str = "UTC"

    def copy(self) -> "PeriodicConfig":
        return dataclasses.replace(self)


@dataclass(slots=True)
class ParameterizedJobConfig:
    """Dispatch-job config (reference: structs.go ParameterizedJobConfig :5095)."""

    payload: str = "optional"  # optional | required | forbidden
    meta_required: list[str] = field(default_factory=list)
    meta_optional: list[str] = field(default_factory=list)

    def copy(self) -> "ParameterizedJobConfig":
        return ParameterizedJobConfig(
            payload=self.payload,
            meta_required=list(self.meta_required),
            meta_optional=list(self.meta_optional),
        )


@dataclass(slots=True)
class VolumeRequest:
    """Group-level volume ask (reference: structs.go VolumeRequest :7162)."""

    name: str = ""
    type: str = "host"  # host | csi
    source: str = ""
    read_only: bool = False
    access_mode: str = ""
    attachment_mode: str = ""
    per_alloc: bool = False

    def copy(self) -> "VolumeRequest":
        return dataclasses.replace(self)


@dataclass(slots=True)
class VolumeMount:
    """Task-level mount of a group volume into the task filesystem
    (reference: structs.go VolumeMount :7263)."""

    volume: str = ""
    destination: str = ""
    read_only: bool = False
    propagation_mode: str = "private"

    def copy(self) -> "VolumeMount":
        return dataclasses.replace(self)


@dataclass(slots=True)
class ConnectUpstream:
    """One mesh upstream a sidecar exposes locally (reference:
    structs.go ConsulUpstream :8210)."""

    destination_name: str = ""
    local_bind_port: int = 0

    def copy(self) -> "ConnectUpstream":
        return dataclasses.replace(self)


@dataclass(slots=True)
class SidecarService:
    """connect { sidecar_service { ... } } (reference: structs.go
    ConsulSidecarService :8080)."""

    port: str = ""  # explicit sidecar port label; default injected
    upstreams: list[ConnectUpstream] = field(default_factory=list)

    def copy(self) -> "SidecarService":
        return SidecarService(
            port=self.port,
            upstreams=[u.copy() for u in self.upstreams],
        )


@dataclass(slots=True)
class Connect:
    """The service-mesh stanza (reference: structs.go ConsulConnect
    :8016). `native=True` means the workload speaks mesh natively and
    only wants the catalog registration, no sidecar."""

    sidecar_service: Optional[SidecarService] = None
    native: bool = False

    def copy(self) -> "Connect":
        return Connect(
            sidecar_service=(
                self.sidecar_service.copy()
                if self.sidecar_service is not None
                else None
            ),
            native=self.native,
        )


@dataclass(slots=True)
class Service:
    """Service registration (reference: structs.go Service :7582)."""

    name: str = ""
    port_label: str = ""
    address_mode: str = "auto"
    tags: list[str] = field(default_factory=list)
    checks: list[dict[str, Any]] = field(default_factory=list)
    provider: str = "builtin"
    connect: Optional[Connect] = None

    def copy(self) -> "Service":
        return Service(
            name=self.name,
            port_label=self.port_label,
            address_mode=self.address_mode,
            tags=list(self.tags),
            checks=[dict(c) for c in self.checks],
            provider=self.provider,
            connect=self.connect.copy() if self.connect is not None else None,
        )


@dataclass(slots=True)
class ScalingPolicy:
    """A group's scaling bounds + opaque autoscaler policy (reference:
    structs.go ScalingPolicy :5397 — stored and served by the cluster;
    the autoscaler itself is an external consumer)."""

    id: str = ""
    type: str = "horizontal"
    namespace: str = DEFAULT_NAMESPACE
    job_id: str = ""
    group: str = ""
    min: int = 0
    max: int = 0
    enabled: bool = True
    policy: dict[str, Any] = field(default_factory=dict)
    create_index: int = 0
    modify_index: int = 0

    def copy(self) -> "ScalingPolicy":
        c = dataclasses.replace(self)
        c.policy = dict(self.policy)
        return c


@dataclass(slots=True)
class SecretEntry:
    """A namespaced secret document in the cluster's embedded secrets
    store (the tpu-native stand-in for the reference's external Vault:
    nomad/vault.go talks to a Vault server; here the KV rides raft and
    task tokens are scoped ACL tokens — same derive/renew/revoke
    lifecycle, no external daemon)."""

    path: str = ""
    namespace: str = DEFAULT_NAMESPACE
    items: dict[str, str] = field(default_factory=dict)
    create_index: int = 0
    modify_index: int = 0

    def copy(self) -> "SecretEntry":
        c = dataclasses.replace(self)
        c.items = dict(self.items)
        return c


@dataclass(slots=True)
class ServiceRegistration:
    """One task/group service instance registered in the cluster catalog
    (reference: structs/service_registration.go — the native
    service-discovery provider; the tree's consul sync is the external
    analog, command/agent/consul/service_client.go)."""

    id: str = ""
    service_name: str = ""
    namespace: str = DEFAULT_NAMESPACE
    node_id: str = ""
    datacenter: str = ""
    job_id: str = ""
    alloc_id: str = ""
    task_name: str = ""
    tags: list[str] = field(default_factory=list)
    address: str = ""
    port: int = 0
    # aggregate check verdict pushed by the owning client's check watcher
    # ("passing" | "critical" | "" when the service has no checks)
    status: str = ""
    create_index: int = 0
    modify_index: int = 0

    def copy(self) -> "ServiceRegistration":
        c = dataclasses.replace(self)
        c.tags = list(self.tags)
        return c


@dataclass(slots=True)
class LogConfig:
    max_files: int = 10
    max_file_size_mb: int = 10

    def copy(self) -> "LogConfig":
        return dataclasses.replace(self)


@dataclass(slots=True)
class TaskArtifact:
    getter_source: str = ""
    getter_options: dict[str, str] = field(default_factory=dict)
    getter_mode: str = "any"
    relative_dest: str = "local/"

    def copy(self) -> "TaskArtifact":
        return TaskArtifact(
            getter_source=self.getter_source,
            getter_options=dict(self.getter_options),
            getter_mode=self.getter_mode,
            relative_dest=self.relative_dest,
        )


@dataclass(slots=True)
class Template:
    source_path: str = ""
    dest_path: str = ""
    embedded_tmpl: str = ""
    change_mode: str = "restart"
    change_signal: str = ""
    splay_s: float = 5.0
    perms: str = "0644"

    def copy(self) -> "Template":
        return dataclasses.replace(self)


@dataclass(slots=True)
class TaskLifecycleConfig:
    hook: str = ""  # prestart | poststart | poststop
    sidecar: bool = False

    def copy(self) -> "TaskLifecycleConfig":
        return dataclasses.replace(self)


# ---------------------------------------------------------------------------
# Task / TaskGroup / Job
# ---------------------------------------------------------------------------


@dataclass(slots=True)
class Task:
    """A unit of work executed by a driver (reference: structs.go Task :6652)."""

    name: str = ""
    driver: str = "mock"
    user: str = ""
    config: dict[str, Any] = field(default_factory=dict)
    env: dict[str, str] = field(default_factory=dict)
    services: list[Service] = field(default_factory=list)
    resources: Resources = field(default_factory=Resources)
    meta: dict[str, str] = field(default_factory=dict)
    constraints: list[Constraint] = field(default_factory=list)
    affinities: list[Affinity] = field(default_factory=list)
    artifacts: list[TaskArtifact] = field(default_factory=list)
    templates: list[Template] = field(default_factory=list)
    log_config: LogConfig = field(default_factory=LogConfig)
    volume_mounts: list[VolumeMount] = field(default_factory=list)
    # vault stanza analog (reference structs.go Vault :7800): policies
    # scope the task's derived secrets token; env controls VAULT_TOKEN
    vault: Optional[dict] = None
    kill_timeout_s: float = 5.0
    kill_signal: str = ""
    leader: bool = False
    lifecycle: Optional[TaskLifecycleConfig] = None
    shutdown_delay_s: float = 0.0

    def copy(self) -> "Task":
        return Task(
            name=self.name,
            driver=self.driver,
            user=self.user,
            config=dict(self.config),
            env=dict(self.env),
            services=[s.copy() for s in self.services],
            resources=self.resources.copy(),
            meta=dict(self.meta),
            constraints=[c.copy() for c in self.constraints],
            affinities=[a.copy() for a in self.affinities],
            artifacts=[a.copy() for a in self.artifacts],
            templates=[t.copy() for t in self.templates],
            log_config=self.log_config.copy(),
            volume_mounts=[m.copy() for m in self.volume_mounts],
            vault=dict(self.vault) if self.vault else None,
            kill_timeout_s=self.kill_timeout_s,
            kill_signal=self.kill_signal,
            leader=self.leader,
            lifecycle=self.lifecycle.copy() if self.lifecycle else None,
            shutdown_delay_s=self.shutdown_delay_s,
        )

    def validate(self, job_type: str = JOB_TYPE_SERVICE) -> None:
        if not self.name:
            raise ValueError("task: missing name")
        if "/" in self.name or "\\" in self.name:
            raise ValueError("task: name cannot contain slashes")
        if not self.driver:
            raise ValueError(f"task {self.name}: missing driver")
        self.resources.validate()
        for c in self.constraints:
            c.validate()
        for a in self.affinities:
            a.validate()
        for svc in self.services:
            for check in svc.checks:
                if check.get("type") == "script" and not check.get(
                    "command"
                ):
                    raise ValueError(
                        f"task {self.name}: script check on service "
                        f"{svc.name!r} requires a command"
                    )

    def is_prestart(self) -> bool:
        return self.lifecycle is not None and self.lifecycle.hook == "prestart"

    def is_poststart(self) -> bool:
        return self.lifecycle is not None and self.lifecycle.hook == "poststart"

    def is_poststop(self) -> bool:
        return self.lifecycle is not None and self.lifecycle.hook == "poststop"

    def is_main(self) -> bool:
        return self.lifecycle is None


@dataclass(slots=True)
class TaskGroup:
    """A co-scheduled set of tasks (reference: structs.go TaskGroup :5923)."""

    name: str = ""
    count: int = 1
    tasks: list[Task] = field(default_factory=list)
    constraints: list[Constraint] = field(default_factory=list)
    affinities: list[Affinity] = field(default_factory=list)
    spreads: list[Spread] = field(default_factory=list)
    restart_policy: RestartPolicy = field(default_factory=RestartPolicy)
    reschedule_policy: Optional[ReschedulePolicy] = None
    update: Optional[UpdateStrategy] = None
    migrate: Optional[MigrateStrategy] = None
    networks: list[NetworkResource] = field(default_factory=list)
    services: list[Service] = field(default_factory=list)
    volumes: dict[str, VolumeRequest] = field(default_factory=dict)
    ephemeral_disk: EphemeralDisk = field(default_factory=EphemeralDisk)
    meta: dict[str, str] = field(default_factory=dict)
    # scaling stanza (reference TaskGroup.Scaling): bounds + opaque
    # autoscaler policy; None = group not scalable
    scaling: Optional[ScalingPolicy] = None
    stop_after_client_disconnect_s: float = 0.0
    shutdown_delay_s: float = 0.0

    def copy(self) -> "TaskGroup":
        return TaskGroup(
            name=self.name,
            count=self.count,
            tasks=[t.copy() for t in self.tasks],
            constraints=[c.copy() for c in self.constraints],
            affinities=[a.copy() for a in self.affinities],
            spreads=[s.copy() for s in self.spreads],
            restart_policy=self.restart_policy.copy(),
            reschedule_policy=(
                self.reschedule_policy.copy() if self.reschedule_policy else None
            ),
            update=self.update.copy() if self.update else None,
            migrate=self.migrate.copy() if self.migrate else None,
            networks=[n.copy() for n in self.networks],
            services=[s.copy() for s in self.services],
            volumes={k: v.copy() for k, v in self.volumes.items()},
            ephemeral_disk=self.ephemeral_disk.copy(),
            meta=dict(self.meta),
            scaling=self.scaling.copy() if self.scaling else None,
            stop_after_client_disconnect_s=self.stop_after_client_disconnect_s,
            shutdown_delay_s=self.shutdown_delay_s,
        )

    def lookup_task(self, name: str) -> Optional[Task]:
        for t in self.tasks:
            if t.name == name:
                return t
        return None

    def combined_resources(self) -> Resources:
        """Sum of task asks plus ephemeral disk, for solver lowering."""
        total = Resources(cpu=0, memory_mb=0, disk_mb=0)
        for t in self.tasks:
            total.cpu += t.resources.cpu
            total.memory_mb += t.resources.memory_mb
        total.disk_mb = self.ephemeral_disk.size_mb
        return total

    def validate(self, job: "Job") -> None:
        if not self.name:
            raise ValueError("task group: missing name")
        if self.count < 0:
            raise ValueError(f"group {self.name}: count must be >= 0")
        if not self.tasks:
            raise ValueError(f"group {self.name}: missing tasks")
        names = set()
        for t in self.tasks:
            if t.name in names:
                raise ValueError(f"group {self.name}: duplicate task {t.name}")
            names.add(t.name)
            t.validate(job.type)
        for c in self.constraints:
            c.validate()
        for s in self.spreads:
            s.validate()
        leaders = sum(1 for t in self.tasks if t.leader)
        if leaders > 1:
            raise ValueError(f"group {self.name}: only one task may be leader")
        for svc in self.services:
            for check in svc.checks:
                if check.get("type") == "script":
                    if not check.get("command"):
                        raise ValueError(
                            f"group {self.name}: script check on "
                            f"service {svc.name!r} requires a command"
                        )
                    target = check.get("task", "")
                    if not target:
                        raise ValueError(
                            f"group {self.name}: script check on group "
                            f"service {svc.name!r} requires a task field"
                        )
                    if target not in names:
                        raise ValueError(
                            f"group {self.name}: script check on "
                            f"service {svc.name!r} names unknown task "
                            f"{target!r}"
                        )


@dataclass(slots=True)
class Job:
    """The user-submitted unit of intent (reference: structs.go Job :3958)."""

    id: str = ""
    name: str = ""
    namespace: str = DEFAULT_NAMESPACE
    region: str = "global"
    type: str = JOB_TYPE_SERVICE
    priority: int = JOB_DEFAULT_PRIORITY
    all_at_once: bool = False
    datacenters: list[str] = field(default_factory=lambda: ["dc1"])
    constraints: list[Constraint] = field(default_factory=list)
    affinities: list[Affinity] = field(default_factory=list)
    spreads: list[Spread] = field(default_factory=list)
    task_groups: list[TaskGroup] = field(default_factory=list)
    update: Optional[UpdateStrategy] = None
    periodic: Optional[PeriodicConfig] = None
    parameterized: Optional[ParameterizedJobConfig] = None
    dispatched: bool = False
    payload: bytes = b""
    meta: dict[str, str] = field(default_factory=dict)
    vault_token: str = ""
    stop: bool = False
    parent_id: str = ""
    status: str = JOB_STATUS_PENDING
    status_description: str = ""
    stable: bool = False
    version: int = 0
    submit_time: int = 0
    create_index: int = 0
    modify_index: int = 0
    job_modify_index: int = 0

    def copy(self) -> "Job":
        return Job(
            id=self.id,
            name=self.name,
            namespace=self.namespace,
            region=self.region,
            type=self.type,
            priority=self.priority,
            all_at_once=self.all_at_once,
            datacenters=list(self.datacenters),
            constraints=[c.copy() for c in self.constraints],
            affinities=[a.copy() for a in self.affinities],
            spreads=[s.copy() for s in self.spreads],
            task_groups=[tg.copy() for tg in self.task_groups],
            update=self.update.copy() if self.update else None,
            periodic=self.periodic.copy() if self.periodic else None,
            parameterized=self.parameterized.copy() if self.parameterized else None,
            dispatched=self.dispatched,
            payload=self.payload,
            meta=dict(self.meta),
            vault_token=self.vault_token,
            stop=self.stop,
            parent_id=self.parent_id,
            status=self.status,
            status_description=self.status_description,
            stable=self.stable,
            version=self.version,
            submit_time=self.submit_time,
            create_index=self.create_index,
            modify_index=self.modify_index,
            job_modify_index=self.job_modify_index,
        )

    def canonicalize(self) -> None:
        if not self.name:
            self.name = self.id
        if not self.namespace:
            self.namespace = DEFAULT_NAMESPACE
        if not self.submit_time:
            self.submit_time = now_ns()
        for tg in self.task_groups:
            if tg.reschedule_policy is None and self.type in (
                JOB_TYPE_SERVICE,
                JOB_TYPE_BATCH,
            ):
                if self.type == JOB_TYPE_SERVICE:
                    tg.reschedule_policy = ReschedulePolicy(
                        attempts=0,
                        interval_s=0,
                        delay_s=30,
                        delay_function="exponential",
                        max_delay_s=3600,
                        unlimited=True,
                    )
                else:
                    tg.reschedule_policy = ReschedulePolicy(
                        attempts=1,
                        interval_s=24 * 3600,
                        delay_s=5,
                        delay_function="constant",
                        max_delay_s=0,
                        unlimited=False,
                    )
            if tg.update is None and self.update is not None:
                tg.update = self.update.copy()

    def validate(self) -> None:
        if not self.id:
            raise ValueError("job: missing ID")
        if " " in self.id:
            raise ValueError("job: ID contains a space")
        if not self.name:
            raise ValueError("job: missing name")
        if self.type not in (
            JOB_TYPE_CORE,
            JOB_TYPE_SERVICE,
            JOB_TYPE_BATCH,
            JOB_TYPE_SYSTEM,
            JOB_TYPE_SYSBATCH,
        ):
            raise ValueError(f"job: invalid type {self.type!r}")
        max_priority = CORE_JOB_PRIORITY if self.type == JOB_TYPE_CORE else JOB_MAX_PRIORITY
        if not JOB_MIN_PRIORITY <= self.priority <= max_priority:
            raise ValueError(
                f"job: priority must be within [{JOB_MIN_PRIORITY}, {max_priority}]"
            )
        if not self.datacenters:
            raise ValueError("job: missing datacenters")
        if not self.task_groups:
            raise ValueError("job: missing task groups")
        names = set()
        for tg in self.task_groups:
            if tg.name in names:
                raise ValueError(f"job: duplicate task group {tg.name}")
            names.add(tg.name)
            tg.validate(self)
        for c in self.constraints:
            c.validate()
        if self.type == JOB_TYPE_SYSTEM and any(
            tg.reschedule_policy and tg.reschedule_policy.enabled()
            for tg in self.task_groups
        ):
            raise ValueError("job: system jobs cannot have a reschedule policy")

    def lookup_task_group(self, name: str) -> Optional[TaskGroup]:
        for tg in self.task_groups:
            if tg.name == name:
                return tg
        return None

    def stopped(self) -> bool:
        return self.stop

    def is_periodic(self) -> bool:
        return self.periodic is not None and self.periodic.enabled

    def is_parameterized(self) -> bool:
        return self.parameterized is not None and not self.dispatched

    def ns_id(self) -> tuple[str, str]:
        return (self.namespace, self.id)

    def specification_changed(self, other: "Job") -> bool:
        """True when the job definition differs in a scheduling-relevant way.

        Mirrors the reference's Job.SpecChanged (structs.go:4189): compare
        everything except bookkeeping fields.
        """
        a, b = self.copy(), other.copy()
        for j in (a, b):
            j.status = ""
            j.status_description = ""
            j.stable = False
            j.version = 0
            j.submit_time = 0
            j.create_index = 0
            j.modify_index = 0
            j.job_modify_index = 0
            j.vault_token = ""
        return a != b


# ---------------------------------------------------------------------------
# Node
# ---------------------------------------------------------------------------


@dataclass(slots=True)
class DrainStrategy:
    """Node drain spec (reference: structs.go DrainStrategy :1710)."""

    deadline_s: float = 0.0  # <=0: no deadline; -1 means force
    ignore_system_jobs: bool = False
    force_deadline_ns: int = 0

    def copy(self) -> "DrainStrategy":
        return dataclasses.replace(self)

    def deadline_expired(self) -> bool:
        return (
            self.force_deadline_ns > 0 and now_ns() >= self.force_deadline_ns
        ) or self.deadline_s < 0


@dataclass(slots=True)
class NodeEvent:
    message: str = ""
    subsystem: str = "Cluster"
    details: dict[str, str] = field(default_factory=dict)
    timestamp_ns: int = 0


@dataclass(slots=True)
class HostVolumeConfig:
    name: str = ""
    path: str = ""
    read_only: bool = False


@dataclass(slots=True)
class Namespace:
    """A namespace record (reference: structs.go Namespace :5971 — OSS
    since 1.0; jobs/volumes register INTO one and ACL policies scope
    capabilities BY one)."""

    name: str = ""
    description: str = ""
    create_index: int = 0
    modify_index: int = 0

    def copy(self) -> "Namespace":
        return dataclasses.replace(self)

    def validate(self) -> None:
        import re as _re

        if not _re.fullmatch(r"[a-zA-Z0-9-]{1,128}", self.name or ""):
            raise ValueError(
                f"invalid namespace name {self.name!r} "
                "(alphanumeric and dashes, 1-128 chars)"
            )


VOLUME_ACCESS_SINGLE_WRITER = "single-node-writer"
VOLUME_ACCESS_MULTI_WRITER = "multi-node-multi-writer"
VOLUME_ACCESS_READ_ONLY = "multi-node-reader-only"


@dataclass(slots=True)
class VolumeClaim:
    """One alloc's hold on a registered volume."""

    alloc_id: str = ""
    node_id: str = ""
    read_only: bool = False
    create_index: int = 0


@dataclass(slots=True)
class Volume:
    """A cluster-registered volume (reference: the CSIVolume table,
    nomad/structs/csi.go, reshaped for host volumes — the claim/release
    lifecycle is the part that matters for parity; see
    nomad/volumewatcher/volumes_watcher.go)."""

    id: str = ""
    namespace: str = DEFAULT_NAMESPACE
    name: str = ""  # the group volume.source this volume satisfies
    type: str = "host"  # host | csi
    node_id: str = ""  # host volumes live on one node ("" = any)
    path: str = ""
    access_mode: str = VOLUME_ACCESS_MULTI_WRITER
    # CSI-only fields (reference: nomad/structs/csi.go CSIVolume)
    plugin_id: str = ""
    external_id: str = ""
    attachment_mode: str = "file-system"
    context: dict[str, str] = field(default_factory=dict)
    claims: dict[str, VolumeClaim] = field(default_factory=dict)
    create_index: int = 0
    modify_index: int = 0

    def copy(self) -> "Volume":
        c = dataclasses.replace(self)
        c.context = dict(self.context)
        c.claims = {k: dataclasses.replace(v) for k, v in self.claims.items()}
        return c

    def write_claims(self) -> list[VolumeClaim]:
        return [c for c in self.claims.values() if not c.read_only]

    def claimable(self, read_only: bool) -> tuple[bool, str]:
        """May a new claim of the given mode attach?"""
        if self.access_mode == VOLUME_ACCESS_READ_ONLY and not read_only:
            return False, "volume is read-only"
        if (
            self.access_mode == VOLUME_ACCESS_SINGLE_WRITER
            and not read_only
            and self.write_claims()
        ):
            return False, "volume has an active writer"
        return True, ""


@dataclass(slots=True)
class Node:
    """A fingerprinted machine (reference: structs.go Node :1812)."""

    id: str = ""
    name: str = ""
    datacenter: str = "dc1"
    node_class: str = ""
    attributes: dict[str, str] = field(default_factory=dict)
    meta: dict[str, str] = field(default_factory=dict)
    resources: NodeResources = field(default_factory=NodeResources)
    reserved: NodeReservedResources = field(default_factory=NodeReservedResources)
    host_volumes: dict[str, HostVolumeConfig] = field(default_factory=dict)
    # CSI plugins fingerprinted on this node: plugin_id -> info dict
    # (version/healthy/controller/node; reference: Node.CSINodePlugins)
    csi_plugins: dict[str, dict] = field(default_factory=dict)
    links: dict[str, str] = field(default_factory=dict)
    drivers: dict[str, "DriverInfo"] = field(default_factory=dict)
    status: str = NODE_STATUS_INIT
    status_description: str = ""
    scheduling_eligibility: str = NODE_SCHEDULING_ELIGIBLE
    drain_strategy: Optional[DrainStrategy] = None
    computed_class: str = ""
    events: list[NodeEvent] = field(default_factory=list)
    http_addr: str = ""
    secret_id: str = ""
    status_updated_at: int = 0
    create_index: int = 0
    modify_index: int = 0

    def copy(self) -> "Node":
        return Node(
            id=self.id,
            name=self.name,
            datacenter=self.datacenter,
            node_class=self.node_class,
            attributes=dict(self.attributes),
            meta=dict(self.meta),
            resources=self.resources.copy(),
            reserved=self.reserved.copy(),
            host_volumes={k: dataclasses.replace(v) for k, v in self.host_volumes.items()},
            csi_plugins={k: dict(v) for k, v in self.csi_plugins.items()},
            links=dict(self.links),
            drivers={k: v.copy() for k, v in self.drivers.items()},
            status=self.status,
            status_description=self.status_description,
            scheduling_eligibility=self.scheduling_eligibility,
            drain_strategy=self.drain_strategy.copy() if self.drain_strategy else None,
            computed_class=self.computed_class,
            events=[dataclasses.replace(e, details=dict(e.details)) for e in self.events],
            http_addr=self.http_addr,
            secret_id=self.secret_id,
            status_updated_at=self.status_updated_at,
            create_index=self.create_index,
            modify_index=self.modify_index,
        )

    @property
    def drain(self) -> bool:
        return self.drain_strategy is not None

    def ready(self) -> bool:
        return (
            self.status == NODE_STATUS_READY
            and not self.drain
            and self.scheduling_eligibility == NODE_SCHEDULING_ELIGIBLE
        )

    def canonicalize(self) -> None:
        if self.drain_strategy is not None:
            self.scheduling_eligibility = NODE_SCHEDULING_INELIGIBLE
        elif not self.scheduling_eligibility:
            self.scheduling_eligibility = NODE_SCHEDULING_ELIGIBLE

    def terminal_status(self) -> bool:
        return self.status == NODE_STATUS_DOWN

    def available_resources(self) -> Resources:
        """node resources minus reserved, as the solver's capacity vector."""
        return Resources(
            cpu=self.resources.cpu - self.reserved.cpu,
            memory_mb=self.resources.memory_mb - self.reserved.memory_mb,
            disk_mb=self.resources.disk_mb - self.reserved.disk_mb,
        )


@dataclass(slots=True)
class DriverInfo:
    attributes: dict[str, str] = field(default_factory=dict)
    detected: bool = False
    healthy: bool = False
    health_description: str = ""
    update_time_ns: int = 0

    def copy(self) -> "DriverInfo":
        return DriverInfo(
            attributes=dict(self.attributes),
            detected=self.detected,
            healthy=self.healthy,
            health_description=self.health_description,
            update_time_ns=self.update_time_ns,
        )


# ---------------------------------------------------------------------------
# Allocation
# ---------------------------------------------------------------------------


@dataclass(slots=True)
class AllocMetric:
    """Placement decision metadata (reference: structs.go AllocMetric :9826)."""

    nodes_evaluated: int = 0
    nodes_filtered: int = 0
    nodes_available: dict[str, int] = field(default_factory=dict)  # per DC
    class_filtered: dict[str, int] = field(default_factory=dict)
    constraint_filtered: dict[str, int] = field(default_factory=dict)
    nodes_exhausted: int = 0
    class_exhausted: dict[str, int] = field(default_factory=dict)
    dimension_exhausted: dict[str, int] = field(default_factory=dict)
    quota_exhausted: list[str] = field(default_factory=list)
    scores: dict[str, float] = field(default_factory=dict)  # node.scorer -> score
    allocation_time_ns: int = 0
    coalesced_failures: int = 0

    def copy(self) -> "AllocMetric":
        return AllocMetric(
            nodes_evaluated=self.nodes_evaluated,
            nodes_filtered=self.nodes_filtered,
            nodes_available=dict(self.nodes_available),
            class_filtered=dict(self.class_filtered),
            constraint_filtered=dict(self.constraint_filtered),
            nodes_exhausted=self.nodes_exhausted,
            class_exhausted=dict(self.class_exhausted),
            dimension_exhausted=dict(self.dimension_exhausted),
            quota_exhausted=list(self.quota_exhausted),
            scores=dict(self.scores),
            allocation_time_ns=self.allocation_time_ns,
            coalesced_failures=self.coalesced_failures,
        )

    def exhausted_node(self, node: Node, dimension: str) -> None:
        self.nodes_exhausted += 1
        if node.computed_class:
            self.class_exhausted[node.computed_class] = (
                self.class_exhausted.get(node.computed_class, 0) + 1
            )
        if dimension:
            self.dimension_exhausted[dimension] = (
                self.dimension_exhausted.get(dimension, 0) + 1
            )

    def filter_node(self, node: Optional[Node], constraint: str) -> None:
        self.nodes_filtered += 1
        if node is not None and node.computed_class:
            self.class_filtered[node.computed_class] = (
                self.class_filtered.get(node.computed_class, 0) + 1
            )
        if constraint:
            self.constraint_filtered[constraint] = (
                self.constraint_filtered.get(constraint, 0) + 1
            )

    def score_node(self, node_id: str, scorer: str, score: float) -> None:
        self.scores[f"{node_id}.{scorer}"] = score


@dataclass(slots=True)
class RescheduleEvent:
    reschedule_time_ns: int = 0
    prev_alloc_id: str = ""
    prev_node_id: str = ""
    delay_s: float = 0.0


@dataclass(slots=True)
class RescheduleTracker:
    events: list[RescheduleEvent] = field(default_factory=list)

    def copy(self) -> "RescheduleTracker":
        return RescheduleTracker(events=[dataclasses.replace(e) for e in self.events])


@dataclass(slots=True)
class DesiredTransition:
    """Server-instructed transitions (reference: structs.go DesiredTransition :9042)."""

    migrate: Optional[bool] = None
    reschedule: Optional[bool] = None
    force_reschedule: Optional[bool] = None

    def copy(self) -> "DesiredTransition":
        return dataclasses.replace(self)

    def should_migrate(self) -> bool:
        return bool(self.migrate)

    def should_force_reschedule(self) -> bool:
        return bool(self.force_reschedule)


@dataclass(slots=True)
class TaskState:
    state: str = "pending"  # pending | running | dead
    failed: bool = False
    restarts: int = 0
    started_at_ns: int = 0
    finished_at_ns: int = 0
    last_restart_ns: int = 0
    events: list[dict[str, Any]] = field(default_factory=list)

    def copy(self) -> "TaskState":
        return TaskState(
            state=self.state,
            failed=self.failed,
            restarts=self.restarts,
            started_at_ns=self.started_at_ns,
            finished_at_ns=self.finished_at_ns,
            last_restart_ns=self.last_restart_ns,
            events=[dict(e) for e in self.events],
        )

    def successful(self) -> bool:
        return self.state == "dead" and not self.failed


@dataclass(slots=True)
class AllocDeploymentStatus:
    healthy: Optional[bool] = None
    timestamp_ns: int = 0
    canary: bool = False
    modify_index: int = 0

    def copy(self) -> "AllocDeploymentStatus":
        return dataclasses.replace(self)

    def is_healthy(self) -> bool:
        return self.healthy is True

    def is_unhealthy(self) -> bool:
        return self.healthy is False


@dataclass(slots=True)
class AllocNetworkStatus:
    interface_name: str = ""
    address: str = ""
    dns: dict[str, Any] = field(default_factory=dict)


@dataclass(slots=True)
class AllocatedTaskResources:
    cpu: int = 0
    memory_mb: int = 0
    networks: list[NetworkResource] = field(default_factory=list)
    devices: list[dict[str, Any]] = field(default_factory=list)
    # dedicated core ids granted for a `cores` ask (reference
    # structs.go AllocatedCpuResources.ReservedCores): disjoint across
    # every alloc on the node; cpu above holds the DERIVED MHz
    reserved_cores: list[int] = field(default_factory=list)

    def copy(self) -> "AllocatedTaskResources":
        return AllocatedTaskResources(
            cpu=self.cpu,
            memory_mb=self.memory_mb,
            networks=[n.copy() for n in self.networks],
            devices=[dict(d) for d in self.devices],
            reserved_cores=list(self.reserved_cores),
        )


@dataclass(slots=True)
class AllocatedResources:
    """Resources actually granted to an alloc (reference: structs.go :3609)."""

    tasks: dict[str, AllocatedTaskResources] = field(default_factory=dict)
    shared_disk_mb: int = 0
    shared_networks: list[NetworkResource] = field(default_factory=list)

    def copy(self) -> "AllocatedResources":
        return AllocatedResources(
            tasks={k: v.copy() for k, v in self.tasks.items()},
            shared_disk_mb=self.shared_disk_mb,
            shared_networks=[n.copy() for n in self.shared_networks],
        )

    def comparable(self) -> Resources:
        total = Resources(cpu=0, memory_mb=0, disk_mb=self.shared_disk_mb)
        for tr in self.tasks.values():
            total.cpu += tr.cpu
            total.memory_mb += tr.memory_mb
        return total


@dataclass(slots=True)
class Allocation:
    """A placement of a task group on a node (reference: structs.go Allocation :9110)."""

    id: str = ""
    namespace: str = DEFAULT_NAMESPACE
    eval_id: str = ""
    name: str = ""  # jobid.group[index]
    node_id: str = ""
    node_name: str = ""
    job_id: str = ""
    job: Optional[Job] = None
    task_group: str = ""
    resources: Optional[AllocatedResources] = None
    desired_status: str = ALLOC_DESIRED_STATUS_RUN
    desired_description: str = ""
    desired_transition: DesiredTransition = field(default_factory=DesiredTransition)
    client_status: str = ALLOC_CLIENT_STATUS_PENDING
    client_description: str = ""
    task_states: dict[str, TaskState] = field(default_factory=dict)
    deployment_id: str = ""
    deployment_status: Optional[AllocDeploymentStatus] = None
    reschedule_tracker: Optional[RescheduleTracker] = None
    network_status: Optional[AllocNetworkStatus] = None
    followup_eval_id: str = ""
    previous_allocation: str = ""
    next_allocation: str = ""
    metrics: AllocMetric = field(default_factory=AllocMetric)
    preempted_by_allocation: str = ""
    preempted_allocations: list[str] = field(default_factory=list)
    create_index: int = 0
    modify_index: int = 0
    alloc_modify_index: int = 0
    create_time: int = 0
    modify_time: int = 0

    def copy(self, keep_job: bool = True) -> "Allocation":
        return Allocation(
            id=self.id,
            namespace=self.namespace,
            eval_id=self.eval_id,
            name=self.name,
            node_id=self.node_id,
            node_name=self.node_name,
            job_id=self.job_id,
            job=self.job if keep_job else None,  # jobs are immutable once stored
            task_group=self.task_group,
            resources=self.resources.copy() if self.resources else None,
            desired_status=self.desired_status,
            desired_description=self.desired_description,
            desired_transition=self.desired_transition.copy(),
            client_status=self.client_status,
            client_description=self.client_description,
            task_states={k: v.copy() for k, v in self.task_states.items()},
            deployment_id=self.deployment_id,
            deployment_status=(
                self.deployment_status.copy() if self.deployment_status else None
            ),
            reschedule_tracker=(
                self.reschedule_tracker.copy() if self.reschedule_tracker else None
            ),
            network_status=(
                dataclasses.replace(self.network_status, dns=dict(self.network_status.dns))
                if self.network_status
                else None
            ),
            followup_eval_id=self.followup_eval_id,
            previous_allocation=self.previous_allocation,
            next_allocation=self.next_allocation,
            metrics=self.metrics.copy(),
            preempted_by_allocation=self.preempted_by_allocation,
            preempted_allocations=list(self.preempted_allocations),
            create_index=self.create_index,
            modify_index=self.modify_index,
            alloc_modify_index=self.alloc_modify_index,
            create_time=self.create_time,
            modify_time=self.modify_time,
        )

    # -- status predicates (reference: structs.go:9400-9460) --

    def terminal_status(self) -> bool:
        """Desired or actual status is terminal."""
        if self.desired_status in (
            ALLOC_DESIRED_STATUS_STOP,
            ALLOC_DESIRED_STATUS_EVICT,
        ):
            return True
        return self.client_terminal_status()

    def client_terminal_status(self) -> bool:
        return self.client_status in (
            ALLOC_CLIENT_STATUS_COMPLETE,
            ALLOC_CLIENT_STATUS_FAILED,
            ALLOC_CLIENT_STATUS_LOST,
        )

    def server_terminal_status(self) -> bool:
        return self.desired_status in (
            ALLOC_DESIRED_STATUS_STOP,
            ALLOC_DESIRED_STATUS_EVICT,
        )

    def migrate_disk(self) -> bool:
        if self.job is None:
            return False
        tg = self.job.lookup_task_group(self.task_group)
        return tg is not None and tg.ephemeral_disk.migrate

    def comparable_resources(self) -> Resources:
        if self.resources is not None:
            return self.resources.comparable()
        if self.job is not None:
            tg = self.job.lookup_task_group(self.task_group)
            if tg is not None:
                return tg.combined_resources()
        return Resources(cpu=0, memory_mb=0, disk_mb=0)

    def index(self) -> int:
        """The alloc's name index: 'job.group[3]' -> 3."""
        l = self.name.rfind("[")
        r = self.name.rfind("]")
        if l == -1 or r == -1:
            return -1
        try:
            return int(self.name[l + 1 : r])
        except ValueError:
            return -1

    def ran_successfully(self) -> bool:
        if not self.task_states:
            return False
        return all(ts.successful() for ts in self.task_states.values())

    def should_migrate(self) -> bool:
        if self.desired_status != ALLOC_DESIRED_STATUS_STOP:
            return False
        if self.client_terminal_status():
            return False
        if self.job is None:
            return False
        tg = self.job.lookup_task_group(self.task_group)
        if tg is None:
            return False
        return tg.ephemeral_disk.sticky

    def next_reschedule_time(self) -> tuple[int, bool]:
        """(wall-clock ns when a reschedule is allowed, eligible) — reference
        structs.go Allocation.NextRescheduleTime."""
        fail_time = self.last_event_time_ns()
        policy = self.reschedule_policy()
        if policy is None or fail_time == 0:
            return 0, False
        if self.desired_status == ALLOC_DESIRED_STATUS_STOP or (
            self.client_status != ALLOC_CLIENT_STATUS_FAILED
            and self.client_status != ALLOC_CLIENT_STATUS_LOST
        ):
            return 0, False
        delay_s = self.reschedule_delay(policy)
        next_t = fail_time + int(delay_s * 1e9)
        if policy.unlimited:
            return next_t, True
        attempted = 0
        if self.reschedule_tracker:
            window_start = fail_time - int(policy.interval_s * 1e9)
            for ev in self.reschedule_tracker.events:
                if ev.reschedule_time_ns > window_start:
                    attempted += 1
        return next_t, attempted < policy.attempts

    def reschedule_policy(self) -> Optional[ReschedulePolicy]:
        if self.job is None:
            return None
        tg = self.job.lookup_task_group(self.task_group)
        return tg.reschedule_policy if tg else None

    def reschedule_delay(self, policy: ReschedulePolicy) -> float:
        n_prev = len(self.reschedule_tracker.events) if self.reschedule_tracker else 0
        fn = policy.delay_function
        if fn == "constant" or n_prev == 0:
            delay = policy.delay_s
        elif fn == "exponential":
            delay = policy.delay_s * (2**n_prev)
        elif fn == "fibonacci":
            a, b = policy.delay_s, policy.delay_s
            for _ in range(n_prev - 1):
                a, b = b, a + b
            delay = b
        else:
            delay = policy.delay_s
        if policy.max_delay_s > 0:
            delay = min(delay, policy.max_delay_s)
        return delay

    def last_event_time_ns(self) -> int:
        """Latest task finished-at, falling back to modify_time."""
        latest = 0
        for ts in self.task_states.values():
            if ts.finished_at_ns > latest:
                latest = ts.finished_at_ns
        return latest or self.modify_time

    def stub(self) -> "Allocation":
        """Job-stripped copy for list endpoints."""
        c = self.copy(keep_job=False)
        return c


def alloc_name(job_id: str, group: str, index: int) -> str:
    return f"{job_id}.{group}[{index}]"


# ---------------------------------------------------------------------------
# Evaluation
# ---------------------------------------------------------------------------


@dataclass(slots=True)
class Evaluation:
    """A request to (re)consider a job's placements (reference :10211)."""

    id: str = ""
    namespace: str = DEFAULT_NAMESPACE
    priority: int = JOB_DEFAULT_PRIORITY
    type: str = JOB_TYPE_SERVICE
    triggered_by: str = EVAL_TRIGGER_JOB_REGISTER
    job_id: str = ""
    job_modify_index: int = 0
    node_id: str = ""
    node_modify_index: int = 0
    deployment_id: str = ""
    status: str = EVAL_STATUS_PENDING
    status_description: str = ""
    wait_until_ns: int = 0
    next_eval: str = ""
    previous_eval: str = ""
    blocked_eval: str = ""
    failed_tg_allocs: dict[str, AllocMetric] = field(default_factory=dict)
    class_eligibility: dict[str, bool] = field(default_factory=dict)
    escaped_computed_class: bool = False
    quota_limit_reached: str = ""
    annotate_plan: bool = False
    queued_allocations: dict[str, int] = field(default_factory=dict)
    leader_ack: str = ""
    snapshot_index: int = 0
    create_index: int = 0
    modify_index: int = 0
    create_time: int = 0
    modify_time: int = 0

    def copy(self) -> "Evaluation":
        return Evaluation(
            id=self.id,
            namespace=self.namespace,
            priority=self.priority,
            type=self.type,
            triggered_by=self.triggered_by,
            job_id=self.job_id,
            job_modify_index=self.job_modify_index,
            node_id=self.node_id,
            node_modify_index=self.node_modify_index,
            deployment_id=self.deployment_id,
            status=self.status,
            status_description=self.status_description,
            wait_until_ns=self.wait_until_ns,
            next_eval=self.next_eval,
            previous_eval=self.previous_eval,
            blocked_eval=self.blocked_eval,
            failed_tg_allocs={k: v.copy() for k, v in self.failed_tg_allocs.items()},
            class_eligibility=dict(self.class_eligibility),
            escaped_computed_class=self.escaped_computed_class,
            quota_limit_reached=self.quota_limit_reached,
            annotate_plan=self.annotate_plan,
            queued_allocations=dict(self.queued_allocations),
            leader_ack=self.leader_ack,
            snapshot_index=self.snapshot_index,
            create_index=self.create_index,
            modify_index=self.modify_index,
            create_time=self.create_time,
            modify_time=self.modify_time,
        )

    def terminal_status(self) -> bool:
        return self.status in (
            EVAL_STATUS_COMPLETE,
            EVAL_STATUS_FAILED,
            EVAL_STATUS_CANCELLED,
        )

    def should_enqueue(self) -> bool:
        return self.status == EVAL_STATUS_PENDING

    def should_block(self) -> bool:
        return self.status == EVAL_STATUS_BLOCKED

    def make_plan(self, job: Optional[Job]) -> "Plan":
        return Plan(
            eval_id=self.id,
            priority=self.priority,
            job=job,
            all_at_once=job.all_at_once if job else False,
        )

    def next_rolling_eval(self, wait_s: float) -> "Evaluation":
        return Evaluation(
            id=generate_uuid(),
            namespace=self.namespace,
            priority=self.priority,
            type=self.type,
            triggered_by=EVAL_TRIGGER_ROLLING_UPDATE,
            job_id=self.job_id,
            job_modify_index=self.job_modify_index,
            status=EVAL_STATUS_PENDING,
            wait_until_ns=now_ns() + int(wait_s * 1e9),
            previous_eval=self.id,
            create_time=now_ns(),
            modify_time=now_ns(),
        )

    def create_blocked_eval(
        self,
        classes: dict[str, bool],
        escaped: bool,
        quota_reached: str,
        failed_tg_allocs: dict[str, AllocMetric] | None = None,
    ) -> "Evaluation":
        return Evaluation(
            id=generate_uuid(),
            namespace=self.namespace,
            priority=self.priority,
            type=self.type,
            triggered_by=EVAL_TRIGGER_QUEUED_ALLOCS,
            job_id=self.job_id,
            job_modify_index=self.job_modify_index,
            status=EVAL_STATUS_BLOCKED,
            previous_eval=self.id,
            class_eligibility=classes,
            escaped_computed_class=escaped,
            quota_limit_reached=quota_reached,
            failed_tg_allocs=failed_tg_allocs or {},
            create_time=now_ns(),
            modify_time=now_ns(),
        )

    def create_failed_followup_eval(self, wait_s: float) -> "Evaluation":
        return Evaluation(
            id=generate_uuid(),
            namespace=self.namespace,
            priority=self.priority,
            type=self.type,
            triggered_by=EVAL_TRIGGER_FAILED_FOLLOWUP,
            job_id=self.job_id,
            job_modify_index=self.job_modify_index,
            status=EVAL_STATUS_PENDING,
            wait_until_ns=now_ns() + int(wait_s * 1e9),
            previous_eval=self.id,
            create_time=now_ns(),
            modify_time=now_ns(),
        )


# ---------------------------------------------------------------------------
# Plan
# ---------------------------------------------------------------------------


@dataclass(slots=True)
class DeploymentStatusUpdate:
    deployment_id: str = ""
    status: str = ""
    status_description: str = ""


@dataclass(slots=True)
class Plan:
    """A scheduler's proposed state mutation (reference: structs.go Plan :10505)."""

    eval_id: str = ""
    eval_token: str = ""
    priority: int = JOB_DEFAULT_PRIORITY
    all_at_once: bool = False
    job: Optional[Job] = None
    # node_id -> allocs to stop/evict on that node
    node_update: dict[str, list[Allocation]] = field(default_factory=dict)
    # node_id -> allocs to create/update on that node
    node_allocation: dict[str, list[Allocation]] = field(default_factory=dict)
    # node_id -> allocs preempted on that node
    node_preemptions: dict[str, list[Allocation]] = field(default_factory=dict)
    annotations: Optional[dict[str, Any]] = None
    deployment: Optional["Deployment"] = None
    deployment_updates: list[DeploymentStatusUpdate] = field(default_factory=list)
    snapshot_index: int = 0
    # struct-of-arrays fresh placements (structs/placement_batch.py):
    # the solver's fast-mint path appends whole PlacementBatches here
    # instead of per-row Allocations in node_allocation — the applier,
    # codec, and store consume the columns directly.
    alloc_batches: list = field(default_factory=list)

    def append_placement_batch(self, batch) -> None:
        """Attach a SoA batch of fresh placements (already job-stamped
        by the solver; no per-row copy — batch rows are solver-minted
        and referenced nowhere else, the append_fresh_alloc contract)."""
        if batch.job is None:
            batch.job = self.job
        self.alloc_batches.append(batch)

    def materialize_batches(self) -> None:
        """Fold SoA batches into node_allocation as eager per-row
        Allocations — the eager-object equivalent of this plan. Boundary
        escape hatch (and the differential identity battery's
        comparator); the hot paths never call it."""
        for b in self.alloc_batches:
            for a in b.materialize():
                self.node_allocation.setdefault(a.node_id, []).append(a)
        self.alloc_batches = []

    def append_stopped_alloc(
        self, alloc: Allocation, desired_desc: str, client_status: str = ""
    ) -> None:
        """Mark an alloc for stopping (reference: Plan.AppendStoppedAlloc :10556)."""
        new_alloc = alloc.copy()
        new_alloc.job = None  # normalized: job is derivable from the plan
        new_alloc.desired_status = ALLOC_DESIRED_STATUS_STOP
        new_alloc.desired_description = desired_desc
        if client_status:
            new_alloc.client_status = client_status
        self.node_update.setdefault(alloc.node_id, []).append(new_alloc)

    def append_alloc(self, alloc: Allocation, job: Optional[Job] = None) -> None:
        new_alloc = alloc.copy()
        new_alloc.job = job if job is not None else self.job
        self.node_allocation.setdefault(new_alloc.node_id, []).append(new_alloc)

    def append_fresh_alloc(self, alloc: Allocation, job: Optional[Job] = None) -> None:
        """append_alloc without the defensive copy — ONLY for allocs minted
        by the caller this pass and referenced nowhere else (the batch
        solver's hot path: 100k copies would dominate the solve)."""
        alloc.job = job if job is not None else self.job
        self.node_allocation.setdefault(alloc.node_id, []).append(alloc)

    def append_preempted_alloc(self, alloc: Allocation, preempting_id: str) -> None:
        new_alloc = alloc.copy()
        new_alloc.job = None
        new_alloc.desired_status = ALLOC_DESIRED_STATUS_EVICT
        new_alloc.preempted_by_allocation = preempting_id
        new_alloc.desired_description = (
            f"Preempted by alloc ID {preempting_id}"
        )
        self.node_preemptions.setdefault(alloc.node_id, []).append(new_alloc)

    def pop_update(self, alloc: Allocation) -> None:
        """Remove a pending stop for alloc (in-place update promotion)."""
        existing = self.node_update.get(alloc.node_id, [])
        n = len(existing)
        if n > 0 and existing[n - 1].id == alloc.id:
            existing.pop()
            if not existing:
                del self.node_update[alloc.node_id]

    def is_no_op(self) -> bool:
        return (
            not self.node_update
            and not self.node_allocation
            and not self.alloc_batches
            and self.deployment is None
            and not self.deployment_updates
        )


@dataclass(slots=True)
class PlanResult:
    """What the plan applier committed (reference: structs.go PlanResult :10749)."""

    node_update: dict[str, list[Allocation]] = field(default_factory=dict)
    node_allocation: dict[str, list[Allocation]] = field(default_factory=dict)
    node_preemptions: dict[str, list[Allocation]] = field(default_factory=dict)
    # The job version this plan was scheduled against, carried ONCE: allocs
    # in node_allocation with job=None re-attach to it on apply (denormalized
    # payload — see PlanApplier.apply_one).
    job: Optional[Job] = None
    deployment: Optional["Deployment"] = None
    deployment_updates: list[DeploymentStatusUpdate] = field(default_factory=list)
    # follow-up evals for the jobs whose allocs were preempted, so they
    # reschedule elsewhere (reference plan_apply.go PreemptionEvals)
    preemption_evals: list["Evaluation"] = field(default_factory=list)
    refresh_index: int = 0
    alloc_index: int = 0
    # committed SoA placement batches (possibly per-node-trimmed views of
    # the plan's batches). NEVER on the wire as a field: the codec folds
    # these into node_allocation row maps so the raft entry is
    # byte-identical to the eager form (codec._install_plan_result_encoder).
    alloc_batches: list = field(default_factory=list)

    def full_commit(self, plan: Plan) -> tuple[bool, int, int]:
        expected = sum(len(v) for v in plan.node_allocation.values()) + sum(
            len(b) for b in plan.alloc_batches
        )
        actual = sum(len(v) for v in self.node_allocation.values()) + sum(
            len(b) for b in self.alloc_batches
        )
        return expected == actual, expected, actual

    def is_no_op(self) -> bool:
        return (
            not self.node_update
            and not self.node_allocation
            and not self.alloc_batches
            and not self.deployment_updates
            and self.deployment is None
        )


# ---------------------------------------------------------------------------
# Deployment
# ---------------------------------------------------------------------------


@dataclass(slots=True)
class DeploymentState:
    """Per-task-group rollout state (reference: structs.go DeploymentState :8863)."""

    auto_revert: bool = False
    auto_promote: bool = False
    promoted: bool = False
    placed_canaries: list[str] = field(default_factory=list)
    desired_canaries: int = 0
    desired_total: int = 0
    placed_allocs: int = 0
    healthy_allocs: int = 0
    unhealthy_allocs: int = 0
    progress_deadline_s: float = 600.0
    require_progress_by_ns: int = 0

    def copy(self) -> "DeploymentState":
        return DeploymentState(
            auto_revert=self.auto_revert,
            auto_promote=self.auto_promote,
            promoted=self.promoted,
            placed_canaries=list(self.placed_canaries),
            desired_canaries=self.desired_canaries,
            desired_total=self.desired_total,
            placed_allocs=self.placed_allocs,
            healthy_allocs=self.healthy_allocs,
            unhealthy_allocs=self.unhealthy_allocs,
            progress_deadline_s=self.progress_deadline_s,
            require_progress_by_ns=self.require_progress_by_ns,
        )


@dataclass(slots=True)
class Deployment:
    """A tracked rollout of one job version (reference: structs.go Deployment :8767)."""

    id: str = ""
    namespace: str = DEFAULT_NAMESPACE
    job_id: str = ""
    job_version: int = 0
    job_modify_index: int = 0
    job_spec_modify_index: int = 0
    job_create_index: int = 0
    is_multiregion: bool = False
    task_groups: dict[str, DeploymentState] = field(default_factory=dict)
    status: str = DEPLOYMENT_STATUS_RUNNING
    status_description: str = "Deployment is running"
    create_index: int = 0
    modify_index: int = 0
    modify_time: int = 0  # wall-clock ns, for GC thresholds

    def copy(self) -> "Deployment":
        return Deployment(
            id=self.id,
            namespace=self.namespace,
            job_id=self.job_id,
            job_version=self.job_version,
            job_modify_index=self.job_modify_index,
            job_spec_modify_index=self.job_spec_modify_index,
            job_create_index=self.job_create_index,
            is_multiregion=self.is_multiregion,
            task_groups={k: v.copy() for k, v in self.task_groups.items()},
            status=self.status,
            status_description=self.status_description,
            create_index=self.create_index,
            modify_index=self.modify_index,
            modify_time=self.modify_time,
        )

    def active(self) -> bool:
        return self.status in (DEPLOYMENT_STATUS_RUNNING, DEPLOYMENT_STATUS_PAUSED)

    def requires_promotion(self) -> bool:
        return any(
            s.desired_canaries > 0 and not s.promoted
            for s in self.task_groups.values()
        )

    def has_auto_promote(self) -> bool:
        states = self.task_groups.values()
        return bool(states) and all(s.auto_promote for s in states)


def new_deployment(job: Job) -> Deployment:
    d = Deployment(
        id=generate_uuid(),
        namespace=job.namespace,
        job_id=job.id,
        job_version=job.version,
        job_modify_index=job.modify_index,
        job_spec_modify_index=job.job_modify_index,
        job_create_index=job.create_index,
        status=DEPLOYMENT_STATUS_RUNNING,
        status_description="Deployment is running",
    )
    return d
