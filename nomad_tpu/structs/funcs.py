"""Scheduling math shared by the host oracle and the TPU solver.

Reference: nomad/structs/funcs.go — AllocsFit :148, ScoreFitBinPack :237,
ScoreFitSpread :264. The scoring formulas here are the scalar versions; the
TPU solver re-expresses them as vectorized JAX ops over the full
(alloc x node) tensor in nomad_tpu/scheduler/tpu/kernels.py.
"""

from __future__ import annotations

from typing import Optional

from .network import NetworkIndex
from .structs import Allocation, Node, Resources

# ScoreFit constants: Best-Fit v3 — at perfect fit score is 18, empty node 0.
MAX_FIT_SCORE = 18.0


def compute_free_percentage(node: Node, util: Resources) -> tuple[float, float]:
    node_cpu = float(node.resources.cpu - node.reserved.cpu)
    node_mem = float(node.resources.memory_mb - node.reserved.memory_mb)
    free_cpu = 1.0 - (float(util.cpu) / node_cpu) if node_cpu > 0 else 0.0
    free_mem = 1.0 - (float(util.memory_mb) / node_mem) if node_mem > 0 else 0.0
    return free_cpu, free_mem


def score_fit_binpack(node: Node, util: Resources) -> float:
    """Best-fit score in [0, 18]; higher is fuller (reference funcs.go:237)."""
    free_cpu, free_mem = compute_free_percentage(node, util)
    total = 10.0**free_cpu + 10.0**free_mem
    score = 20.0 - total
    return max(0.0, min(MAX_FIT_SCORE, score))


def score_fit_spread(node: Node, util: Resources) -> float:
    """Worst-fit score in [0, 18]; higher is emptier (reference funcs.go:264)."""
    free_cpu, free_mem = compute_free_percentage(node, util)
    total = 10.0**free_cpu + 10.0**free_mem
    score = total - 2.0
    return max(0.0, min(MAX_FIT_SCORE, score))


def node_core_pool(node, allocs):
    """Free dedicated-core ids on a node given live allocs, plus the
    node's MHz per core (the derived cpu share a `cores` grant carries).
    The single source of truth both scheduler backends use, keeping
    grant ordering and derivation in lockstep (reference: the cpuset
    idset in structs/numalib)."""
    total = node.resources.total_cores or 0
    used: set[int] = set()
    for a in allocs:
        if not a.terminal_status() and a.resources is not None:
            for tr in a.resources.tasks.values():
                used.update(tr.reserved_cores)
    free = [c for c in range(total) if c not in used]
    # derive from AVAILABLE MHz (minus the client reserved carve-out):
    # otherwise a node with any reservation could never grant all of
    # its cores — the derived total would exceed what is grantable
    mhz_per_core = (
        node.available_resources().cpu // total if total else 0
    )
    return free, mhz_per_core


def allocs_fit(
    node: Node,
    allocs: list[Allocation],
    net_idx: Optional[NetworkIndex] = None,
    check_devices: bool = False,
) -> tuple[bool, str, Resources]:
    """Would this set of allocs fit on the node? (reference funcs.go:148)

    Returns (fit, exhausted-dimension, used-resources). Terminal allocs are
    free. If a NetworkIndex is supplied the caller has already checked port
    collisions; otherwise one is built here.
    """
    used = Resources(cpu=0, memory_mb=0, disk_mb=0)
    seen_cores: set[int] = set()
    for alloc in allocs:
        if alloc.terminal_status():
            continue
        r = alloc.comparable_resources()
        used.cpu += r.cpu
        used.memory_mb += r.memory_mb
        used.disk_mb += r.disk_mb
        # dedicated cores must be disjoint (reference funcs.go AllocsFit
        # cpuset overlap check)
        if alloc.resources is not None:
            total = node.resources.total_cores or 0
            for tr in alloc.resources.tasks.values():
                for c in tr.reserved_cores:
                    if c in seen_cores:
                        return False, "cores (id collision)", used
                    if c < 0 or c >= total:
                        # node shrank since scheduling, or a corrupt grant
                        return False, "cores (stale id)", used
                    seen_cores.add(c)

    available = node.available_resources()
    ok, dim = available.superset(used)
    if not ok:
        return False, dim, used

    if net_idx is None:
        net_idx = NetworkIndex()
        if net_idx.set_node(node):
            return False, "reserved port collision", used
        if net_idx.add_allocs(allocs):
            return False, "reserved port collision", used

    if net_idx.overcommitted():
        return False, "bandwidth exceeded", used

    if check_devices:
        from .devices import DeviceAccounter

        accounter = DeviceAccounter(node)
        if accounter.add_allocs(allocs):
            return False, "device oversubscribed", used

    return True, "", used


def filter_terminal_allocs(
    allocs: list[Allocation],
) -> tuple[list[Allocation], list[Allocation]]:
    """Split into (live, terminal), keeping the newest terminal per name.

    Reference: structs/funcs.go FilterTerminalAllocs :53.
    """
    terminal: dict[str, Allocation] = {}
    live: list[Allocation] = []
    for alloc in allocs:
        if alloc.terminal_status():
            prev = terminal.get(alloc.name)
            if prev is None or prev.create_index < alloc.create_index:
                terminal[alloc.name] = alloc
        else:
            live.append(alloc)
    return live, list(terminal.values())


def allocs_by_node(allocs: list[Allocation]) -> dict[str, list[Allocation]]:
    out: dict[str, list[Allocation]] = {}
    for a in allocs:
        out.setdefault(a.node_id, []).append(a)
    return out
