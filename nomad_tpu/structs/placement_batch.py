"""Struct-of-arrays placements: the array-native data plane's core type.

A ``PlacementBatch`` is K fresh placements of ONE lowered group (same
eval, job version, task group, resource ask) kept as dense columns —
ids, names, and a node-index array into a shared node table — instead
of K ``Allocation`` objects. The batch flows unchanged from kernel
readback (solver fast-mint) through plan assembly (``Plan.alloc_batches``),
the plan applier's vectorized verification, the raft entry codec
(folded into the eager wire form, byte-identical — codec._enc_plan_result),
and the store's bulk transaction (``_upsert_batch_txn``), where the
table rows are lazy ``AllocRow`` handles.

``Allocation`` objects are materialized lazily, only at API/client/
event-stream boundaries, with a cached-on-first-access view (``row(i)``)
so repeated reads don't re-pay the construction. A materialized row is
field-for-field identical to what the eager path would have minted and
stored — the differential identity battery
(tests/test_plan_apply_batch.py) pins that, byte-for-byte, across the
merged-plan-apply matrix.

Only the fast-mint shape rides a batch: no per-row ports, devices,
dedicated cores, canary status, or previous-alloc rewiring — exactly
the rows that share one AllocatedResources/AllocMetric today. Everything
else keeps the eager per-row path.
"""

from __future__ import annotations

from dataclasses import dataclass, field, fields as dataclass_fields
from typing import Optional

import numpy as np

from .structs import (
    ALLOC_CLIENT_STATUS_PENDING,
    ALLOC_DESIRED_STATUS_RUN,
    Allocation,
    AllocMetric,
    AllocatedResources,
    DEFAULT_NAMESPACE,
    Job,
)

_ALLOC_FIELDS = tuple(f.name for f in dataclass_fields(Allocation))


@dataclass(eq=False)
class PlacementBatch:
    """Dense columns for K same-group placements.

    node_idx_raw is the int32 node-index column as raw bytes (numpy
    ``tobytes``) so the wire codec ships it as one msgpack bin instead
    of K ints; ``node_idx`` exposes the array view. node_ids/node_names
    are indexed BY that column (they may be the whole solve's node
    table — shared references, not copies).
    """

    # shared scalars (identical across every row)
    namespace: str = DEFAULT_NAMESPACE
    eval_id: str = ""
    job_id: str = ""
    job: Optional[Job] = None
    task_group: str = ""
    resources: Optional[AllocatedResources] = None
    metrics: Optional[AllocMetric] = None
    deployment_id: str = ""
    # stamped by the store transaction (one value for the whole batch —
    # the eager txn stamps every row with the same index/now anyway)
    create_index: int = 0
    modify_index: int = 0
    create_time: int = 0
    modify_time: int = 0
    # per-row columns
    ids: list[str] = field(default_factory=list)
    names: list[str] = field(default_factory=list)
    node_idx_raw: bytes = b""
    node_ids: list[str] = field(default_factory=list)
    node_names: list[str] = field(default_factory=list)

    # -- column views ---------------------------------------------------

    @property
    def node_idx(self) -> np.ndarray:
        arr = getattr(self, "_idx_arr", None)
        if arr is None:
            arr = np.frombuffer(self.node_idx_raw, dtype=np.int32)
            self._idx_arr = arr
        return arr

    @property
    def count(self) -> int:
        return len(self.ids)

    def __len__(self) -> int:
        return len(self.ids)

    # -- per-node aggregation (the vectorized-verify inputs) ------------

    def touched_nodes(self) -> list[tuple[str, int, int]]:
        """(node_id, table_idx, row_count) per distinct node, in
        FIRST-APPEARANCE order — the same order the eager per-row loop
        would first touch each node, so downstream dict insertion order
        (usage aggregates, node_allocation folds) is byte-identical.
        Cached: the columns are immutable once built (take() returns a
        NEW batch), and the partition key, verifier, codec fold, and
        store txn all read this."""
        cached = getattr(self, "_touched", None)
        if cached is not None:
            return cached
        # plain dict walk, not np.unique: dict insertion order IS
        # first-appearance order, and numpy's per-call overhead loses to
        # the interpreter below ~10^4 rows (the common batch size)
        counts: dict[int, int] = {}
        for ti in self.node_idx.tolist():
            counts[ti] = counts.get(ti, 0) + 1
        nid = self.node_ids
        self._touched = [(nid[ti], ti, c) for ti, c in counts.items()]
        return self._touched

    def row_contribution(self) -> tuple[int, int, int, int]:
        """One row's usage contribution (cpu, mem, disk, complex=0) —
        fast-mint rows never carry ports/cores, so complex is 0 by
        construction (the property the store's vectorized aggregate
        update rides on)."""
        r = self.resources.comparable() if self.resources else None
        if r is None:
            return (0, 0, 0, 0)
        return (r.cpu, r.memory_mb, r.disk_mb, 0)

    # -- masking (plan-apply per-node rejection) ------------------------

    def take(self, keep: np.ndarray) -> "PlacementBatch":
        """Sub-batch of the rows where ``keep`` is True (plan apply
        drops a rejected node's rows). Shares the node tables and the
        shared scalars; never copies the survivors' strings."""
        sel = np.nonzero(keep)[0]
        return PlacementBatch(
            namespace=self.namespace,
            eval_id=self.eval_id,
            job_id=self.job_id,
            job=self.job,
            task_group=self.task_group,
            resources=self.resources,
            metrics=self.metrics,
            deployment_id=self.deployment_id,
            create_index=self.create_index,
            modify_index=self.modify_index,
            create_time=self.create_time,
            modify_time=self.modify_time,
            ids=[self.ids[i] for i in sel],
            names=[self.names[i] for i in sel],
            node_idx_raw=np.ascontiguousarray(
                self.node_idx[keep]
            ).tobytes(),
            node_ids=self.node_ids,
            node_names=self.node_names,
        )

    # -- store stamping -------------------------------------------------

    def stamp(self, index: int, now: int) -> None:
        """Store-commit stamp (the eager txn's per-row index/time writes,
        once per batch). Drops any cached materializations: a row
        materialized before the stamp (e.g. the codec's wire template)
        would otherwise serve stale index fields to store readers."""
        self.create_index = index
        self.modify_index = index
        if not self.create_time:
            self.create_time = now
        self.modify_time = now
        if getattr(self, "_rows", None) is not None:
            self._rows = None

    # -- lazy materialization -------------------------------------------

    def _row_cache(self) -> list:
        rows = getattr(self, "_rows", None)
        if rows is None:
            rows = self._rows = [None] * len(self.ids)
        return rows

    def _proto_items(self) -> list:
        """Per-batch default field values: fresh default-factory
        containers minted ONCE per batch and shared across its rows —
        the exact sharing the eager _MintTemplate prototype had (the
        store's copy-on-write discipline makes stored sub-object
        sharing safe; sharing is per-batch, never process-global)."""
        items = getattr(self, "_proto", None)
        if items is None:
            proto = Allocation()
            items = self._proto = [
                (n, getattr(proto, n)) for n in _ALLOC_FIELDS
            ]
        return items

    def _mint(self, i: int) -> Allocation:
        """Construct row i — field-identical to the eager fast-mint."""
        a = Allocation.__new__(Allocation)
        ni = int(self.node_idx[i])
        for name, v in self._proto_items():
            setattr(a, name, v)
        a.id = self.ids[i]
        a.namespace = self.namespace
        a.eval_id = self.eval_id
        a.name = self.names[i]
        a.node_id = self.node_ids[ni]
        a.node_name = self.node_names[ni]
        a.job_id = self.job_id
        a.job = self.job
        a.task_group = self.task_group
        a.resources = self.resources
        a.metrics = self.metrics
        a.deployment_id = self.deployment_id
        a.create_index = self.create_index
        a.modify_index = self.modify_index
        a.create_time = self.create_time
        a.modify_time = self.modify_time
        return a

    def row(self, i: int) -> Allocation:
        """Materialize row i, cached on first access."""
        rows = self._row_cache()
        a = rows[i]
        if a is None:
            a = rows[i] = self._mint(i)
        return a

    def materialize(self) -> list[Allocation]:
        """All rows, cached (the API/client boundary view)."""
        return [self.row(i) for i in range(len(self.ids))]

    def handles(self) -> list["AllocRow"]:
        """One lazy store-table handle per row. Cached: the columns are
        immutable once built, and both the plan applier and the store
        txn ask for the same handle list."""
        cached = getattr(self, "_handles", None)
        if cached is not None:
            return cached
        out = [AllocRow(self, i) for i in range(len(self.ids))]
        self._handles = out
        return out

    # -- wire fold (codec._enc_plan_result) -----------------------------

    def extend_wire_rows(self, out: dict) -> None:
        """Append this batch's rows to a node_allocation WIRE map
        (node_id -> [row maps]), exactly as the eager encoder would:
        per-node lists in first-touch order, rows in placement order.

        Rows share one template wire dict (the to_wire(_elide) form of a
        transient row 0) with the four per-row fields re-set per row;
        shared nested values (resources/metrics wire maps) are aliased,
        not copied — msgpack re-encodes them per row, reproducing the
        eager bytes. Native fastpack's wire_rows does the dict fan-out
        in C when present."""
        if not self.ids:
            return
        from .. import codec

        template = codec.to_wire(self._mint(0), _elide=True)
        idx = self.node_idx
        nid_of = self.node_ids
        node_col = [nid_of[int(i)] for i in idx]
        rows = _wire_rows(
            template, self.ids, self.names, node_col,
            [self.node_names[int(i)] for i in idx],
        )
        for nid, row in zip(node_col, rows):
            bucket = out.get(nid)
            if bucket is None:
                bucket = out[nid] = []
            bucket.append(row)


def _wire_rows_py(template, ids, names, node_ids, node_names):
    out = []
    ap = out.append
    for uid, name, nid, nname in zip(ids, names, node_ids, node_names):
        d = dict(template)
        d["id"] = uid
        d["name"] = name
        d["node_id"] = nid
        d["node_name"] = nname
        ap(d)
    return out


def _wire_rows(template, ids, names, node_ids, node_names):
    fp = _native()
    if fp is not None:
        try:
            return fp.wire_rows(template, ids, names, node_ids, node_names)
        except Exception:
            pass
    return _wire_rows_py(template, ids, names, node_ids, node_names)


def _native():
    """The fastpack extension if (and only if) it is already resolved —
    this module must never trigger the C build itself (codec.warm_native
    is the one sanctioned build point, outside any lock)."""
    from .. import codec

    return codec.native_module()


class AllocRow:
    """Lazy store-table handle for one batch row.

    The hot fields the store's own bookkeeping reads (ids, statuses,
    job/node keys, the terminal predicate) answer straight from the
    batch columns without materializing; anything else falls through to
    the cached materialized row. Store READERS materialize at the mixin
    boundary — handles never escape the store/event layer."""

    __slots__ = ("b", "i")

    def __init__(self, b: PlacementBatch, i: int) -> None:
        self.b = b
        self.i = i

    # cheap column-backed fields ---------------------------------------
    @property
    def id(self) -> str:
        return self.b.ids[self.i]

    @property
    def name(self) -> str:
        return self.b.names[self.i]

    @property
    def node_id(self) -> str:
        return self.b.node_ids[int(self.b.node_idx[self.i])]

    @property
    def node_name(self) -> str:
        return self.b.node_names[int(self.b.node_idx[self.i])]

    @property
    def namespace(self) -> str:
        return self.b.namespace

    @property
    def eval_id(self) -> str:
        return self.b.eval_id

    @property
    def job_id(self) -> str:
        return self.b.job_id

    @property
    def job(self):
        return self.b.job

    @property
    def task_group(self) -> str:
        return self.b.task_group

    @property
    def resources(self):
        return self.b.resources

    @property
    def deployment_id(self) -> str:
        return self.b.deployment_id

    @property
    def desired_status(self) -> str:
        return ALLOC_DESIRED_STATUS_RUN

    @property
    def client_status(self) -> str:
        return ALLOC_CLIENT_STATUS_PENDING

    @property
    def create_index(self) -> int:
        return self.b.create_index

    @property
    def modify_index(self) -> int:
        return self.b.modify_index

    def terminal_status(self) -> bool:
        return False  # fresh run/pending by construction

    def client_terminal_status(self) -> bool:
        return False

    def server_terminal_status(self) -> bool:
        return False

    def get(self) -> Allocation:
        """The materialized row (cached in the batch)."""
        return self.b.row(self.i)

    def __getattr__(self, name):
        # safety net: any field not column-backed materializes
        return getattr(self.b.row(self.i), name)


# The store's read mixin inlines the materialization expression
# (`a.get() if a.__class__ is AllocRow else a`) at each reader — a
# helper call per row would be the hot paths' dominant remaining cost.
