"""Event streaming (reference: nomad/stream/)."""

from .event_broker import (
    Event,
    EventBroker,
    Subscription,
    SubscriptionClosedError,
    TOPIC_ALL,
)

__all__ = [
    "Event",
    "EventBroker",
    "Subscription",
    "SubscriptionClosedError",
    "TOPIC_ALL",
]
