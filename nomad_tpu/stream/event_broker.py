"""Ring-buffer event broker with topic-filtered subscriptions.

Reference: nomad/stream/event_broker.go:55 (EventBroker),
event_buffer.go (ring buffer of event blocks, dropped-tail detection) and
subscription.go (per-subscriber cursor + filter). The TPU-native redesign
keeps the same contract:

  * `publish` appends a block of events sharing one raft index;
  * each `Subscription` holds a cursor into the buffer and blocks until
    events past its cursor arrive;
  * a slow subscriber whose cursor falls off the ring is closed with
    `SubscriptionClosedError` and must re-subscribe (possibly re-reading
    current state first) — exactly the reference's
    ErrSubscriptionClosed discipline.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Optional

from .. import metrics

TOPIC_ALL = "*"
KEY_ALL = "*"

# Topics (reference: nomad/structs/structs.go TopicNode/TopicJob/...)
TOPIC_NODE = "Node"
TOPIC_JOB = "Job"
TOPIC_EVAL = "Evaluation"
TOPIC_ALLOC = "Allocation"
TOPIC_DEPLOYMENT = "Deployment"
TOPIC_SERVICE = "Service"
TOPIC_VOLUME = "Volume"


@dataclass(frozen=True)
class Event:
    """One change event (reference structs.Event)."""

    topic: str
    type: str
    key: str
    index: int
    payload: object
    namespace: str = ""
    filter_keys: tuple = field(default_factory=tuple)

    def matches(self, topics: dict[str, list[str]]) -> bool:
        for topic in (self.topic, TOPIC_ALL):
            keys = topics.get(topic)
            if keys is None:
                continue
            for k in keys:
                if k == KEY_ALL or k == self.key or k in self.filter_keys:
                    return True
        return False


class SubscriptionClosedError(Exception):
    """The subscriber fell off the ring buffer (or the broker closed)."""


class Subscription:
    def __init__(
        self,
        broker: "EventBroker",
        topics: dict[str, list[str]],
        start_seq: int,
        namespace: str = "",
    ):
        self._broker = broker
        self._topics = topics
        self._namespace = namespace  # "" ⇒ all namespaces
        self._seq = start_seq  # next block sequence number to consume
        self._closed = False

    def _match(self, e: Event) -> bool:
        if self._namespace and e.namespace and e.namespace != self._namespace:
            return False
        return e.matches(self._topics)

    def next(self, timeout_s: Optional[float] = 5.0) -> list[Event]:
        """Block for the next matching block of events.

        Returns [] on timeout. Raises SubscriptionClosedError if the ring
        has overwritten our cursor or the broker shut down. The timeout is
        a single deadline across non-matching blocks — a busy broker full
        of filtered-out events can't extend it.
        """
        deadline = None if timeout_s is None else time.monotonic() + timeout_s
        while True:
            remaining = None if deadline is None else deadline - time.monotonic()
            if remaining is not None and remaining <= 0:
                return []
            block = self._broker._next_block(self, remaining)
            if block is None:
                return []
            events = [e for e in block if self._match(e)]
            if events:
                return events

    def close(self) -> None:
        self._closed = True
        with self._broker._cv:
            self._broker._subs.discard(self)
            self._broker._cv.notify_all()


class EventBroker:
    """Fixed-size ring of event blocks; fan-out to subscriptions.

    Reference: nomad/stream/event_broker.go (size from
    `event_buffer_size` agent config, default 100).
    """

    def __init__(self, size: int = 1024) -> None:
        self._size = size
        # seq -> (raft index, events); insertion-ordered, evicted oldest
        # first. A dict keyed by seq gives O(1) random access for lagging
        # subscribers (a deque would make catch-up O(size) per block).
        self._blocks: dict[int, tuple[int, list[Event]]] = {}
        self._next_seq = 0
        self._latest_index = 0
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._closed = False
        # live Subscription registry: every subscriber is accounted for
        # from subscribe() until close()/eviction, so a fleet of
        # streamers shows up in `operator top` and a leak is visible as
        # a gauge, not an OOM
        self._subs: set[Subscription] = set()
        self._evicted = 0

    # -- publishing ----------------------------------------------------

    def publish(self, events: list[Event]) -> None:
        if not events:
            return
        with self._cv:
            index = events[0].index
            self._blocks[self._next_seq] = (index, list(events))
            self._next_seq += 1
            while len(self._blocks) > self._size:
                self._blocks.pop(next(iter(self._blocks)))
            if index > self._latest_index:
                self._latest_index = index
            self._cv.notify_all()

    def latest_index(self) -> int:
        with self._lock:
            return self._latest_index

    def close(self) -> None:
        with self._cv:
            self._closed = True
            self._cv.notify_all()

    # -- subscribing ---------------------------------------------------

    def subscribe(
        self,
        topics: Optional[dict[str, list[str]]] = None,
        from_index: int = 0,
        namespace: str = "",
    ) -> Subscription:
        """Subscribe starting at the first buffered block with
        index > from_index (0 ⇒ only new events). A non-empty namespace
        scopes the subscription (reference SubscribeRequest.Namespace)."""
        topics = topics or {TOPIC_ALL: [KEY_ALL]}
        with self._lock:
            start_seq = self._next_seq
            if from_index != 0:
                for seq, (index, _) in self._blocks.items():
                    if index > from_index:
                        start_seq = seq
                        break
            sub = Subscription(self, topics, start_seq, namespace)
            self._subs.add(sub)
            return sub

    def subscriber_count(self) -> int:
        with self._lock:
            return len(self._subs)

    def stats(self) -> dict[str, float]:
        """Provider gauges (``nomad.stream.*``): live subscriber count,
        ring depth, and cumulative slow-consumer evictions."""
        with self._lock:
            return {
                "subscribers": len(self._subs),
                "buffered_blocks": len(self._blocks),
                "evicted": self._evicted,
            }

    def _next_block(
        self, sub: Subscription, timeout_s: Optional[float]
    ) -> Optional[list[Event]]:
        with self._cv:
            while True:
                if sub._closed or self._closed:
                    raise SubscriptionClosedError()
                block = self._blocks.get(sub._seq)
                if block is not None:
                    sub._seq += 1
                    return block[1]
                if sub._seq < self._next_seq:
                    # Evicted from the ring before we read it: too slow.
                    # The ring IS the bounded queue — a consumer that
                    # can't keep up is cut loose, never buffered for.
                    sub._closed = True
                    self._subs.discard(sub)
                    self._evicted += 1
                    break
                if not self._cv.wait(timeout_s):
                    return None
        # counter bumped outside the broker lock (lock discipline)
        metrics.incr("nomad.stream.evicted_total")
        raise SubscriptionClosedError("subscriber fell behind")
