"""Cluster-scope cost attribution: who is this server spending its
time on?

Every observability layer before this one (traces, histograms,
solverobs, hostobs) answers "where does the second go" for ONE agent;
nothing attributed server-side cost to the client node, peer server, or
tenant namespace that CAUSED it — the capability ROADMAP item 4's
"bounded server-CPU-per-node" gate needs. This module is that layer:

  * :class:`SourceLedger` — a bounded top-K ledger of per-(source,
    method) call counts and handler seconds, LRU-evicting cold sources
    into an explicit ``(other)`` bucket (the hostobs pattern: coverage
    loss is COUNTED, never silent). One instance per server
    (``ClusterServer`` owns its own, so an in-process test cluster
    attributes per member); a process-global default serves bare
    ``RPCServer``\\ s.
  * source identity — :func:`source_of` derives the source for one
    inbound request: the node identity in the args when the request IS
    about a node (heartbeats, alloc updates — ``node:<id>``), else the
    dialing peer's label from the RPC envelope (server-to-server
    forwards and raft — ``srv:<id>``), else the object namespace
    (tenant-attributable writes — ``ns:<name>``), else ``(unknown)``.
    The dialer tags its envelope via :data:`~nomad_tpu.rpc.wire.SRC_KEY`.
  * thread→source registry — the RPC dispatch path publishes "this
    thread is currently serving <source>" (GIL-atomic dict stores, the
    trace.thread_spans shape) so the hostobs sampling profiler can add
    a SOURCE dimension to its CPU attribution: ``handler CPU x source
    node`` becomes answerable from ``/v1/profile/status``.

Surfaced through ``Status.peer_telemetry`` / ``GET
/v1/operator/cluster/health`` (server/cluster.py), the
``nomad.rpc.source.*`` provider gauges (docs/metrics.md), and
``operator cluster health`` / ``operator top -cluster``.

Deliberately a stdlib-only leaf (registered in analysis/rules.py
LEAF_MODULES): metrics/trace are never imported here at all — the
ledger is pull-read by providers and the health RPC.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Optional

DEFAULT_TOP_K = 128
OTHER_SOURCE = "(other)"
UNKNOWN_SOURCE = "(unknown)"

_enabled = True


def enabled() -> bool:
    return _enabled


def set_enabled(on: bool) -> None:
    """Recording gate (GIL-atomic flag): the uninstrumented side of the
    throughput comparison gate; production leaves it on."""
    global _enabled
    _enabled = bool(on)


# -- source identity ------------------------------------------------------


def source_of(envelope_src: str, args) -> str:
    """The source identity one inbound request is attributed to.

    Node identity wins when the request is ABOUT a node (a heartbeat
    forwarded leaderward should still bill the node, not the forwarding
    server), then the dialing peer's envelope label, then the tenant
    namespace, then ``(unknown)``."""
    if isinstance(args, dict):
        node_id = args.get("node_id")
        if not node_id:
            node = args.get("node")
            node_id = getattr(node, "id", None)
        if node_id:
            return f"node:{node_id}"
    if envelope_src:
        return f"srv:{envelope_src}"
    if isinstance(args, dict):
        ns = args.get("namespace")
        if not ns:
            job = args.get("job")
            ns = getattr(job, "namespace", None)
        if ns:
            return f"ns:{ns}"
    return UNKNOWN_SOURCE


# -- the bounded per-source ledger ----------------------------------------


class SourceLedger:
    """Top-K (source -> per-method calls/seconds) with LRU overflow.

    A 5k-node fleet must not grow a 5k-entry dict per method on every
    server: the ledger keeps the `top_k` most-recently-active sources
    exact and folds evicted ones into ``(other)`` (totals stay
    conserved; `evicted` counts the identity loss). Per-source method
    maps are themselves bounded — the method set is closed in practice,
    the bound only guards pathological names."""

    MAX_METHODS_PER_SOURCE = 64

    def __init__(self, top_k: int = DEFAULT_TOP_K) -> None:
        self.top_k = max(2, int(top_k))
        self._lock = threading.Lock()
        # source -> {"calls": int, "seconds": float,
        #            "methods": {method: [calls, seconds]}}
        self._sources: "OrderedDict[str, dict]" = OrderedDict()
        self.evicted = 0
        self.total_calls = 0
        self.total_seconds = 0.0
        self.unattributed_calls = 0
        self.unattributed_seconds = 0.0

    def record(self, source: str, method: str, seconds: float) -> None:
        if not _enabled:
            return
        with self._lock:
            self.total_calls += 1
            self.total_seconds += seconds
            if source == UNKNOWN_SOURCE:
                self.unattributed_calls += 1
                self.unattributed_seconds += seconds
            ent = self._sources.get(source)
            if ent is None:
                # Make room: the ledger holds at most top_k EXACT
                # sources plus the explicit (other) bucket. The
                # LEAST-recently-active exact source folds into (other)
                # — totals conserved, identity loss counted.
                real = len(self._sources) - (
                    1 if OTHER_SOURCE in self._sources else 0
                )
                if real >= self.top_k and source != OTHER_SOURCE:
                    victim = next(
                        (s for s in self._sources if s != OTHER_SOURCE),
                        None,
                    )
                    if victim is not None:
                        v = self._sources.pop(victim)
                        self.evicted += 1
                        other = self._sources.get(OTHER_SOURCE)
                        if other is None:
                            other = self._sources[OTHER_SOURCE] = {
                                "calls": 0, "seconds": 0.0,
                                "methods": {},
                            }
                        other["calls"] += v["calls"]
                        other["seconds"] += v["seconds"]
                ent = self._sources[source] = {
                    "calls": 0, "seconds": 0.0, "methods": {},
                }
            else:
                self._sources.move_to_end(source)
            ent["calls"] += 1
            ent["seconds"] += seconds
            methods = ent["methods"]
            m = methods.get(method)
            if m is None:
                if len(methods) >= self.MAX_METHODS_PER_SOURCE:
                    method = OTHER_SOURCE
                    m = methods.get(method)
                if m is None:
                    m = methods[method] = [0, 0.0]
            m[0] += 1
            m[1] += seconds

    def snapshot(self, top: int = 10, methods_per_source: int = 3) -> dict:
        """Top sources by handler seconds + coverage stats — the
        ``/v1/operator/cluster/health`` per-member payload."""
        with self._lock:
            items = [
                (src, ent["calls"], ent["seconds"], dict(ent["methods"]))
                for src, ent in self._sources.items()
            ]
            out = {
                "tracked": len(self._sources),
                "top_k": self.top_k,
                "evicted": self.evicted,
                "total_calls": self.total_calls,
                "total_seconds": round(self.total_seconds, 6),
                "unattributed_calls": self.unattributed_calls,
                "unattributed_seconds": round(
                    self.unattributed_seconds, 6
                ),
            }
        items.sort(key=lambda it: -it[2])
        out["coverage"] = (
            round(
                1.0 - out["unattributed_seconds"]
                / max(out["total_seconds"], 1e-12),
                4,
            )
            if out["total_calls"]
            else 1.0
        )
        out["top"] = [
            {
                "source": src,
                "calls": calls,
                "seconds": round(secs, 6),
                "methods": {
                    name: {"calls": c, "seconds": round(s, 6)}
                    for name, (c, s) in sorted(
                        meths.items(), key=lambda kv: -kv[1][1]
                    )[: max(1, methods_per_source)]
                },
            }
            for src, calls, secs, meths in items[: max(1, top)]
        ]
        return out

    def stats(self) -> dict:
        """Bounded-cardinality provider gauges (``nomad.rpc.source.*``
        rides the registry; per-source values stay in the ledger)."""
        with self._lock:
            return {
                "tracked": float(len(self._sources)),
                "evicted": float(self.evicted),
                "calls": float(self.total_calls),
                "seconds": round(self.total_seconds, 6),
                "unattributed_calls": float(self.unattributed_calls),
                "unattributed_seconds": round(
                    self.unattributed_seconds, 6
                ),
            }

    def reset(self) -> None:
        with self._lock:
            self._sources.clear()
            self.evicted = 0
            self.total_calls = 0
            self.total_seconds = 0.0
            self.unattributed_calls = 0
            self.unattributed_seconds = 0.0


def merge_top_sources(rows, top: int = 5) -> list[dict]:
    """Merge per-member ``snapshot()["top"]`` rows into one fleet-wide
    top-K: calls/seconds summed per source, heaviest seconds first.
    Shared by the cluster_health fleet block and run_soak's report so
    the two surfaces can never drift."""
    merged: dict[str, list] = {}
    for row in rows:
        agg = merged.setdefault(row["source"], [0, 0.0])
        agg[0] += row["calls"]
        agg[1] += row["seconds"]
    return [
        {"source": src, "calls": calls, "seconds": round(secs, 6)}
        for src, (calls, secs) in sorted(
            merged.items(), key=lambda kv: -kv[1][1]
        )[: max(1, int(top))]
    ]


# -- thread -> active-source registry (the hostobs source dimension) ------

# tid -> source, maintained by the RPC dispatch paths around handler
# execution. GIL-atomic dict stores/deletes, same discipline as
# trace.py's thread->span registry: the sampling profiler reads it
# from its own thread without locks.
_thread_sources: dict[int, str] = {}


def set_thread_source(source: str) -> None:
    _thread_sources[threading.get_ident()] = source


def clear_thread_source() -> None:
    _thread_sources.pop(threading.get_ident(), None)


def thread_sources() -> dict[int, str]:
    """Live view for the sampler (reads are GIL-atomic; the sampler
    copies nothing on the fast path)."""
    return _thread_sources


def prune_thread_sources(live_tids) -> None:
    """Drop dead threads' entries (hostobs flush calls this alongside
    trace.prune_thread_spans)."""
    for tid in [t for t in _thread_sources if t not in live_tids]:
        _thread_sources.pop(tid, None)


# -- lightweight host summary (peer_telemetry's CPU/RSS block) ------------


def host_summary() -> dict:
    """Process-level host cost: CPU seconds (all threads, monotonic),
    RSS, thread count. In production one agent is one process so these
    ARE the server's numbers; in-process test clusters share a process
    and the docs say so (docs/operations.md)."""
    rss = 0
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    rss = int(line.split()[1]) * 1024
                    break
    except (OSError, ValueError, IndexError):
        pass
    return {
        "cpu_seconds": round(time.process_time(), 3),
        "rss_bytes": rss,
        "threads": threading.active_count(),
    }


# -- process-global default ledger ---------------------------------------

_global = SourceLedger()


def ledger() -> SourceLedger:
    return _global


def _install(lg: SourceLedger) -> SourceLedger:
    """Swap the process-global default ledger (test isolation hook,
    mirroring hostobs._install). Servers that own their ledger are
    unaffected."""
    global _global
    old = _global
    _global = lg
    return old
