/* fastpack: native msgpack encoder for the wire codec.
 *
 * The wire codec (nomad_tpu/codec.py) is on every hot path that
 * matters at c2m scale — raft replication of plan results, RPC
 * payloads, state snapshots. Encoding 10^5 Allocations per plan in
 * interpreted Python was the plan applier's largest cost, so the
 * ENCODE side lives here as a CPython extension; decode stays in
 * Python: measured head-to-head, msgpack's C unpacker + the generated
 * dataclass __init__ beat a C-side __new__+setattr loop on 3.12.
 *
 * Wire format parity with codec.to_wire(_elide=True) is exact:
 *   scalars/str/bytes  -> native msgpack
 *   list/set/frozenset -> array
 *   tuple              -> {"$tuple": [...]}
 *   dict (str keys, no "$" prefix) -> map
 *   dict (other)       -> {"$map": [[k, v], ...]}
 *   registered dataclass -> {"$t": ClassName, <non-default fields>}
 *     field elided iff it has a declared default, the value's exact
 *     class matches the default's, and value == default
 *   registered __dict__ class (JobSummary et al) -> {"$t": ..., **vars}
 *
 * Anything else raises Fallback; the Python wrapper re-encodes the
 * whole payload with the pure-Python path, so behavior can never
 * diverge — only speed.
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <stdint.h>
#include <string.h>

/* ------------------------------------------------------------------ */
/* growable output buffer                                              */

typedef struct {
  char *buf;
  Py_ssize_t len;
  Py_ssize_t cap;
} Out;

static int out_reserve(Out *o, Py_ssize_t extra) {
  if (o->len + extra <= o->cap) return 0;
  Py_ssize_t ncap = o->cap ? o->cap * 2 : 4096;
  while (ncap < o->len + extra) ncap *= 2;
  char *nb = PyMem_Realloc(o->buf, ncap);
  if (!nb) {
    PyErr_NoMemory();
    return -1;
  }
  o->buf = nb;
  o->cap = ncap;
  return 0;
}

static int out_byte(Out *o, unsigned char b) {
  if (out_reserve(o, 1) < 0) return -1;
  o->buf[o->len++] = (char)b;
  return 0;
}

static int out_bytes(Out *o, const char *p, Py_ssize_t n) {
  if (out_reserve(o, n) < 0) return -1;
  memcpy(o->buf + o->len, p, n);
  o->len += n;
  return 0;
}

static int out_u16(Out *o, uint16_t v) {
  unsigned char b[2] = {(unsigned char)(v >> 8), (unsigned char)v};
  return out_bytes(o, (char *)b, 2);
}

static int out_u32(Out *o, uint32_t v) {
  unsigned char b[4] = {(unsigned char)(v >> 24), (unsigned char)(v >> 16),
                        (unsigned char)(v >> 8), (unsigned char)v};
  return out_bytes(o, (char *)b, 4);
}

static int out_u64(Out *o, uint64_t v) {
  unsigned char b[8];
  for (int i = 0; i < 8; i++) b[i] = (unsigned char)(v >> (56 - 8 * i));
  return out_bytes(o, (char *)b, 8);
}

/* ------------------------------------------------------------------ */
/* msgpack primitives. Multi-step emits chain with BITWISE `|`: every
 * step returns 0/-1 and -1 must reach the caller's `< 0` check (`||`
 * would collapse -1 to 1 and read as success). Steps after a failure
 * may still run; the buffer is discarded on error, so that is moot.   */

static int emit_nil(Out *o) { return out_byte(o, 0xc0); }

static int emit_bool(Out *o, int truth) {
  return out_byte(o, truth ? 0xc3 : 0xc2);
}

static int emit_int64(Out *o, int64_t v) {
  if (v >= 0) {
    if (v < 0x80) return out_byte(o, (unsigned char)v);
    if (v <= 0xff)
      return out_byte(o, 0xcc) | out_byte(o, (unsigned char)v);
    if (v <= 0xffff) return out_byte(o, 0xcd) | out_u16(o, (uint16_t)v);
    if (v <= 0xffffffffLL)
      return out_byte(o, 0xce) | out_u32(o, (uint32_t)v);
    return out_byte(o, 0xcf) | out_u64(o, (uint64_t)v);
  }
  if (v >= -32) return out_byte(o, (unsigned char)(0xe0 | (v + 32)));
  if (v >= -128)
    return out_byte(o, 0xd0) | out_byte(o, (unsigned char)(uint8_t)v);
  if (v >= -32768)
    return out_byte(o, 0xd1) | out_u16(o, (uint16_t)(int16_t)v);
  if (v >= -2147483648LL)
    return out_byte(o, 0xd2) | out_u32(o, (uint32_t)(int32_t)v);
  return out_byte(o, 0xd3) | out_u64(o, (uint64_t)v);
}

static int emit_double(Out *o, double d) {
  union {
    double d;
    uint64_t u;
  } u;
  u.d = d;
  return out_byte(o, 0xcb) | out_u64(o, u.u);
}

static int emit_str(Out *o, const char *p, Py_ssize_t n) {
  int rc;
  if (n < 32)
    rc = out_byte(o, (unsigned char)(0xa0 | n));
  else if (n <= 0xff)
    rc = out_byte(o, 0xd9) | out_byte(o, (unsigned char)n);
  else if (n <= 0xffff)
    rc = out_byte(o, 0xda) | out_u16(o, (uint16_t)n);
  else
    rc = out_byte(o, 0xdb) | out_u32(o, (uint32_t)n);
  return rc | out_bytes(o, p, n);
}

static int emit_bin(Out *o, const char *p, Py_ssize_t n) {
  int rc;
  if (n <= 0xff)
    rc = out_byte(o, 0xc4) | out_byte(o, (unsigned char)n);
  else if (n <= 0xffff)
    rc = out_byte(o, 0xc5) | out_u16(o, (uint16_t)n);
  else
    rc = out_byte(o, 0xc6) | out_u32(o, (uint32_t)n);
  return rc | out_bytes(o, p, n);
}

static int emit_array_header(Out *o, Py_ssize_t n) {
  if (n < 16) return out_byte(o, (unsigned char)(0x90 | n));
  if (n <= 0xffff) return out_byte(o, 0xdc) | out_u16(o, (uint16_t)n);
  return out_byte(o, 0xdd) | out_u32(o, (uint32_t)n);
}

static int emit_map_header(Out *o, Py_ssize_t n) {
  if (n < 16) return out_byte(o, (unsigned char)(0x80 | n));
  if (n <= 0xffff) return out_byte(o, 0xde) | out_u16(o, (uint16_t)n);
  return out_byte(o, 0xdf) | out_u32(o, (uint32_t)n);
}

/* ------------------------------------------------------------------ */
/* module state                                                        */

static PyObject *Registry;      /* dict: type -> plan tuple | None      */
static PyObject *FallbackError; /* raised for unsupported objects       */

#define MAX_FIELDS 96
#define MAX_DEPTH 64

static int encode(Out *o, PyObject *obj, int depth);

static int emit_pystr(Out *o, PyObject *s) {
  Py_ssize_t n;
  const char *p = PyUnicode_AsUTF8AndSize(s, &n);
  if (!p) return -1;
  return emit_str(o, p, n);
}

/* a plain dict: str keys without "$" -> map; else $map pair list */
static int encode_dict(Out *o, PyObject *d, int depth) {
  Py_ssize_t pos = 0;
  PyObject *k, *v;
  int plain = 1;
  while (PyDict_Next(d, &pos, &k, &v)) {
    if (!PyUnicode_CheckExact(k)) {
      plain = 0;
      break;
    }
    Py_ssize_t n;
    const char *p = PyUnicode_AsUTF8AndSize(k, &n);
    if (!p) return -1;
    if (n > 0 && p[0] == '$') {
      plain = 0;
      break;
    }
  }
  if (plain) {
    if (emit_map_header(o, PyDict_Size(d)) < 0) return -1;
    pos = 0;
    while (PyDict_Next(d, &pos, &k, &v)) {
      if (emit_pystr(o, k) < 0) return -1;
      if (encode(o, v, depth) < 0) return -1;
    }
    return 0;
  }
  /* {"$map": [[k, v], ...]} */
  if (emit_map_header(o, 1) < 0) return -1;
  if (emit_str(o, "$map", 4) < 0) return -1;
  if (emit_array_header(o, PyDict_Size(d)) < 0) return -1;
  pos = 0;
  while (PyDict_Next(d, &pos, &k, &v)) {
    if (emit_array_header(o, 2) < 0) return -1;
    if (encode(o, k, depth) < 0) return -1;
    if (encode(o, v, depth) < 0) return -1;
  }
  return 0;
}

static int encode_registered(Out *o, PyObject *obj, PyObject *plan,
                             int depth) {
  PyTypeObject *tp = Py_TYPE(obj);
  if (plan == Py_None) {
    /* __dict__ round-trip (JobSummary et al) */
    PyObject *d = PyObject_GenericGetDict(obj, NULL);
    if (!d) return -1;
    Py_ssize_t n = PyDict_Size(d);
    const char *full = tp->tp_name;
    const char *dot = strrchr(full, '.');
    const char *nm = dot ? dot + 1 : full;
    if (emit_map_header(o, n + 1) < 0 || emit_str(o, "$t", 2) < 0 ||
        emit_str(o, nm, strlen(nm)) < 0) {
      Py_DECREF(d);
      return -1;
    }
    Py_ssize_t pos = 0;
    PyObject *k, *v;
    while (PyDict_Next(d, &pos, &k, &v)) {
      if (emit_pystr(o, k) < 0 || encode(o, v, depth) < 0) {
        Py_DECREF(d);
        return -1;
      }
    }
    Py_DECREF(d);
    return 0;
  }
  /* dataclass plan: tuple of (name, default, has_default) */
  Py_ssize_t nf = PyTuple_GET_SIZE(plan);
  if (nf > MAX_FIELDS) {
    PyErr_SetString(FallbackError, "too many fields");
    return -1;
  }
  PyObject *names[MAX_FIELDS];
  PyObject *vals[MAX_FIELDS];
  Py_ssize_t emit_n = 0;
  int rc = -1;
  for (Py_ssize_t i = 0; i < nf; i++) {
    PyObject *spec = PyTuple_GET_ITEM(plan, i); /* (name, default, has) */
    PyObject *name = PyTuple_GET_ITEM(spec, 0);
    PyObject *dflt = PyTuple_GET_ITEM(spec, 1);
    int has_default = PyObject_IsTrue(PyTuple_GET_ITEM(spec, 2));
    PyObject *v = PyObject_GetAttr(obj, name);
    if (!v) goto done;
    if (has_default && Py_TYPE(v) == Py_TYPE(dflt)) {
      int eq = PyObject_RichCompareBool(v, dflt, Py_EQ);
      if (eq < 0) {
        Py_DECREF(v);
        goto done;
      }
      if (eq) {
        Py_DECREF(v);
        continue; /* elided */
      }
    }
    names[emit_n] = name;
    vals[emit_n] = v; /* owned */
    emit_n++;
  }
  if (emit_map_header(o, emit_n + 1) < 0) goto done;
  if (emit_str(o, "$t", 2) < 0) goto done;
  {
    /* class name: use the short name like Python's cls.__name__ */
    const char *full = Py_TYPE(obj)->tp_name;
    const char *dot = strrchr(full, '.');
    const char *nm = dot ? dot + 1 : full;
    if (emit_str(o, nm, strlen(nm)) < 0) goto done;
  }
  for (Py_ssize_t i = 0; i < emit_n; i++) {
    if (emit_pystr(o, names[i]) < 0) goto done;
    if (encode(o, vals[i], depth) < 0) goto done;
  }
  rc = 0;
done:
  for (Py_ssize_t i = 0; i < emit_n; i++) Py_DECREF(vals[i]);
  return rc;
}

static int encode(Out *o, PyObject *obj, int depth) {
  if (depth > MAX_DEPTH) {
    PyErr_SetString(FallbackError, "depth");
    return -1;
  }
  depth++;
  if (obj == Py_None) return emit_nil(o);
  if (obj == Py_True) return emit_bool(o, 1);
  if (obj == Py_False) return emit_bool(o, 0);
  PyTypeObject *tp = Py_TYPE(obj);
  if (tp == &PyLong_Type) {
    int overflow = 0;
    int64_t v = PyLong_AsLongLongAndOverflow(obj, &overflow);
    if (overflow) {
      PyErr_SetString(FallbackError, "bigint");
      return -1;
    }
    if (v == -1 && PyErr_Occurred()) return -1;
    return emit_int64(o, v);
  }
  if (tp == &PyFloat_Type) return emit_double(o, PyFloat_AS_DOUBLE(obj));
  if (tp == &PyUnicode_Type) return emit_pystr(o, obj);
  if (tp == &PyBytes_Type)
    return emit_bin(o, PyBytes_AS_STRING(obj), PyBytes_GET_SIZE(obj));
  if (tp == &PyList_Type) {
    Py_ssize_t n = PyList_GET_SIZE(obj);
    if (emit_array_header(o, n) < 0) return -1;
    for (Py_ssize_t i = 0; i < n; i++)
      if (encode(o, PyList_GET_ITEM(obj, i), depth) < 0) return -1;
    return 0;
  }
  if (tp == &PyTuple_Type) {
    /* {"$tuple": [...]} */
    Py_ssize_t n = PyTuple_GET_SIZE(obj);
    if (emit_map_header(o, 1) < 0 || emit_str(o, "$tuple", 6) < 0 ||
        emit_array_header(o, n) < 0)
      return -1;
    for (Py_ssize_t i = 0; i < n; i++)
      if (encode(o, PyTuple_GET_ITEM(obj, i), depth) < 0) return -1;
    return 0;
  }
  if (tp == &PyDict_Type) return encode_dict(o, obj, depth);
  if (tp == &PySet_Type || tp == &PyFrozenSet_Type) {
    Py_ssize_t n = PySet_GET_SIZE(obj);
    if (emit_array_header(o, n) < 0) return -1;
    PyObject *it = PyObject_GetIter(obj);
    if (!it) return -1;
    PyObject *item;
    while ((item = PyIter_Next(it))) {
      int rc = encode(o, item, depth);
      Py_DECREF(item);
      if (rc < 0) {
        Py_DECREF(it);
        return -1;
      }
    }
    Py_DECREF(it);
    return PyErr_Occurred() ? -1 : 0;
  }
  /* registered struct? */
  {
    PyObject *plan = PyDict_GetItem(Registry, (PyObject *)tp); /* borrowed */
    if (plan) return encode_registered(o, obj, plan, depth);
  }
  /* bool/int/str SUBCLASSES and anything else: let Python handle it */
  PyErr_Format(FallbackError, "unsupported type %s", tp->tp_name);
  return -1;
}

/* ------------------------------------------------------------------ */
/* bulk helpers for the array-native data plane. Each has a pure-
 * Python / numpy fallback with identical behavior (structs/structs.py
 * _uuid_hex_py, placement_batch._wire_rows_py, network.py
 * _pick_ports_py) — the extension buys only speed, never semantics.  */

/* uuid_hex(raw: bytes) -> list[str]: one uuid4-shaped "8-4-4-4-12"
 * string per 16 input bytes (the bulk id-minting formatter).          */
static PyObject *py_uuid_hex(PyObject *self, PyObject *arg) {
  static const char hexd[] = "0123456789abcdef";
  const char *raw;
  Py_ssize_t n;
  if (PyBytes_Check(arg)) {
    raw = PyBytes_AS_STRING(arg);
    n = PyBytes_GET_SIZE(arg);
  } else {
    PyErr_SetString(PyExc_TypeError, "uuid_hex expects bytes");
    return NULL;
  }
  if (n % 16 != 0) {
    PyErr_SetString(PyExc_ValueError, "length must be a multiple of 16");
    return NULL;
  }
  Py_ssize_t k = n / 16;
  PyObject *out = PyList_New(k);
  if (!out) return NULL;
  for (Py_ssize_t i = 0; i < k; i++) {
    PyObject *s = PyUnicode_New(36, 127);
    if (!s) {
      Py_DECREF(out);
      return NULL;
    }
    Py_UCS1 *d = PyUnicode_1BYTE_DATA(s);
    const unsigned char *b = (const unsigned char *)raw + i * 16;
    int w = 0;
    for (int j = 0; j < 16; j++) {
      if (j == 4 || j == 6 || j == 8 || j == 10) d[w++] = '-';
      d[w++] = hexd[b[j] >> 4];
      d[w++] = hexd[b[j] & 0xf];
    }
    PyList_SET_ITEM(out, i, s);
  }
  return out;
}

/* wire_rows(template: dict, ids, names, node_ids, node_names) ->
 * list[dict]: the SoA plan-row assembly — one template copy + the four
 * per-row field stores per row, in C (placement_batch.extend_wire_rows).*/
static PyObject *py_wire_rows(PyObject *self, PyObject *args) {
  PyObject *template, *ids, *names, *node_ids, *node_names;
  if (!PyArg_ParseTuple(args, "O!O!O!O!O!", &PyDict_Type, &template,
                        &PyList_Type, &ids, &PyList_Type, &names,
                        &PyList_Type, &node_ids, &PyList_Type, &node_names))
    return NULL;
  Py_ssize_t k = PyList_GET_SIZE(ids);
  if (PyList_GET_SIZE(names) != k || PyList_GET_SIZE(node_ids) != k ||
      PyList_GET_SIZE(node_names) != k) {
    PyErr_SetString(PyExc_ValueError, "column length mismatch");
    return NULL;
  }
  /* guard on the LAST key assigned: a partial init failure must retry
   * the whole set next call, never skip to NULL PyDict_SetItem keys   */
  static PyObject *k_id, *k_name, *k_node_id, *k_node_name;
  if (!k_node_name) {
    k_id = PyUnicode_InternFromString("id");
    k_name = PyUnicode_InternFromString("name");
    k_node_id = PyUnicode_InternFromString("node_id");
    k_node_name = PyUnicode_InternFromString("node_name");
    if (!k_id || !k_name || !k_node_id || !k_node_name) {
      k_node_name = NULL;
      return NULL;
    }
  }
  PyObject *out = PyList_New(k);
  if (!out) return NULL;
  for (Py_ssize_t i = 0; i < k; i++) {
    PyObject *d = PyDict_Copy(template);
    if (!d) goto fail;
    if (PyDict_SetItem(d, k_id, PyList_GET_ITEM(ids, i)) < 0 ||
        PyDict_SetItem(d, k_name, PyList_GET_ITEM(names, i)) < 0 ||
        PyDict_SetItem(d, k_node_id, PyList_GET_ITEM(node_ids, i)) < 0 ||
        PyDict_SetItem(d, k_node_name, PyList_GET_ITEM(node_names, i)) < 0) {
      Py_DECREF(d);
      goto fail;
    }
    PyList_SET_ITEM(out, i, d);
  }
  return out;
fail:
  Py_DECREF(out);
  return NULL;
}

/* pick_ports(taken: bytes bitmap over [min, max], k, min, max, seed)
 * -> list[int] | None. Deterministic given seed: per port, up to 20
 * LCG draws, then a linear scan from the range floor — the numpy/
 * Python fallback (network.py _pick_ports_py) runs the SAME LCG so the
 * two paths pick identical ports for one seed.                        */
static PyObject *py_pick_ports(PyObject *self, PyObject *args) {
  Py_buffer taken;
  long k, lo, hi;
  unsigned long long seed;
  if (!PyArg_ParseTuple(args, "y*lllK", &taken, &k, &lo, &hi, &seed))
    return NULL;
  long span = hi - lo + 1;
  if (span <= 0 || taken.len * 8 < span) {
    PyBuffer_Release(&taken);
    PyErr_SetString(PyExc_ValueError, "bitmap smaller than port range");
    return NULL;
  }
  unsigned char *bits = (unsigned char *)PyMem_Malloc(taken.len);
  if (!bits) {
    PyBuffer_Release(&taken);
    return PyErr_NoMemory();
  }
  memcpy(bits, taken.buf, taken.len);
  PyBuffer_Release(&taken);
  PyObject *out = PyList_New(0);
  if (!out) {
    PyMem_Free(bits);
    return NULL;
  }
  uint64_t x = (uint64_t)seed;
  for (long i = 0; i < k; i++) {
    long got = -1;
    for (int attempt = 0; attempt < 20; attempt++) {
      x = x * 6364136223846793005ULL + 1442695040888963407ULL;
      long off = (long)((x >> 33) % (uint64_t)span);
      if (!(bits[off >> 3] & (1 << (off & 7)))) {
        got = off;
        break;
      }
    }
    if (got < 0) {
      for (long off = 0; off < span; off++) {
        if (!(bits[off >> 3] & (1 << (off & 7)))) {
          got = off;
          break;
        }
      }
    }
    if (got < 0) {
      Py_DECREF(out);
      PyMem_Free(bits);
      Py_RETURN_NONE; /* range exhausted */
    }
    bits[got >> 3] |= (unsigned char)(1 << (got & 7));
    PyObject *port = PyLong_FromLong(lo + got);
    if (!port || PyList_Append(out, port) < 0) {
      Py_XDECREF(port);
      Py_DECREF(out);
      PyMem_Free(bits);
      return NULL;
    }
    Py_DECREF(port);
  }
  PyMem_Free(bits);
  return out;
}

/* store_rows(ids, handles, idx_raw, main, job_inner, eval_inner,
 * node_inners) -> None: the bulk id-index insert for one SoA placement
 * batch. Rows are grouped per node — FIRST-TOUCH node order, row order
 * within a node, the exact insertion sequence the eager per-row txn
 * produces from a node_allocation dict — and each row gets the four
 * dict inserts (main table + job/eval/node inners) in C under the GIL.
 * idx_raw is the batch's int32 node-index column as raw bytes
 * (PlacementBatch.node_idx_raw); node_inners maps int node-table index
 * -> writable inner dict. Fallback: store._store_rows_py (identical
 * loop; the byte-identity battery compares serialized state).         */
static PyObject *py_store_rows(PyObject *self, PyObject *args) {
  PyObject *ids, *handles, *main_t, *job_t, *eval_t, *node_inners;
  Py_buffer idx;
  if (!PyArg_ParseTuple(args, "O!O!y*O!O!O!O!", &PyList_Type, &ids,
                        &PyList_Type, &handles, &idx, &PyDict_Type, &main_t,
                        &PyDict_Type, &job_t, &PyDict_Type, &eval_t,
                        &PyDict_Type, &node_inners))
    return NULL;
  Py_ssize_t n = PyList_GET_SIZE(ids);
  if (PyList_GET_SIZE(handles) != n ||
      idx.len != n * (Py_ssize_t)sizeof(int32_t)) {
    PyBuffer_Release(&idx);
    PyErr_SetString(PyExc_ValueError, "column length mismatch");
    return NULL;
  }
  if (n == 0) {
    PyBuffer_Release(&idx);
    Py_RETURN_NONE;
  }
  const int32_t *ti = (const int32_t *)idx.buf;
  int32_t max_ti = 0;
  for (Py_ssize_t i = 0; i < n; i++) {
    if (ti[i] < 0) {
      PyBuffer_Release(&idx);
      PyErr_SetString(PyExc_ValueError, "negative node index");
      return NULL;
    }
    if (ti[i] > max_ti) max_ti = ti[i];
  }
  /* first-touch grouping: per-node linked list of row indices (head/
   * tail per node index, next per row, distinct nodes in touch order) */
  size_t m = (size_t)max_ti + 1;
  Py_ssize_t *head = (Py_ssize_t *)PyMem_Malloc(m * 2 * sizeof(Py_ssize_t));
  Py_ssize_t *next = (Py_ssize_t *)PyMem_Malloc((size_t)n * sizeof(Py_ssize_t));
  int32_t *order = (int32_t *)PyMem_Malloc((size_t)n * sizeof(int32_t));
  if (!head || !next || !order) {
    PyMem_Free(head);
    PyMem_Free(next);
    PyMem_Free(order);
    PyBuffer_Release(&idx);
    return PyErr_NoMemory();
  }
  Py_ssize_t *tail = head + m;
  for (size_t j = 0; j < m; j++) head[j] = -1;
  Py_ssize_t norder = 0;
  for (Py_ssize_t i = 0; i < n; i++) {
    int32_t t = ti[i];
    if (head[t] < 0) {
      head[t] = i;
      order[norder++] = t;
    } else {
      next[tail[t]] = i;
    }
    tail[t] = i;
    next[i] = -1;
  }
  int ok = 1;
  for (Py_ssize_t g = 0; g < norder && ok; g++) {
    int32_t t = order[g];
    PyObject *key = PyLong_FromLong((long)t);
    if (!key) {
      ok = 0;
      break;
    }
    PyObject *node_t = PyDict_GetItemWithError(node_inners, key);
    Py_DECREF(key);
    if (!node_t) {
      if (!PyErr_Occurred())
        PyErr_Format(PyExc_KeyError, "missing node inner for index %d",
                     (int)t);
      ok = 0;
      break;
    }
    if (!PyDict_Check(node_t)) {
      PyErr_SetString(PyExc_TypeError, "node inner must be a dict");
      ok = 0;
      break;
    }
    for (Py_ssize_t i = head[t]; i >= 0; i = next[i]) {
      PyObject *uid = PyList_GET_ITEM(ids, i);
      PyObject *h = PyList_GET_ITEM(handles, i);
      if (PyDict_SetItem(main_t, uid, h) < 0 ||
          PyDict_SetItem(job_t, uid, h) < 0 ||
          PyDict_SetItem(eval_t, uid, h) < 0 ||
          PyDict_SetItem(node_t, uid, h) < 0) {
        ok = 0;
        break;
      }
    }
  }
  PyMem_Free(head);
  PyMem_Free(next);
  PyMem_Free(order);
  PyBuffer_Release(&idx);
  if (!ok) return NULL;
  Py_RETURN_NONE;
}

/* ------------------------------------------------------------------ */
/* module API                                                          */

static PyObject *py_pack(PyObject *self, PyObject *obj) {
  Out o = {NULL, 0, 0};
  if (encode(&o, obj, 0) < 0) {
    PyMem_Free(o.buf);
    return NULL;
  }
  PyObject *res = PyBytes_FromStringAndSize(o.buf, o.len);
  PyMem_Free(o.buf);
  return res;
}

static PyObject *py_register_class(PyObject *self, PyObject *args) {
  PyObject *cls, *plan;
  if (!PyArg_ParseTuple(args, "OO", &cls, &plan)) return NULL;
  if (!PyType_Check(cls)) {
    PyErr_SetString(PyExc_TypeError, "first arg must be a type");
    return NULL;
  }
  if (plan != Py_None && !PyTuple_Check(plan)) {
    PyErr_SetString(PyExc_TypeError, "plan must be a tuple or None");
    return NULL;
  }
  if (PyDict_SetItem(Registry, cls, plan) < 0) return NULL;
  Py_RETURN_NONE;
}

static PyObject *py_clear_registry(PyObject *self, PyObject *noarg) {
  PyDict_Clear(Registry);
  Py_RETURN_NONE;
}

static PyMethodDef methods[] = {
    {"pack", py_pack, METH_O,
     "Encode a wire payload to msgpack bytes (elide-defaults format)."},
    {"register_class", py_register_class, METH_VARARGS,
     "register_class(cls, plan): plan = ((name, default, has_default), "
     "...) for dataclasses, None for __dict__ round-trip types."},
    {"clear_registry", py_clear_registry, METH_NOARGS, "Forget classes."},
    {"uuid_hex", py_uuid_hex, METH_O,
     "uuid_hex(raw): one uuid4-shaped string per 16 bytes of entropy."},
    {"wire_rows", py_wire_rows, METH_VARARGS,
     "wire_rows(template, ids, names, node_ids, node_names): bulk "
     "plan-row wire maps from SoA columns."},
    {"pick_ports", py_pick_ports, METH_VARARGS,
     "pick_ports(taken_bitmap, k, min, max, seed): k distinct free "
     "ports, deterministic per seed (LCG + linear-scan fallback)."},
    {"store_rows", py_store_rows, METH_VARARGS,
     "store_rows(ids, handles, idx_raw, main, job_inner, eval_inner, "
     "node_inners): bulk node-grouped id-index inserts for one SoA "
     "placement batch (state.store._upsert_batches_txn)."},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "fastpack", NULL, -1, methods,
};

PyMODINIT_FUNC PyInit_fastpack(void) {
  PyObject *m = PyModule_Create(&moduledef);
  if (!m) return NULL;
  Registry = PyDict_New();
  if (!Registry) return NULL;
  FallbackError =
      PyErr_NewException("fastpack.Fallback", PyExc_TypeError, NULL);
  if (!FallbackError) return NULL;
  if (PyModule_AddObject(m, "Fallback", FallbackError) < 0) return NULL;
  Py_INCREF(FallbackError);
  return m;
}
