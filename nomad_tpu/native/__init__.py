"""Native runtime components, compiled on demand and cached by source
hash (the same discipline as drivers/executor.py):

  * executor.cc — the daemonized task supervisor (drivers/native/)
  * fastpack.c  — the wire codec's msgpack encoder (this package)
"""

from __future__ import annotations

import hashlib
import importlib.machinery
import importlib.util
import os
import shutil
import subprocess
import sysconfig
import threading
from pathlib import Path
from typing import Optional

_SRC = Path(__file__).parent / "fastpack.c"
_LOCK = threading.Lock()
_module = None
_load_failed = False

# The extension's public surface. codec.warm_native() resolving the
# module resolves EVERY entry point at once (one .so, one build) — no
# caller can trigger a lock-held C compile by touching a "new" function
# later (the NV-lock-blocking rule warm_native exists for). Each entry
# has a behavior-identical Python/numpy fallback; tests/test_native.py
# pins this list against the C PyMethodDef table and the fallbacks.
FASTPACK_ENTRY_POINTS = (
    "pack",          # elide-defaults msgpack encoder (codec.pack)
    "register_class",  # class-plan registry sync (codec._fastpack_module)
    "clear_registry",
    "uuid_hex",      # bulk id formatting (structs.generate_uuids)
    "wire_rows",     # SoA plan-row wire assembly (placement_batch)
    "pick_ports",    # bulk dynamic-port picking (structs.network)
    "store_rows",    # bulk store id-index inserts (state.store)
)

# Wall seconds load_fastpack spent making the extension importable in
# this process (compile on a cold cache, dlopen on a warm one); -1.0
# until attempted. codec.warm_native publishes it as
# nomad.native.build_seconds so an operator can see cold builds.
last_build_seconds: float = -1.0


def load_fastpack():
    """Compile (once) and import the fastpack extension; None when the
    toolchain is unavailable — callers fall back to pure Python."""
    global _module, _load_failed, last_build_seconds
    if _module is not None or _load_failed:
        return _module
    with _LOCK:
        if _module is not None or _load_failed:
            return _module
        import time

        t0 = time.monotonic()
        try:
            _module = _build_and_load()
        except Exception:
            import logging

            logging.getLogger("nomad_tpu.native").exception(
                "fastpack build failed; using the pure-Python encoder"
            )
            _load_failed = True
        last_build_seconds = time.monotonic() - t0
    return _module


def _build_and_load():
    if os.environ.get("NOMAD_TPU_NO_FASTPACK"):
        raise RuntimeError("fastpack disabled by env")
    cache = Path(
        os.environ.get("NOMAD_TPU_BIN_DIR")
        or Path.home() / ".cache" / "nomad_tpu" / "bin"
    )
    src = _SRC.read_bytes()
    tag = hashlib.sha256(src).hexdigest()[:16]
    so = cache / f"fastpack-{tag}.so"
    if not so.exists():
        cache.mkdir(parents=True, exist_ok=True)
        cc = shutil.which("gcc") or shutil.which("cc") or shutil.which("g++")
        if cc is None:
            raise RuntimeError("no C compiler")
        include = sysconfig.get_paths()["include"]
        tmp = str(so) + ".tmp"
        proc = subprocess.run(
            [cc, "-O2", "-shared", "-fPIC", f"-I{include}",
             "-o", tmp, str(_SRC)],
            capture_output=True, text=True, timeout=120,
        )
        if proc.returncode != 0:
            raise RuntimeError(f"fastpack compile failed: {proc.stderr[:400]}")
        os.replace(tmp, so)
    loader = importlib.machinery.ExtensionFileLoader("fastpack", str(so))
    spec = importlib.util.spec_from_loader("fastpack", loader)
    mod = importlib.util.module_from_spec(spec)
    loader.exec_module(mod)
    return mod
