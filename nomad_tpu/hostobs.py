"""Continuous host-profiling: span-correlated CPU attribution, runtime
telemetry (GC / RSS / fds / threads), and lock-wait accounting.

The solver's own telemetry (VERDICT r5, trace/solverobs) shows ~86% of a
c2m batch is host-side Python — but nothing attributed that second to
CODE: traces give stage wall time, the compile ledger covers the device,
and the only CPU profiler was the on-demand, enable_debug-gated capture
in agent/debug.py. This module is the always-on layer, in the spirit of
fleet continuous profilers (Google-Wide Profiling; Pyroscope/Parca):

  * sampling profiler — a background thread samples
    ``sys._current_frames()`` on an interval adaptive to load and
    attributes each busy sample to **(thread role x active trace span x
    leaf function)**, using the per-thread active-span registry
    maintained by nomad_tpu/trace.py (``trace.thread_spans()``). The
    pipelined worker's solve and commit threads profile as distinct
    roles. Ledgers are bounded (site/stack overflow aggregates into an
    explicit ``(other)`` bucket — coverage loss is COUNTED, never
    silent), and the idle fast path allocates nothing: a thread whose
    leaf frame is a known blocking wait is skipped before any tuple or
    string is built.
  * runtime telemetry — GC pause/collection accounting via
    ``gc.callbacks`` (pauses are buffered in the callback and flushed to
    the metrics registry by the sampler thread: the callback itself can
    fire while ANY lock — including the registry's — is held by the
    collecting thread, so it must never take one), gctune paused-GC
    section accounting (gctune.on_section_end), and RSS / fd-count /
    thread-count / gc-generation gauges sampled once per flush interval.
  * lock-wait attribution — :class:`TimedLock` wraps the hot locks
    (eval broker, plan queue, metrics registry): the uncontended path is
    a single non-blocking try-acquire (no timestamps, no allocation);
    only a CONTENDED acquire takes two clock reads and lands in the
    per-lock wait ledger + ``nomad.runtime.lock_wait_seconds.<lock>``.

Deliberately a stdlib-only leaf (like solverobs/faultplane): metrics and
trace are imported lazily inside functions so metrics.py itself can use
TimedLock without an import cycle.

Surfaces: ``GET /v1/profile/status`` (summary) and
``GET /v1/profile/collapsed`` (collapsed-stack flamegraph text) behind
``agent:read`` — always on, unlike the enable_debug-gated pprof capture;
``operator profile status|top|stacks``; a Host row in ``operator top``;
the ``operator debug`` bundle; and the bench's per-config
``host_attribution`` block. All ``nomad.host.*`` / ``nomad.runtime.*``
names are catalogued in docs/metrics.md (source-walk enforced). Design
notes and flamegraph reading: docs/profiling.md.
"""

from __future__ import annotations

import gc
import os
import sys
import threading
import time
import weakref
from typing import Optional

now_ns = time.monotonic_ns

# -- bounds --------------------------------------------------------------
# Sites are (role, span, function) triples — a closed set in practice
# (the codebase has a few hundred hot functions); the bound only matters
# under pathological frame churn (generated code), where overflow lands
# in "(other)" and sites_evicted counts the loss.
MAX_SITES = 2048
MAX_STACKS = 8192
MAX_DEPTH = 48
OTHER_SITE = "(other)"

# Leaf frames that mean "parked, not working": skipped before any
# allocation (the zero-allocation idle fast path). The basename match
# is anchored to the STDLIB directory (threading.__file__'s home) —
# a bare suffix match would classify this repo's own
# server/plan_queue.py as "queue.py" and silently drop one of the very
# hot paths this layer exists to attribute. The name set covers this
# repo's known blocking read loops, whose leaf is repo code parked in
# a C recv/accept.
_STDLIB_DIR = os.path.dirname(threading.__file__) + os.sep
_IDLE_STDLIB_BASENAMES = frozenset({
    "threading.py",
    "selectors.py",
    "queue.py",
    "socketserver.py",
    "socket.py",
    "ssl.py",
    "subprocess.py",
    "_base.py",  # concurrent/futures/_base.py (Future.result waits)
})
_IDLE_NAMES = frozenset({
    "recv_exact",
    "recv_frame",
    "_read_loop",
    "_accept_loop",
})

_enabled = True


def enabled() -> bool:
    return _enabled


def set_enabled(on: bool) -> None:
    """Recording gate (GIL-atomic flag): the sampler thread keeps
    running but skips the frame walk entirely when off. The bench uses
    this to exclude cluster-build time from attribution windows and as
    the unprofiled side of the overhead gate; production leaves it on."""
    global _enabled
    _enabled = bool(on)


# -- lock-wait attribution ----------------------------------------------

_lock_registry: "weakref.WeakSet[TimedLock]" = weakref.WeakSet()


class TimedLock:
    """A Lock/RLock wrapper attributing contended-acquire wait time.

    Fast path: one non-blocking try-acquire — an uncontended lock costs
    a single extra C call, no clock reads, no allocation. Contended
    path: two monotonic_ns reads around the blocking acquire, instance
    counters (safe unsynchronized: the incrementing thread HOLDS the
    lock), and a ``nomad.runtime.lock_wait_seconds.<name>`` histogram
    observation unless ``histogram=False`` — the metrics registry's own
    lock MUST pass False (observing would re-acquire the very lock the
    caller now holds: self-deadlock).

    Condition-compatible: ``_release_save``/``_acquire_restore``/
    ``_is_owned`` delegate to the inner primitive where it provides them
    (RLock) and fall back to the stdlib default shapes otherwise, so
    ``threading.Condition(TimedLock(...))`` behaves exactly like
    Condition over the bare primitive. Pass the inner lock explicitly
    (``TimedLock("broker", threading.RLock())``) so the racecheck
    lock-order detector classes it by the REAL allocation site.
    """

    __slots__ = (
        "name", "_inner", "_histogram",
        "contended", "wait_ns", "max_wait_ns", "__weakref__",
    )

    def __init__(self, name: str, inner=None, histogram: bool = True) -> None:
        self.name = name
        self._inner = inner if inner is not None else threading.Lock()
        self._histogram = histogram
        self.contended = 0
        self.wait_ns = 0
        self.max_wait_ns = 0
        _lock_registry.add(self)

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        inner = self._inner
        if inner.acquire(False):
            return True
        if not blocking:
            return False
        t0 = now_ns()
        ok = inner.acquire(True, timeout)
        dt = now_ns() - t0
        if ok:
            # serialized by the lock itself: plain int ops are safe
            self.contended += 1
            self.wait_ns += dt
            if dt > self.max_wait_ns:
                self.max_wait_ns = dt
            if self._histogram and _enabled:
                from . import metrics

                metrics.incr(f"nomad.runtime.lock_contended.{self.name}")
                metrics.observe(
                    f"nomad.runtime.lock_wait_seconds.{self.name}", dt / 1e9
                )
        return ok

    def release(self) -> None:
        self._inner.release()

    def __enter__(self) -> "TimedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        return self._inner.locked()

    # Condition plumbing (threading.Condition grabs these at __init__;
    # wait()'s release/reacquire cycles bypass the timing on purpose —
    # a Condition sleeper is parked, not contending).

    def _release_save(self):
        f = getattr(self._inner, "_release_save", None)
        if f is not None:
            return f()
        self._inner.release()

    def _acquire_restore(self, state) -> None:
        f = getattr(self._inner, "_acquire_restore", None)
        if f is not None:
            f(state)
            return
        self._inner.acquire()

    def _is_owned(self) -> bool:
        f = getattr(self._inner, "_is_owned", None)
        if f is not None:
            return f()
        if self._inner.acquire(False):
            self._inner.release()
            return False
        return True

    def stats(self) -> dict:
        return {
            "contended": self.contended,
            "wait_seconds_total": round(self.wait_ns / 1e9, 6),
            "max_wait_s": round(self.max_wait_ns / 1e9, 6),
        }


def lock_stats() -> dict[str, dict]:
    """Aggregate TimedLock stats by lock name across live instances
    (in-process test clusters run several brokers; operators run one)."""
    agg: dict[str, dict] = {}
    for lk in list(_lock_registry):
        cur = agg.setdefault(
            lk.name,
            {"contended": 0, "wait_seconds_total": 0.0, "max_wait_s": 0.0},
        )
        s = lk.stats()
        cur["contended"] += s["contended"]
        cur["wait_seconds_total"] = round(
            cur["wait_seconds_total"] + s["wait_seconds_total"], 6
        )
        cur["max_wait_s"] = max(cur["max_wait_s"], s["max_wait_s"])
    return agg


# -- thread-role classification ------------------------------------------

_ROLE_PREFIXES = (
    ("MainThread", "main"),
    ("tpu-batch-solve", "solve"),
    ("tpu-batch-commit", "commit"),
    ("worker", "worker"),
    ("plan-applier", "applier"),
    ("http-agent", "http"),
    ("rpc-", "rpc"),
    ("raft", "raft"),
    ("serf", "serf"),
    ("broker-delayed", "broker"),
    ("statsd-sink", "telemetry"),
    ("heartbeat", "heartbeat"),
)


def _role_of(name: str) -> str:
    for prefix, role in _ROLE_PREFIXES:
        if name.startswith(prefix):
            return role
    if "process_request_thread" in name:  # ThreadingHTTPServer workers
        return "http"
    if name.startswith("Thread-"):
        return "other"
    # bounded by the live thread-name set; strip trailing numbering so
    # "logmon-3" and "logmon-7" share a role
    return name.rstrip("0123456789-") or "other"


# -- the profiler --------------------------------------------------------


class HostProfiler:
    """One process-wide instance (module functions delegate); tests and
    the bench may install a fresh one via :func:`_install`.

    Writer discipline: the sampler thread is the only ledger writer (GC
    callbacks buffer into a bounded pending list the sampler flushes);
    readers (snapshot/collapsed, any thread) copy under ``_lock``. The
    lock is therefore uncontended at steady state — held by the sampler
    for the microseconds of one sample pass."""

    def __init__(
        self,
        interval_s: float = 0.010,
        idle_interval_s: float = 0.10,
        flush_interval_s: float = 10.0,
        max_sites: int = MAX_SITES,
        max_stacks: int = MAX_STACKS,
        max_depth: int = MAX_DEPTH,
    ) -> None:
        self.interval_s = max(0.001, float(interval_s))
        self.idle_interval_s = max(self.interval_s, float(idle_interval_s))
        self.flush_interval_s = max(0.05, float(flush_interval_s))
        # the sampler's EFFECTIVE period right now (backoff observable)
        self.cur_interval_s = self.interval_s
        self.max_sites = max(16, int(max_sites))
        self.max_stacks = max(16, int(max_stacks))
        self.max_depth = max(4, int(max_depth))
        self._lock = threading.Lock()
        # Serializes _flush: the sampler's periodic flush and a
        # snapshot() reader (HTTP thread) must not drain the GC-pending
        # buffers concurrently — the copy+clear is two bytecodes, and a
        # double drain double-counts every pause. Ordered BEFORE _lock
        # and the metrics registry lock everywhere.
        self._flush_lock = threading.Lock()
        # (role, span, site) -> [samples, busy_ns]
        self._sites: dict[tuple, list] = {}
        # collapsed "role;span;f0;f1;...;leaf" -> samples
        self._stacks: dict[str, int] = {}
        self._span_ns: dict[str, int] = {}
        # source -> busy ns: the clusterobs thread->source registry's
        # dimension ("handler CPU x source node") — bounded, overflow
        # folds into "(other)" like the site ledger
        self._source_ns: dict[str, int] = {}
        self.max_sources = 512
        self._role_stats: dict[str, list] = {}  # role -> [samples, ns]
        self.samples = 0
        self.idle_samples = 0
        self.busy_ns = 0
        self.sites_evicted = 0
        self.stacks_dropped = 0
        self._sampler_ns = 0  # time spent inside sample passes
        self._started_ns = 0
        # code object -> (qualified frame label, leaf-site label, idle?)
        self._code_cache: dict = {}
        self._roles: dict[int, str] = {}
        # GC accounting (callback-side buffers; sampler flushes)
        self._gc_t0 = 0
        self._gc_pending: list[tuple[int, int]] = []  # (gen, pause_ns)
        self.gc_dropped = 0
        self.gc_collections = [0, 0, 0]
        self.gc_collected = 0
        self.gc_pause_ns = 0
        self.gc_pause_max_ns = 0
        # gctune paused-GC sections (hook-side buffer; sampler flushes)
        self._section_pending: list[int] = []
        self.gc_sections = 0
        self.gc_section_ns = 0
        self._gc_collected_flushed = 0
        # lifecycle
        self._refs = 0
        self._ref_lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._provider_handle = None
        self._prev_section_hook = None

    # -- lifecycle -------------------------------------------------------

    def start(self) -> None:
        """Refcounted: every Agent (and the bench) calls start/stop in
        pairs; one sampler thread serves the whole process."""
        with self._ref_lock:
            self._refs += 1
            if self._thread is not None and self._thread.is_alive():
                return
            self._stop = threading.Event()
            self._started_ns = now_ns()
            self._thread = threading.Thread(
                target=self._run, args=(self._stop,), daemon=True,
                name="host-profiler",
            )
            gc.callbacks.append(self._gc_cb)
            from . import gctune

            # save the previous owner: a PRIVATE instance (run_soak's
            # measurement apparatus) must hand the hook back to a
            # co-resident global profiler on stop, not null it out
            self._prev_section_hook = gctune.on_section_end
            gctune.on_section_end = self.note_gc_section
            if self._provider_handle is None:
                from . import metrics

                self._provider_handle = metrics.register_provider(
                    "nomad.host", self._provider
                )
            self._thread.start()

    def stop(self) -> None:
        with self._ref_lock:
            if self._refs > 0:
                self._refs -= 1
            if self._refs > 0 or self._thread is None:
                return
            self._stop.set()
            t = self._thread
            self._thread = None
        t.join(timeout=2)
        try:
            gc.callbacks.remove(self._gc_cb)
        except ValueError:
            pass
        from . import gctune

        if gctune.on_section_end == self.note_gc_section:
            gctune.on_section_end = self._prev_section_hook
        self._prev_section_hook = None

    def running(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    def configure(
        self,
        interval_s: Optional[float] = None,
        flush_interval_s: Optional[float] = None,
        idle_interval_s: Optional[float] = None,
    ) -> None:
        """Operator knobs (telemetry { host_profile_interval }, SIGHUP
        reload): picked up by the sampler on its next wakeup.
        idle_interval_s clamps the idle backoff ceiling — the bench's
        attribution passes pin it to the busy interval so short bursts
        after long idle builds aren't sampled at the backed-off rate."""
        if interval_s is not None:
            self.interval_s = max(0.001, float(interval_s))
            self.idle_interval_s = max(self.interval_s, self.idle_interval_s)
        if idle_interval_s is not None:
            self.idle_interval_s = max(
                self.interval_s, float(idle_interval_s)
            )
        if flush_interval_s is not None:
            self.flush_interval_s = max(0.05, float(flush_interval_s))

    def reset_stats(self) -> None:
        """Forget attribution (bench per-config isolation; the sampler
        thread and lifecycle state are untouched)."""
        with self._flush_lock, self._lock:
            self._sites.clear()
            self._stacks.clear()
            self._span_ns.clear()
            self._source_ns.clear()
            self._role_stats.clear()
            self.samples = 0
            self.idle_samples = 0
            self.busy_ns = 0
            self.sites_evicted = 0
            self.stacks_dropped = 0
            self._sampler_ns = 0
            self._started_ns = now_ns()
            self.gc_collections = [0, 0, 0]
            self.gc_collected = 0
            self.gc_pause_ns = 0
            self.gc_pause_max_ns = 0
            self.gc_sections = 0
            self.gc_section_ns = 0
            self._gc_collected_flushed = 0
            del self._gc_pending[:]
            del self._section_pending[:]
        for lk in list(_lock_registry):
            lk.contended = 0
            lk.wait_ns = 0
            lk.max_wait_ns = 0

    # -- GC hooks (MUST NOT touch the metrics registry: the collector
    # can fire while the collecting thread holds any lock, including
    # the registry's — the sampler flushes these buffers instead) ------

    def _gc_cb(self, phase: str, info: dict) -> None:
        if phase == "start":
            self._gc_t0 = now_ns()
            return
        t0 = self._gc_t0
        if not t0:
            return
        self._gc_t0 = 0
        if not _enabled:
            return
        dt = now_ns() - t0
        gen = int(info.get("generation", 0))
        # GIL-atomic appends; bounded so a collection storm between
        # flushes can't grow the buffer without bound
        if len(self._gc_pending) < 1024:
            self._gc_pending.append((gen, dt))
        else:
            self.gc_dropped += 1
        self.gc_collected += int(info.get("collected", 0))

    def note_gc_section(self, dur_ns: int) -> None:
        """gctune.paused_gc outermost-exit hook: how long the collector
        was deliberately off for a batch (docs/profiling.md — a long
        paused section means the RE-ENABLE pays one big young-gen
        scan)."""
        if not _enabled:
            return
        if len(self._section_pending) < 1024:
            self._section_pending.append(int(dur_ns))

    # -- sampler ---------------------------------------------------------

    def _run(self, stop: threading.Event) -> None:
        last = now_ns()
        interval = self.interval_s
        idle_streak = 0
        next_flush = 0.0
        while not stop.wait(interval):
            self.cur_interval_s = interval
            t0 = now_ns()
            # wall time since the previous sample is what this sample's
            # busy threads are charged with (capped: a sampler starved
            # for seconds must not attribute the whole gap to whatever
            # runs at wakeup)
            dt = min(t0 - last, 2_000_000_000)
            last = t0
            if _enabled:
                busy = self._sample(dt)
                if busy:
                    idle_streak = 0
                    interval = self.interval_s
                else:
                    # adaptive idle backoff: a quiet agent converges to
                    # idle_interval_s, ~10x fewer wakeups
                    idle_streak += 1
                    if idle_streak >= 50:
                        interval = min(interval * 2, self.idle_interval_s)
            now = time.monotonic()
            if now >= next_flush:
                next_flush = now + self.flush_interval_s
                try:
                    self._flush()
                except Exception:  # flush must never kill the sampler
                    pass
            self._sampler_ns += now_ns() - t0

    def _sample(self, dt_ns: int) -> bool:
        """One pass over every live thread's current frame. Returns
        whether any thread was busy (drives the adaptive interval)."""
        from . import clusterobs as _clusterobs, trace as _trace

        me = threading.get_ident()
        spans = _trace.thread_spans()
        sources = _clusterobs.thread_sources()
        frames = sys._current_frames()
        busy_any = False
        code_cache = self._code_cache
        with self._lock:
            self.samples += 1
            for tid, frame in frames.items():
                if tid == me:
                    continue
                code = frame.f_code
                cached = code_cache.get(code)
                if cached is None:
                    cached = self._describe(code)
                    if len(code_cache) < 8192:
                        code_cache[code] = cached
                label, site, is_idle = cached
                if is_idle:
                    continue
                busy_any = True
                role = self._roles.get(tid)
                if role is None:
                    role = self._refresh_role(tid)
                span = spans.get(tid) or "-"
                key = (role, span, site)
                ent = self._sites.get(key)
                if ent is None:
                    if len(self._sites) >= self.max_sites:
                        key = (role, span, OTHER_SITE)
                        self.sites_evicted += 1
                        ent = self._sites.get(key)
                    if ent is None:
                        ent = self._sites[key] = [0, 0]
                ent[0] += 1
                ent[1] += dt_ns
                self.busy_ns += dt_ns
                self._span_ns[span] = self._span_ns.get(span, 0) + dt_ns
                # source dimension (clusterobs thread registry): only
                # threads currently serving an attributed request carry
                # one — handler CPU lands on its source node/namespace
                src = sources.get(tid)
                if src is not None:
                    if (
                        src not in self._source_ns
                        and len(self._source_ns) >= self.max_sources
                    ):
                        src = OTHER_SITE
                    self._source_ns[src] = (
                        self._source_ns.get(src, 0) + dt_ns
                    )
                rs = self._role_stats.get(role)
                if rs is None:
                    rs = self._role_stats[role] = [0, 0]
                rs[0] += 1
                rs[1] += dt_ns
                # collapsed stack (flamegraph surface): root-first
                parts = []
                f = frame
                depth = 0
                while f is not None and depth < self.max_depth:
                    c = f.f_code
                    cc = code_cache.get(c)
                    if cc is None:
                        cc = self._describe(c)
                        if len(code_cache) < 8192:
                            code_cache[c] = cc
                    parts.append(cc[0])
                    f = f.f_back
                    depth += 1
                parts.append(f"{role};{span}")
                parts.reverse()
                stack_key = ";".join(parts)
                cnt = self._stacks.get(stack_key)
                if cnt is None:
                    if len(self._stacks) >= self.max_stacks:
                        self.stacks_dropped += 1
                        continue
                    self._stacks[stack_key] = 1
                else:
                    self._stacks[stack_key] = cnt + 1
            if not busy_any:
                self.idle_samples += 1
        return busy_any

    @staticmethod
    def _describe(code) -> tuple[str, str, bool]:
        """(frame label, leaf-site label, idle?) for one code object —
        computed once and cached; the per-sample path is dict hits."""
        fn = code.co_filename
        name = code.co_name
        if name == "_gc_cb" and fn.endswith("hostobs.py"):
            # gc.collect holds the GIL for the whole collection; the
            # sampler's only chance to run "inside" one is while the
            # Python gc callback executes, so the entire collection gap
            # lands on this frame — name it what it is
            return "(gc-collect)", "(gc-collect)", False
        base = os.path.basename(fn)
        mod = base[:-3] if base.endswith(".py") else base
        label = f"{mod}.{name}"
        site = f"{name} ({base}:{code.co_firstlineno})"
        idle = name in _IDLE_NAMES or (
            fn.startswith(_STDLIB_DIR) and base in _IDLE_STDLIB_BASENAMES
        )
        return label, site, idle

    def _refresh_role(self, tid: int) -> str:
        names = {t.ident: t.name for t in threading.enumerate()}
        for ident, name in names.items():
            if ident not in self._roles:
                self._roles[ident] = _role_of(name)
        role = self._roles.get(tid)
        if role is None:
            role = self._roles[tid] = "other"
        return role

    # -- flush: buffered GC events + runtime gauges ----------------------

    def _flush(self) -> None:
        from . import metrics, trace as _trace

        with self._flush_lock:
            self._flush_locked(metrics, _trace)

    def _flush_locked(self, metrics, _trace) -> None:
        # drain the callback-side buffers (list slicing under the GIL;
        # the callback only appends)
        pending, self._gc_pending[:] = self._gc_pending[:], []
        sections, self._section_pending[:] = self._section_pending[:], []
        for gen, dt in pending:
            if 0 <= gen < 3:
                self.gc_collections[gen] += 1
            self.gc_pause_ns += dt
            if dt > self.gc_pause_max_ns:
                self.gc_pause_max_ns = dt
            metrics.incr("nomad.runtime.gc_collections")
            metrics.incr(f"nomad.runtime.gc_collections.gen{gen}")
            metrics.observe("nomad.runtime.gc_pause_seconds", dt / 1e9)
        if self.gc_dropped:
            metrics.incr("nomad.runtime.gc_pauses_dropped", self.gc_dropped)
            self.gc_dropped = 0
        collected_delta = self.gc_collected - self._gc_collected_flushed
        if collected_delta > 0:
            metrics.incr("nomad.runtime.gc_collected", collected_delta)
            self._gc_collected_flushed = self.gc_collected
        for dt in sections:
            self.gc_sections += 1
            self.gc_section_ns += dt
            metrics.incr("nomad.runtime.gc_paused_sections")
            metrics.observe(
                "nomad.runtime.gc_paused_section_seconds", dt / 1e9
            )
        # runtime gauges
        metrics.set_gauge(
            "nomad.runtime.threads", float(threading.active_count())
        )
        counts = gc.get_count()
        for gen in range(min(3, len(counts))):
            metrics.set_gauge(
                f"nomad.runtime.gc_pending.gen{gen}", float(counts[gen])
            )
        rss = _read_rss()
        if rss:
            metrics.set_gauge("nomad.runtime.rss_bytes", float(rss))
        fds = _count_fds()
        if fds is not None:
            metrics.set_gauge("nomad.runtime.fds", float(fds))
        # prune role cache + the trace-side span registry + the
        # clusterobs source registry for dead tids
        live = {t.ident for t in threading.enumerate()}
        for tid in [t for t in self._roles if t not in live]:
            self._roles.pop(tid, None)
        _trace.prune_thread_spans(live)
        from . import clusterobs as _clusterobs

        _clusterobs.prune_thread_sources(live)

    def _provider(self) -> dict:
        wall = max(1, now_ns() - self._started_ns)
        return {
            "samples": float(self.samples),
            "idle_samples": float(self.idle_samples),
            "busy_seconds": round(self.busy_ns / 1e9, 3),
            "duty_cycle": round(self._sampler_ns / wall, 6),
            "interval_ms": round(self.interval_s * 1e3, 3),
            "sites": float(len(self._sites)),
            "sites_evicted": float(self.sites_evicted),
            "stacks": float(len(self._stacks)),
            "stacks_dropped": float(self.stacks_dropped),
        }

    # -- read side -------------------------------------------------------

    def snapshot(self, top: int = 50) -> dict:
        """The /v1/profile/status payload."""
        try:
            self._flush()
        except Exception:
            pass
        with self._lock:
            sites = sorted(
                self._sites.items(), key=lambda kv: -kv[1][1]
            )[: max(1, top)]
            spans = {
                k: round(v / 1e9, 4)
                for k, v in sorted(
                    self._span_ns.items(), key=lambda kv: -kv[1]
                )
            }
            sources = {
                k: round(v / 1e9, 4)
                for k, v in sorted(
                    self._source_ns.items(), key=lambda kv: -kv[1]
                )[: max(1, top)]
            }
            roles = {
                r: {"samples": s[0], "busy_seconds": round(s[1] / 1e9, 4)}
                for r, s in sorted(self._role_stats.items())
            }
            wall_ns = max(1, now_ns() - self._started_ns)
            out = {
                "enabled": _enabled,
                "running": self.running(),
                "interval_ms": round(self.interval_s * 1e3, 3),
                "window_seconds": round(wall_ns / 1e9, 3),
                "samples": self.samples,
                "idle_samples": self.idle_samples,
                "busy_seconds": round(self.busy_ns / 1e9, 4),
                "overhead": {
                    "sampler_seconds": round(self._sampler_ns / 1e9, 4),
                    "duty_cycle": round(self._sampler_ns / wall_ns, 6),
                },
                "top_sites": [
                    {
                        "role": role,
                        "span": span,
                        "site": site,
                        "samples": ent[0],
                        "seconds": round(ent[1] / 1e9, 4),
                    }
                    for (role, span, site), ent in sites
                ],
                "spans": spans,
                # handler CPU x source (clusterobs dimension): seconds
                # of busy samples taken while the thread was serving an
                # attributed request for that source
                "sources": sources,
                "threads": roles,
                "sites": len(self._sites),
                "sites_evicted": self.sites_evicted,
                "stacks": len(self._stacks),
                "stacks_dropped": self.stacks_dropped,
                "gc": {
                    "collections": {
                        f"gen{i}": n
                        for i, n in enumerate(self.gc_collections)
                    },
                    "collected": self.gc_collected,
                    "pause_seconds_total": round(self.gc_pause_ns / 1e9, 6),
                    "pause_max_s": round(self.gc_pause_max_ns / 1e9, 6),
                    "paused_sections": self.gc_sections,
                    "paused_section_seconds": round(
                        self.gc_section_ns / 1e9, 6
                    ),
                },
                "locks": lock_stats(),
                "runtime": {
                    "rss_bytes": _read_rss(),
                    "threads": threading.active_count(),
                    "fds": _count_fds(),
                    "gc_pending": list(gc.get_count()),
                },
            }
        return out

    def collapsed(self, limit: int = 0) -> str:
        """Collapsed-stack text (``role;span;frame;...;leaf count`` per
        line, Brendan-Gregg format): feed to flamegraph.pl / speedscope
        / inferno verbatim. Sorted by sample count, heaviest first."""
        with self._lock:
            items = sorted(self._stacks.items(), key=lambda kv: -kv[1])
        if limit > 0:
            items = items[:limit]
        return "\n".join(f"{stack} {count}" for stack, count in items) + (
            "\n" if items else ""
        )


def _read_rss() -> int:
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1]) * 1024
    except (OSError, ValueError, IndexError):
        pass
    return 0


def _count_fds() -> Optional[int]:
    try:
        return len(os.listdir("/proc/self/fd"))
    except OSError:
        return None


# -- process-global instance + module conveniences -----------------------

_global = HostProfiler()


def profiler() -> HostProfiler:
    return _global


def _install(prof: HostProfiler) -> HostProfiler:
    """Swap the process-global profiler (returns the previous one) —
    the test isolation hook, mirroring solverobs._install. The caller
    owns stopping the old instance's thread if it started one."""
    global _global, start, stop, running, configure, reset_stats
    global snapshot, collapsed, note_gc_section
    old = _global
    _global = prof
    start = prof.start
    stop = prof.stop
    running = prof.running
    configure = prof.configure
    reset_stats = prof.reset_stats
    snapshot = prof.snapshot
    collapsed = prof.collapsed
    note_gc_section = prof.note_gc_section
    return old


start = _global.start
stop = _global.stop
running = _global.running
configure = _global.configure
reset_stats = _global.reset_stats
snapshot = _global.snapshot
collapsed = _global.collapsed
note_gc_section = _global.note_gc_section
