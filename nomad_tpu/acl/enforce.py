"""HTTP-route → ACL capability enforcement.

Reference: each endpoint in nomad/ resolves the token and checks the
specific capability (e.g. nomad/job_endpoint.go Register checks
NamespaceValidator(acl.NamespaceCapabilitySubmitJob)). Here the mapping
lives in one table keyed on route shape, applied by the HTTP layer
before dispatch.
"""

from __future__ import annotations

import re
from typing import Optional

from .acl import ACL
from .policy import (
    CAP_ALLOC_LIFECYCLE,
    CAP_DISPATCH_JOB,
    CAP_LIST_JOBS,
    CAP_READ_FS,
    CAP_READ_JOB,
    CAP_READ_LOGS,
    CAP_READ_SECRET,
    CAP_SUBMIT_JOB,
    CAP_WRITE_SECRET,
)


class AuthError(Exception):
    def __init__(self, status: int, message: str) -> None:
        self.status = status
        self.message = message
        super().__init__(message)


_NS_ROUTES: list[tuple[str, re.Pattern, str]] = [
    ("GET", re.compile(r"^/v1/jobs$"), CAP_LIST_JOBS),
    ("PUT", re.compile(r"^/v1/jobs$"), CAP_SUBMIT_JOB),
    ("POST", re.compile(r"^/v1/jobs$"), CAP_SUBMIT_JOB),
    ("GET", re.compile(r"^/v1/job/[^/]+(/.*)?$"), CAP_READ_JOB),
    ("DELETE", re.compile(r"^/v1/job/[^/]+$"), CAP_SUBMIT_JOB),
    ("PUT", re.compile(r"^/v1/job/[^/]+/dispatch$"), CAP_DISPATCH_JOB),
    ("POST", re.compile(r"^/v1/job/[^/]+/dispatch$"), CAP_DISPATCH_JOB),
    ("PUT", re.compile(r"^/v1/job/[^/]+/.*$"), CAP_SUBMIT_JOB),
    ("GET", re.compile(r"^/v1/allocations$"), CAP_READ_JOB),
    ("GET", re.compile(r"^/v1/allocation/.*$"), CAP_READ_JOB),
    ("GET", re.compile(r"^/v1/evaluations$"), CAP_READ_JOB),
    ("GET", re.compile(r"^/v1/evaluation/.*$"), CAP_READ_JOB),
    ("GET", re.compile(r"^/v1/deployments$"), CAP_READ_JOB),
    ("GET", re.compile(r"^/v1/deployment/.*$"), CAP_READ_JOB),
    ("PUT", re.compile(r"^/v1/deployment/.*$"), CAP_SUBMIT_JOB),
    ("GET", re.compile(r"^/v1/event/stream$"), CAP_READ_JOB),
    # streaming alloc surface (handlers re-check against the alloc's
    # own namespace via _ns_guard; exec rides the RPC fabric and is
    # checked in ClusterServer._handle_exec_stream with CAP_ALLOC_EXEC)
    ("GET", re.compile(r"^/v1/client/fs/logs/.*$"), CAP_READ_LOGS),
    # alloc lifecycle (handlers re-check against the alloc's own
    # namespace via _ns_guard)
    ("PUT", re.compile(r"^/v1/client/allocation/[^/]+/(restart|signal)$"),
     CAP_ALLOC_LIFECYCLE),
    ("POST", re.compile(r"^/v1/client/allocation/[^/]+/(restart|signal)$"),
     CAP_ALLOC_LIFECYCLE),
    ("PUT", re.compile(r"^/v1/allocation/[^/]+/stop$"), CAP_ALLOC_LIFECYCLE),
    ("POST", re.compile(r"^/v1/allocation/[^/]+/stop$"),
     CAP_ALLOC_LIFECYCLE),
    ("GET", re.compile(r"^/v1/client/fs/(ls|cat|stat)/.*$"), CAP_READ_FS),
    # volumes ride the job caps (the reference gates host volumes with
    # namespace host_volume policies; submit-job is this tree's write cap)
    ("GET", re.compile(r"^/v1/volumes$"), CAP_READ_JOB),
    ("PUT", re.compile(r"^/v1/volumes$"), CAP_SUBMIT_JOB),
    ("POST", re.compile(r"^/v1/volumes$"), CAP_SUBMIT_JOB),
    ("PUT", re.compile(r"^/v1/volumes/create$"), CAP_SUBMIT_JOB),
    ("POST", re.compile(r"^/v1/volumes/create$"), CAP_SUBMIT_JOB),
    ("PUT", re.compile(r"^/v1/volumes/snapshot$"), CAP_SUBMIT_JOB),
    ("POST", re.compile(r"^/v1/volumes/snapshot$"), CAP_SUBMIT_JOB),
    ("DELETE", re.compile(r"^/v1/volumes/snapshot$"), CAP_SUBMIT_JOB),
    ("GET", re.compile(r"^/v1/volumes/snapshot$"), CAP_READ_JOB),
    ("GET", re.compile(r"^/v1/volume/.*$"), CAP_READ_JOB),
    ("DELETE", re.compile(r"^/v1/volume/.*$"), CAP_SUBMIT_JOB),
    # CSI plugin health rides the volume read gate (reference
    # csi_endpoint.go: plugin list/read allowed with namespace read)
    ("GET", re.compile(r"^/v1/plugins$"), CAP_READ_JOB),
    ("GET", re.compile(r"^/v1/plugin/csi/.*$"), CAP_READ_JOB),
    # embedded secrets store: explicit capabilities, never implied by
    # namespace read (values are sensitive)
    ("GET", re.compile(r"^/v1/secrets$"), CAP_READ_SECRET),
    ("GET", re.compile(r"^/v1/secret/.*$"), CAP_READ_SECRET),
    ("PUT", re.compile(r"^/v1/secret/.*$"), CAP_WRITE_SECRET),
    ("POST", re.compile(r"^/v1/secret/.*$"), CAP_WRITE_SECRET),
    ("DELETE", re.compile(r"^/v1/secret/.*$"), CAP_WRITE_SECRET),
    # server-side job validation: read-level (nothing is committed;
    # reference agent ValidateJobRequest allows any submitter)
    ("PUT", re.compile(r"^/v1/validate/job$"), CAP_READ_JOB),
    ("POST", re.compile(r"^/v1/validate/job$"), CAP_READ_JOB),
    # HCL parse is pure computation (nothing committed) — read-level,
    # so the UI Run view works with a submit-job token
    ("PUT", re.compile(r"^/v1/jobs/parse$"), CAP_READ_JOB),
    ("POST", re.compile(r"^/v1/jobs/parse$"), CAP_READ_JOB),
    # scaling policies read with namespace read (reference
    # scaling_endpoint.go ListPolicies: read-job or list-scaling-policies)
    ("GET", re.compile(r"^/v1/scaling/policies$"), CAP_READ_JOB),
    ("GET", re.compile(r"^/v1/scaling/policy/.*$"), CAP_READ_JOB),
    # native service discovery (reference
    # service_registration_endpoint.go: read-job to list, submit-job to
    # delete a registration)
    ("GET", re.compile(r"^/v1/services$"), CAP_READ_JOB),
    ("GET", re.compile(r"^/v1/service/[^/]+$"), CAP_READ_JOB),
    ("DELETE", re.compile(r"^/v1/service/[^/]+/[^/]+$"), CAP_SUBMIT_JOB),
    # search reads cluster objects (reference search_endpoint ACL: the
    # per-context capability; read-job is the broadest gate here)
    ("PUT", re.compile(r"^/v1/search(/fuzzy)?$"), CAP_READ_JOB),
    ("POST", re.compile(r"^/v1/search(/fuzzy)?$"), CAP_READ_JOB),
]

_NODE_READ = [("GET", re.compile(r"^/v1/nodes$")), ("GET", re.compile(r"^/v1/node/.*$"))]
_NODE_WRITE = [("PUT", re.compile(r"^/v1/node/.*$")), ("POST", re.compile(r"^/v1/node/.*$"))]
# pprof dumps internal state and can occupy handler threads for seconds:
# agent:write, like the reference (command/agent/agent_endpoint.go
# AgentPprofRequest). Checked BEFORE the broader agent-read rule.
_AGENT_WRITE = [
    ("GET", re.compile(r"^/v1/agent/pprof/.*$")),
    # force-leave ejects a member from gossip (reference agent:write)
    ("PUT", re.compile(r"^/v1/agent/force-leave$")),
    ("POST", re.compile(r"^/v1/agent/force-leave$")),
    # gossip-join mutates membership (reference agent:write)
    ("PUT", re.compile(r"^/v1/agent/join$")),
    ("POST", re.compile(r"^/v1/agent/join$")),
    # keyring rotation swaps the fabric's live auth secret (reference
    # keyring management is agent:write); status stays agent:read via
    # the broader GET rule below
    ("PUT", re.compile(r"^/v1/agent/keyring/rotate$")),
    ("POST", re.compile(r"^/v1/agent/keyring/rotate$")),
]
_AGENT_READ = [
    ("GET", re.compile(r"^/v1/agent/.*$")),
    ("GET", re.compile(r"^/v1/metrics$")),
    # traces expose request-level internals (job/eval ids, stage
    # timings): same agent:read gate as /v1/metrics
    ("GET", re.compile(r"^/v1/traces(/.*)?$")),
    # solver observability snapshot (compile ledger / occupancy /
    # transfers / device memory): agent-local read surface like
    # /v1/metrics — read-only, so agent:read, not the pprof-style
    # agent:write
    ("GET", re.compile(r"^/v1/solver/status$")),
    # host profiler summary + collapsed stacks (hostobs.py): always-on
    # read surface like /v1/metrics and /v1/solver/status — the raw
    # on-demand pprof capture stays agent:write + enable_debug, but the
    # continuous profiler's bounded aggregate is agent:read
    ("GET", re.compile(r"^/v1/profile(/.*)?$")),
    # cluster health federation (cluster.py cluster_health): the
    # observability surface family's gate — agent:read like /v1/metrics
    # and /v1/profile, NOT operator:read (checked before the broader
    # operator rule below; the payload is telemetry, not raft control)
    ("GET", re.compile(r"^/v1/operator/cluster/health$")),
    # blackbox flight recorder (blackbox.py): status, incident index,
    # and causal timelines — the same always-on observability family as
    # /v1/metrics and /v1/profile (incident bundles carry the same
    # internals traces do, so the same agent:read gate)
    ("GET", re.compile(r"^/v1/blackbox(/.*)?$")),
    ("GET", re.compile(r"^/v1/incidents(/.*)?$")),
    ("GET", re.compile(r"^/v1/timeline(/.*)?$")),
]
# reference: raft list-peers / snapshot save need operator:read; snapshot
# restore needs operator:write (nomad/operator_endpoint.go)
_OPERATOR_READ = [("GET", re.compile(r"^/v1/operator/.*$"))]
# Any VALID token may read these (the reference filters the namespace
# list to ones the token can use; every token can at least resolve names).
_ANY_TOKEN_READ = [
    ("GET", re.compile(r"^/v1/namespaces$")),
    ("GET", re.compile(r"^/v1/namespace/.*$")),
]
_OPERATOR_WRITE = [
    ("PUT", re.compile(r"^/v1/operator/.*$")),
    ("DELETE", re.compile(r"^/v1/operator/.*$")),
    # system gc is an operator action (reference System.GarbageCollect
    # requires management)
    ("PUT", re.compile(r"^/v1/system/.*$")),
    ("POST", re.compile(r"^/v1/system/.*$")),
    ("POST", re.compile(r"^/v1/operator/.*$")),
    # namespace CRUD is an operator action (reference
    # namespace_endpoint.go requires management)
    ("PUT", re.compile(r"^/v1/namespaces$")),
    ("POST", re.compile(r"^/v1/namespaces$")),
    ("DELETE", re.compile(r"^/v1/namespace/.*$")),
]


def make_http_resolver(server, enabled: bool = True):
    """Returns resolver(method, path, token_secret, query) raising
    AuthError on deny. `server` is the core Server (owns state +
    resolve_token)."""

    def resolver(
        method: str, path: str, secret: str, query: dict, body: bytes = b""
    ) -> None:
        if not enabled:
            return
        # Status endpoints stay open (cluster plumbing, like the
        # reference's unauthenticated Status.Ping/Leader).
        if path.startswith("/v1/status/") or path == "/v1/regions":
            return
        # Bootstrap is the chicken-and-egg exception.
        if path == "/v1/acl/bootstrap":
            return
        try:
            acl: Optional[ACL] = server.resolve_token(secret)
        except PermissionError:
            raise AuthError(401, "ACL token not found")
        if path == "/v1/acl/token/self":
            if acl is None:
                raise AuthError(401, "missing ACL token")
            return
        if path.startswith("/v1/acl/"):
            if acl is None or not acl.is_management():
                raise AuthError(403, "management token required")
            return
        if acl is None:
            # anonymous: deny by default (no anonymous policy support yet)
            raise AuthError(401, "missing ACL token")
        if acl.is_management():
            return
        ns = query.get("namespace", ["default"])[0]
        # Job registration: the namespace that matters is the one in the
        # JOB BODY (that's what the handler registers into) — checking
        # only the query namespace would let a default-scoped token write
        # into any namespace.
        if path == "/v1/jobs" and method in ("PUT", "POST") and body:
            import json as _json

            try:
                job = _json.loads(body).get("Job") or {}
                ns = job.get("namespace") or ns
            except Exception:
                pass
        # Search: the body names the namespace being searched.
        if path.startswith("/v1/search") and method in ("PUT", "POST") and body:
            import json as _json

            try:
                ns = _json.loads(body).get("Namespace") or ns
            except Exception:
                pass
        # Volume registration/creation: same body-namespace rule as
        # job register.
        if (
            path in ("/v1/volumes", "/v1/volumes/create")
            and method in ("PUT", "POST")
            and body
        ):
            import json as _json

            try:
                vol = _json.loads(body).get("Volume") or {}
                ns = vol.get("namespace") or ns
            except Exception:
                pass
        if path == "/v1/event/stream":
            # "*" streams every namespace: management only.
            if ns == "*":
                raise AuthError(403, "all-namespace stream requires management")
        # job scale authorizes with EITHER scale-job or submit-job
        # (reference Job.Scale) — the table below is single-capability
        if method in ("PUT", "POST") and re.fullmatch(
            r"/v1/job/[^/]+/scale", path
        ):
            if not (
                acl.allow_namespace_op(ns, "scale-job")
                or acl.allow_namespace_op(ns, "submit-job")
            ):
                raise AuthError(
                    403, "missing namespace capability 'scale-job'"
                )
            return
        for m, pat, cap in _NS_ROUTES:
            if m == method and pat.match(path):
                if not acl.allow_namespace_op(ns, cap):
                    raise AuthError(
                        403, f"missing namespace capability {cap!r}"
                    )
                return
        for m, pat in _NODE_WRITE:
            if m == method and pat.match(path):
                if not acl.allow_node_write():
                    raise AuthError(403, "node write denied")
                return
        for m, pat in _NODE_READ:
            if m == method and pat.match(path):
                if not acl.allow_node_read():
                    raise AuthError(403, "node read denied")
                return
        for m, pat in _AGENT_WRITE:
            if m == method and pat.match(path):
                if not acl.allow_agent_write():
                    raise AuthError(403, "agent write denied")
                return
        for m, pat in _AGENT_READ:
            if m == method and pat.match(path):
                if not acl.allow_agent_read():
                    raise AuthError(403, "agent read denied")
                return
        for m, pat in _ANY_TOKEN_READ:
            if m == method and pat.match(path):
                return  # token already resolved as valid above
        for m, pat in _OPERATOR_WRITE:
            if m == method and pat.match(path):
                if not acl.allow_operator_write():
                    raise AuthError(403, "operator write denied")
                return
        for m, pat in _OPERATOR_READ:
            if m == method and pat.match(path):
                if not acl.allow_operator_read():
                    raise AuthError(403, "operator read denied")
                return
        # Unmapped route under enforcement: require management (safe
        # default — new routes must be classified to be non-management).
        raise AuthError(403, "permission denied")

    return resolver
