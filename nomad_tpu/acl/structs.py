"""ACL state objects (reference: nomad/structs/structs.go ACLPolicy /
ACLToken)."""

from __future__ import annotations

from dataclasses import dataclass, field

from ..structs.structs import generate_uuid, now_ns

TOKEN_TYPE_CLIENT = "client"
TOKEN_TYPE_MANAGEMENT = "management"

ANONYMOUS_TOKEN_ACCESSOR = "anonymous"


@dataclass
class ACLPolicy:
    name: str = ""
    description: str = ""
    rules: str = ""  # HCL rules text
    create_index: int = 0
    modify_index: int = 0

    def copy(self) -> "ACLPolicy":
        return ACLPolicy(
            name=self.name,
            description=self.description,
            rules=self.rules,
            create_index=self.create_index,
            modify_index=self.modify_index,
        )

    def validate(self) -> None:
        from .policy import parse_policy

        if not self.name:
            raise ValueError("policy: missing name")
        parse_policy(self.rules)


@dataclass
class ACLToken:
    accessor_id: str = ""
    secret_id: str = ""
    name: str = ""
    type: str = TOKEN_TYPE_CLIENT
    policies: list[str] = field(default_factory=list)
    global_: bool = False
    create_time_ns: int = 0
    # 0 = never expires; task-derived tokens carry a TTL and ride the
    # client's renewal loop (reference: 1.4 token expiration +
    # client/vaultclient renewal)
    expiration_time_ns: int = 0
    create_index: int = 0
    modify_index: int = 0

    @staticmethod
    def new(
        name: str = "",
        type: str = TOKEN_TYPE_CLIENT,
        policies: list[str] | None = None,
    ) -> "ACLToken":
        return ACLToken(
            accessor_id=generate_uuid(),
            secret_id=generate_uuid(),
            name=name,
            type=type,
            policies=list(policies or []),
            create_time_ns=now_ns(),
        )

    def copy(self) -> "ACLToken":
        return ACLToken(
            accessor_id=self.accessor_id,
            secret_id=self.secret_id,
            name=self.name,
            type=self.type,
            policies=list(self.policies),
            global_=self.global_,
            create_time_ns=self.create_time_ns,
            expiration_time_ns=self.expiration_time_ns,
            create_index=self.create_index,
            modify_index=self.modify_index,
        )

    def is_management(self) -> bool:
        return self.type == TOKEN_TYPE_MANAGEMENT

    def validate(self) -> None:
        if self.type not in (TOKEN_TYPE_CLIENT, TOKEN_TYPE_MANAGEMENT):
            raise ValueError(f"token: bad type {self.type!r}")
        if self.type == TOKEN_TYPE_CLIENT and not self.policies:
            raise ValueError("client token requires at least one policy")
        if self.type == TOKEN_TYPE_MANAGEMENT and self.policies:
            raise ValueError("management token must not list policies")
