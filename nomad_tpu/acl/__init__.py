"""ACL system (reference: acl/ + nomad/acl.go)."""

from .acl import ACL, ACLError, compile_policies
from .policy import (
    CAP_DENY,
    NAMESPACE_CAPABILITIES,
    Policy,
    parse_policy,
)
from .structs import ACLPolicy, ACLToken

__all__ = [
    "ACL",
    "ACLError",
    "ACLPolicy",
    "ACLToken",
    "CAP_DENY",
    "NAMESPACE_CAPABILITIES",
    "Policy",
    "compile_policies",
    "parse_policy",
]
