"""ACL policy DSL.

Reference: acl/policy.go — HCL rules like:

    namespace "default" {
      policy = "write"
    }
    namespace "ops-*" {
      policy       = "read"
      capabilities = ["submit-job"]
    }
    node    { policy = "read" }
    agent   { policy = "write" }
    operator { policy = "read" }
    plugin  { policy = "list" }

Shorthand policies expand to capability sets exactly as the reference's
expandNamespacePolicy (acl/policy.go:92).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..jobspec.hcl import parse as parse_hcl

# Namespace capabilities (reference acl/policy.go:37-66)
CAP_DENY = "deny"
CAP_LIST_JOBS = "list-jobs"
CAP_READ_JOB = "read-job"
CAP_SUBMIT_JOB = "submit-job"
CAP_DISPATCH_JOB = "dispatch-job"
CAP_READ_LOGS = "read-logs"
CAP_READ_FS = "read-fs"
CAP_ALLOC_EXEC = "alloc-exec"
CAP_ALLOC_LIFECYCLE = "alloc-lifecycle"
CAP_SCALE_JOB = "scale-job"
CAP_ALLOC_NODE_EXEC = "alloc-node-exec"
# embedded secrets store (the Vault-analog; reference: Nomad variables
# ACL + vault policy scoping). NOT granted by the "read" shorthand — a
# read-only token must not see secret values unless explicitly given.
CAP_READ_SECRET = "read-secret"
CAP_WRITE_SECRET = "write-secret"

NAMESPACE_CAPABILITIES = [
    CAP_DENY,
    CAP_LIST_JOBS,
    CAP_READ_JOB,
    CAP_SUBMIT_JOB,
    CAP_DISPATCH_JOB,
    CAP_READ_LOGS,
    CAP_READ_FS,
    CAP_ALLOC_EXEC,
    CAP_ALLOC_LIFECYCLE,
    CAP_SCALE_JOB,
    CAP_ALLOC_NODE_EXEC,
    CAP_READ_SECRET,
    CAP_WRITE_SECRET,
]

_READ_CAPS = [CAP_LIST_JOBS, CAP_READ_JOB]
_WRITE_CAPS = _READ_CAPS + [
    CAP_SUBMIT_JOB,
    CAP_DISPATCH_JOB,
    CAP_READ_LOGS,
    CAP_READ_FS,
    CAP_ALLOC_EXEC,
    CAP_ALLOC_LIFECYCLE,
    CAP_SCALE_JOB,
    CAP_READ_SECRET,
    CAP_WRITE_SECRET,
]

POLICY_DENY = "deny"
POLICY_READ = "read"
POLICY_WRITE = "write"
POLICY_LIST = "list"
POLICY_SCALE = "scale"


class PolicyError(Exception):
    pass


@dataclass
class NamespacePolicy:
    name: str  # may contain glob '*'
    policy: str = ""
    capabilities: list[str] = field(default_factory=list)


@dataclass
class Policy:
    namespaces: list[NamespacePolicy] = field(default_factory=list)
    node: str = ""  # deny | read | write
    agent: str = ""
    operator: str = ""
    plugin: str = ""  # deny | list | read


def expand_namespace_policy(policy: str) -> list[str]:
    if policy == POLICY_DENY:
        return [CAP_DENY]
    if policy == POLICY_READ:
        return list(_READ_CAPS)
    if policy == POLICY_WRITE:
        return list(_WRITE_CAPS)
    if policy == POLICY_SCALE:
        return [CAP_SCALE_JOB, CAP_LIST_JOBS, CAP_READ_JOB]
    raise PolicyError(f"invalid namespace policy {policy!r}")


def parse_policy(rules: str) -> Policy:
    """Parse HCL rules text into a Policy (reference acl/policy.go:237)."""
    try:
        body = parse_hcl(rules)
    except Exception as e:
        raise PolicyError(f"failed to parse policy: {e}") from None
    pol = Policy()
    for blk in body.blocks("namespace"):
        name = blk.labels[0] if blk.labels else "default"
        a = blk.body.attrs()
        np = NamespacePolicy(
            name=name,
            policy=a.get("policy", ""),
            capabilities=[str(c) for c in a.get("capabilities", [])],
        )
        if np.policy:
            if np.policy not in (
                POLICY_DENY,
                POLICY_READ,
                POLICY_WRITE,
                POLICY_SCALE,
            ):
                raise PolicyError(f"invalid namespace policy {np.policy!r}")
        for c in np.capabilities:
            if c not in NAMESPACE_CAPABILITIES:
                raise PolicyError(f"invalid namespace capability {c!r}")
        pol.namespaces.append(np)
    for key in ("node", "agent", "operator"):
        blk = body.block(key)
        if blk is not None:
            p = blk.body.attrs().get("policy", "")
            if p not in (POLICY_DENY, POLICY_READ, POLICY_WRITE):
                raise PolicyError(f"invalid {key} policy {p!r}")
            setattr(pol, key, p)
    blk = body.block("plugin")
    if blk is not None:
        p = blk.body.attrs().get("policy", "")
        if p not in (POLICY_DENY, POLICY_LIST, POLICY_READ):
            raise PolicyError(f"invalid plugin policy {p!r}")
        pol.plugin = p
    return pol
