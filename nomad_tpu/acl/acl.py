"""Compiled ACL object.

Reference: acl/acl.go:43 — merges a set of parsed policies into one
capability view. Namespace rules support globs; the most-specific
matching rule wins (the reference scores glob matches by literal prefix
length via its radix tree; same outcome here via sort key).
"""

from __future__ import annotations

import fnmatch
from typing import Optional

from .policy import CAP_DENY, Policy, expand_namespace_policy

_LEVELS = {"": 0, "deny": 0, "read": 1, "write": 2}
# plugin has its own ladder: list < read (the policy validator keeps them
# distinct; collapsing them would give list-scoped tokens read access)
_PLUGIN_LEVELS = {"": 0, "deny": 0, "list": 1, "read": 2}


class ACLError(Exception):
    """Permission denied."""


class ACL:
    def __init__(self, management: bool = False) -> None:
        self.management = management
        # exact-or-glob namespace name -> set of capabilities
        self._namespaces: dict[str, set[str]] = {}
        self.node = ""
        self.agent = ""
        self.operator = ""
        self.plugin = ""

    # -- checks --------------------------------------------------------

    def is_management(self) -> bool:
        return self.management

    def allow_namespace_op(self, namespace: str, capability: str) -> bool:
        if self.management:
            return True
        caps = self._match_namespace(namespace)
        if caps is None or CAP_DENY in caps:
            return False
        return capability in caps

    def allow_namespace(self, namespace: str) -> bool:
        """Any non-deny capability on the namespace (reference
        AllowNamespace)."""
        if self.management:
            return True
        caps = self._match_namespace(namespace)
        return bool(caps) and CAP_DENY not in caps

    def _match_namespace(self, namespace: str) -> Optional[set[str]]:
        if namespace in self._namespaces:
            return self._namespaces[namespace]
        best: Optional[tuple[int, set[str]]] = None
        for pattern, caps in self._namespaces.items():
            if "*" not in pattern and "?" not in pattern:
                continue
            if fnmatch.fnmatchcase(namespace, pattern):
                # specificity = literal characters in the pattern
                score = len(pattern.replace("*", "").replace("?", ""))
                if best is None or score > best[0]:
                    best = (score, caps)
        return best[1] if best else None

    def _level(self, attr: str) -> int:
        levels = _PLUGIN_LEVELS if attr == "plugin" else _LEVELS
        return levels.get(getattr(self, attr), 0)

    def allow_node_read(self) -> bool:
        return self.management or self._level("node") >= 1

    def allow_node_write(self) -> bool:
        return self.management or self._level("node") >= 2

    def allow_agent_read(self) -> bool:
        return self.management or self._level("agent") >= 1

    def allow_agent_write(self) -> bool:
        return self.management or self._level("agent") >= 2

    def allow_operator_read(self) -> bool:
        return self.management or self._level("operator") >= 1

    def allow_operator_write(self) -> bool:
        return self.management or self._level("operator") >= 2

    def allow_plugin_read(self) -> bool:
        return self.management or self._level("plugin") >= 2

    def allow_plugin_list(self) -> bool:
        return self.management or self._level("plugin") >= 1


# The management singleton (reference ManagementACL)
MANAGEMENT_ACL = ACL(management=True)


def compile_policies(policies: list[Policy]) -> ACL:
    """Merge policies. Namespace capabilities union (explicit CAP_DENY
    poisons the namespace); for the coarse node/agent/operator/plugin
    levels an explicit deny ALWAYS wins, exactly like the reference's
    maxPrivilege — a read policy must never override a deny policy."""
    acl = ACL()
    denied: set[str] = set()
    for pol in policies:
        for np in pol.namespaces:
            caps = acl._namespaces.setdefault(np.name, set())
            if np.policy:
                caps.update(expand_namespace_policy(np.policy))
            caps.update(np.capabilities)
        for attr in ("node", "agent", "operator", "plugin"):
            val = getattr(pol, attr)
            if not val:
                continue
            if val == "deny":
                denied.add(attr)
            levels = _PLUGIN_LEVELS if attr == "plugin" else _LEVELS
            if levels.get(val, 0) >= levels.get(getattr(acl, attr), 0):
                setattr(acl, attr, val)
    for attr in denied:
        setattr(acl, attr, "deny")
    return acl
