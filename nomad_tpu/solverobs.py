"""Solver device observability: compile ledger, batch occupancy, and
host<->device transfer / device-memory accounting.

The batched solver's design claim — "compiles once per bucket"
(scheduler/tpu/kernels.py pad_n/pad_g) — was previously unmeasured: a
bucket recompile, padding waste, and host<->device transfer cost all
look identical from the outside (a slow solve). This module is the
always-on attribution layer that separates them:

  * compile ledger — every jit entry-point call records its padded-shape
    signature; a new signature is a TRACE/COMPILE event (with the call's
    wall time, split first-compile vs steady-state recompile), a repeat
    is a cache hit. The ledger is bounded (per-kernel signature FIFO) so
    a pathological shape storm can't grow it without bound — an evicted
    signature re-counts as a compile, which is exactly the pessimistic
    direction a regression guard wants.
  * batch occupancy — real rows/cols vs the padded bucket shapes
    (pad_n/pad_g): occupancy fraction, padding-waste fraction, and
    asks-per-batch, per solve.
  * transfer accounting — host->device bytes from the numpy arrays
    actually uploaded per dispatch (device-resident inputs excluded) and
    device->host bytes read back, from array ``nbytes``.
  * device memory — ``device.memory_stats()`` where the backend provides
    it (TPU/GPU; the CPU backend tier-1 uses returns None — kept as an
    explicit null, never fabricated) plus a live-array byte census and
    its high-water mark.

Deliberately a stdlib-only leaf (like faultplane.py): the control plane
imports it for the ``/v1/solver/status`` surface without paying the jax
import; jax is touched only inside :func:`sample_device_memory`, and only
when jax is already loaded in this process.

Everything is published through the established machinery: the
``nomad.solver.*`` metric names below are catalogued in docs/metrics.md
(the source-walk test enforces the names), ``solver.compile`` /
``solver.transfer`` spans land on the live trace, and ``snapshot()``
feeds ``GET /v1/solver/status``, ``operator solver status|top``, the
``operator debug`` bundle, and the bench's ``solver_observability``
block.
"""

from __future__ import annotations

import sys
import threading
import time
from typing import Optional

from . import metrics, trace

# Bounds: kernels are a closed set (the jit entry points in
# scheduler/tpu); signatures per kernel are the shape buckets, a handful
# in practice. The FIFO bound only matters under a shape storm — the
# very condition the ledger exists to surface.
MAX_KERNELS = 64
MAX_SIGNATURES = 256

_enabled = True


def enabled() -> bool:
    return _enabled


def set_enabled(on: bool) -> None:
    """The e2e overhead comparator's off switch (tests); production
    leaves this on — the whole point is always-on attribution."""
    global _enabled
    _enabled = bool(on)


class _Kernel:
    __slots__ = (
        "sigs", "compiles", "cache_hits", "steady_recompiles",
        "first_compile_ns", "steady_compile_ns", "last_sig", "evicted",
    )

    def __init__(self) -> None:
        # sig -> hit count; insertion-ordered dict IS the FIFO bound
        self.sigs: dict = {}
        self.compiles = 0
        self.cache_hits = 0
        self.steady_recompiles = 0
        self.first_compile_ns = 0
        self.steady_compile_ns = 0
        self.last_sig: Optional[tuple] = None
        self.evicted = 0

    def to_wire(self) -> dict:
        return {
            "compiles": self.compiles,
            "cache_hits": self.cache_hits,
            "steady_recompiles": self.steady_recompiles,
            "first_compile_ms": round(self.first_compile_ns / 1e6, 3),
            "steady_compile_ms": round(self.steady_compile_ns / 1e6, 3),
            "signatures": len(self.sigs),
            "signatures_evicted": self.evicted,
            "last_signature": (
                list(self.last_sig) if self.last_sig is not None else None
            ),
        }


class SolverObservatory:
    """One process-wide instance (module functions below delegate);
    tests may install a fresh one via _install()."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._kernels: dict[str, _Kernel] = {}
        # occupancy over batches
        self.batches = 0
        self.occupancy_sum = 0.0
        self.last_batch: Optional[dict] = None
        # asks-per-batch (recorded at the eval-batch layer, scheduler.py)
        self.last_asks: Optional[dict] = None
        # lowered node-table shape (lower.py build_node_table)
        self.last_table: Optional[dict] = None
        # transfer totals (bytes); allgather = modeled ICI traffic of
        # node-sharded solves, scatter = delta-sync rows landing in
        # their owning resident shard (scheduler/tpu/sharding.py)
        self.h2d_bytes = 0
        self.d2h_bytes = 0
        self.allgather_bytes = 0
        self.scatter_bytes = 0
        # sharding: device count + per-shard occupancy of the last
        # node-sharded dispatch (bounded: a mesh is <= 64 devices here)
        self.mesh_devices = 0
        self.last_shards: Optional[list] = None
        # device memory
        self.device_memory: Optional[dict] = None
        self.live_array_bytes = 0
        self.live_array_highwater = 0
        self._last_mem_sample = 0.0

    # -- compile ledger -------------------------------------------------

    def record_call(self, kernel: str, signature: tuple, wall_ns: int) -> bool:
        """One jit entry-point call: True when it was a trace/compile
        event (new padded-shape signature), False on a cache hit. Emits
        the nomad.solver.* compile metrics and a solver.compile span on
        the live trace for compile events."""
        if not _enabled:
            return False
        with self._lock:
            k = self._kernels.get(kernel)
            if k is None:
                if len(self._kernels) >= MAX_KERNELS:
                    return False  # closed set in practice; never grow past
                k = self._kernels[kernel] = _Kernel()
            k.last_sig = signature
            if signature in k.sigs:
                k.sigs[signature] += 1
                k.cache_hits += 1
                hit = True
            else:
                while len(k.sigs) >= MAX_SIGNATURES:
                    k.sigs.pop(next(iter(k.sigs)))
                    k.evicted += 1
                k.sigs[signature] = 0
                k.compiles += 1
                # steady-state recompile = the kernel had already
                # settled into serving cache hits, then compiled again.
                # Warm-up compiles (a multi-bucket cluster filling its
                # buckets before any repeat traffic) are NOT steady
                # recompiles — a healthy server reads ~0 here, and a
                # CLIMBING count is the recompile storm (operations.md).
                if k.cache_hits > 0:
                    k.steady_recompiles += 1
                    k.steady_compile_ns += wall_ns
                else:
                    k.first_compile_ns += wall_ns
                hit = False
        if hit:
            metrics.incr("nomad.solver.cache_hits")
            return False
        metrics.incr("nomad.solver.compiles")
        metrics.observe("nomad.solver.compile_seconds", wall_ns / 1e9)
        trace.stage_attrs(
            "solver.compile", wall_ns, kernel=kernel,
            signature=str(signature),
        )
        return True

    def compiles(self, prefix: str = "") -> int:
        with self._lock:
            return sum(
                k.compiles
                for name, k in self._kernels.items()
                if name.startswith(prefix)
            )

    def steady_recompiles(self, prefix: str = "") -> int:
        with self._lock:
            return sum(
                k.steady_recompiles
                for name, k in self._kernels.items()
                if name.startswith(prefix)
            )

    # -- batch occupancy ------------------------------------------------

    def record_batch(self, n: int, g: int, pad_n: int, pad_g: int) -> None:
        """One kernel dispatch's real vs padded shape."""
        if not _enabled:
            return
        denom = max(1, pad_n * pad_g)
        occ = (n * g) / denom
        waste = 1.0 - occ
        with self._lock:
            self.batches += 1
            self.occupancy_sum += occ
            self.last_batch = {
                "n": n, "g": g, "pad_n": pad_n, "pad_g": pad_g,
                "occupancy": round(occ, 4), "pad_waste": round(waste, 4),
            }
        metrics.observe("nomad.solver.occupancy", occ)
        metrics.observe("nomad.solver.pad_waste", waste)

    def note_asks(self, groups: int, requests: int) -> None:
        """Asks-per-batch at the eval-batch layer (scheduler.py)."""
        if not _enabled:
            return
        with self._lock:
            self.last_asks = {"groups": groups, "requests": requests}
        metrics.observe("nomad.solver.batch_asks", float(groups))
        metrics.observe("nomad.solver.batch_requests", float(requests))

    def note_table(self, n: int, nbytes: int) -> None:
        """The lowered node table's host-side tensor footprint
        (lower.py build_node_table)."""
        if not _enabled:
            return
        with self._lock:
            self.last_table = {"nodes": n, "host_bytes": int(nbytes)}

    def record_shards(self, n_dev: int, shards: list) -> None:
        """Per-shard occupancy of one node-sharded dispatch
        (sharding.SolverMesh.shard_occupancy rows). Bounded: a mesh
        larger than 64 devices keeps its first 64 rows plus the count —
        enough to read an imbalance, never an unbounded payload."""
        if not _enabled:
            return
        shards = list(shards[:64])
        with self._lock:
            self.mesh_devices = int(n_dev)
            self.last_shards = shards
        for s in shards:
            metrics.observe(
                "nomad.solver.shard_occupancy", float(s.get("occupancy", 0.0))
            )

    # -- transfers ------------------------------------------------------

    def record_transfer(
        self, direction: str, nbytes: int, dur_ns: int = 0, span: bool = False
    ) -> None:
        """direction: 'h2d' | 'd2h' | 'allgather' | 'scatter'. span=True
        also lands a solver.transfer span of dur_ns on the live trace."""
        if not _enabled or nbytes <= 0:
            return
        with self._lock:
            if direction == "h2d":
                self.h2d_bytes += nbytes
            elif direction == "allgather":
                self.allgather_bytes += nbytes
            elif direction == "scatter":
                self.scatter_bytes += nbytes
            else:
                self.d2h_bytes += nbytes
        metrics.incr(f"nomad.solver.transfer_bytes.{direction}", nbytes)
        # per-dispatch size distribution in MEGABYTES: the registry's
        # fixed exponential bounds (1e-4 .. ~1677, tuned for seconds)
        # then cover 100B .. ~1.6GB per dispatch — byte-unit values
        # would all land in the +Inf bucket and make the percentiles
        # meaningless
        metrics.observe(f"nomad.solver.{direction}_mb", nbytes / 1e6)
        if span:
            trace.stage_attrs(
                "solver.transfer", dur_ns, direction=direction, bytes=nbytes
            )

    # -- device memory --------------------------------------------------

    def sample_device_memory(self, force: bool = False) -> None:
        """Sample backend memory stats + live-array census. Only touches
        jax when it is already imported (never drags the backend into a
        control-plane process); memory_stats() is None on backends that
        don't report (the CPU tier-1 backend) and stays an explicit
        null. Rate-limited to ~1/s on the solve path (live_arrays()
        walks every live array — per-batch cost that matters at
        millisecond solve sizes); force=True (the /v1/solver/status
        read) always samples fresh."""
        if not _enabled or "jax" not in sys.modules:
            return
        now = time.monotonic()
        if not force and now - self._last_mem_sample < 1.0:
            return
        self._last_mem_sample = now
        try:
            import jax

            dev = jax.devices()[0]
            stats = dev.memory_stats() if hasattr(dev, "memory_stats") else None
            live = 0
            for arr in jax.live_arrays():
                live += getattr(arr, "nbytes", 0) or 0
        except Exception:  # device introspection must never break a solve
            return
        with self._lock:
            self.device_memory = dict(stats) if stats else None
            self.live_array_bytes = live
            if live > self.live_array_highwater:
                self.live_array_highwater = live
        metrics.set_gauge("nomad.solver.live_array_bytes", float(live))
        metrics.set_gauge(
            "nomad.solver.live_array_highwater_bytes",
            float(self.live_array_highwater),
        )
        if stats and "bytes_in_use" in stats:
            metrics.set_gauge(
                "nomad.solver.device_bytes_in_use",
                float(stats["bytes_in_use"]),
            )

    # -- read side ------------------------------------------------------

    def snapshot(self, sample: bool = True) -> dict:
        """The /v1/solver/status payload. sample=True refreshes the
        device-memory census first (no-op unless jax is loaded)."""
        if sample:
            self.sample_device_memory(force=True)
        with self._lock:
            kernels = {
                name: k.to_wire() for name, k in self._kernels.items()
            }
            compiles = sum(k.compiles for k in self._kernels.values())
            hits = sum(k.cache_hits for k in self._kernels.values())
            steady = sum(
                k.steady_recompiles for k in self._kernels.values()
            )
            batches = self.batches
            occ_mean = (
                self.occupancy_sum / batches if batches else None
            )
            return {
                "enabled": _enabled,
                "ledger": {
                    "kernels": kernels,
                    "compiles": compiles,
                    "cache_hits": hits,
                    "steady_recompiles": steady,
                },
                "occupancy": {
                    "batches": batches,
                    "mean": round(occ_mean, 4) if occ_mean is not None else None,
                    "last_batch": dict(self.last_batch)
                    if self.last_batch else None,
                    "last_asks": dict(self.last_asks)
                    if self.last_asks else None,
                    "last_table": dict(self.last_table)
                    if self.last_table else None,
                },
                "transfers": {
                    "h2d_bytes": self.h2d_bytes,
                    "d2h_bytes": self.d2h_bytes,
                    "allgather_bytes": self.allgather_bytes,
                    "scatter_bytes": self.scatter_bytes,
                },
                "sharding": {
                    "devices": self.mesh_devices,
                    "last_shards": (
                        [dict(s) for s in self.last_shards]
                        if self.last_shards else None
                    ),
                },
                "device_memory": dict(self.device_memory)
                if self.device_memory else None,
                "live_array_bytes": self.live_array_bytes,
                "live_array_highwater_bytes": self.live_array_highwater,
            }


_global = SolverObservatory()


def observatory() -> SolverObservatory:
    return _global


def _install(obs: SolverObservatory) -> SolverObservatory:
    """Swap the process-global observatory (returns the previous one) —
    the test/bench isolation hook, mirroring metrics._install_registry."""
    global _global, record_call, record_batch, note_asks, note_table
    global record_transfer, record_shards, sample_device_memory, snapshot
    global compiles, steady_recompiles
    old = _global
    _global = obs
    record_call = obs.record_call
    record_batch = obs.record_batch
    note_asks = obs.note_asks
    note_table = obs.note_table
    record_transfer = obs.record_transfer
    record_shards = obs.record_shards
    sample_device_memory = obs.sample_device_memory
    snapshot = obs.snapshot
    compiles = obs.compiles
    steady_recompiles = obs.steady_recompiles
    return old


# Module-level conveniences, rebindable via _install (call sites read
# `solverobs.<fn>` through the module at call time).
record_call = _global.record_call
record_batch = _global.record_batch
note_asks = _global.note_asks
note_table = _global.note_table
record_transfer = _global.record_transfer
record_shards = _global.record_shards
sample_device_memory = _global.sample_device_memory
snapshot = _global.snapshot
compiles = _global.compiles
steady_recompiles = _global.steady_recompiles


def timed_call(kernel: str, signature: tuple, fn, *args, **kwargs):
    """Run a jit entry point under the compile ledger: times the call
    (tracing + compilation happen synchronously at dispatch; execution
    is async and NOT awaited here) and records compile-vs-hit."""
    t0 = time.monotonic_ns()
    out = fn(*args, **kwargs)
    record_call(kernel, signature, time.monotonic_ns() - t0)
    return out
