"""nomad-vet rules: the repo's concurrency & layering invariants, named.

Every rule returns ``Finding`` objects with a STABLE suppression key
(``relpath:qual#anchor`` — no line numbers, so the baseline ledger
survives unrelated edits) plus file:line for humans. The rule ids:

  NV-lock-blocking  no blocking call (RPC / raft apply / device
                    dispatch / time.sleep / socket / fsync / Future
                    .result / thread join / Event.wait) while a known
                    lock is held, resolved through the per-module call
                    graph. Waiting on a Condition is exempt for the
                    cv's own lock (wait releases it) but flagged for
                    any OTHER lock held around it.
  NV-lock-order     static lock acquire graph (nested with-regions,
                    propagated through calls); cycles are findings.
                    Cross-checking against the dynamic racecheck edge
                    set reports coverage gaps as ADVISORIES.
  NV-layering       stdlib-leaf modules must not import jax or app
                    packages at module scope; jax eagerly only under
                    scheduler/tpu; production never imports
                    nomad_tpu.testing.
  NV-except         no bare ``except:``; a handler that names
                    CancelledError / NotLeaderError /
                    LeadershipLostError must nack or re-raise.
  NV-thread         every threading.Thread has an explicit ``name=``
                    and is daemon=True or joined by its owner.
  NV-literal        metrics.* and trace-span name arguments are string
                    literals present in the docs catalogues.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field

from .model import (CallSite, FuncInfo, Index, _call_target,
                    _callable_fullname, iter_scope, iter_scope_stmts,
                    resolve_name)

GATE_RULES = (
    "NV-lock-blocking", "NV-lock-order", "NV-layering",
    "NV-except", "NV-thread", "NV-literal",
)


@dataclass
class Finding:
    rule: str
    file: str
    line: int
    message: str
    key: str            # stable suppression anchor (no line numbers)
    chain: tuple = ()   # call/lock chain, outermost first
    advisory: bool = False

    def to_dict(self) -> dict:
        return {
            "rule": self.rule, "file": self.file, "line": self.line,
            "message": self.message, "key": self.key,
            "chain": list(self.chain), "advisory": self.advisory,
        }


# ---------------------------------------------------------------------------
# blocking-sink model
# ---------------------------------------------------------------------------

# module-level callables that block the calling thread
BLOCKING_DOTTED = {
    "time.sleep": "time.sleep",
    "os.fsync": "os.fsync",
    "os.fdatasync": "os.fdatasync",
    "select.select": "select.select",
    "socket.create_connection": "socket.create_connection",
    "subprocess.run": "subprocess.run",
    "subprocess.check_output": "subprocess.check_output",
    "subprocess.check_call": "subprocess.check_call",
    "jax.device_put": "jax.device_put (device dispatch)",
    "nomad_tpu.scheduler.tpu.solve_eval_batch":
        "solve_eval_batch (device dispatch)",
}

# method names distinctive enough to flag on ANY receiver
BLOCKING_METHODS = {
    "sendall": "socket send",
    "recv": "socket recv",
    "recvfrom": "socket recv",
    "accept": "socket accept",
    "communicate": "subprocess wait",
    "fsync": "fsync",
    "raft_apply": "raft apply (quorum round-trip)",
    "apply_wait": "raft apply wait",
    "block_until_ready": "device sync",
    "result": "Future.result",
}

# `.call(...)` is an RPC round-trip only on rpc-ish receivers
_RPC_RECEIVER_RE = re.compile(r"pool|rpc|conn|client", re.I)


@dataclass
class _Blocking:
    label: str           # sink description
    chain: tuple         # ("qual (file:line)", ...) down to the sink
    exempt_token: str = ""  # condition-wait: the cv's own lock token


_UNRESOLVED = object()  # cache sentinel (None is a valid resolution)


class Resolver:
    """Call-target resolution + blocking/acquire fixpoints."""

    MAX_PASSES = 200  # runaway backstop; run_vet gates when hit

    def __init__(self, index: Index) -> None:
        self.index = index
        self.blocking: dict = {}        # funckey -> _Blocking
        self.acquired: dict = {}        # funckey -> {token: chain tuple}
        self._cache: dict = {}          # id(site) -> resolution
        self.converged = False
        self._fixpoint()

    # -- resolution ---------------------------------------------------------

    def resolve(self, f: FuncInfo, site: CallSite):
        """("func", FuncInfo) | ("sink", label) |
        ("cond", label, own_token) | None.

        Memoized per site: resolution reads only the immutable index
        (never the blocking/acquired fixpoint state), and the fixpoint
        re-visits every site each pass — without the cache the walk's
        dominant cost scales as passes x sites, and check_lock_blocking
        / static_edges resolve everything yet again. Sites are owned by
        FuncInfo.calls for the Resolver's whole lifetime, so id() keys
        are stable."""
        got = self._cache.get(id(site), _UNRESOLVED)
        if got is not _UNRESOLVED:
            return got
        got = self._resolve(f, site)
        self._cache[id(site)] = got
        return got

    def _resolve(self, f: FuncInfo, site: CallSite):
        t = site.target
        m = f.module
        cls = m.classes.get(f.cls) if f.cls else None
        if t[0] == "name":
            if t[1] in m.functions:
                return ("func", m.functions[t[1]])
            full = m.aliases.get(t[1])
            if full:
                return self._resolve_dotted(full)
            return None
        if t[0] in ("var", "dotted"):
            if t[0] == "var":
                root, meth = t[1], t[2]
                if root in m.aliases:
                    return self._resolve_dotted(
                        m.aliases[root] + "." + meth)
                if root in f.thread_vars and meth == "join":
                    return ("sink", "Thread.join")
                if root in f.var_types:
                    got = self.index.method(f.var_types[root], meth)
                    if got is not None:
                        return ("func", got)
                return self._method_sink(root, meth)
            return self._resolve_dotted(resolve_name(m, t[1]))
        if t[0] == "self":
            meth = t[1]
            if cls is not None:
                got = self.index.method(cls.fullname, meth)
                if got is not None:
                    return ("func", got)
            return self._method_sink("self", meth)
        if t[0] == "selfattr":
            attr, meth = t[1], t[2]
            if cls is not None:
                ld = cls.locks.get(attr)
                if ld is not None and ld.kind == "condition" \
                        and meth == "wait":
                    own = ld.token
                    if ld.wraps and ld.wraps in cls.locks:
                        own = cls.locks[ld.wraps].token
                    return ("cond", f"Condition.wait ({ld.name})", own)
                if attr in cls.events and meth == "wait":
                    return ("sink", f"Event.wait (self.{attr})")
                if attr in cls.threads and meth == "join":
                    return ("sink", "Thread.join")
                if attr in cls.attr_types:
                    got = self.index.method(cls.attr_types[attr], meth)
                    if got is not None:
                        return ("func", got)
            return self._method_sink(attr, meth)
        if t[0] == "expr":
            return self._method_sink("", t[1])
        return None

    def _resolve_dotted(self, full: str):
        if full in BLOCKING_DOTTED:
            return ("sink", BLOCKING_DOTTED[full])
        got = self.index.repo_function(full)
        if got is not None:
            return ("func", got)
        cls = self.index.classes.get(full)
        if cls is not None and "__init__" in cls.methods:
            return ("func", cls.methods["__init__"])
        # mod.Class.method / alias.Class(...)
        head, _, meth = full.rpartition(".")
        cls = self.index.classes.get(head)
        if cls is not None:
            got = self.index.method(head, meth)
            if got is not None:
                return ("func", got)
        if meth in BLOCKING_METHODS:
            return ("sink", BLOCKING_METHODS[meth])
        return None

    def _method_sink(self, receiver: str, meth: str):
        if meth == "call" and _RPC_RECEIVER_RE.search(receiver):
            return ("sink", f"RPC call ({receiver}.call)")
        if meth in BLOCKING_METHODS:
            return ("sink", BLOCKING_METHODS[meth])
        return None

    # -- fixpoints ----------------------------------------------------------

    def _fixpoint(self) -> None:
        funcs = list(self.index.funcs.values())
        for f in funcs:
            self.acquired[f.key] = {
                tok: (f"{f.qual} ({f.module.relpath}:{ln})",)
                for tok, ln, _held in f.acquires
            }
        # MAX_PASSES is a runaway backstop, not a depth budget:
        # information moves at least one call-graph level per pass, so
        # a non-converged exit means chains deeper than the cap were
        # silently dropped. run_vet surfaces that as a GATE error (the
        # no-silent-caps contract this tool enforces on everything
        # else) — converged stays False unless the loop exits clean.
        for _pass in range(self.MAX_PASSES):
            changed = False
            for f in funcs:
                for site in f.calls:
                    got = self.resolve(f, site)
                    if got is None:
                        continue
                    here = f"{f.qual} ({f.module.relpath}:{site.lineno})"
                    if got[0] == "sink":
                        changed |= self._mark_blocking(
                            f, _Blocking(got[1], (here, got[1])))
                    elif got[0] == "cond":
                        changed |= self._mark_blocking(
                            f, _Blocking(got[1], (here, got[1]), got[2]))
                    elif got[0] == "func":
                        callee = got[1]
                        b = self.blocking.get(callee.key)
                        if b is not None:
                            changed |= self._mark_blocking(
                                f, _Blocking(
                                    b.label, (here,) + b.chain,
                                    b.exempt_token))
                        mine = self.acquired[f.key]
                        for tok, chain in self.acquired.get(
                                callee.key, {}).items():
                            if tok not in mine:
                                mine[tok] = (here,) + chain
                                changed = True
            if not changed:
                self.converged = True
                break

    def _mark_blocking(self, f: FuncInfo, b: _Blocking) -> bool:
        cur = self.blocking.get(f.key)
        # prefer unconditional sinks over condition-wait (exemptable),
        # then shorter chains — stable under iteration order
        if cur is None or (cur.exempt_token and not b.exempt_token) or (
                bool(cur.exempt_token) == bool(b.exempt_token)
                and len(b.chain) < len(cur.chain)):
            if cur is not None and cur.label == b.label \
                    and len(cur.chain) <= len(b.chain):
                return False
            self.blocking[f.key] = b
            return True
        return False


def _lock_label(index: Index, token: str) -> str:
    ld = index.locks.get(token)
    if ld is None:
        return token
    role = f" role={ld.role}" if ld.role else ""
    return f"{ld.name} [{token}]{role}"


# ---------------------------------------------------------------------------
# NV-lock-blocking
# ---------------------------------------------------------------------------


def check_lock_blocking(index: Index, resolver: Resolver) -> list:
    out: list = []
    seen: set = set()
    for f in index.funcs.values():
        if f.module.is_testing:
            continue
        for site in f.calls:
            if not site.held:
                continue
            got = resolver.resolve(f, site)
            if got is None:
                continue
            if got[0] == "sink":
                label, chain, exempt = got[1], (got[1],), ""
            elif got[0] == "cond":
                label, chain, exempt = got[1], (got[1],), got[2]
            else:
                b = resolver.blocking.get(got[1].key)
                if b is None:
                    continue
                label, chain, exempt = b.label, b.chain, b.exempt_token
            held = [t for t in site.held if t != exempt]
            if not held:
                continue  # cv.wait under only its own lock: releases it
            # the held-lock NAMES are part of the key (stable across
            # unrelated edits, unlike the line-numbered tokens): a
            # baselined sleep under lock A must not mask a NEW sleep
            # under lock B in the same function
            held_names = "+".join(sorted(
                _slug(index.locks[t].name if t in index.locks else t)
                for t in held))
            key = f"{f.module.relpath}:{f.qual}#{_slug(label)}@{held_names}"
            if key in seen:
                continue
            seen.add(key)
            locks = ", ".join(_lock_label(index, t) for t in held)
            here = f"{f.qual} ({f.module.relpath}:{site.lineno})"
            out.append(Finding(
                "NV-lock-blocking", f.module.relpath, site.lineno,
                f"blocking call [{label}] while holding {locks}",
                key, chain=(here,) + chain))
    return out


def _slug(label: str) -> str:
    return re.sub(r"[^A-Za-z0-9_.]+", "-", label).strip("-")


# ---------------------------------------------------------------------------
# NV-lock-order
# ---------------------------------------------------------------------------


def static_edges(index: Index, resolver: Resolver) -> dict:
    """(from_token, to_token) -> witness chain tuple."""
    edges: dict = {}
    for f in index.funcs.values():
        if f.module.is_testing:
            continue
        for tok, ln, held in f.acquires:
            for prior in held:
                if prior != tok:
                    edges.setdefault(
                        (prior, tok),
                        (f"{f.qual} ({f.module.relpath}:{ln})",))
        for site in f.calls:
            if not site.held:
                continue
            got = resolver.resolve(f, site)
            if got is None or got[0] != "func":
                continue
            here = f"{f.qual} ({f.module.relpath}:{site.lineno})"
            for tok, chain in resolver.acquired.get(
                    got[1].key, {}).items():
                for prior in site.held:
                    if prior != tok:
                        edges.setdefault(
                            (prior, tok), (here,) + chain)
    return edges


def check_lock_order(index: Index, resolver: Resolver,
                     dynamic_edges=None, edges: dict = None) -> list:
    if edges is None:
        edges = static_edges(index, resolver)
    out = _cycles(index, edges)
    if dynamic_edges is not None:
        dyn = {(e["from"], e["to"]) for e in dynamic_edges}
        for (a, b), chain in sorted(edges.items()):
            if (a, b) not in dyn:
                out.append(Finding(
                    "NV-lock-order", a.rsplit(":", 1)[0],
                    int(a.rsplit(":", 1)[1]),
                    f"static lock edge {_lock_label(index, a)} -> "
                    f"{_lock_label(index, b)} never covered by the "
                    f"dynamic racecheck run",
                    f"edge-uncovered:{a}->{b}", chain=chain,
                    advisory=True))
        stat = set(edges)
        for a, b in sorted(dyn):
            if (a, b) not in stat and a in index.locks \
                    and b in index.locks:
                out.append(Finding(
                    "NV-lock-order", a.rsplit(":", 1)[0],
                    int(a.rsplit(":", 1)[1]),
                    f"dynamic lock edge {_lock_label(index, a)} -> "
                    f"{_lock_label(index, b)} invisible to the static "
                    f"acquire graph (acquired outside `with` regions?)",
                    f"edge-unseen:{a}->{b}", advisory=True))
    return out


def _cycles(index: Index, edges: dict) -> list:
    """Tarjan SCCs over the acquire graph; size>1 (or a self-edge) is a
    potential deadlock. One finding per SCC, keyed by its sorted
    members so the baseline survives witness drift."""
    graph: dict = {}
    for (a, b) in edges:
        graph.setdefault(a, set()).add(b)
        graph.setdefault(b, set())
    idx = {}
    low = {}
    stack: list = []
    on: set = set()
    counter = [0]
    sccs: list = []

    def strongconnect(v):
        work = [(v, iter(sorted(graph[v])))]
        idx[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on.add(v)
        while work:
            node, it = work[-1]
            advanced = False
            for w in it:
                if w not in idx:
                    idx[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on.add(w)
                    work.append((w, iter(sorted(graph[w]))))
                    advanced = True
                    break
                if w in on:
                    low[node] = min(low[node], idx[w])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == idx[node]:
                scc = []
                while True:
                    w = stack.pop()
                    on.discard(w)
                    scc.append(w)
                    if w == node:
                        break
                if len(scc) > 1 or node in graph.get(node, ()):
                    sccs.append(sorted(scc))

    for v in sorted(graph):
        if v not in idx:
            strongconnect(v)

    out = []
    for scc in sccs:
        members = ", ".join(_lock_label(index, t) for t in scc)
        witness = []
        for (a, b), chain in sorted(edges.items()):
            if a in scc and b in scc:
                witness.append(f"{a} -> {b} via {chain[0]}")
        first = scc[0]
        out.append(Finding(
            "NV-lock-order", first.rsplit(":", 1)[0],
            int(first.rsplit(":", 1)[1]),
            f"lock-order cycle: {members}",
            "cycle:" + "|".join(scc), chain=tuple(witness)))
    return out


# ---------------------------------------------------------------------------
# NV-layering
# ---------------------------------------------------------------------------

LEAF_MODULES = (
    "trace", "metrics", "hostobs", "solverobs", "faultplane",
    "ratelimit", "retry", "gctune", "clusterobs", "blackbox",
)
JAX_EAGER_OK_PREFIX = "scheduler/tpu"


def check_layering(index: Index, package: str = "nomad_tpu") -> list:
    out = []
    leaf_full = {f"{package}.{m}" for m in LEAF_MODULES}
    for m in index.modules.values():
        if m.is_testing:
            continue
        is_leaf = m.modname in leaf_full
        for imp in m.imports:
            full = imp.fullname
            if full == f"{package}.testing" or \
                    full.startswith(f"{package}.testing."):
                out.append(Finding(
                    "NV-layering", m.relpath, imp.lineno,
                    f"production module imports {full} — the testing "
                    f"package must never be a production dependency",
                    f"{m.relpath}:<module>#import-testing"))
                continue
            if not imp.module_scope:
                continue  # lazy import: the sanctioned pattern
            if full == "jax" or full.startswith("jax."):
                if not m.relpath.startswith(
                        f"{package}/{JAX_EAGER_OK_PREFIX}"):
                    out.append(Finding(
                        "NV-layering", m.relpath, imp.lineno,
                        f"eager `import {full}` outside "
                        f"{package}/{JAX_EAGER_OK_PREFIX} — the control "
                        f"plane must serve without loading jax",
                        f"{m.relpath}:<module>#eager-jax"))
                continue
            if is_leaf and full.split(".")[0] == package:
                target = full[len(package) + 1:].split(".")[0]
                if target and target not in LEAF_MODULES:
                    out.append(Finding(
                        "NV-layering", m.relpath, imp.lineno,
                        f"stdlib-leaf module eagerly imports {full} — "
                        f"leaves may only import stdlib or other "
                        f"leaves at module scope",
                        f"{m.relpath}:<module>#leaf-imports-{target}"))
    return out


# ---------------------------------------------------------------------------
# NV-except
# ---------------------------------------------------------------------------

GUARDED_EXCEPTIONS = (
    "CancelledError", "NotLeaderError", "LeadershipLostError",
)


def _handler_names(h: ast.ExceptHandler) -> list:
    types = []
    t = h.type
    elts = t.elts if isinstance(t, ast.Tuple) else ([t] if t else [])
    for e in elts:
        if isinstance(e, ast.Name):
            types.append(e.id)
        elif isinstance(e, ast.Attribute):
            types.append(e.attr)
    return types


def check_except(index: Index) -> list:
    out = []
    for m in index.modules.values():
        if m.is_testing:
            continue
        for f in m.all_funcs:
            counts: dict = {}
            for node in iter_scope_stmts(f.node):
                if not isinstance(node, ast.ExceptHandler):
                    continue
                if node.type is None:
                    key = f"{m.relpath}:{f.qual}#bare-except"
                    n = counts.setdefault(key, 0)
                    counts[key] += 1
                    out.append(Finding(
                        "NV-except", m.relpath, node.lineno,
                        "bare `except:` swallows SystemExit/"
                        "KeyboardInterrupt and every cancellation "
                        "signal — name the exceptions",
                        key if n == 0 else f"{key}-{n}"))
                    continue
                caught = _handler_names(node)
                guarded = [c for c in caught if c in GUARDED_EXCEPTIONS]
                if not guarded:
                    continue
                if _handler_reraises_or_nacks(node):
                    continue
                names = "/".join(sorted(set(guarded)))
                key = f"{m.relpath}:{f.qual}#swallows-{names}"
                n = counts.setdefault(key, 0)
                counts[key] += 1
                out.append(Finding(
                    "NV-except", m.relpath, node.lineno,
                    f"handler catches {names} without nack or "
                    f"re-raise — a cancellation/leadership signal "
                    f"dies here and the eval is neither redelivered "
                    f"nor surfaced",
                    key if n == 0 else f"{key}-{n}"))
    return out


def _handler_reraises_or_nacks(h: ast.ExceptHandler) -> bool:
    for node in iter_scope(h):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call):
            t = _call_target(node.func)
            name = t[-1] if t else ""
            if "nack" in str(name):
                return True
    return False


# ---------------------------------------------------------------------------
# NV-thread
# ---------------------------------------------------------------------------


def check_threads(index: Index) -> list:
    out = []
    for m in index.modules.values():
        if m.is_testing:
            continue
        for f in m.all_funcs:
            cls = m.classes.get(f.cls) if f.cls else None
            for node in iter_scope(f.node):
                if not isinstance(node, ast.Call):
                    continue
                fn = node.func
                # cheap syntactic pre-filter before alias resolution
                if not (isinstance(fn, ast.Name) and fn.id == "Thread"
                        or isinstance(fn, ast.Attribute)
                        and fn.attr == "Thread"):
                    continue
                if _callable_fullname(m, node) != "threading.Thread":
                    continue
                kw = {k.arg: k.value for k in node.keywords if k.arg}
                binding = _thread_binding(f.node, node)
                anchor = binding or f"L{_ordinal(f.node, node)}"
                if "name" not in kw:
                    out.append(Finding(
                        "NV-thread", m.relpath, node.lineno,
                        "threading.Thread without an explicit name= — "
                        "anonymous threads are invisible to the host "
                        "profiler's role attribution and to shutdown "
                        "triage",
                        f"{m.relpath}:{f.qual}#thread-unnamed-"
                        f"{anchor}"))
                if not _thread_owned(m, f, cls, node, kw, binding):
                    out.append(Finding(
                        "NV-thread", m.relpath, node.lineno,
                        "thread is neither daemon=True nor joined by "
                        "its owner — it can outlive stop() and leak "
                        "across agent reloads",
                        f"{m.relpath}:{f.qual}#thread-leaked-"
                        f"{anchor}"))
    return out


def _ordinal(fnode, call) -> int:
    n = 0
    for node in iter_scope(fnode):
        if isinstance(node, ast.Call) and node is call:
            return n
        if isinstance(node, ast.Call):
            n += 1
    return n


def _thread_binding(fnode, call):
    """'self.X' / local name the Thread lands in, else None."""
    for node in iter_scope(fnode):
        if isinstance(node, ast.Assign) and node.value is call \
                and len(node.targets) == 1:
            tgt = node.targets[0]
            if isinstance(tgt, ast.Attribute) \
                    and isinstance(tgt.value, ast.Name) \
                    and tgt.value.id == "self":
                return f"self.{tgt.attr}"
            if isinstance(tgt, ast.Name):
                return tgt.id
    return None


def _truthy(node) -> bool:
    return isinstance(node, ast.Constant) and node.value is True


def _thread_owned(m, f, cls, call, kw, binding) -> bool:
    if "daemon" in kw and _truthy(kw["daemon"]):
        return True
    if binding is None:
        # fire-and-forget expression (threading.Thread(...).start()):
        # only daemon=True can make that safe
        return False
    # X.daemon = True anywhere in the creating function
    for node in iter_scope(f.node):
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            tgt = node.targets[0]
            if isinstance(tgt, ast.Attribute) and tgt.attr == "daemon" \
                    and _expr_matches(tgt.value, binding) \
                    and _truthy(node.value):
                return True
    # joined: self-attr threads anywhere in the owning class (stop()
    # conventionally, but any owner join keeps the thread accounted);
    # local threads joined in the same function
    scope = cls.methods.values() if (
        binding.startswith("self.") and cls is not None) else [f]
    attr = binding[5:] if binding.startswith("self.") else binding
    for fn in scope:
        for node in iter_scope(fn.node):
            if isinstance(node, ast.Call):
                t = _call_target(node.func)
                if t[0] == "selfattr" and t[1] == attr \
                        and t[2] == "join" and binding.startswith("self."):
                    return True
                if t[0] == "var" and t[1] == attr and t[2] == "join" \
                        and not binding.startswith("self."):
                    return True
    # a local thread appended to a list that is later join()ed in the
    # same function (for t in ts: t.join()) — the joined variable must
    # be a loop target, or a bare str.join(...) like sep.join(parts)
    # would silently vouch for every leaked thread in the function
    if not binding.startswith("self."):
        loop_vars = set()
        for node in iter_scope(f.node):
            if isinstance(node, ast.For) and isinstance(
                    node.target, ast.Name):
                loop_vars.add(node.target.id)
            elif isinstance(node, (ast.ListComp, ast.SetComp,
                                   ast.GeneratorExp)):
                for gen in node.generators:
                    if isinstance(gen.target, ast.Name):
                        loop_vars.add(gen.target.id)
        for node in iter_scope(f.node):
            if isinstance(node, ast.Call):
                t = _call_target(node.func)
                if t[0] == "var" and t[2] == "join" \
                        and t[1] in loop_vars:
                    return True
    return False


def _expr_matches(expr, binding: str) -> bool:
    if binding.startswith("self."):
        return isinstance(expr, ast.Attribute) \
            and isinstance(expr.value, ast.Name) \
            and expr.value.id == "self" and expr.attr == binding[5:]
    return isinstance(expr, ast.Name) and expr.id == binding


# ---------------------------------------------------------------------------
# NV-literal
# ---------------------------------------------------------------------------

METRICS_FNS = ("incr", "observe", "set_gauge", "time_ns",
               "register_provider")
SPAN_ARG_INDEX = {  # call-form -> position of the name argument
    "span": 1,          # trace.span(ctx, "name", ...)
    "start_span": 0,    # ctx.start_span("name", ...)
    "stage": 0,         # trace.stage("name", dur)
    "stage_attrs": 0,   # trace.stage_attrs("name", dur, ...)
    "add_stage": 0,     # span.add_stage("name", ...)
}
# the engines themselves manipulate names dynamically by design
LITERAL_EXEMPT = ("nomad_tpu/metrics.py", "nomad_tpu/trace.py")
_LITERAL_ATTRS = frozenset(METRICS_FNS) | frozenset(SPAN_ARG_INDEX)


def _canonical(name: str) -> str:
    return re.sub(r"(\{[^}]*\}|<[^>]+>)", "※", name)


def _fstring_head(node: ast.JoinedStr) -> str:
    """Literal text with {…} placeholders for formatted values."""
    parts = []
    for v in node.values:
        if isinstance(v, ast.Constant):
            parts.append(str(v.value))
        else:
            parts.append("{}")
    return "".join(parts)


def check_literals(index: Index, metric_names: list,
                   span_names: set) -> list:
    """metric_names: docs/metrics.md catalogue rows; span_names:
    docs/tracing.md span-catalogue table rows. Empty catalogues
    (fixture runs) skip the respective membership check but still
    require literalness."""
    out = []
    raw = set(metric_names)
    canon = [_canonical(n) for n in metric_names]
    for m in index.modules.values():
        if m.is_testing or m.relpath in LITERAL_EXEMPT:
            continue
        for f in m.all_funcs:
            for node in iter_scope(f.node):
                if not isinstance(node, ast.Call):
                    continue
                fn = node.func
                if not (isinstance(fn, ast.Attribute)
                        and fn.attr in _LITERAL_ATTRS):
                    continue
                t = _call_target(node.func)
                if t[0] == "var" and t[2] in METRICS_FNS and \
                        resolve_name(m, t[1]).endswith("metrics"):
                    out.extend(_check_metric_site(
                        m, f, node, t[2], raw, canon, metric_names))
                elif _is_span_site(m, t):
                    out.extend(_check_span_site(
                        m, f, node, t, span_names))
    return out


def _is_span_site(m, t) -> bool:
    if t[0] == "var" and t[2] in ("span", "stage", "stage_attrs"):
        return resolve_name(m, t[1]).endswith("trace")
    return t[0] in ("var", "selfattr", "expr", "self") \
        and t[-1] in ("start_span", "add_stage")


def _name_arg(node: ast.Call, pos: int):
    if len(node.args) > pos:
        return node.args[pos]
    return None


def _check_metric_site(m, f, node, fn, raw, canon, names) -> list:
    arg = _name_arg(node, 0)
    where = f"{m.relpath}:{f.qual}"
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        name = arg.value
        if not names:
            return []
        if fn == "register_provider":
            if not any(r.startswith(name + ".") for r in raw):
                return [Finding(
                    "NV-literal", m.relpath, node.lineno,
                    f"provider prefix {name!r} has no "
                    f"docs/metrics.md entries",
                    f"{where}#metric-{name}")]
            return []
        if name in raw or name.endswith(".error"):
            return []
        c = _canonical(name)
        # a base name matches its labeled variants only at a dot
        # boundary — bare startswith would let "nomad.raft.leader"
        # ride on "nomad.raft.leader_changes"
        if any(cat == c or cat.startswith(c + ".") for cat in canon):
            return []
        return [Finding(
            "NV-literal", m.relpath, node.lineno,
            f"metric name {name!r} is not in the docs/metrics.md "
            f"catalogue",
            f"{where}#metric-{name}")]
    if isinstance(arg, ast.JoinedStr):
        head = _fstring_head(arg)
        if not names:
            return []
        c = _canonical(head)
        if any(cat == c or cat.startswith(c + ".") for cat in canon):
            return []
        return [Finding(
            "NV-literal", m.relpath, node.lineno,
            f"metric name f-string {head!r} matches no "
            f"docs/metrics.md entry",
            f"{where}#metric-f-{_slug(head)}")]
    return [Finding(
        "NV-literal", m.relpath, node.lineno,
        f"metrics.{fn} name argument is not a string literal — "
        f"dynamic names defeat the catalogue tripwire",
        f"{where}#metric-dynamic-{fn}")]


def _check_span_site(m, f, node, t, span_names) -> list:
    pos = SPAN_ARG_INDEX[t[-1]]
    arg = _name_arg(node, pos)
    where = f"{m.relpath}:{f.qual}"
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        if not span_names or arg.value in span_names:
            return []
        return [Finding(
            "NV-literal", m.relpath, node.lineno,
            f"span name {arg.value!r} is not catalogued in "
            f"docs/tracing.md",
            f"{where}#span-{arg.value}")]
    if arg is None:
        return []
    return [Finding(
        "NV-literal", m.relpath, node.lineno,
        f"{t[-1]} name argument is not a string literal",
        f"{where}#span-dynamic-{t[-1]}")]
