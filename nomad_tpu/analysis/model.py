"""AST module index for nomad-vet (the static analyzer package).

One parse pass over the production tree builds everything every rule
needs, so the full walk stays well under the 10s CI budget:

  * per-module import records (module-scope vs lazy) and an alias map
    (local name -> dotted fullname) used to resolve call targets;
  * per-class lock definitions — ``self._lock = threading.Lock()``,
    ``TimedLock("broker", threading.RLock())``, ``threading.Condition``
    over either — keyed by the ALLOCATION SITE of the underlying
    primitive ctor (``relpath:lineno``), the same class key the dynamic
    lock-order detector (testing/racecheck.py) derives at runtime, so
    static and dynamic edge sets cross-check by equality;
  * per-function call sites annotated with the lock tokens HELD at the
    call (``with self._lock:`` regions, nested and multi-item), plus
    direct lock acquisitions with the held-before set — the raw
    material for NV-lock-blocking and NV-lock-order;
  * thread/event/condition attribute tracking for NV-thread and the
    Condition-wait exemption (waiting on a cv RELEASES its own lock,
    so it only blocks locks held OUTSIDE it).

The model is deliberately syntactic: ``self.X = PlanQueue()`` types the
attribute for per-module (and imported-class) method resolution, and
anything it cannot resolve falls through to a curated method-name sink
table in rules.py. False negatives cost coverage; the rules are tuned
so false positives stay small enough for a reviewed baseline ledger.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Optional


@dataclass
class ImportRecord:
    fullname: str        # resolved dotted target ("nomad_tpu.metrics")
    lineno: int
    module_scope: bool   # executed at import time (not under a def)


@dataclass
class LockDef:
    token: str           # "relpath:lineno" of the primitive ctor call
    kind: str            # "lock" | "rlock" | "condition" | "event"
    owner: str           # class name, "" for module-level
    attr: str            # attribute / global name
    name: str            # display label, e.g. "EvalBroker._lock"
    role: str = ""       # TimedLock("broker", ...) label when present
    wraps: Optional[str] = None  # condition: attr of the wrapped lock


@dataclass
class CallSite:
    lineno: int
    held: tuple          # lock tokens held at the call, outermost first
    target: tuple        # ("name", f) | ("var", root, meth) |
    #                      ("dotted", "a.b.c") | ("self", meth) |
    #                      ("selfattr", attr, meth) | ("expr", meth)


@dataclass
class FuncInfo:
    module: "ModuleInfo"
    cls: Optional[str]   # enclosing class name, None for module level
    qual: str            # "Class.meth", "func", "Class.meth.<locals>.f"
    name: str
    lineno: int
    node: ast.AST = None
    calls: list = field(default_factory=list)      # [CallSite]
    acquires: list = field(default_factory=list)   # [(token, lineno, held_before)]
    var_types: dict = field(default_factory=dict)  # local -> class fullname
    thread_vars: set = field(default_factory=set)  # locals = threading.Thread(...)

    @property
    def key(self) -> tuple:
        return (self.module.relpath, self.qual)


@dataclass
class ClassInfo:
    module: "ModuleInfo"
    name: str
    lineno: int
    bases: list = field(default_factory=list)   # alias-resolved dotted names
    locks: dict = field(default_factory=dict)   # attr -> LockDef
    events: set = field(default_factory=set)
    threads: dict = field(default_factory=dict)  # attr -> ctor ast.Call
    attr_types: dict = field(default_factory=dict)  # attr -> class fullname
    methods: dict = field(default_factory=dict)  # name -> FuncInfo

    @property
    def fullname(self) -> str:
        return f"{self.module.modname}.{self.name}"


@dataclass
class ModuleInfo:
    relpath: str         # posix path relative to the analysis root
    modname: str         # dotted module name ("nomad_tpu.server.worker")
    tree: ast.AST
    is_testing: bool
    path: str = ""
    imports: list = field(default_factory=list)     # [ImportRecord]
    aliases: dict = field(default_factory=dict)     # local -> dotted full
    classes: dict = field(default_factory=dict)     # name -> ClassInfo
    functions: dict = field(default_factory=dict)   # module-level name -> FuncInfo
    all_funcs: list = field(default_factory=list)   # every FuncInfo
    module_locks: dict = field(default_factory=dict)  # global name -> LockDef


class Index:
    """All parsed modules plus the cross-module resolution tables."""

    def __init__(self) -> None:
        self.modules: dict[str, ModuleInfo] = {}          # by relpath
        self.by_modname: dict[str, ModuleInfo] = {}
        self.funcs: dict[tuple, FuncInfo] = {}            # (relpath, qual)
        self.classes: dict[str, ClassInfo] = {}           # by fullname
        self.locks: dict[str, LockDef] = {}               # by token

    def repo_function(self, fullname: str) -> Optional[FuncInfo]:
        """Resolve "pkg.mod.func" to a module-level FuncInfo."""
        modname, _, fn = fullname.rpartition(".")
        mod = self.by_modname.get(modname)
        if mod is not None:
            return mod.functions.get(fn)
        return None

    def method(self, class_fullname: str, meth: str,
               _depth: int = 0) -> Optional[FuncInfo]:
        """Resolve a method through the (repo-local) base-class chain."""
        cls = self.classes.get(class_fullname)
        if cls is None or _depth > 8:
            return None
        if meth in cls.methods:
            return cls.methods[meth]
        for base in cls.bases:
            got = self.method(base, meth, _depth + 1)
            if got is not None:
                return got
        return None


# ---------------------------------------------------------------------------
# parsing helpers
# ---------------------------------------------------------------------------


_SCOPE_BARRIERS = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda,
                   ast.ClassDef)


def iter_scope(fnode: ast.AST):
    """Yield every node in a function's OWN scope — unlike ast.walk,
    nested defs/lambdas/classes are not descended into (they are
    indexed as their own functions; descending would double-report
    their contents under the enclosing scope)."""
    work = list(ast.iter_child_nodes(fnode))
    while work:
        node = work.pop()
        yield node
        if not isinstance(node, _SCOPE_BARRIERS):
            work.extend(ast.iter_child_nodes(node))


_STMT_LIST_FIELDS = ("body", "orelse", "finalbody", "handlers")


def iter_scope_stmts(fnode: ast.AST):
    """iter_scope restricted to statement lists — for rules that only
    look at statement-position nodes (except handlers)."""
    work = [fnode]
    while work:
        node = work.pop()
        yield node
        if isinstance(node, _SCOPE_BARRIERS) and node is not fnode:
            continue
        for f in _STMT_LIST_FIELDS:
            sub = getattr(node, f, None)
            if isinstance(sub, list):
                work.extend(sub)


def _attr_chain(e: ast.AST) -> Optional[tuple]:
    """(root_name, [attrs...]) for a Name-rooted attribute chain."""
    parts: list = []
    cur = e
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    parts.reverse()
    if isinstance(cur, ast.Name):
        return cur.id, parts
    return None


def _call_target(func_expr: ast.AST) -> tuple:
    if isinstance(func_expr, ast.Name):
        return ("name", func_expr.id)
    if isinstance(func_expr, ast.Attribute):
        chain = _attr_chain(func_expr)
        if chain is None:
            return ("expr", func_expr.attr)
        root, parts = chain
        if root == "self":
            if len(parts) == 1:
                return ("self", parts[0])
            if len(parts) == 2:
                return ("selfattr", parts[0], parts[1])
            return ("expr", parts[-1])
        if len(parts) == 1:
            return ("var", root, parts[0])
        return ("dotted", root + "." + ".".join(parts))
    return ("expr", "")


def resolve_name(module: ModuleInfo, dotted: str) -> str:
    """Expand the root of a dotted name through the module's imports."""
    root, _, rest = dotted.partition(".")
    full = module.aliases.get(root)
    if full is None:
        return dotted
    return full + ("." + rest if rest else "")


def _callable_fullname(module: ModuleInfo, call: ast.Call) -> str:
    t = _call_target(call.func)
    if t[0] == "name":
        return module.aliases.get(t[1], t[1])
    if t[0] in ("var", "dotted"):
        dotted = t[1] + "." + t[2] if t[0] == "var" else t[1]
        return resolve_name(module, dotted)
    return ""


_LOCK_CTORS = {
    "threading.Lock": "lock",
    "threading.RLock": "rlock",
    "_thread.allocate_lock": "lock",
}


def _lock_ctor(module: ModuleInfo, expr: ast.AST):
    """(kind, ctor_lineno, role, wraps_attr) for lock-ish ctor exprs.

    ``TimedLock(name, inner)`` (hostobs) unwraps to the inner primitive:
    both the lineno (racecheck keys classes by the line the REAL
    Lock()/RLock() factory ran on) and the kind come from the inner
    ctor, while the TimedLock label becomes the lock's role.
    """
    if not isinstance(expr, ast.Call):
        return None
    full = _callable_fullname(module, expr)
    if full in _LOCK_CTORS:
        return (_LOCK_CTORS[full], expr.lineno, "", None)
    if full == "threading.Event":
        return ("event", expr.lineno, "", None)
    if full.endswith(".TimedLock") or full == "TimedLock":
        role = ""
        if expr.args and isinstance(expr.args[0], ast.Constant) and \
                isinstance(expr.args[0].value, str):
            role = expr.args[0].value
        if len(expr.args) > 1:
            inner = _lock_ctor(module, expr.args[1])
            if inner is not None:
                return (inner[0], inner[1], role, None)
        return ("lock", expr.lineno, role, None)
    if full == "threading.Condition":
        if expr.args:
            arg = expr.args[0]
            chain = _attr_chain(arg) if isinstance(arg, ast.Attribute) else None
            if chain is not None and chain[0] == "self" and len(chain[1]) == 1:
                return ("condition", expr.lineno, "", chain[1][0])
            inner = _lock_ctor(module, arg)
            if inner is not None:
                return ("condition", inner[1], inner[2], None)
        return ("condition", expr.lineno, "", None)
    return None


def _relative_base(modname: str, is_pkg: bool, level: int) -> str:
    parts = modname.split(".")
    if not is_pkg:
        parts = parts[:-1]
    drop = level - 1
    if drop:
        parts = parts[:-drop] if drop < len(parts) else []
    return ".".join(parts)


class _ImportScanner:
    """Collect ImportRecords + the alias map, tracking def-nesting so
    module-scope (eager) imports are distinguished from lazy ones.
    Imports only occur in statement position, so expression subtrees
    are never entered."""

    def __init__(self, module: ModuleInfo, is_pkg: bool) -> None:
        self.m = module
        self.is_pkg = is_pkg

    def scan(self) -> None:
        work = [(n, True) for n in self.m.tree.body]
        while work:
            node, mscope = work.pop()
            if isinstance(node, ast.Import):
                for alias in node.names:
                    self.m.imports.append(
                        ImportRecord(alias.name, node.lineno, mscope))
                    if alias.asname:
                        self.m.aliases[alias.asname] = alias.name
                    else:
                        root = alias.name.split(".")[0]
                        self.m.aliases.setdefault(root, root)
                continue
            if isinstance(node, ast.ImportFrom):
                if node.level:
                    base = _relative_base(
                        self.m.modname, self.is_pkg, node.level)
                    base = (f"{base}.{node.module}"
                            if node.module else base)
                else:
                    base = node.module or ""
                for alias in node.names:
                    full = f"{base}.{alias.name}" if base else alias.name
                    self.m.imports.append(
                        ImportRecord(full, node.lineno, mscope))
                    self.m.aliases[alias.asname or alias.name] = full
                continue
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                work.extend((n, False) for n in node.body)
                continue
            if isinstance(node, ast.If) and _is_type_checking(node.test):
                # `if TYPE_CHECKING:` bodies never execute — not eager
                work.extend((n, False) for n in node.body)
                work.extend((n, mscope) for n in node.orelse)
                continue
            for f in _STMT_LIST_FIELDS:
                sub = getattr(node, f, None)
                if isinstance(sub, list):
                    work.extend((n, mscope) for n in sub)


def _is_type_checking(test: ast.AST) -> bool:
    chain = None
    if isinstance(test, ast.Name):
        chain = test.id
    elif isinstance(test, ast.Attribute) and isinstance(test.value, ast.Name):
        chain = test.attr
    return chain == "TYPE_CHECKING"


# ---------------------------------------------------------------------------
# pass A: classes, locks, functions
# ---------------------------------------------------------------------------


def _scan_class(module: ModuleInfo, node: ast.ClassDef) -> ClassInfo:
    info = ClassInfo(module, node.name, node.lineno)
    for b in node.bases:
        chain = _attr_chain(b) if isinstance(b, ast.Attribute) else None
        if isinstance(b, ast.Name):
            info.bases.append(resolve_name(module, b.id))
        elif chain is not None:
            info.bases.append(
                resolve_name(module, chain[0] + "." + ".".join(chain[1])))
    # Descend into methods (self.X = Lock() lives in __init__) but NOT
    # into nested ClassDefs: a nested handler class's `self.*` refers
    # to ITS instances — ast.walk attributed those locks/threads/attrs
    # to the enclosing class, giving `with self._lock:` in the outer
    # class a wrong LockDef identity.
    assigns = []
    work = list(ast.iter_child_nodes(node))
    while work:
        n = work.pop()
        if isinstance(n, ast.ClassDef):
            continue
        if isinstance(n, ast.Assign):
            assigns.append(n)
        work.extend(ast.iter_child_nodes(n))
    for assign in assigns:
        if len(assign.targets) != 1:
            continue
        tgt = assign.targets[0]
        if not (isinstance(tgt, ast.Attribute)
                and isinstance(tgt.value, ast.Name)
                and tgt.value.id == "self"):
            continue
        attr = tgt.attr
        lock = _lock_ctor(module, assign.value)
        if lock is not None:
            kind, lineno, role, wraps = lock
            token = f"{module.relpath}:{lineno}"
            if kind == "event":
                info.events.add(attr)
                continue
            info.locks[attr] = LockDef(
                token, kind, node.name, attr,
                f"{node.name}.{attr}", role, wraps)
            continue
        if isinstance(assign.value, ast.Call):
            full = _callable_fullname(module, assign.value)
            if full in ("threading.Thread", "threading.Timer"):
                info.threads[attr] = assign.value
                continue
            if full:
                # type the attribute by its ctor; resolution later only
                # hits when the name indexes a repo class, so typing
                # `self.x = dict()` costs nothing
                info.attr_types.setdefault(
                    attr,
                    full if "." in full else f"{module.modname}.{full}")
    return info


# ---------------------------------------------------------------------------
# pass B: per-function body walk (held-lock tracking)
# ---------------------------------------------------------------------------


class _BodyWalker:
    def __init__(self, index: Index, module: ModuleInfo,
                 cls: Optional[ClassInfo], func: FuncInfo) -> None:
        self.index = index
        self.m = module
        self.cls = cls
        self.f = func
        self.held: list = []

    def run(self) -> None:
        self._prescan(self.f.node)
        for stmt in self.f.node.body:
            self._visit(stmt)

    def _prescan(self, fnode) -> None:
        """Type obvious locals: x = Ctor(...) and t = threading.Thread."""
        for node in iter_scope(fnode):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and isinstance(node.value, ast.Call):
                name = node.targets[0].id
                full = _callable_fullname(self.m, node.value)
                if full in ("threading.Thread", "threading.Timer"):
                    self.f.thread_vars.add(name)
                elif full:
                    self.f.var_types.setdefault(
                        name,
                        full if "." in full
                        else f"{self.m.modname}.{full}")

    def _lock_token(self, expr: ast.AST) -> Optional[str]:
        """Lock token for a with-item context expression, or None."""
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name) and expr.value.id == "self" \
                and self.cls is not None:
            ld = self.cls.locks.get(expr.attr)
            if ld is None:
                return None
            if ld.kind == "condition" and ld.wraps:
                wrapped = self.cls.locks.get(ld.wraps)
                if wrapped is not None:
                    return wrapped.token
            return ld.token
        if isinstance(expr, ast.Name):
            ld = self.m.module_locks.get(expr.id)
            if ld is not None:
                return ld.token
        return None

    def _visit(self, node) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            return  # nested defs walk as their own functions, held reset
        if isinstance(node, (ast.With, ast.AsyncWith)):
            acquired: list = []
            for item in node.items:
                self._visit(item.context_expr)
                tok = self._lock_token(item.context_expr)
                if tok is not None:
                    if tok not in self.held:  # reentrant RLock: no edge
                        self.f.acquires.append(
                            (tok, item.context_expr.lineno,
                             tuple(self.held)))
                    acquired.append(tok)
                    self.held.append(tok)
            for stmt in node.body:
                self._visit(stmt)
            for _ in acquired:
                self.held.pop()
            return
        if isinstance(node, ast.Call):
            self.f.calls.append(CallSite(
                node.lineno, tuple(self.held), _call_target(node.func)))
            # the receiver chain itself may contain calls (a().b())
            for child in ast.iter_child_nodes(node.func):
                self._visit(child)
            for arg in node.args:
                self._visit(arg)
            for kw in node.keywords:
                self._visit(kw.value)
            return
        for child in ast.iter_child_nodes(node):
            self._visit(child)


# ---------------------------------------------------------------------------
# tree walk
# ---------------------------------------------------------------------------


def _iter_py_files(pkg_dir: str):
    for dirpath, dirs, files in os.walk(pkg_dir):
        dirs[:] = sorted(d for d in dirs if d != "__pycache__")
        for fname in sorted(files):
            if fname.endswith(".py"):
                yield os.path.join(dirpath, fname)


def build_index(root: str, package: str = "nomad_tpu",
                testing_prefix: str = "nomad_tpu/testing") -> Index:
    """Parse every module under ``root/package`` into an Index."""
    index = Index()
    pkg_dir = os.path.join(root, package)
    for path in _iter_py_files(pkg_dir):
        rel = os.path.relpath(path, root).replace(os.sep, "/")
        parts = rel[:-3].split("/")
        is_pkg = parts[-1] == "__init__"
        if is_pkg:
            parts = parts[:-1]
        modname = ".".join(parts)
        try:
            tree = ast.parse(open(path, encoding="utf-8").read(),
                             filename=path)
        except SyntaxError as e:  # pragma: no cover - tree must parse
            raise RuntimeError(f"nomad-vet: cannot parse {rel}: {e}")
        m = ModuleInfo(
            rel, modname, tree,
            rel == testing_prefix + ".py"
            or rel.startswith(testing_prefix + "/"),
            path=path)
        _ImportScanner(m, is_pkg).scan()
        index.modules[rel] = m
        index.by_modname[modname] = m

    # pass A: classes, locks, function shells
    for m in index.modules.values():
        for node in m.tree.body:
            if isinstance(node, ast.ClassDef):
                cls = _scan_class(m, node)
                m.classes[node.name] = cls
                index.classes[cls.fullname] = cls
            elif isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                lock = _lock_ctor(m, node.value)
                if lock is not None and lock[0] != "event":
                    kind, lineno, role, wraps = lock
                    name = node.targets[0].id
                    m.module_locks[name] = LockDef(
                        f"{m.relpath}:{lineno}", kind, "", name,
                        f"{m.modname.split('.')[-1]}.{name}", role, wraps)
        _collect_funcs(index, m)

    for m in index.modules.values():
        for lock in m.module_locks.values():
            index.locks[lock.token] = lock
        for cls in m.classes.values():
            for lock in cls.locks.values():
                index.locks.setdefault(lock.token, lock)

    # pass B: body walks with held-lock tracking
    for m in index.modules.values():
        for f in m.all_funcs:
            cls = m.classes.get(f.cls) if f.cls else None
            _BodyWalker(index, m, cls, f).run()
    return index


def _collect_funcs(index: Index, m: ModuleInfo) -> None:
    work = [(n, "", None) for n in m.tree.body]
    while work:
        node, qual_prefix, cls_name = work.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            qual = (f"{qual_prefix}.{node.name}"
                    if qual_prefix else node.name)
            f = FuncInfo(m, cls_name, qual, node.name,
                         node.lineno, node=node)
            m.all_funcs.append(f)
            index.funcs[f.key] = f
            if not qual_prefix:
                m.functions[node.name] = f
            elif cls_name and qual_prefix == cls_name:
                m.classes[cls_name].methods[node.name] = f
            work.extend(
                (n, f"{qual}.<locals>", cls_name) for n in node.body)
            continue
        if isinstance(node, ast.ClassDef):
            if not qual_prefix:
                work.extend(
                    (n, node.name, node.name) for n in node.body)
            else:
                # class defined inside a function (the HTTP handler
                # pattern): its methods still get FuncInfos so the
                # per-node rules see them, but `self` inside them is
                # the NESTED class's instance — carrying the outer
                # cls_name made `with self._lock:` resolve to the
                # OUTER class's LockDef (phantom held tokens feeding
                # static_edges). No ClassInfo models nested classes,
                # so their self.* stays unresolved rather than wrong.
                work.extend(
                    (n, f"{qual_prefix}.{node.name}", None)
                    for n in node.body)
            continue
        for fname in _STMT_LIST_FIELDS:
            sub = getattr(node, fname, None)
            if isinstance(sub, list):
                work.extend((n, qual_prefix, cls_name) for n in sub)
