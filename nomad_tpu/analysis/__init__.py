"""nomad-vet: AST-level concurrency & layering analyzer.

The reference keeps its Go control plane honest with ``go vet`` and
``go test -race`` in CI; this package is the Python rebuild's analog.
It walks the production tree with ``ast`` (stdlib-only, like
faultplane/solverobs) and enforces the repo's real invariants as named
rules — see rules.py for the catalogue and docs/static-analysis.md for
how to read a finding.

CI gate: zero unsuppressed findings (tests/test_analysis.py). Accepted
findings live in analysis/baseline.toml, each with a one-line reason;
stale entries fail the gate too. Operators run the same engine via
``nomad-tpu operator vet [-json] [-rule ...]``.
"""

from .engine import (DEFAULT_BASELINE, REPO_ROOT, VetReport,
                     dynamic_edges_from_json, load_baseline, run_vet)
from .rules import GATE_RULES, Finding

__all__ = [
    "DEFAULT_BASELINE", "Finding", "GATE_RULES", "REPO_ROOT",
    "VetReport", "dynamic_edges_from_json", "load_baseline", "run_vet",
]
