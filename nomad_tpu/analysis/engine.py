"""nomad-vet engine: walk → rules → baseline ledger → report.

The CI contract is ZERO unsuppressed findings: a true-but-accepted
finding lives in ``analysis/baseline.toml`` with a one-line reason, and
a suppression that no longer matches anything is itself an error (the
code it excused was fixed or moved — the ledger must shrink with it).
Advisories (dynamic-coverage gaps from the NV-lock-order cross-check)
inform but never gate.

Stdlib-only, like the other leaf tooling: tomllib for the ledger, ast
for the walk. The full production tree analyzes in well under the 10s
tier-1 budget (one parse pass + two fixpoints).
"""

from __future__ import annotations

import json
import os
import re
from dataclasses import dataclass, field

try:
    import tomllib as _toml
except ImportError:  # pragma: no cover - pre-3.11 interpreters
    _toml = None

from .model import build_index
from .rules import (Finding, GATE_RULES, Resolver, check_except,
                    check_layering, check_literals, check_lock_blocking,
                    check_lock_order, check_threads, static_edges)

_HERE = os.path.dirname(os.path.abspath(__file__))
REPO_ROOT = os.path.dirname(os.path.dirname(_HERE))
DEFAULT_BASELINE = os.path.join(_HERE, "baseline.toml")


@dataclass
class Suppression:
    rule: str
    key: str
    reason: str
    matched: int = 0


@dataclass
class VetReport:
    findings: list = field(default_factory=list)    # unsuppressed, gate
    advisories: list = field(default_factory=list)  # never gate
    suppressed: list = field(default_factory=list)  # (Finding, Suppression)
    stale: list = field(default_factory=list)       # Suppression, gate
    errors: list = field(default_factory=list)      # ledger/engine defects, gate
    modules: int = 0
    locks: int = 0
    edges: int = 0

    @property
    def gate_count(self) -> int:
        return len(self.findings) + len(self.stale) + len(self.errors)

    def to_dict(self) -> dict:
        return {
            "findings": [f.to_dict() for f in self.findings],
            "advisories": [f.to_dict() for f in self.advisories],
            "suppressed": [
                {"finding": f.to_dict(), "reason": s.reason}
                for f, s in self.suppressed
            ],
            "stale_suppressions": [
                {"rule": s.rule, "key": s.key, "reason": s.reason}
                for s in self.stale
            ],
            "errors": list(self.errors),
            "summary": {
                "modules": self.modules, "locks": self.locks,
                "static_edges": self.edges,
                "gate_count": self.gate_count,
                "suppressed": len(self.suppressed),
            },
        }

    def render(self, advisories: bool = False) -> str:
        out = []
        for f in self.findings:
            out.append(f"{f.rule} {f.file}:{f.line}\n    {f.message}")
            for hop in f.chain:
                out.append(f"      -> {hop}")
            out.append(f"    key: {f.key}")
        for s in self.stale:
            out.append(
                f"NV-stale-suppression {s.rule} key={s.key}\n"
                f"    suppression matches no current finding — the "
                f"code it excused changed; delete the ledger entry "
                f"(reason was: {s.reason})")
        for e in self.errors:
            out.append(f"NV-error {e}")
        if advisories:
            for f in self.advisories:
                out.append(
                    f"advisory {f.rule} {f.file}:{f.line}\n"
                    f"    {f.message}")
        out.append(
            f"nomad-vet: {self.modules} modules, {self.locks} lock "
            f"classes, {self.edges} static lock edges; "
            f"{self.gate_count} unsuppressed finding(s), "
            f"{len(self.suppressed)} baselined, "
            f"{len(self.advisories)} advisory")
        return "\n".join(out)


def load_baseline(path: str) -> tuple:
    """(suppressions, errors). Every entry needs rule, key and a
    nonempty one-line reason — an unjustified suppression is a ledger
    defect, not a suppression."""
    sups: list = []
    errors: list = []
    if not os.path.exists(path):
        return sups, errors
    if _toml is not None:
        with open(path, "rb") as fh:
            data = _toml.load(fh)
    else:
        data = _parse_suppress_toml(
            open(path, encoding="utf-8").read())
    for i, entry in enumerate(data.get("suppress", [])):
        rule = entry.get("rule", "")
        key = entry.get("key", "")
        reason = str(entry.get("reason", "")).strip()
        if not rule or not key:
            errors.append(
                f"{os.path.basename(path)} entry #{i + 1}: rule and "
                f"key are required")
            continue
        if not reason or "\n" in reason:
            errors.append(
                f"{os.path.basename(path)} {rule} {key}: a one-line "
                f"reason is required")
            continue
        if rule not in GATE_RULES:
            errors.append(
                f"{os.path.basename(path)} entry #{i + 1}: unknown "
                f"rule {rule!r}")
            continue
        sups.append(Suppression(rule, key, reason))
    return sups, errors


def _parse_suppress_toml(text: str) -> dict:
    """Minimal reader for the ledger's TOML subset — ``[[suppress]]``
    array-of-tables with double-quoted string pairs and # comments —
    used only where the interpreter predates stdlib tomllib. The
    format is enforced by load_baseline's validation either way."""
    entries: list = []
    cur = None
    for lineno, raw in enumerate(text.splitlines(), 1):
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        if line == "[[suppress]]":
            cur = {}
            entries.append(cur)
            continue
        # the value group stops at the first unescaped quote — a greedy
        # `"(.*)"` ran through quotes inside a trailing comment and
        # corrupted the key/reason (this parser is LIVE on pre-3.11
        # interpreters, tomllib handles these lines fine)
        m = re.match(
            r'^([A-Za-z0-9_-]+)\s*=\s*"((?:[^"\\]|\\.)*)"\s*(#.*)?$',
            line)
        if m and cur is not None:
            cur[m.group(1)] = m.group(2).replace('\\"', '"')
            continue
        raise ValueError(
            f"baseline.toml line {lineno}: unsupported syntax "
            f"{line!r} (this reader handles [[suppress]] tables of "
            f'key = "value" pairs)')
    return {"suppress": entries}


def _doc_metric_names(root: str) -> list:
    path = os.path.join(root, "docs", "metrics.md")
    if not os.path.exists(path):
        return []
    doc = open(path, encoding="utf-8").read()
    return re.findall(r"^\| `([^`]+)` \|", doc, re.M)


def _doc_span_names(root: str) -> set:
    """First-column backticked table cells in docs/tracing.md — the
    span-catalogue rows (the trace-name table's rows are valid root
    names too). Prose backticks (attr names, env knobs, file names) do
    NOT catalogue a span: the contract is an explicit table row, same
    as _doc_metric_names' anchor on the metrics.md catalogue."""
    path = os.path.join(root, "docs", "tracing.md")
    if not os.path.exists(path):
        return set()
    doc = open(path, encoding="utf-8").read()
    return set(re.findall(r"^\| `([^`]+)` \|", doc, re.M))


def run_vet(root: str = REPO_ROOT, package: str = "nomad_tpu",
            rules=None, baseline_path: str = None,
            dynamic_edges=None) -> VetReport:
    """Run the analyzer. ``rules`` narrows to a subset of GATE_RULES;
    ``dynamic_edges`` is the racecheck ``edges()`` export (a list of
    {"from","to"} dicts) enabling the NV-lock-order cross-check."""
    wanted = set(rules) if rules else set(GATE_RULES)
    unknown = wanted - set(GATE_RULES)
    if unknown:
        raise ValueError(f"unknown rule(s): {sorted(unknown)}")
    if baseline_path is None:
        baseline_path = DEFAULT_BASELINE if root == REPO_ROOT else ""
    elif baseline_path and not os.path.exists(baseline_path):
        # an explicitly requested ledger that isn't there is an error,
        # not an empty ledger: a typo'd -baseline path would otherwise
        # surface every baselined finding as confusing gate noise
        raise ValueError(f"baseline ledger not found: {baseline_path}")

    index = build_index(root, package,
                        testing_prefix=f"{package}/testing")
    report = VetReport()
    report.modules = len(index.modules)
    report.locks = len(index.locks)
    # the blocking/acquire fixpoint and the static edge graph are the
    # dominant cost of the walk — only the two lock rules consume them
    edges: dict = {}
    resolver = None
    if wanted & {"NV-lock-blocking", "NV-lock-order"}:
        resolver = Resolver(index)
        edges = static_edges(index, resolver)
        if not resolver.converged:
            # a capped fixpoint silently drops chains deeper than the
            # pass bound — that would let the gate report "zero
            # findings" over code it never finished analyzing
            report.errors.append(
                "call-graph fixpoint hit its pass cap before "
                "converging — lock rules may be incomplete; raise the "
                "bound in analysis/rules.py Resolver._fixpoint")
    report.edges = len(edges)

    found: list = []
    if "NV-lock-blocking" in wanted:
        found += check_lock_blocking(index, resolver)
    if "NV-lock-order" in wanted:
        found += check_lock_order(index, resolver, dynamic_edges,
                                  edges=edges)
    if "NV-layering" in wanted:
        found += check_layering(index, package)
    if "NV-except" in wanted:
        found += check_except(index)
    if "NV-thread" in wanted:
        found += check_threads(index)
    if "NV-literal" in wanted:
        found += check_literals(
            index, _doc_metric_names(root), _doc_span_names(root))

    sups, errors = ([], [])
    if baseline_path:
        sups, errors = load_baseline(baseline_path)
    report.errors.extend(errors)
    by_key = {(s.rule, s.key): s for s in sups}
    for f in sorted(found, key=lambda f: (f.file, f.line, f.rule)):
        if f.advisory:
            report.advisories.append(f)
            continue
        s = by_key.get((f.rule, f.key))
        if s is not None:
            s.matched += 1
            report.suppressed.append((f, s))
        else:
            report.findings.append(f)
    # stale check only makes sense against the full rule set — a
    # narrowed -rule run must not brand the other rules' entries stale
    if wanted == set(GATE_RULES):
        report.stale = [s for s in sups if s.matched == 0]
    return report


def dynamic_edges_from_json(text: str) -> list:
    """Parse the racecheck edges() export (either the bare edge list or
    the full {"edges": [...], "violations": [...]} document)."""
    data = json.loads(text)
    if isinstance(data, dict):
        data = data.get("edges", [])
    out = []
    for e in data:
        if not isinstance(e, dict) or "from" not in e or "to" not in e:
            raise ValueError(
                f"dynamic-edges entries need 'from' and 'to' keys "
                f"(racecheck edges() / export_json() format): {e!r}")
        out.append({"from": e["from"], "to": e["to"]})
    return out
