"""Server-side Job.Plan dry-run.

Reference: nomad/job_endpoint.go:521 (Job.Plan RPC) — snapshot current
state, overlay the CANDIDATE job (never committed), run the real scheduler
with plan annotations enabled against a planner that records instead of
applying, and return the annotated counts + a structural diff + placement
failures. The CLI's `job plan` renders this and keeps the reference's exit
codes (0 no changes / 1 changes).
"""

from __future__ import annotations

import logging
from typing import Optional

from ..scheduler import new_scheduler
from ..scheduler.context import SchedulerConfig
from ..structs import Evaluation, Job, Plan, PlanResult, generate_uuid
from ..structs.diff import DIFF_NONE, job_diff
from ..structs.structs import (
    EVAL_STATUS_PENDING,
    EVAL_TRIGGER_JOB_REGISTER,
    now_ns,
)

logger = logging.getLogger("nomad_tpu.server.plan")


class _OverlaySnapshot:
    """A read snapshot with ONE job replaced by the plan candidate.

    The scheduler only reads, so overriding the job lookup is the whole
    overlay — every other table delegates to the frozen snapshot.
    """

    def __init__(self, snap, job: Job):
        self._snap = snap
        self._job = job

    def __getattr__(self, name):
        return getattr(self._snap, name)

    def job_by_id(self, namespace: str, job_id: str) -> Optional[Job]:
        if namespace == self._job.namespace and job_id == self._job.id:
            return self._job
        return self._snap.job_by_id(namespace, job_id)


class _RecordingPlanner:
    """Planner that acknowledges plans without committing anything
    (reference: the Plan RPC's scheduler.NewScheduler with a Harness)."""

    def __init__(self, snap) -> None:
        self._snap = snap
        self.plans: list[Plan] = []
        self.evals: list[Evaluation] = []
        self.updates: list[Evaluation] = []

    def submit_plan(self, plan: Plan):
        self.plans.append(plan)
        result = PlanResult(
            node_update=plan.node_update,
            node_allocation=plan.node_allocation,
            node_preemptions=plan.node_preemptions,
            deployment=plan.deployment,
            deployment_updates=plan.deployment_updates,
            alloc_index=self._snap.index,
            alloc_batches=plan.alloc_batches,
        )
        return result, None

    def create_eval(self, eval_obj: Evaluation) -> None:
        self.evals.append(eval_obj)

    def update_eval(self, eval_obj: Evaluation) -> None:
        self.updates.append(eval_obj)

    def refresh_state(self, min_index: int):
        return self._snap


def plan_job(state, candidate: Job, diff: bool = True,
             config: Optional[SchedulerConfig] = None) -> dict:
    """Dry-run the candidate job against a state snapshot.

    Returns the wire-shaped plan response: scheduler annotations
    (per-group place/stop/migrate/in-place/destructive/ignore), the
    structural job diff, per-group placement failures, and the existing
    job's modify index for `job run -check-index` fencing.
    """
    candidate = candidate.copy()
    candidate.canonicalize()
    candidate.validate()
    snap = state.snapshot()
    existing = snap.job_by_id(candidate.namespace, candidate.id)
    # Mirror upsert_job's version rule: an unchanged spec keeps the current
    # version, so the reconciler sees no drift and plans a no-op — the
    # reference gets the same effect from UpsertJob into the plan snapshot.
    if existing is None:
        candidate.version = 0
    elif candidate.specification_changed(existing):
        candidate.version = existing.version + 1
    else:
        candidate.version = existing.version

    overlay = _OverlaySnapshot(snap, candidate)
    planner = _RecordingPlanner(snap)
    ev = Evaluation(
        id=generate_uuid(),
        namespace=candidate.namespace,
        priority=candidate.priority,
        type=candidate.type,
        triggered_by=EVAL_TRIGGER_JOB_REGISTER,
        job_id=candidate.id,
        status=EVAL_STATUS_PENDING,
        annotate_plan=True,
        create_time=now_ns(),
        modify_time=now_ns(),
    )
    sched = new_scheduler(
        candidate.type, logger, overlay, planner, config
    )
    sched.process(ev)

    plan = planner.plans[-1] if planner.plans else None
    annotations = (plan.annotations if plan else None) or {
        "DesiredTGUpdates": {}
    }
    failed = {}
    for u in reversed(planner.updates):
        if u.failed_tg_allocs:
            failed = u.failed_tg_allocs
            break
    d = job_diff(existing, candidate) if diff else None
    changes = any(
        any(v for k, v in s.items() if k != "ignore")
        for s in annotations["DesiredTGUpdates"].values()
    )
    return {
        "Annotations": annotations,
        "Diff": d,
        # AllocMetric values JSON-encode via codec.json_default's struct
        # lowering at the HTTP boundary (works on forwarded RPCs too).
        "FailedTGAllocs": dict(failed),
        "JobModifyIndex": existing.job_modify_index if existing else 0,
        "Changes": bool(changes),
    }
