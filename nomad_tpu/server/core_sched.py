"""Core scheduler: internal `_core` evals for garbage collection.

Reference: nomad/core_sched.go — the leader enqueues `_core` evals on a
timer (leader.go schedulePeriodic); a worker dequeues them like any other
eval and dispatches on the eval's JobID (core_sched.go:47-57): eval GC, job
GC, node GC, deployment GC, or force-GC (all at once, ignoring thresholds).
"""

from __future__ import annotations

import logging

from ..structs import Evaluation, generate_uuid, now_ns
from ..structs.structs import (
    CORE_JOB_PRIORITY,
    EVAL_STATUS_PENDING,
    JOB_STATUS_DEAD,
    JOB_TYPE_CORE,
    NODE_STATUS_DOWN,
)

logger = logging.getLogger("nomad_tpu.core_sched")

CORE_JOB_EVAL_GC = "eval-gc"
CORE_JOB_JOB_GC = "job-gc"
CORE_JOB_NODE_GC = "node-gc"
CORE_JOB_DEPLOYMENT_GC = "deployment-gc"
CORE_JOB_SERVICE_GC = "service-gc"
CORE_JOB_TOKEN_GC = "token-gc"
CORE_JOB_FORCE_GC = "force-gc"

# Reference defaults (nomad/config.go): EvalGCThreshold 1h, JobGCThreshold
# 4h, DeploymentGCThreshold 1h, NodeGCThreshold 24h.
EVAL_GC_THRESHOLD_S = 3600.0
JOB_GC_THRESHOLD_S = 4 * 3600.0
NODE_GC_THRESHOLD_S = 24 * 3600.0
DEPLOYMENT_GC_THRESHOLD_S = 3600.0


def core_eval(kind: str) -> Evaluation:
    """Build a `_core` eval for the given GC kind (reference
    core_sched.go coreJobEval)."""
    return Evaluation(
        id=generate_uuid(),
        namespace="-",
        priority=CORE_JOB_PRIORITY,
        type=JOB_TYPE_CORE,
        triggered_by="scheduled",
        job_id=kind,
        status=EVAL_STATUS_PENDING,
        create_time=now_ns(),
        modify_time=now_ns(),
    )


class CoreScheduler:
    """Processes `_core` evals. Unlike the placement schedulers it mutates
    state directly through raft (reference: CoreScheduler holds *Server)."""

    def __init__(self, server, snapshot) -> None:
        self.server = server
        self.snapshot = snapshot

    def process(self, ev: Evaluation) -> None:
        kind = ev.job_id.split(":")[0]
        if kind == CORE_JOB_EVAL_GC:
            self.eval_gc()
        elif kind == CORE_JOB_JOB_GC:
            self.job_gc()
        elif kind == CORE_JOB_NODE_GC:
            self.node_gc()
        elif kind == CORE_JOB_DEPLOYMENT_GC:
            self.deployment_gc()
        elif kind == CORE_JOB_SERVICE_GC:
            self.service_gc()
        elif kind == CORE_JOB_TOKEN_GC:
            self.token_gc()
        elif kind == CORE_JOB_FORCE_GC:
            self.eval_gc(force=True)
            self.job_gc(force=True)
            self.deployment_gc(force=True)
            self.node_gc(force=True)
            self.service_gc()
            self.token_gc()
        else:
            raise ValueError(f"unknown core job {ev.job_id!r}")

    # -- GC passes -----------------------------------------------------

    def _cutoff_ns(self, threshold_s: float, force: bool) -> int:
        if force:
            return now_ns() + 1
        return now_ns() - int(threshold_s * 1e9)

    def eval_gc(self, force: bool = False) -> tuple[int, int]:
        """Delete terminal evals (and their terminal allocs) older than the
        threshold (reference core_sched.go evalGC). Batch-job evals are
        kept while the job exists so `job status` history survives."""
        cutoff = self._cutoff_ns(EVAL_GC_THRESHOLD_S, force)
        gc_evals: list[str] = []
        gc_allocs: list[str] = []
        for ev in self.snapshot.evals():
            if not ev.terminal_status() or ev.modify_time > cutoff:
                continue
            if ev.type == "batch" and not force:
                job = self.snapshot.job_by_id(ev.namespace, ev.job_id)
                if job is not None and not job.stopped():
                    continue
            allocs = self.snapshot.allocs_by_eval(ev.id)
            if any(not a.terminal_status() for a in allocs):
                continue
            old = [a for a in allocs if a.modify_time <= cutoff]
            if len(old) != len(allocs) and not force:
                continue
            gc_evals.append(ev.id)
            gc_allocs.extend(a.id for a in allocs)
        if gc_evals or gc_allocs:
            self.server.raft_apply("eval_delete", (gc_evals, gc_allocs))
        return len(gc_evals), len(gc_allocs)

    def job_gc(self, force: bool = False) -> int:
        """Purge dead jobs whose evals and allocs are all terminal and old
        (reference core_sched.go jobGC)."""
        cutoff = self._cutoff_ns(JOB_GC_THRESHOLD_S, force)
        purged = 0
        for job in self.snapshot.jobs():
            if job.status != JOB_STATUS_DEAD or job.is_periodic():
                continue
            evals = self.snapshot.evals_by_job(job.namespace, job.id)
            if any(not e.terminal_status() for e in evals):
                continue
            allocs = self.snapshot.allocs_by_job(job.namespace, job.id)
            if any(not a.terminal_status() for a in allocs):
                continue
            latest = max(
                [job.submit_time]
                + [e.modify_time for e in evals]
                + [a.modify_time for a in allocs]
            )
            if latest > cutoff:
                continue
            self.server.raft_apply(
                "job_deregister", (job.namespace, job.id, True, None)
            )
            if evals or allocs:
                self.server.raft_apply(
                    "eval_delete",
                    ([e.id for e in evals], [a.id for a in allocs]),
                )
            purged += 1
        return purged

    def node_gc(self, force: bool = False) -> int:
        """Deregister down nodes with no allocs (reference nodeGC)."""
        cutoff = self._cutoff_ns(NODE_GC_THRESHOLD_S, force)
        removed = 0
        for node in self.snapshot.nodes():
            if node.status != NODE_STATUS_DOWN:
                continue
            if node.status_updated_at > cutoff:
                continue
            if any(
                not a.terminal_status()
                for a in self.snapshot.allocs_by_node(node.id)
            ):
                continue
            self.server.raft_apply("node_deregister", node.id)
            removed += 1
        return removed

    def deployment_gc(self, force: bool = False) -> int:
        """Delete terminal deployments past the threshold (reference
        deploymentGC)."""
        cutoff = self._cutoff_ns(DEPLOYMENT_GC_THRESHOLD_S, force)
        gc: list[str] = []
        for d in self.snapshot.deployments():
            if d.active():
                continue
            if d.modify_time > cutoff:
                continue
            job = self.snapshot.job_by_id(d.namespace, d.job_id)
            if job is not None and job.version == d.job_version and not force:
                continue  # still the job's live version: keep for status
            gc.append(d.id)
        if gc:
            self.server.raft_apply("deployment_delete", gc)
        return len(gc)

    def token_gc(self) -> int:
        """Delete expired ACL tokens (reference: 1.4's
        ExpiredACLTokenGC; ours come from task-derived secrets tokens)."""
        from ..structs import now_ns as _now

        expired = self.snapshot.expired_acl_tokens(_now())
        if expired:
            self.server.raft_apply(
                "acl_token_delete", [t.accessor_id for t in expired]
            )
        return len(expired)

    def service_gc(self) -> int:
        """Drop service registrations whose alloc is terminal or gone —
        the sweep behind client-side deregistration for clients that died
        without deregistering (reference: the native-SD analog of
        core_sched's one-shot cleanups)."""
        orphaned: list[str] = []
        for ns_row in self.snapshot.service_names():
            for reg in self.snapshot.service_registrations(
                ns_row["namespace"], ns_row["service_name"]
            ):
                alloc = self.snapshot.alloc_by_id(reg.alloc_id)
                if alloc is None or alloc.terminal_status():
                    orphaned.append(reg.id)
        if orphaned:
            self.server.raft_apply("service_delete", orphaned)
        return len(orphaned)
