"""Leader-only deployment watcher.

Reference: nomad/deploymentwatcher/deployments_watcher.go (interface :36) +
deployment_watcher.go — per-deployment goroutines judging alloc health,
auto-promoting canaries, auto-reverting on failure, and emitting follow-up
evals so the scheduler continues (or rolls back) the rollout.

TPU-native redesign: instead of one goroutine per deployment blocking on
state watch channels, a single reconciliation pass (`run_once`) judges ALL
active deployments against one state snapshot — the same batching philosophy
as the TPU placement solver. A background thread polls; tests call
`run_once` directly for determinism.
"""

from __future__ import annotations

import logging
import threading
from typing import Optional

from ..structs import Evaluation, generate_uuid, now_ns
from ..structs.structs import (
    DEPLOYMENT_STATUS_FAILED,
    DEPLOYMENT_STATUS_SUCCESSFUL,
    EVAL_STATUS_PENDING,
    EVAL_TRIGGER_DEPLOYMENT_WATCHER,
    Deployment,
    DeploymentStatusUpdate,
    Job,
)

logger = logging.getLogger("nomad_tpu.deployment_watcher")

DESC_FAILED_ALLOCS = "Failed due to unhealthy allocations"
DESC_PROGRESS_DEADLINE = "Failed due to progress deadline"
DESC_FAILED_REVERT = (
    "Failed due to unhealthy allocations - rolling back to job version %d"
)
DESC_PROMOTED = "Deployment promoted"
DESC_MANUAL_FAIL = "Deployment marked as failed"
DESC_PAUSED = "Deployment paused"
DESC_RESUMED = "Deployment is running"


def check_promotion_ready(state, d: Deployment, groups: Optional[list[str]] = None):
    """Raise unless every targeted group has its desired healthy canaries —
    run by the promote endpoint BEFORE the raft commit (reference
    deployment_watcher.go PromoteDeployment validation)."""
    targets = groups if groups else [
        g for g, s in d.task_groups.items() if s.desired_canaries > 0
    ]
    for g in targets:
        dstate = d.task_groups.get(g)
        if dstate is None:
            raise KeyError(f"deployment has no group {g!r}")
        healthy = 0
        for cid in dstate.placed_canaries:
            a = state.alloc_by_id(cid)
            if (
                a is not None
                and a.deployment_status is not None
                and a.deployment_status.is_healthy()
            ):
                healthy += 1
        if healthy < dstate.desired_canaries:
            raise ValueError(
                f"group {g!r} has {healthy}/{dstate.desired_canaries} "
                "healthy canaries — cannot promote"
            )


class DeploymentsWatcher:
    """Judges active deployments and drives their lifecycle via raft.

    raft_apply / state are the only dependencies, so the watcher runs
    identically under the test harness and the live server.
    """

    def __init__(self, state, raft_apply, poll_interval_s: float = 0.25) -> None:
        self.state = state
        self.raft_apply = raft_apply
        self.poll_interval_s = poll_interval_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle -----------------------------------------------------

    def start(self) -> None:
        # Fresh Event per incarnation (see drainer.start): a thread that
        # outlives join(timeout) polls its own event and still exits.
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, args=(self._stop,), daemon=True,
            name="deployment-watcher"
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)
            self._thread = None

    def _run(self, stop: threading.Event) -> None:
        while not stop.wait(self.poll_interval_s):
            try:
                self.run_once()
            except Exception:
                logger.exception("deployment watcher pass failed")

    # -- the reconciliation pass ---------------------------------------

    def run_once(self) -> int:
        """Judge every active deployment. Returns number acted upon."""
        acted = 0
        for d in self.state.deployments():
            if d.status == DEPLOYMENT_STATUS_SUCCESSFUL:
                # A deployment may be completed by the reconciler's plan
                # (deployment_updates in the committed plan) rather than by
                # this watcher — job stability still must follow.
                self._mark_job_stable(d)
                continue
            if not d.active() or d.status == "paused":
                continue
            if self._judge(d):
                acted += 1
        return acted

    def _judge(self, d: Deployment) -> bool:
        allocs = self.state.allocs_by_deployment(d.id)
        healthy: dict[str, int] = {g: 0 for g in d.task_groups}
        unhealthy_ids: list[str] = []
        canary_healthy: dict[str, int] = {g: 0 for g in d.task_groups}
        now = now_ns()

        for a in allocs:
            if a.terminal_status():
                # Stopped/completed/lost allocs no longer count toward the
                # rollout (their replacements will be judged instead).
                continue
            ds = a.deployment_status
            g = a.task_group
            if g not in d.task_groups:
                continue
            dstate = d.task_groups[g]
            if ds is not None and ds.is_healthy():
                healthy[g] += 1
                if a.id in dstate.placed_canaries:
                    canary_healthy[g] += 1
            elif ds is not None and ds.is_unhealthy():
                unhealthy_ids.append(a.id)
            else:
                # Not yet judged: past the group's healthy deadline the
                # watcher marks it unhealthy (reference: the client's
                # allochealth watcher enforces HealthyDeadline; the server
                # backstops it here so a dead client can't wedge a rollout).
                deadline = self._healthy_deadline_ns(d, a)
                if deadline and now > deadline and not a.terminal_status():
                    unhealthy_ids.append(a.id)
                elif a.client_status == "failed":
                    unhealthy_ids.append(a.id)

        # 1. unhealthy allocs → fail (with optional auto-revert)
        if unhealthy_ids:
            self._fail(d, unhealthy_ids)
            return True

        # 2. progress deadline exceeded → fail
        for g, dstate in d.task_groups.items():
            if (
                dstate.require_progress_by_ns
                and now > dstate.require_progress_by_ns
                and healthy[g] < dstate.desired_total
            ):
                self._fail(d, [], desc=DESC_PROGRESS_DEADLINE)
                return True

        # 3. auto-promote when all canaries are healthy
        if d.requires_promotion() and d.has_auto_promote():
            ready = all(
                canary_healthy[g] >= s.desired_canaries
                for g, s in d.task_groups.items()
                if s.desired_canaries > 0
            )
            if ready:
                self.promote(d)
                return True

        # 4. counter drift: resync healthy counts so `nomad deployment
        # status` and the reconciler's computeLimit see fresh numbers.
        drift = any(
            d.task_groups[g].healthy_allocs != healthy[g] for g in d.task_groups
        )
        if drift:
            healthy_ids = [
                a.id
                for a in allocs
                if a.deployment_status is not None
                and a.deployment_status.is_healthy()
            ]
            self.raft_apply(
                "deployment_alloc_health",
                {
                    "deployment_id": d.id,
                    "healthy_ids": healthy_ids,
                    "unhealthy_ids": [],
                    "eval": self._new_eval(d),
                },
            )
            return True

        # 5. all groups fully healthy (and promoted) → successful
        complete = all(
            healthy[g] >= s.desired_total for g, s in d.task_groups.items()
        ) and not d.requires_promotion()
        if complete and d.task_groups:
            self.raft_apply(
                "deployment_status_update",
                DeploymentStatusUpdate(
                    deployment_id=d.id,
                    status=DEPLOYMENT_STATUS_SUCCESSFUL,
                    status_description="Deployment completed successfully",
                ),
            )
            self._mark_job_stable(d)
            return True
        return False

    # -- actions (also the Deployment RPC endpoints' backend) ----------

    def promote(self, d: Deployment, groups: Optional[list[str]] = None) -> None:
        """Reference: deployments_watcher.go PromoteDeployment."""
        check_promotion_ready(self.state, d, groups)
        self.raft_apply(
            "deployment_promote", (d.id, groups, self._new_eval(d))
        )

    def pause(self, d: Deployment, pause: bool) -> None:
        self.raft_apply(
            "deployment_status_update",
            DeploymentStatusUpdate(
                deployment_id=d.id,
                status="paused" if pause else "running",
                status_description=DESC_PAUSED if pause else DESC_RESUMED,
            ),
        )

    def fail_deployment(self, d: Deployment) -> None:
        self._fail(d, [], desc=DESC_MANUAL_FAIL)

    def _fail(
        self, d: Deployment, unhealthy_ids: list[str], desc: str = DESC_FAILED_ALLOCS
    ) -> None:
        revert_job: Optional[Job] = None
        if any(s.auto_revert for s in d.task_groups.values()):
            revert_job = self._latest_stable_job(d)
            if revert_job is not None:
                desc = DESC_FAILED_REVERT % revert_job.version
        self.raft_apply(
            "deployment_alloc_health",
            {
                "deployment_id": d.id,
                "healthy_ids": [],
                "unhealthy_ids": unhealthy_ids,
                "status_update": DeploymentStatusUpdate(
                    deployment_id=d.id,
                    status=DEPLOYMENT_STATUS_FAILED,
                    status_description=desc,
                ),
                "eval": self._new_eval(d),
                "revert_job": revert_job,
            },
        )

    # -- helpers -------------------------------------------------------

    def _healthy_deadline_ns(self, d: Deployment, alloc) -> int:
        job = alloc.job or self.state.job_by_id(d.namespace, d.job_id)
        if job is None:
            return 0
        tg = job.lookup_task_group(alloc.task_group)
        if tg is None or tg.update is None:
            return 0
        base = alloc.create_time or alloc.modify_time
        if not base:
            return 0
        return base + int(tg.update.healthy_deadline_s * 1e9)

    def _latest_stable_job(self, d: Deployment) -> Optional[Job]:
        """Most recent stable version BELOW the deployment's version
        (reference deployment_watcher.go latestStableJob)."""
        best: Optional[Job] = None
        for j in self.state.job_versions(d.namespace, d.job_id):
            if j.stable and j.version < d.job_version and (
                best is None or j.version > best.version
            ):
                best = j
        if best is None:
            return None
        revert = best.copy()
        revert.stable = True
        return revert

    def _mark_job_stable(self, d: Deployment) -> None:
        """Successful deployment marks the job version stable (reference
        deployment_watcher.go setDeploymentStatusImpl + job stability)."""
        job = self.state.job_by_id(d.namespace, d.job_id)
        if job is None or job.version != d.job_version or job.stable:
            return
        stable = job.copy()
        stable.stable = True
        self.raft_apply("job_register", (stable, None))

    def _new_eval(self, d: Deployment) -> Evaluation:
        job = self.state.job_by_id(d.namespace, d.job_id)
        return Evaluation(
            id=generate_uuid(),
            namespace=d.namespace,
            priority=job.priority if job else 50,
            type=job.type if job else "service",
            triggered_by=EVAL_TRIGGER_DEPLOYMENT_WATCHER,
            job_id=d.job_id,
            deployment_id=d.id,
            status=EVAL_STATUS_PENDING,
            create_time=now_ns(),
            modify_time=now_ns(),
        )
