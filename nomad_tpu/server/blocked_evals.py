"""Blocked-evaluations tracker.

Reference: nomad/blocked_evals.go (781 LoC) — evals that failed placement
wait here, keyed by the computed node classes they found ineligible; any
capacity-changing event (node up/updated, alloc freed) unblocks the evals
that could now succeed and re-enqueues them into the broker.

Storm containment (overload protection): per-job dedup means repeated
capacity churn can never mint unbounded duplicates for one job (newest
blocked eval wins, mirroring the state store's cancel-on-upsert), and a
configurable ``cap`` bounds the total tracked population — past it the
OLDEST blocked eval is evicted back into the broker (re-enqueued, not
silently dropped: it gets another placement attempt, and if capacity is
still missing it re-blocks, keeping the population at the cap instead
of growing without bound).
"""

from __future__ import annotations

import threading
from typing import Callable, Optional

from .. import metrics
from ..structs import Evaluation
from ..structs.structs import EVAL_TRIGGER_MAX_PLANS


class BlockedEvals:
    def __init__(self, enqueue_fn: Callable[[Evaluation], None],
                 cap: int = 0) -> None:
        self.enqueue_fn = enqueue_fn
        # Max tracked blocked evals (captured + escaped); 0 = unbounded.
        self.cap = cap
        self._lock = threading.Lock()
        self._enabled = False
        # eval id -> eval, for evals blocked on specific classes
        self._captured: dict[str, Evaluation] = {}
        # evals whose constraints escaped class tracking: unblock on any change
        self._escaped: dict[str, Evaluation] = {}
        # (ns, job) -> blocked eval id (one blocked eval per job)
        self._by_job: dict[tuple[str, str], str] = {}
        # insertion-age journal (dict = insertion-ordered): the cap's
        # oldest-eviction order. Ids leave lazily — a key may be stale
        # (already unblocked); eviction skips those.
        self._ages: dict[str, None] = {}
        # computed class -> state index of the last capacity change for
        # that class (reference unblockIndexes): closes the lost-wakeup
        # race where capacity appears BETWEEN the scheduler's snapshot
        # and the eval landing here.
        self._unblock_indexes: dict[str, int] = {}
        self._global_unblock_index = 0
        self.stats = {
            "total_blocked": 0,
            "total_escaped": 0,
            "unblocks": 0,
            "deduped": 0,
            "evicted": 0,
        }

    def configure(self, cap: Optional[int] = None) -> None:
        """Live reconfiguration (agent SIGHUP reload). Shrinking the cap
        applies to FUTURE blocks; the population drains to the new bound
        as churn arrives (no mass eviction storm on reload)."""
        with self._lock:
            if cap is not None:
                self.cap = int(cap)

    def set_enabled(self, enabled: bool) -> None:
        with self._lock:
            self._enabled = enabled
            if not enabled:
                self._captured.clear()
                self._escaped.clear()
                self._by_job.clear()
                self._ages.clear()

    def _missed_unblock(self, ev: Evaluation) -> bool:
        """Did a capacity change land after this eval's snapshot?
        (reference blocked_evals.go missedUnblock)"""
        if ev.escaped_computed_class or not ev.class_eligibility:
            return self._global_unblock_index > ev.snapshot_index
        for cls, index in self._unblock_indexes.items():
            if index <= ev.snapshot_index:
                continue
            elig = ev.class_eligibility.get(cls)
            if elig is None or elig:
                return True
        return False

    def block(self, ev: Evaluation) -> None:
        requeued = None
        evicted: list[Evaluation] = []
        with self._lock:
            if not self._enabled:
                return
            if self._missed_unblock(ev):
                # Don't park it — the capacity it failed to find already
                # appeared. Hand it straight back to the broker.
                self.stats["unblocks"] += 1
                requeued = ev.copy()
                requeued.status = "pending"
                requeued.triggered_by = "queued-allocs"
            else:
                self._block_locked(ev)
                evicted = self._evict_over_cap_locked()
        # enqueue outside the lock, like unblock()/unblock_all()
        if requeued is not None:
            self.enqueue_fn(requeued)
        for old in evicted:
            metrics.incr("nomad.blocked_evals.evicted")
            re = old.copy()
            re.status = "pending"
            re.triggered_by = "queued-allocs"
            self.enqueue_fn(re)

    def _block_locked(self, ev: Evaluation) -> None:
        key = (ev.namespace, ev.job_id)
        # newest blocked eval per job wins (the state store cancels the
        # older one on upsert — mirror that here)
        old_id = self._by_job.get(key)
        if old_id and old_id != ev.id:
            self._captured.pop(old_id, None)
            self._escaped.pop(old_id, None)
            self._ages.pop(old_id, None)
            self.stats["deduped"] += 1
            metrics.incr("nomad.blocked_evals.deduped")
        self._by_job[key] = ev.id
        if ev.escaped_computed_class or not ev.class_eligibility:
            self._escaped[ev.id] = ev
        else:
            self._captured[ev.id] = ev
        self._ages.pop(ev.id, None)
        self._ages[ev.id] = None
        self.stats["total_escaped"] = len(self._escaped)
        self.stats["total_blocked"] = len(self._captured) + len(self._escaped)

    def _evict_over_cap_locked(self) -> list[Evaluation]:
        """Oldest-first eviction down to the cap; returns the evals to
        re-enqueue (caller does so outside the lock)."""
        if self.cap <= 0:
            return []
        out: list[Evaluation] = []
        while len(self._captured) + len(self._escaped) > self.cap:
            victim = None
            # _ages may lead with stale ids (already unblocked) — skip
            while self._ages:
                vid = next(iter(self._ages))
                del self._ages[vid]
                victim = self._captured.pop(vid, None) or self._escaped.pop(
                    vid, None
                )
                if victim is not None:
                    break
            if victim is None:
                break  # journal exhausted (shouldn't happen)
            self._by_job.pop((victim.namespace, victim.job_id), None)
            self.stats["evicted"] += 1
            out.append(victim)
        self.stats["total_escaped"] = len(self._escaped)
        self.stats["total_blocked"] = len(self._captured) + len(self._escaped)
        return out

    def untrack(self, namespace: str, job_id: str) -> None:
        """Job deregistered: drop its blocked eval."""
        with self._lock:
            eid = self._by_job.pop((namespace, job_id), None)
            if eid:
                self._captured.pop(eid, None)
                self._escaped.pop(eid, None)
                self._ages.pop(eid, None)
                self.stats["total_escaped"] = len(self._escaped)
                self.stats["total_blocked"] = (
                    len(self._captured) + len(self._escaped)
                )

    # -- unblock triggers ---------------------------------------------

    def unblock(self, computed_class: str, index: int = 0) -> None:
        """Capacity freed/added on nodes of this class (reference Unblock).
        `index` is the state index of the capacity change; future blocks
        with an older snapshot are re-enqueued immediately."""
        to_run: list[Evaluation] = []
        with self._lock:
            if not self._enabled:
                return
            if index:
                self._unblock_indexes[computed_class] = max(
                    self._unblock_indexes.get(computed_class, 0), index
                )
                self._global_unblock_index = max(
                    self._global_unblock_index, index
                )
            for eid in list(self._escaped):
                to_run.append(self._escaped.pop(eid))
                self._ages.pop(eid, None)
            for eid, ev in list(self._captured.items()):
                # eligible (True) => the class could place it: unblock.
                # unknown class (not in map) => untested: unblock to retest.
                elig = ev.class_eligibility.get(computed_class)
                if elig is None or elig:
                    to_run.append(self._captured.pop(eid))
                    self._ages.pop(eid, None)
            for ev in to_run:
                self._by_job.pop((ev.namespace, ev.job_id), None)
            self.stats["unblocks"] += len(to_run)
            self.stats["total_blocked"] = len(self._captured) + len(self._escaped)
            self.stats["total_escaped"] = len(self._escaped)
        for ev in to_run:
            requeued = ev.copy()
            requeued.status = "pending"
            requeued.triggered_by = "queued-allocs"
            self.enqueue_fn(requeued)

    def unblock_all(self) -> None:
        with self._lock:
            evs = list(self._captured.values()) + list(self._escaped.values())
            self._captured.clear()
            self._escaped.clear()
            self._by_job.clear()
            self._ages.clear()
        for ev in evs:
            requeued = ev.copy()
            requeued.status = "pending"
            self.enqueue_fn(requeued)

    def blocked_count(self) -> int:
        with self._lock:
            return len(self._captured) + len(self._escaped)
