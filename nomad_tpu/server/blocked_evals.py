"""Blocked-evaluations tracker.

Reference: nomad/blocked_evals.go (781 LoC) — evals that failed placement
wait here, keyed by the computed node classes they found ineligible; any
capacity-changing event (node up/updated, alloc freed) unblocks the evals
that could now succeed and re-enqueues them into the broker.
"""

from __future__ import annotations

import threading
from typing import Callable, Optional

from ..structs import Evaluation
from ..structs.structs import EVAL_TRIGGER_MAX_PLANS


class BlockedEvals:
    def __init__(self, enqueue_fn: Callable[[Evaluation], None]) -> None:
        self.enqueue_fn = enqueue_fn
        self._lock = threading.Lock()
        self._enabled = False
        # eval id -> eval, for evals blocked on specific classes
        self._captured: dict[str, Evaluation] = {}
        # evals whose constraints escaped class tracking: unblock on any change
        self._escaped: dict[str, Evaluation] = {}
        # (ns, job) -> blocked eval id (one blocked eval per job)
        self._by_job: dict[tuple[str, str], str] = {}
        # computed class -> state index of the last capacity change for
        # that class (reference unblockIndexes): closes the lost-wakeup
        # race where capacity appears BETWEEN the scheduler's snapshot
        # and the eval landing here.
        self._unblock_indexes: dict[str, int] = {}
        self._global_unblock_index = 0
        self.stats = {"total_blocked": 0, "total_escaped": 0, "unblocks": 0}

    def set_enabled(self, enabled: bool) -> None:
        with self._lock:
            self._enabled = enabled
            if not enabled:
                self._captured.clear()
                self._escaped.clear()
                self._by_job.clear()

    def _missed_unblock(self, ev: Evaluation) -> bool:
        """Did a capacity change land after this eval's snapshot?
        (reference blocked_evals.go missedUnblock)"""
        if ev.escaped_computed_class or not ev.class_eligibility:
            return self._global_unblock_index > ev.snapshot_index
        for cls, index in self._unblock_indexes.items():
            if index <= ev.snapshot_index:
                continue
            elig = ev.class_eligibility.get(cls)
            if elig is None or elig:
                return True
        return False

    def block(self, ev: Evaluation) -> None:
        requeued = None
        with self._lock:
            if not self._enabled:
                return
            if self._missed_unblock(ev):
                # Don't park it — the capacity it failed to find already
                # appeared. Hand it straight back to the broker.
                self.stats["unblocks"] += 1
                requeued = ev.copy()
                requeued.status = "pending"
                requeued.triggered_by = "queued-allocs"
            else:
                self._block_locked(ev)
        # enqueue outside the lock, like unblock()/unblock_all()
        if requeued is not None:
            self.enqueue_fn(requeued)

    def _block_locked(self, ev: Evaluation) -> None:
        key = (ev.namespace, ev.job_id)
        # newest blocked eval per job wins (the state store cancels the
        # older one on upsert — mirror that here)
        old_id = self._by_job.get(key)
        if old_id:
            self._captured.pop(old_id, None)
            self._escaped.pop(old_id, None)
        self._by_job[key] = ev.id
        if ev.escaped_computed_class or not ev.class_eligibility:
            self._escaped[ev.id] = ev
            self.stats["total_escaped"] = len(self._escaped)
        else:
            self._captured[ev.id] = ev
        self.stats["total_blocked"] = len(self._captured) + len(self._escaped)

    def untrack(self, namespace: str, job_id: str) -> None:
        """Job deregistered: drop its blocked eval."""
        with self._lock:
            eid = self._by_job.pop((namespace, job_id), None)
            if eid:
                self._captured.pop(eid, None)
                self._escaped.pop(eid, None)

    # -- unblock triggers ---------------------------------------------

    def unblock(self, computed_class: str, index: int = 0) -> None:
        """Capacity freed/added on nodes of this class (reference Unblock).
        `index` is the state index of the capacity change; future blocks
        with an older snapshot are re-enqueued immediately."""
        to_run: list[Evaluation] = []
        with self._lock:
            if not self._enabled:
                return
            if index:
                self._unblock_indexes[computed_class] = max(
                    self._unblock_indexes.get(computed_class, 0), index
                )
                self._global_unblock_index = max(
                    self._global_unblock_index, index
                )
            for eid in list(self._escaped):
                to_run.append(self._escaped.pop(eid))
            for eid, ev in list(self._captured.items()):
                # eligible (True) => the class could place it: unblock.
                # unknown class (not in map) => untested: unblock to retest.
                elig = ev.class_eligibility.get(computed_class)
                if elig is None or elig:
                    to_run.append(self._captured.pop(eid))
            for ev in to_run:
                self._by_job.pop((ev.namespace, ev.job_id), None)
            self.stats["unblocks"] += len(to_run)
            self.stats["total_blocked"] = len(self._captured) + len(self._escaped)
            self.stats["total_escaped"] = len(self._escaped)
        for ev in to_run:
            requeued = ev.copy()
            requeued.status = "pending"
            requeued.triggered_by = "queued-allocs"
            self.enqueue_fn(requeued)

    def unblock_all(self) -> None:
        with self._lock:
            evs = list(self._captured.values()) + list(self._escaped.values())
            self._captured.clear()
            self._escaped.clear()
            self._by_job.clear()
        for ev in evs:
            requeued = ev.copy()
            requeued.status = "pending"
            self.enqueue_fn(requeued)

    def blocked_count(self) -> int:
        with self._lock:
            return len(self._captured) + len(self._escaped)
