"""Node-liveness heartbeats: a sharded hierarchical timer wheel.

Reference: nomad/heartbeat.go — per-node TTL timers scaled by cluster size
(lib.RateScaledInterval: max 50 heartbeats/sec cluster-wide, min 10s TTL);
a missed TTL marks the node down and creates evals for its jobs.

The reference (and PR 10's port) kept one timer object per node. At
fleet scale that design collapses: 10k armed ``threading.Timer``s are
10k pending thread starts, every expiry spawns a thread, and a mass
expiry (partition, leader-side stall) fires thousands of concurrent
down-mark raft writes. The wheel replaces all of it with ONE ticker
thread over sharded tick-indexed buckets:

  * ``reset`` is O(1): write the node's authoritative deadline and drop
    its id into the bucket for that tick (shard chosen by hash, so
    10k concurrent heartbeats don't serialize on one lock);
  * re-arm is lazy: the old bucket entry is left in place and
    invalidated by the deadline check at expiry time — a heartbeat
    racing its own expiry wins iff its deadline write lands first;
  * the ticker processes EVERY bucket that is due, not just the
    current tick, so a late wake (GC pause, scheduler stall, paused-GC
    bench section) expires overdue nodes in one catch-up sweep instead
    of skipping them;
  * all nodes expiring in one sweep are delivered as ONE
    ``on_expire_batch`` call — the server turns a mass expiry into a
    bounded number of batched raft writes instead of N.
"""

from __future__ import annotations

import logging
import random
import threading
from typing import Callable, Iterable, Optional

logger = logging.getLogger("nomad_tpu.server")

MIN_HEARTBEAT_TTL_S = 10.0
MAX_HEARTBEATS_PER_SECOND = 50.0
FAILOVER_GRACE_S = 5.0

DEFAULT_WHEEL_TICK_S = 0.1
DEFAULT_WHEEL_SHARDS = 8


def rate_scaled_interval(
    n_nodes: int,
    min_ttl_s: float = MIN_HEARTBEAT_TTL_S,
    rate_hz: float = MAX_HEARTBEATS_PER_SECOND,
) -> float:
    """TTL grows with the cluster to bound heartbeat throughput
    (reference: helper lib.RateScaledInterval, heartbeat.go:104)."""
    interval = float(n_nodes) / max(rate_hz, 1e-9)
    return max(min_ttl_s, interval)


class _WheelShard:
    """One shard: an authoritative deadline map plus tick-indexed
    buckets of node ids. Buckets are HINTS — a bucket entry whose
    deadline moved (re-arm) or vanished (clear) is dropped when its
    bucket is processed; the deadline map alone decides expiry."""

    __slots__ = ("lock", "deadlines", "buckets")

    def __init__(self) -> None:
        self.lock = threading.Lock()
        self.deadlines: dict[str, float] = {}
        self.buckets: dict[int, set[str]] = {}


class HeartbeatWheel:
    """Leader-local node TTL tracking on a sharded timer wheel.

    API-compatible with the flat-dict ``HeartbeatTimers`` it replaces
    (``set_enabled`` / ``initialize`` / ``reset`` / ``clear`` /
    ``active_count`` / ``min_ttl_s`` / ``node_count_fn``), plus:

      * ``on_expire_batch`` — preferred delivery: one call per ticker
        sweep with EVERY node that expired in it (storm coalescing);
        ``on_expire`` remains the per-node fallback;
      * ``tick_s`` — wheel resolution, instance-tunable like
        ``min_ttl_s`` (scenarios shrink both to fit a test budget
        without faking the expiry path).
    """

    def __init__(
        self,
        on_expire: Callable[[str], None],
        on_expire_batch: Optional[Callable[[list], None]] = None,
        shards: int = DEFAULT_WHEEL_SHARDS,
        tick_s: float = DEFAULT_WHEEL_TICK_S,
    ) -> None:
        self.on_expire = on_expire
        self.on_expire_batch = on_expire_batch
        self.tick_s = tick_s
        self._shards = [_WheelShard() for _ in range(max(1, shards))]
        # lifecycle lock: guards enabled flag + ticker thread handle
        # only — never held while arming timers or delivering expiries
        self._lifecycle = threading.Lock()
        self._enabled = False
        self._stop: Optional[threading.Event] = None
        self._ticker: Optional[threading.Thread] = None
        self.node_count_fn: Callable[[], int] = lambda: 1
        # Instance-tunable TTL floor: production keeps the reference's
        # 10s; chaos scenarios shrink it so spot-churn cycles (node dies
        # silently → TTL expiry → down-mark → reschedule) fit a test
        # budget without faking the expiry path.
        self.min_ttl_s = MIN_HEARTBEAT_TTL_S
        # Instance-tunable cluster-wide heartbeat rate cap (the n/rate
        # term of rate_scaled_interval). Fleet scenarios raise it so a
        # multi-thousand-node fleet's death→down-mark cycle fits a test
        # budget; production keeps the reference's 50/s.
        self.rate_hz = MAX_HEARTBEATS_PER_SECOND
        # monotonic clock, overridable by drift tests
        import time as _time

        self._now = _time.monotonic

    # -- lifecycle -----------------------------------------------------

    def set_enabled(self, enabled: bool) -> None:
        """Leadership edge. Disable clears every armed TTL and stops the
        ticker (timers are leader-local state and die with the leader);
        enable starts a fresh ticker — the new leader re-arms via
        ``initialize`` at establish-leadership."""
        with self._lifecycle:
            if enabled == self._enabled:
                return
            self._enabled = enabled
            if enabled:
                # drop anything armed by a reset() that raced the last
                # disable — this incarnation's TTLs come exclusively
                # from initialize() + live heartbeats
                for shard in self._shards:
                    with shard.lock:
                        shard.deadlines.clear()
                        shard.buckets.clear()
                self._stop = threading.Event()
                self._ticker = threading.Thread(
                    target=self._tick_loop,
                    args=(self._stop,),
                    name="heartbeat-wheel",
                    daemon=True,
                )
                self._ticker.start()
                return
            stop, ticker = self._stop, self._ticker
            self._stop, self._ticker = None, None
        # outside the lifecycle lock: the ticker may be mid-sweep
        # waiting for a shard lock; never join while holding ours
        if stop is not None:
            stop.set()
        if ticker is not None:
            ticker.join(timeout=5)
        for shard in self._shards:
            with shard.lock:
                shard.deadlines.clear()
                shard.buckets.clear()

    def initialize(self, node_ids: Iterable[str]) -> None:
        """Arm a TTL for every live node at once — the new leader's
        establish-leadership step (reference heartbeat.go
        initializeHeartbeatTimers). Without this, a node that dies
        during a leadership transition is never marked down: its timer
        lived on the OLD leader and the new one only arms timers on
        heartbeat arrival — which a dead node never sends."""
        for node_id in node_ids:
            self.reset(node_id)

    # -- arming --------------------------------------------------------

    def reset(self, node_id: str) -> float:
        """(Re)arm the node's TTL; returns the TTL granted, with splay so
        a thundering herd of re-registrations doesn't expire
        simultaneously. O(1): deadline write + bucket insert; the stale
        bucket entry from the previous arm is invalidated lazily."""
        ttl = rate_scaled_interval(
            self.node_count_fn(), self.min_ttl_s, self.rate_hz
        )
        ttl += random.uniform(0, ttl / 2)
        if not self._enabled:
            return ttl
        deadline = self._now() + ttl
        shard = self._shard(node_id)
        tick = int(deadline // self.tick_s) + 1
        with shard.lock:
            shard.deadlines[node_id] = deadline
            shard.buckets.setdefault(tick, set()).add(node_id)
        return ttl

    def clear(self, node_id: str) -> None:
        shard = self._shard(node_id)
        with shard.lock:
            shard.deadlines.pop(node_id, None)

    def active_count(self) -> int:
        total = 0
        for shard in self._shards:
            with shard.lock:
                total += len(shard.deadlines)
        return total

    def stats(self) -> dict[str, float]:
        """Provider gauges (``nomad.heartbeat.*``): armed TTL count and
        live bucket count across shards (wheel depth)."""
        armed = 0
        buckets = 0
        for shard in self._shards:
            with shard.lock:
                armed += len(shard.deadlines)
                buckets += len(shard.buckets)
        return {"armed": armed, "wheel_buckets": buckets}

    # -- expiry --------------------------------------------------------

    def _shard(self, node_id: str) -> _WheelShard:
        return self._shards[hash(node_id) % len(self._shards)]

    def _tick_loop(self, stop: threading.Event) -> None:
        while not stop.wait(self.tick_s):
            try:
                self._advance(self._now())
            except Exception:
                logger.exception("heartbeat wheel sweep failed")

    def _advance(self, now: float) -> list[str]:
        """One sweep: process every due bucket in every shard, expire
        nodes whose authoritative deadline passed, re-file entries whose
        deadline moved. Processes ALL overdue ticks (drift catch-up: a
        ticker delayed by a GC pause expires the backlog in one sweep).
        Expiry delivery happens with NO shard lock held."""
        now_tick = int(now // self.tick_s)
        expired: list[str] = []
        for shard in self._shards:
            with shard.lock:
                if not shard.buckets:
                    continue
                due = [t for t in shard.buckets if t <= now_tick]
                for t in due:
                    for node_id in shard.buckets.pop(t):
                        deadline = shard.deadlines.get(node_id)
                        if deadline is None:
                            continue  # cleared since it was filed
                        if deadline <= now:
                            del shard.deadlines[node_id]
                            expired.append(node_id)
                        else:
                            # re-armed since it was filed: the live
                            # heartbeat won the race — re-file under
                            # the new deadline's tick
                            nt = int(deadline // self.tick_s) + 1
                            shard.buckets.setdefault(nt, set()).add(
                                node_id
                            )
        if not expired:
            return expired
        if not self._enabled:
            return []
        if self.on_expire_batch is not None:
            self.on_expire_batch(expired)
        else:
            for node_id in expired:
                self.on_expire(node_id)
        return expired


# The flat-dict implementation's name, kept as an alias: server wiring,
# scenarios, and older tests refer to HeartbeatTimers.
HeartbeatTimers = HeartbeatWheel
