"""Node-liveness heartbeats (leader-only TTL timers).

Reference: nomad/heartbeat.go — per-node TTL timers scaled by cluster size
(lib.RateScaledInterval: max 50 heartbeats/sec cluster-wide, min 10s TTL);
a missed TTL marks the node down and creates evals for its jobs.
"""

from __future__ import annotations

import random
import threading
from typing import Callable, Optional

MIN_HEARTBEAT_TTL_S = 10.0
MAX_HEARTBEATS_PER_SECOND = 50.0
FAILOVER_GRACE_S = 5.0


def rate_scaled_interval(
    n_nodes: int, min_ttl_s: float = MIN_HEARTBEAT_TTL_S
) -> float:
    """TTL grows with the cluster to bound heartbeat throughput
    (reference: helper lib.RateScaledInterval, heartbeat.go:104)."""
    interval = float(n_nodes) / MAX_HEARTBEATS_PER_SECOND
    return max(min_ttl_s, interval)


class HeartbeatTimers:
    def __init__(self, on_expire: Callable[[str], None]) -> None:
        self.on_expire = on_expire
        self._lock = threading.Lock()
        self._timers: dict[str, threading.Timer] = {}
        self._enabled = False
        self.node_count_fn: Callable[[], int] = lambda: 1
        # Instance-tunable TTL floor: production keeps the reference's
        # 10s; chaos scenarios shrink it so spot-churn cycles (node dies
        # silently → TTL expiry → down-mark → reschedule) fit a test
        # budget without faking the expiry path.
        self.min_ttl_s = MIN_HEARTBEAT_TTL_S

    def set_enabled(self, enabled: bool) -> None:
        with self._lock:
            self._enabled = enabled
            if not enabled:
                for t in self._timers.values():
                    t.cancel()
                self._timers.clear()

    def initialize(self, node_ids) -> None:
        """Arm a TTL for every live node at once — the new leader's
        establish-leadership step (reference heartbeat.go
        initializeHeartbeatTimers). Without this, a node that dies
        during a leadership transition is never marked down: its timer
        lived on the OLD leader and the new one only arms timers on
        heartbeat arrival — which a dead node never sends."""
        for node_id in node_ids:
            self.reset(node_id)

    def reset(self, node_id: str) -> float:
        """(Re)arm the node's TTL; returns the TTL granted, with splay so a
        thundering herd of re-registrations doesn't expire simultaneously."""
        ttl = rate_scaled_interval(self.node_count_fn(), self.min_ttl_s)
        ttl += random.uniform(0, ttl / 2)
        with self._lock:
            if not self._enabled:
                return ttl
            old = self._timers.pop(node_id, None)
            if old:
                old.cancel()
            timer = threading.Timer(ttl, self._expire, args=(node_id,))
            timer.daemon = True
            self._timers[node_id] = timer
            timer.start()
        return ttl

    def clear(self, node_id: str) -> None:
        with self._lock:
            old = self._timers.pop(node_id, None)
            if old:
                old.cancel()

    def _expire(self, node_id: str) -> None:
        with self._lock:
            self._timers.pop(node_id, None)
            if not self._enabled:
                return
        self.on_expire(node_id)

    def active_count(self) -> int:
        with self._lock:
            return len(self._timers)
