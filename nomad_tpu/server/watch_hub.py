"""Event-driven alloc-watch fan-out: per-node wakeups, not a herd.

Reference: nomad's client alloc watch (``client/client.go:2003
watchAllocations``) is a blocking query; the server side wakes it
through memdb watch channels scoped to what actually changed. Our
seed-era port (``StateStore.wait_for_index``) wakes EVERY blocked
watcher on EVERY alloc-table write (``Condition.notify_all``), and each
woken watcher re-scans its node's alloc set — O(watchers) wakeups and
O(watchers × allocs) scan work per write. Ten clients never noticed;
10k make every plan apply a stampede.

:class:`AllocWatchHub` restores the reference's scoping with three
pieces, each bounded:

  * a **store subscriber** that runs under the store lock and does the
    minimum legal there: append the changed block's (index, node-ids)
    to a bounded inbox and set an event (no locks of ours, no store
    re-entry — the lock-order edge is store→inbox only);
  * a **fan-out thread** ("alloc-watch-fanout") that drains the inbox
    and advances a per-node change index, waking only the waiters of
    nodes that actually changed;
  * **per-node waiter lists** bounded at ``max_waiters_per_node`` —
    registering past the bound evicts the oldest waiter (it wakes and
    serves current state; ``nomad.fleet.watch_evicted`` counts) so a
    slow or leaky consumer can't grow an unbounded queue.

If the inbox itself overflows (replay floods, pathological write
storms), the hub degrades honestly: it remembers only the highest
flooded index, bumps EVERY tracked node to it, and counts
``nomad.fleet.fanout_overflow`` — a lost fine-grained route never loses
a wakeup, and a node the hub has never seen still converges through the
watcher's timeout-and-fetch fallback.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Optional

from .. import metrics

DEFAULT_INBOX_CAP = 4096
DEFAULT_MAX_WAITERS_PER_NODE = 4


class AllocWatchHub:
    def __init__(
        self,
        state,
        inbox_cap: int = DEFAULT_INBOX_CAP,
        max_waiters_per_node: int = DEFAULT_MAX_WAITERS_PER_NODE,
    ) -> None:
        from ..state.store import TABLE_ALLOCS

        self._alloc_table = TABLE_ALLOCS
        self._inbox_cap = inbox_cap
        self._max_waiters = max_waiters_per_node
        # inbox: filled under the STORE lock — keep the critical
        # section to an append + event set
        self._inbox_lock = threading.Lock()
        self._inbox: deque = deque()
        self._overflow_index = 0
        self._wake = threading.Event()
        # hub state: per-node change index + waiters. Store reads are
        # NEVER made under this lock (no hub→store lock-order edge).
        self._lock = threading.Lock()
        self._node_index: dict[str, int] = {}
        self._waiters: dict[str, list] = {}  # node_id -> [(min_index, Event)]
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._fanout_loop, name="alloc-watch-fanout", daemon=True
        )
        self._thread.start()
        state.subscribe(self._on_store_write)
        subscribe_restore = getattr(state, "subscribe_restore", None)
        if subscribe_restore is not None:
            subscribe_restore(self.prime)

    # -- store side (called under the store lock) ----------------------

    def _on_store_write(self, index: int, table: str, objs: list, etype: str) -> None:
        if table != self._alloc_table or not objs:
            return
        node_ids = {getattr(o, "node_id", "") for o in objs}
        node_ids.discard("")
        if not node_ids:
            return
        with self._inbox_lock:
            if len(self._inbox) >= self._inbox_cap:
                if index > self._overflow_index:
                    self._overflow_index = index
            else:
                self._inbox.append((index, node_ids))
        self._wake.set()

    def prime(self, index: int, node_ids: set) -> None:
        """Snapshot restore: the store was REPLACED, not written — no
        per-write routes fired, so re-seed every alloc-owning node at
        the restored index. Overwrites (never maxes) because an
        operator restore may rebase indexes DOWNWARD; and wakes every
        parked waiter so in-flight blocking queries resync their cursor
        against the new world instead of sleeping a full timeout."""
        with self._lock:
            self._node_index = {nid: index for nid in node_ids}
            waiters, self._waiters = self._waiters, {}
        for entries in waiters.values():
            for _min_index, ev in entries:
                ev.set()

    # -- fan-out thread ------------------------------------------------

    def _fanout_loop(self) -> None:
        while not self._stop.is_set():
            self._wake.wait(0.5)
            self._wake.clear()
            self._drain()

    def _drain(self) -> None:
        with self._inbox_lock:
            batch = list(self._inbox)
            self._inbox.clear()
            overflow = self._overflow_index
            self._overflow_index = 0
        if not batch and not overflow:
            return
        woken = 0
        with self._lock:
            for index, node_ids in batch:
                for node_id in node_ids:
                    if index > self._node_index.get(node_id, 0):
                        self._node_index[node_id] = index
                    woken += self._wake_waiters(node_id, index)
            if overflow:
                # fine-grained routes were lost: bump every tracked
                # node so no registered watcher sleeps through a write
                for node_id in list(self._node_index):
                    if overflow > self._node_index[node_id]:
                        self._node_index[node_id] = overflow
                    woken += self._wake_waiters(node_id, overflow)
        if overflow:
            metrics.incr("nomad.fleet.fanout_overflow")
        if woken:
            metrics.incr("nomad.fleet.watch_wakeups", woken)

    def _wake_waiters(self, node_id: str, index: int) -> int:
        """Signal waiters satisfied by `index`. Caller holds _lock."""
        waiters = self._waiters.get(node_id)
        if not waiters:
            return 0
        keep = []
        woken = 0
        for min_index, ev in waiters:
            if index >= min_index:
                ev.set()
                woken += 1
            else:
                keep.append((min_index, ev))
        if keep:
            self._waiters[node_id] = keep
        else:
            self._waiters.pop(node_id, None)
        return woken

    # -- watcher side --------------------------------------------------

    def index_of(self, node_id: str) -> int:
        """O(1) probe: the index of the node's last alloc change (0 if
        the hub has never routed one). The simulated fleet's
        cooperative watch poll rides this instead of holding a blocked
        thread per node."""
        with self._lock:
            return self._node_index.get(node_id, 0)

    def wait_for_node(
        self, node_id: str, min_index: int, timeout_s: Optional[float]
    ) -> bool:
        """Block until `node_id`'s alloc set has changed at or past
        `min_index`, or timeout. True = woken by a change (or already
        past), False = timed out (callers fall back to a fetch — the
        contract stays identical to the old wait_for_index poll, minus
        the herd wakeups)."""
        with self._lock:
            if self._node_index.get(node_id, 0) >= min_index:
                return True
            ev = threading.Event()
            waiters = self._waiters.setdefault(node_id, [])
            evicted = None
            if len(waiters) >= self._max_waiters:
                evicted = waiters.pop(0)
            waiters.append((min_index, ev))
        if evicted is not None:
            # wake the displaced waiter so it serves current state and
            # returns — a bounded queue, never a silent strand
            evicted[1].set()
            metrics.incr("nomad.fleet.watch_evicted")
        ok = ev.wait(timeout_s)
        if not ok:
            with self._lock:
                waiters = self._waiters.get(node_id)
                if waiters is not None:
                    self._waiters[node_id] = [
                        w for w in waiters if w[1] is not ev
                    ]
                    if not self._waiters[node_id]:
                        self._waiters.pop(node_id, None)
        return ok

    def stats(self) -> dict[str, float]:
        """Provider gauges (``nomad.fleet.*`` fan-out rows)."""
        with self._lock:
            subs = sum(len(w) for w in self._waiters.values())
            tracked = len(self._node_index)
        return {"watch_subscribers": subs, "nodes_tracked": tracked}

    def stop(self) -> None:
        self._stop.set()
        self._wake.set()
        self._thread.join(timeout=5)
