"""Prefix + fuzzy search across cluster objects.

Reference: nomad/search_endpoint.go — /v1/search resolves a prefix to ids
per context (jobs, evals, allocs, nodes, deployments, namespaces,
volumes) with a 20-item truncation per context; /v1/search/fuzzy matches
substrings and also reaches into job structure (group/task names).
"""

from __future__ import annotations

TRUNCATE_LIMIT = 20

ALL_CONTEXTS = (
    "jobs",
    "evals",
    "allocs",
    "nodes",
    "deployments",
    "namespaces",
    "volumes",
)


def _collect(state, namespace: str, contexts):
    """context -> [(id, extra)] — only the REQUESTED contexts are
    materialized, and namespace-scoped objects are filtered to the
    authorized namespace (the ACL gate checks read-job on it; returning
    other namespaces' eval/alloc/deployment ids would leak them —
    reference search_endpoint.go filters per context the same way).
    Nodes and namespace names are cluster-scoped infrastructure."""
    makers = {
        "jobs": lambda: [(j.id, None) for j in state.jobs(namespace)],
        "evals": lambda: [
            (e.id, None) for e in state.evals() if e.namespace == namespace
        ],
        "allocs": lambda: [
            (a.id, None) for a in state.allocs() if a.namespace == namespace
        ],
        "nodes": lambda: [(n.id, n.name) for n in state.nodes()],
        "deployments": lambda: [
            (d.id, None)
            for d in state.deployments()
            if d.namespace == namespace
        ],
        "namespaces": lambda: [(n.name, None) for n in state.namespaces()],
        "volumes": lambda: [(v.id, None) for v in state.volumes(namespace)],
    }
    return {ctx: makers[ctx]() for ctx in contexts if ctx in makers}


def prefix_search(state, prefix: str, context: str = "all",
                  namespace: str = "default") -> dict:
    contexts = ALL_CONTEXTS if context in ("", "all") else (context,)
    universe = _collect(state, namespace, contexts)
    matches: dict[str, list[str]] = {}
    truncations: dict[str, bool] = {}
    for ctx in contexts:
        ids = sorted(
            i for i, _ in universe.get(ctx, []) if i.startswith(prefix)
        )
        truncations[ctx] = len(ids) > TRUNCATE_LIMIT
        if ids:
            matches[ctx] = ids[:TRUNCATE_LIMIT]
    return {"Matches": matches, "Truncations": truncations}


def fuzzy_search(state, text: str, context: str = "all",
                 namespace: str = "default") -> dict:
    """Substring match; jobs also expose group/task scopes (reference
    fuzzyMatchesJob)."""
    text_l = text.lower()
    contexts = ALL_CONTEXTS if context in ("", "all") else (context,)
    universe = _collect(state, namespace, contexts)
    matches: dict[str, list[dict]] = {}
    truncations: dict[str, bool] = {}
    # namespace-scoped contexts carry the namespace in Scope so a hit is
    # resolvable (reference fuzzyMatchesJob's scope convention)
    ns_scoped = {"jobs", "evals", "allocs", "deployments", "volumes"}
    for ctx in contexts:
        hits: list[dict] = []
        scope = [namespace] if ctx in ns_scoped else []
        for ident, extra in universe.get(ctx, []):
            if text_l in ident.lower() or (
                extra and text_l in str(extra).lower()
            ):
                hits.append({"ID": ident, "Scope": list(scope)})
        if ctx == "jobs":
            for job in state.jobs(namespace):
                for tg in job.task_groups:
                    if text_l in tg.name.lower():
                        hits.append(
                            {"ID": tg.name, "Scope": [namespace, job.id]}
                        )
                    for task in tg.tasks:
                        if text_l in task.name.lower():
                            hits.append(
                                {
                                    "ID": task.name,
                                    "Scope": [namespace, job.id, tg.name],
                                }
                            )
        truncations[ctx] = len(hits) > TRUNCATE_LIMIT
        if hits:
            matches[ctx] = hits[:TRUNCATE_LIMIT]
    return {"Matches": matches, "Truncations": truncations}
